"""Quartz-style cron schedules for ``#window.cron``.

siddhi-core's CronWindowProcessor takes a Quartz cron expression
(6-7 fields: sec min hour day-of-month month day-of-week [year]) and
flushes the collected window at every fire. This module provides the
HOST side of that: parse the expression and map event timestamps to
per-event window indices, which ship to the device as a narrow column
(the device never does calendar math — "an emission schedule, not
device math").

``window_ids`` is a PURE function of the timestamps: a window index is
the absolute number of fires since the epoch (1970-01-01 UTC), computed
from field-set counting plus a lazily-built per-year matching-day table.
No anchor, no data-dependent state — the same timestamp always maps to
the same window id, across micro-batches, jobs, and shards (the
per-year cache is deterministic, so sharing one instance is safe).

Supported field syntax: ``*``, ``?``, lists ``a,b,c``, ranges ``a-b``,
steps ``*/n``, ``a/n`` (= every n from a), ``a-b/n``, month names
JAN..DEC, day names SUN..SAT (Quartz numeric day-of-week 1=SUN..7=SAT;
0 is also accepted as Sunday), and numeric years. ``L``/``W``/``#``
calendar extensions are rejected loudly. All times are UTC.
"""

from __future__ import annotations

import calendar
from dataclasses import dataclass, field
from datetime import datetime, timedelta, timezone
from typing import Dict, Optional

import numpy as np

from ..query.lexer import SiddhiQLError

_MONTHS = {
    n: i + 1
    for i, n in enumerate(
        "JAN FEB MAR APR MAY JUN JUL AUG SEP OCT NOV DEC".split()
    )
}
# Quartz day-of-week numbering: 1 = SUN .. 7 = SAT
_DOW_NAMES = {
    n: i + 1 for i, n in enumerate("SUN MON TUE WED THU FRI SAT".split())
}
_DAY_MS = 86_400_000
_EPOCH_YEAR = 1970


def _parse_field(text: str, lo: int, hi: int, names=None):
    """One cron field -> sorted allowed-value array, or None for */?."""
    text = text.strip().upper()
    if text in ("*", "?"):
        return None
    for bad in ("L", "W", "#"):
        if bad in text:
            raise SiddhiQLError(
                f"#window.cron: calendar extension {bad!r} is not "
                "supported"
            )

    def val(tok: str) -> int:
        if names and tok in names:
            return names[tok]
        try:
            v = int(tok)
        except ValueError:
            raise SiddhiQLError(
                f"#window.cron: bad field value {tok!r}"
            ) from None
        return v

    out = set()
    for part in text.split(","):
        step = 1
        has_step = "/" in part
        if has_step:
            part, s = part.split("/", 1)
            try:
                step = int(s)
            except ValueError:
                raise SiddhiQLError(
                    f"#window.cron: bad step {s!r}"
                ) from None
            if step <= 0:
                raise SiddhiQLError("#window.cron: step must be > 0")
        if part in ("*", "?", ""):
            a, b = lo, hi
        elif "-" in part:
            a_s, b_s = part.split("-", 1)
            a, b = val(a_s), val(b_s)
        else:
            a = val(part)
            # 'a/n' means every n starting at a (even for n == 1)
            b = hi if has_step else a
        if not (lo <= a <= hi and lo <= b <= hi):
            raise SiddhiQLError(
                f"#window.cron: value out of range [{lo},{hi}]: "
                f"{part!r}"
            )
        out.update(range(a, b + 1, step))
    return np.asarray(sorted(out), dtype=np.int64)


@dataclass
class CronSchedule:
    """Parsed Quartz cron expression. ``window_ids`` is pure; the only
    mutable state is a deterministic per-year matching-day cache."""

    expr: str
    sec: Optional[np.ndarray] = None
    minute: Optional[np.ndarray] = None
    hour: Optional[np.ndarray] = None
    dom: Optional[np.ndarray] = None
    month: Optional[np.ndarray] = None
    dow: Optional[np.ndarray] = None  # 0=SUN..6=SAT
    year: Optional[np.ndarray] = None
    # day-ordinal (days since 1970-01-01) -> cumulative matching days
    # strictly before that year's Jan 1 (built lazily, deterministic)
    _year_cum: Dict[int, int] = field(default_factory=dict)
    _day_cache: Dict[int, int] = field(default_factory=dict)

    @classmethod
    def parse(cls, expr: str) -> "CronSchedule":
        fields = expr.split()
        if len(fields) not in (6, 7):
            raise SiddhiQLError(
                "#window.cron expects a Quartz expression with 6-7 "
                f"fields (sec min hour dom month dow [year]); got "
                f"{expr!r}"
            )
        dom_f, dow_f = fields[3].upper(), fields[5].upper()
        if dom_f != "?" and dow_f not in ("?", "*"):
            # Quartz requires one of dom/dow to be '?': AND-ing both
            # is ambiguous — reject loudly instead of guessing
            raise SiddhiQLError(
                "#window.cron: specify day-of-month or day-of-week, "
                "not both (use '?' for the other)"
            )
        # Quartz day-of-week is 1=SUN..7=SAT. A BARE '0' is tolerated
        # as Sunday (common habit), but 0 inside ranges/lists rejects
        # loudly: silently reading '0-6' as unix-style would drop
        # Saturday while Quartz-style reads it as an error — ambiguous
        # either way, so it must not parse.
        dow_text = ",".join(
            "1" if part.strip() == "0" else part
            for part in fields[5].split(",")
        )
        dow_raw = _parse_field(dow_text, 1, 7, _DOW_NAMES)
        dow = None
        if dow_raw is not None:
            dow = np.unique((dow_raw - 1) % 7)
        return cls(
            expr=expr,
            sec=_parse_field(fields[0], 0, 59),
            minute=_parse_field(fields[1], 0, 59),
            hour=_parse_field(fields[2], 0, 23),
            dom=_parse_field(fields[3], 1, 31),
            month=_parse_field(fields[4], 1, 12, _MONTHS),
            dow=dow,
            year=(
                _parse_field(fields[6], 1970, 2099)
                if len(fields) == 7
                else None
            ),
        )

    # -- calendar matching -----------------------------------------------
    def _date_ok(self, y: int, mo: int, d: int) -> bool:
        if self.year is not None and y not in self.year:
            return False
        if self.month is not None and mo not in self.month:
            return False
        if self.dom is not None and d not in self.dom:
            return False
        if self.dow is not None:
            # Python weekday(): Mon=0..Sun=6 -> 0=SUN..6=SAT
            wd = (calendar.weekday(y, mo, d) + 1) % 7
            if wd not in self.dow:
                return False
        return True

    def _days_in_year(self, y: int) -> int:
        n = 0
        months = (
            self.month.tolist()
            if self.month is not None
            else range(1, 13)
        )
        if self.year is not None and y not in self.year:
            return 0
        for mo in months:
            for d in range(1, calendar.monthrange(y, mo)[1] + 1):
                if self._date_ok(y, mo, d):
                    n += 1
        return n

    def _year_cum_before(self, y: int) -> int:
        """Matching days in [1970-01-01, y-01-01)."""
        if y in self._year_cum:
            return self._year_cum[y]
        prev = (
            0
            if y <= _EPOCH_YEAR
            else self._year_cum_before(y - 1) + self._days_in_year(y - 1)
        )
        self._year_cum[y] = prev
        return prev

    def _matching_days_before(self, day_ord: int) -> int:
        """Matching days in [1970-01-01, day_ord)."""
        cached = self._day_cache.get(day_ord)
        if cached is not None:
            return cached
        date = datetime(
            _EPOCH_YEAR, 1, 1, tzinfo=timezone.utc
        ) + timedelta(days=day_ord)
        n = self._year_cum_before(date.year)
        mo = 1
        while mo < date.month:
            for d in range(
                1, calendar.monthrange(date.year, mo)[1] + 1
            ):
                if self._date_ok(date.year, mo, d):
                    n += 1
            mo += 1
        for d in range(1, date.day):
            if self._date_ok(date.year, date.month, d):
                n += 1
        if len(self._day_cache) > 100_000:
            self._day_cache.clear()
        self._day_cache[day_ord] = n
        return n

    # -- fire counting ----------------------------------------------------
    def _sets(self):
        sec = (
            self.sec
            if self.sec is not None
            else np.arange(60, dtype=np.int64)
        )
        minute = (
            self.minute
            if self.minute is not None
            else np.arange(60, dtype=np.int64)
        )
        hour = (
            self.hour
            if self.hour is not None
            else np.arange(24, dtype=np.int64)
        )
        return sec, minute, hour

    def window_ids(self, ts_ms: np.ndarray) -> np.ndarray:
        """Per-event window index = number of fires at-or-before the
        event's timestamp, since the epoch. Pure in ts (modulo the
        deterministic calendar cache); monotone, so sorted tapes ship
        it as small wire deltas after the first batch."""
        ts_ms = np.asarray(ts_ms, dtype=np.int64)
        if ts_ms.size == 0:
            return np.zeros(0, dtype=np.int32)
        sec, minute, hour = self._sets()
        fpd = len(sec) * len(minute) * len(hour)
        day = ts_ms // _DAY_MS
        rem = ts_ms - day * _DAY_MS
        h = rem // 3_600_000
        mi = (rem // 60_000) % 60
        s = (rem // 1000) % 60
        # fires earlier today: full earlier hours + full earlier minutes
        # of this hour + fires at/before this second of this minute
        nh = np.searchsorted(hour, h, side="left")
        nmi = np.searchsorted(minute, mi, side="left")
        ns = np.searchsorted(sec, s, side="right")
        h_ok = hour[np.clip(nh, 0, len(hour) - 1)] == h
        h_ok &= nh < len(hour)
        mi_ok = minute[np.clip(nmi, 0, len(minute) - 1)] == mi
        mi_ok &= nmi < len(minute)
        intra = nh * len(minute) * len(sec) + np.where(
            h_ok, nmi * len(sec) + np.where(mi_ok, ns, 0), 0
        )
        base = np.empty(ts_ms.shape, dtype=np.int64)
        today_ok = np.empty(ts_ms.shape, dtype=bool)
        for d in np.unique(day).tolist():
            seld = day == d
            date = datetime(
                _EPOCH_YEAR, 1, 1, tzinfo=timezone.utc
            ) + timedelta(days=int(d))
            base[seld] = self._matching_days_before(int(d)) * fpd
            today_ok[seld] = self._date_ok(
                date.year, date.month, date.day
            )
        wid = base + np.where(today_ok, intra, 0)
        if wid.size and int(wid.max()) >= 2 ** 31:
            raise SiddhiQLError(
                "#window.cron: window index exceeds int32 (schedule "
                "fires too often for this time range)"
            )
        return wid.astype(np.int32)

    def next_fire(self, after_ms: int) -> Optional[int]:
        """Smallest fire time strictly greater than ``after_ms``
        (diagnostic/test helper; the engine uses window_ids)."""
        t = datetime.fromtimestamp(
            after_ms / 1000.0, tz=timezone.utc
        ).replace(microsecond=0) + timedelta(seconds=1)
        sec, minute, hour = self._sets()
        for _ in range(366 * 8):  # bounded day search (~8 years)
            y, mo, d = t.year, t.month, t.day
            if self.year is not None and y > int(self.year.max()):
                return None
            if not self._date_ok(y, mo, d):
                t = (t + timedelta(days=1)).replace(
                    hour=0, minute=0, second=0
                )
                continue
            for hh in hour.tolist():
                if hh < t.hour:
                    continue
                for mm in minute.tolist():
                    if hh == t.hour and mm < t.minute:
                        continue
                    for ss in sec.tolist():
                        if (
                            hh == t.hour
                            and mm == t.minute
                            and ss < t.second
                        ):
                            continue
                        fire = datetime(
                            y, mo, d, hh, mm, ss, tzinfo=timezone.utc
                        )
                        return int(fire.timestamp() * 1000)
            t = (t + timedelta(days=1)).replace(
                hour=0, minute=0, second=0
            )
        return None
