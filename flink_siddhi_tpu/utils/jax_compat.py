"""Version-compat shims for JAX APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` (where the
replication checker is ``check_rep``) to ``jax.shard_map`` (where it
is ``check_vma``). The engine targets the new spelling; this shim
keeps it running on toolchains that still ship the experimental one.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the ``check_vma`` spelling, falling back
    to ``jax.experimental.shard_map`` / ``check_rep`` on older JAX."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
