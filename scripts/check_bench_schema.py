#!/usr/bin/env python
"""Validate BENCH_*.json lines against the bench output schema.

Schema v2 (telemetry rounds, bench.py ``schema_version: 2``) adds the
honest-wall-clock contract: a ``stage_breakdown`` section whose
top-level stages (flink_siddhi_tpu.telemetry.TOP_LEVEL_STAGES) must
sum to >= 95% of the end-to-end elapsed wall clock — the gate that
keeps "unattributed off-clock time" from ever reappearing in a
headline number. Pre-v2 files (BENCH_r01..r05) validate against the
legacy subset only.

Schema v3 (falsifiable-latency round, bench.py ``schema_version: 3``)
adds the multi-mode + independent-measurement contract:

* ``modes`` must contain ALL of resident, streaming, sink — one bench
  run tracks the engine path, the unbounded path, and the
  rows-materialized data path together (a ``"partial": true`` subset
  run is rejected: headline numbers must carry all three);
* every mode section carries its own ``stage_breakdown`` (same >= 95%
  coverage contract as v2) and a ``latency`` block whose
  ``telemetry_p99_ms`` AND out-of-process ``prober_p50_ms`` /
  ``prober_p99_ms`` are present and finite — a bench line whose
  side-channel prober failed does not validate;
* the prober-vs-telemetry ``discrepancy_ratio`` is reported per mode
  (printed, not just stored), and a declared ``prober_contradiction``
  fails validation outright.

Schema v4 (columnar-sink + tail-aware-drain round, bench.py
``schema_version: 4``) adds the rows-materialized + p99-target
contract:

* ``modes.sink`` must carry a finite positive ``rows_materialized_ev_s``
  (events/sec through the path where every emitted row reaches a
  consumer), a ``rows_emitted`` count, and ``columnar: true`` — the
  sink mode must drive the columnar fast lane, not the row fallback;
* ``p99_target.verdict`` must be ``p99_le_500ms`` (p99 <= 500 ms at a
  >= 1M ev/s offered load) or ``p99_le_2x_prober`` (p99 <= 2x the
  out-of-process prober's under-load p99). ``missed`` — failing BOTH
  targets — is rejected loudly, as is a line missing the block;
* ``drain_staleness.p50_ms`` / ``p99_ms`` must be present and finite:
  the deadline drain scheduler's staleness leg must actually have
  recorded (a scheduler that never ran produces no line).

Schema v5 (fused-dispatch round, bench.py ``schema_version: 5``) adds
the dispatch-bound contract:

* every mode section carries a ``fusion`` block: ``segment_len``
  (int >= 1), ``dispatches_per_1k_batches`` (finite positive — fused
  segments must actually collapse dispatches), and
  ``h2d_overlap_frac`` (finite, in [0, 1] — what fraction of
  streaming H2D tape uploads overlapped in-flight compute);
* the top level carries ``streaming_vs_resident_ratio`` (finite,
  recomputed from the two modes' events_per_sec so a declared ratio
  cannot lie) and a ``fusion_target`` block whose ``verdict`` must be
  ``met``: streaming-mode ev/s >= 80% of resident-mode ev/s on the
  same lane. ``missed`` is rejected loudly — the fused dispatch
  exists to close exactly this gap. Pre-v5 files (BENCH_r01..r05)
  are exempt.

Schema v6 (event-time robustness round, bench.py ``schema_version:
6``) adds the disorder contract:

* the line carries a ``disorder`` block with one run per skew in
  {0, 1000, 10000} ms: the stream arrival-shuffled/duplicated/
  straggled/idle-gapped by a seeded DisorderSchedule, the job
  watermarking with BoundedDisorderWatermark(skew) in EVENT-time mode;
* each run's ``events_per_sec`` and ``p99_ms`` must be present and
  finite (throughput + tail under sustained DISORDERED load — the
  Karimov standard applied to disorder);
* the late/dup/idle accounting must be EXACT against the injected
  schedule: ``late_dropped`` == ``injected.late``, ``idle_marked`` ==
  ``injected.idle_gaps``, ``processed_events`` == ``events`` +
  ``injected.duplicates`` - ``late_dropped``, and ``counts_exact``
  must be true. Pre-v6 files are exempt; a ``disorder`` block present
  in any version is validated.

Schema v7 (dynamic-control-plane round, bench.py ``schema_version:
7``) adds the control contract:

* the line carries a ``control`` block: a sustained-load run against
  the live control plane (docs/control_plane.md) with
  ``admit_rate_qps`` finite positive (queries/s actually admitted at
  epoch boundaries), ``steady_state_events_per_sec`` finite positive
  at ``concurrent_queries`` >= 1 live queries,
  ``added_latency_p99_ms`` and ``baseline_p99_ms`` finite;
* ``dropped_events`` must be 0 — an admit/retire/pause applied at a
  micro-batch epoch boundary must never tear a segment or lose rows;
* a hostile tenant query must have been refused:
  ``admission_rejected`` >= 1 with ``hostile_refused_rule`` naming an
  ADM/PLC rule id;
* the ``cache`` block's hit/miss/eviction counters must be
  non-negative ints (the shape-keyed AOT executable cache really
  ran); ``stack_joins`` non-negative (admits folding into padded
  multi-query stacks as data updates). Pre-v7 files are exempt; a
  ``control`` block present in any version is validated.

Schema v8 (per-tenant observability round, bench.py
``schema_version: 8``) adds the attribution contract: the ``control``
block carries an ``attribution`` block whose

* per-plan ``rows_emitted`` counts (the scoped metric groups,
  runtime/executor.py) must CONSERVE — sum exactly to
  ``rows_emitted_total``, the job-level emitted total — and
  ``conserved`` must say so;
* ``footprint`` map (the admitted-vs-measured meter) must be
  non-empty with finite positive ``measured_bytes`` per runtime, and
  at least ONE runtime must carry a finite positive ``utilization``
  against a finite positive ``admitted_bytes`` (the ADM101/102
  admission prediction actually compared to device reality).

Pre-v8 files are exempt; an ``attribution`` block present in any
version is validated.

Schema v9 (flight-recorder / measured-attribution round, bench.py
``schema_version: 9``) adds the per-mode ``limiting_leg`` contract:
every mode section carries the stage ledger folded into the fixed leg
cover (flink_siddhi_tpu/telemetry/attribution.py), and this gate
RE-DERIVES the claim — the non-overlapped legs must attribute >= 95%
of the mode's measured wall-clock window, the declared coverage and
limiting share must match a recompute from the published per-leg
seconds, and the named leg must be the argmax over the candidate legs
(setup and the overlapped decode/sink detail legs are reported but
never named). Pre-v9 files are exempt; a present block in any version
is validated.

Optional ``recovery`` block (``bench.py --fault``, any version): when
present it must carry a finite positive measured ``recovery_time_ms``,
at least one injected crash, ``stale_tmp_swept: true``, and EXACT
exactly-once numbers — ``duplicate_rows`` and ``lost_rows`` (counted
against an unfaulted oracle, not assumed) must both be 0.

Schema v10 (transactional-sink round, bench.py ``schema_version:
10``) extends the recovery contract to the EXTERNAL boundary: a
``recovery`` block in a v10+ line must carry a ``transactional``
sub-block — the supervised KIP-98 transactional-sink run (crash zoo
extended with a kill-mid-transaction) — with
``read_committed_duplicates`` and ``read_committed_lost`` both 0, a
finite positive measured ``recovery_time_ms``, at least one injected
crash, ``exactly_once: true``, and ``aborted_rows_invisible: true``
(the dead runs' transactions really carried data and a read-committed
consumer never saw it). Pre-v10 lines are exempt from requiring the
sub-block; a present one is validated in any version.

Schema v11 (serving-observatory round, bench.py ``--serve``,
``schema_version: 11``) adds the ``serving`` contract — the open-loop
multi-tenant serving line, every verdict read off the public
observability surface:

* ``sustained_events_per_sec`` finite positive, and the ``search``
  block's ``sustained_rate_ev_s`` finite positive with a non-empty
  ``rates_tried`` ledger;
* the ``isolation`` verdict is RE-DERIVED: every victim's ratio must
  match a recompute from its published pre/post p99s, the declared
  ``max_ratio`` must be the max of the victims' ratios, the verdict
  must follow from ``max_ratio`` vs ``gate_ratio`` — and a ``fail``
  verdict fails the line (a storm tenant that blows through victims'
  tails is a failed claim, not a benchmark);
* the ``slo`` account must RECONCILE EXACTLY: watchdog counter totals
  == flight-recorder journal replay counts, and ``reconciled`` true;
* the ``sustainable`` verdict is re-derived from its own published
  inputs (lag vs budget, loss vs budget, prober-vs-telemetry p99
  within tolerance, health) and must be true;
* the ``limiting_leg`` block is held to the same re-derivation gate
  as schema v9 (coverage >= 95%, named leg is the argmax);
* the ``churn`` block must show live admit/retire/disable/enable all
  >= 1 and a hostile refusal naming an ADM/PLC rule id.

A ``--serve`` line carries ``serving`` INSTEAD of ``modes``: the
replay-mode contracts (v2 stage_breakdown through v10 recovery) do not
apply to it. Pre-v11 files need not carry the block; a present one is
validated in any version.

Schema v12 (serving-fleet round, bench.py ``--fleet``,
``schema_version: 12``) adds the ``fleet`` contract — the cold-vs-warm
replica bootstrap account across a rolling restart:

* both the ``cold`` and ``warm`` boot blocks must publish a finite
  positive ``first_row_s`` (cold-start-to-first-row, the headline);
* the warm boot must BEAT the cold one: ``warm.first_row_s <
  cold.first_row_s`` — a warm store that does not pay for itself is a
  failed claim, not a benchmark;
* the warm boot must be lowering-free: ``warm.compiles == 0`` and
  ``warm.warm_misses == 0`` with ``warm.warm_hits >= 1``, while the
  cold boot must have actually populated the store
  (``cold.persists >= 1``);
* the commit-log exactly-once account must be clean across the
  handoff: ``committed.duplicate_epochs == 0`` and
  ``committed.lost == 0`` with ``committed.rows >= 1``.

A ``--fleet`` line carries ``fleet`` INSTEAD of ``modes`` (same shape
as ``serving``). Pre-v12 files need not carry the block; a present one
is validated in any version.

Schema v13 (subplan-sharing round, bench.py ``schema_version: 13``)
adds the ``subplan_share`` contract — the shared-vs-unshared A/B over
a mixed tenant fleet whose members share a common filter prefix but
are structurally distinct past it (NOT foldable by constants-only
stack-joins alone):

* both sides publish finite positive ``events_per_sec`` over the SAME
  event count, with ``dropped_events == 0`` on each (a side that
  sheds load wins its A/B by cheating), and the timed window includes
  the closing drain (the shared side's suffix compute is deferred to
  drain time — stopping the clock earlier would credit it with work
  it merely postponed);
* the declared ``speedup`` must RE-DERIVE from the two sides'
  published ev/s, and sharing must actually win: >= 1.0 on a full
  fleet (a dryrun's small fleet gets a 0.8 regression backstop —
  the failure modes this gate exists to catch measured <= 0.5);
* the shared side's per-tenant attribution must still CONSERVE
  (``conserved: true`` — host scopes are measured-only, member rows
  sum exactly to the job total), and each ``@shr:`` host must show
  compile spend SUB-LINEAR in members: ``lowerings < members``, since
  one-lowering-per-tenant is precisely the unshared cost.

Replay lines only (``--serve``/``--fleet`` lines early-return above);
pre-v13 files need not carry the block, a present one is validated in
any version.

Usage:
    python scripts/check_bench_schema.py [FILES...]
    python scripts/check_bench_schema.py --require-stages FILES...

With no FILES, validates every BENCH_*.json in the repo root. Exit
status 0 = all valid. ``--require-stages`` additionally fails any file
that lacks a stage_breakdown (used for freshly-produced bench output,
where telemetry is expected on).

Runs in the tier-1 lane via tests/test_bench_schema.py.
"""

from __future__ import annotations

import glob
import json
import math
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MIN_COVERAGE = 0.95
V3_MODES = ("resident", "streaming", "sink")

_NUM = (int, float)

# informational lines (prober-vs-telemetry discrepancy ratios etc.)
# collected during validation and printed by main()
INFO: List[str] = []


def _finite(v) -> bool:
    return isinstance(v, _NUM) and math.isfinite(v)


def _stage_names():
    from flink_siddhi_tpu.telemetry import TOP_LEVEL_STAGES

    return TOP_LEVEL_STAGES


def validate_stage_breakdown(sb, errors: List[str], where: str) -> None:
    if not isinstance(sb, dict):
        errors.append(f"{where}: stage_breakdown is not an object")
        return
    if sb.get("telemetry") == "off":
        return  # explicit opt-out run (BENCH_TELEMETRY=0): no contract
    for key in ("elapsed_s", "attributed_s", "coverage"):
        if not isinstance(sb.get(key), _NUM):
            errors.append(
                f"{where}: stage_breakdown.{key} missing/non-numeric"
            )
            return
    stages = sb.get("stages")
    if not isinstance(stages, dict) or not stages:
        errors.append(f"{where}: stage_breakdown.stages missing/empty")
        return
    bad = [
        k for k, v in stages.items() if not isinstance(v, _NUM) or v < 0
    ]
    if bad:
        errors.append(
            f"{where}: non-numeric/negative stage seconds: {bad}"
        )
        return
    if sb["elapsed_s"] <= 0:
        errors.append(f"{where}: elapsed_s must be > 0")
        return
    top = _stage_names()
    top_sum = sum(v for k, v in stages.items() if k in top)
    cov = top_sum / sb["elapsed_s"]
    # the declared coverage must match a recompute from the stages map
    if abs(cov - sb["coverage"]) > 0.02:
        errors.append(
            f"{where}: declared coverage {sb['coverage']:.4f} != "
            f"recomputed {cov:.4f} from top-level stages"
        )
    if cov < MIN_COVERAGE:
        errors.append(
            f"{where}: top-level stages attribute only {cov:.1%} of "
            f"elapsed wall-clock (< {MIN_COVERAGE:.0%}): "
            "unattributed off-clock time"
        )
    unknown = [
        k
        for k in stages
        if k not in top and not k.startswith("nested.")
    ]
    if unknown:
        errors.append(
            f"{where}: unknown stage names (not in TOP_LEVEL_STAGES, "
            f"not nested.*): {unknown}"
        )


def validate_mode_latency(
    lat, errors: List[str], where: str, telemetry_off: bool = False
) -> None:
    """The v3 falsifiability contract per mode: an in-process number
    AND an out-of-process prober number, both finite. A
    ``BENCH_TELEMETRY=0`` overhead-A/B run is exempt from the
    in-process half only — the prober is external and must still
    report."""
    if not isinstance(lat, dict):
        errors.append(f"{where}: latency is not an object")
        return
    required = ["prober_p50_ms", "prober_p99_ms"]
    if not telemetry_off:
        required.append("telemetry_p99_ms")
    for key in required:
        if not _finite(lat.get(key)):
            errors.append(
                f"{where}: latency.{key} missing/non-finite (a failed "
                "side-channel prober run does not validate)"
            )
    for key in ("prober_pid", "prober_parent_pid"):
        if not isinstance(lat.get(key), int):
            errors.append(f"{where}: latency.{key} missing/non-int")
    if (
        isinstance(lat.get("prober_pid"), int)
        and isinstance(lat.get("prober_parent_pid"), int)
        and lat["prober_pid"] == lat["prober_parent_pid"]
    ):
        errors.append(
            f"{where}: prober_pid == prober_parent_pid — the prober "
            "did not run in a separate OS process"
        )
    ratio = lat.get("discrepancy_ratio")
    if not _finite(ratio):
        if not telemetry_off:
            errors.append(
                f"{where}: latency.discrepancy_ratio missing/non-finite"
            )
    else:
        INFO.append(
            f"{where}: prober p99 {lat.get('prober_p99_ms')}ms vs "
            f"telemetry p99 {lat.get('telemetry_p99_ms')}ms — "
            f"discrepancy ratio {ratio}"
        )


def validate_v3(doc, errors: List[str], where: str) -> None:
    if doc.get("partial"):
        errors.append(
            f"{where}: partial mode subset (BENCH_MODES) — headline "
            "bench lines must carry all of "
            + ", ".join(V3_MODES)
        )
    modes = doc.get("modes")
    if not isinstance(modes, dict):
        errors.append(f"{where}: schema v3 output lacks modes object")
        return
    for name in V3_MODES:
        sec = modes.get(name)
        if not isinstance(sec, dict):
            errors.append(f"{where}: modes.{name} missing")
            continue
        mwhere = f"{where}:modes.{name}"
        if not _finite(sec.get("events_per_sec")) or (
            sec.get("events_per_sec", 0) <= 0
        ):
            errors.append(
                f"{mwhere}: events_per_sec missing/non-positive"
            )
        sb = sec.get("stage_breakdown")
        if sb is None:
            errors.append(f"{mwhere}: stage_breakdown missing")
        else:
            validate_stage_breakdown(sb, errors, mwhere)
        telemetry_off = (
            isinstance(sb, dict) and sb.get("telemetry") == "off"
        )
        lat = sec.get("latency")
        if lat is None:
            errors.append(f"{mwhere}: latency block missing")
        else:
            validate_mode_latency(lat, errors, mwhere, telemetry_off)
    if "prober_contradiction" in doc:
        errors.append(
            f"{where}: prober contradicts the in-process claims: "
            f"{doc['prober_contradiction']}"
        )


V4_VERDICTS = ("p99_le_500ms", "p99_le_2x_prober")


def validate_v4(doc, errors: List[str], where: str) -> None:
    """The columnar-sink + tail-aware-drain contract (on top of v3)."""
    sink = (doc.get("modes") or {}).get("sink")
    if isinstance(sink, dict):
        swhere = f"{where}:modes.sink"
        rm = sink.get("rows_materialized_ev_s")
        if not _finite(rm) or rm <= 0:
            errors.append(
                f"{swhere}: rows_materialized_ev_s missing/non-positive "
                "(schema v4 requires the measured data-path ev/s)"
            )
        if not isinstance(sink.get("rows_emitted"), int):
            errors.append(f"{swhere}: rows_emitted missing/non-int")
        if sink.get("columnar") is not True:
            errors.append(
                f"{swhere}: columnar must be true — the sink mode must "
                "drive the columnar fast lane, not the row fallback"
            )
    tgt = doc.get("p99_target")
    if not isinstance(tgt, dict):
        errors.append(
            f"{where}: p99_target block missing (schema v4 requires "
            "the latency-target verdict)"
        )
    else:
        verdict = tgt.get("verdict")
        if verdict not in V4_VERDICTS:
            errors.append(
                f"{where}: p99_target.verdict {verdict!r} — the line "
                f"fails BOTH latency targets (need one of "
                f"{', '.join(V4_VERDICTS)}: p99 "
                f"{tgt.get('p99_ms')}ms at "
                f"{tgt.get('offered_load_events_per_sec')} ev/s, "
                f"prober p99 {tgt.get('prober_p99_ms')}ms)"
            )
        elif not _finite(tgt.get("p99_ms")):
            errors.append(f"{where}: p99_target.p99_ms missing/non-finite")
        else:
            INFO.append(
                f"{where}: p99 target met via {verdict} — p99 "
                f"{tgt.get('p99_ms')}ms at "
                f"{tgt.get('offered_load_events_per_sec')} ev/s offered"
            )
    st = doc.get("drain_staleness")
    if not isinstance(st, dict):
        errors.append(
            f"{where}: drain_staleness block missing (schema v4 "
            "requires the deadline drain scheduler's staleness stats)"
        )
    else:
        for key in ("p50_ms", "p99_ms"):
            if not _finite(st.get(key)):
                errors.append(
                    f"{where}: drain_staleness.{key} missing/non-finite"
                )


def validate_fusion(fu, errors: List[str], where: str) -> None:
    """One mode's ``fusion`` block (schema v5)."""
    where = f"{where}:fusion"
    if not isinstance(fu, dict):
        errors.append(f"{where}: must be an object")
        return
    if fu.get("telemetry") == "off":
        return  # BENCH_TELEMETRY=0 A/B run: no counters to report
    sl = fu.get("segment_len")
    if not isinstance(sl, int) or isinstance(sl, bool) or sl < 1:
        errors.append(f"{where}: segment_len missing/non-int/<1 ({sl!r})")
    dp = fu.get("dispatches_per_1k_batches")
    if not _finite(dp) or dp <= 0:
        errors.append(
            f"{where}: dispatches_per_1k_batches missing/non-positive "
            f"({dp!r})"
        )
    elif isinstance(sl, int) and sl > 1 and dp >= 1000.0:
        # >= not >: the likeliest regression (the fused gate silently
        # never engaging) reports EXACTLY 1000 via the per-batch
        # fallback counters
        errors.append(
            f"{where}: dispatches_per_1k_batches {dp} >= 1000 with "
            f"segment_len {sl} — fused dispatch did not collapse "
            "anything"
        )
    # a declared collapse ratio cannot lie: re-derive it from the
    # dispatch/batch counts shipped in the same block (the same rule
    # validate_v5 applies to streaming_vs_resident_ratio)
    d, b = fu.get("dispatches"), fu.get("batches")
    if (
        _finite(dp)
        and isinstance(d, int)
        and isinstance(b, int)
        and b > 0
    ):
        recomputed = 1000.0 * d / b
        if abs(recomputed - dp) > 0.02 * max(recomputed, 1.0):
            errors.append(
                f"{where}: declared dispatches_per_1k_batches {dp} != "
                f"recomputed {recomputed:.1f} from dispatches={d} / "
                f"batches={b}"
            )
    of = fu.get("h2d_overlap_frac")
    if not _finite(of) or of < 0.0 or of > 1.0:
        errors.append(
            f"{where}: h2d_overlap_frac missing/outside [0, 1] ({of!r})"
        )


def validate_v5(doc, errors: List[str], where: str) -> None:
    """The fused-dispatch contract (on top of v3/v4)."""
    modes = doc.get("modes")
    if isinstance(modes, dict):
        for name in V3_MODES:
            sec = modes.get(name)
            if not isinstance(sec, dict):
                continue  # v3 already reported the missing mode
            fu = sec.get("fusion")
            if fu is None:
                errors.append(
                    f"{where}:modes.{name}: fusion block missing "
                    "(schema v5 requires per-mode dispatch accounting)"
                )
            else:
                validate_fusion(fu, errors, f"{where}:modes.{name}")
    ratio = doc.get("streaming_vs_resident_ratio")
    if not _finite(ratio):
        errors.append(
            f"{where}: streaming_vs_resident_ratio missing/non-finite"
        )
    else:
        # the ratio's basis is the PAIRED ABBA measurement in
        # fusion_target: per round, resident/streaming/streaming/
        # resident, scored (res1+res2)/(str1+str2) so linear host
        # drift cancels; the published ratio is the BEST round (the
        # repo's min-of-runs convention). Re-derive it from the
        # published run times so a declared ratio cannot lie.
        tgt0 = doc.get("fusion_target") or {}
        res_r = tgt0.get("resident_runs_s")
        str_r = tgt0.get("streaming_runs_s")
        recomputed = None
        if (
            isinstance(res_r, list)
            and isinstance(str_r, list)
            and res_r
            and len(res_r) == len(str_r)
            and len(res_r) % 2 == 0
            and all(_finite(v) and v > 0 for v in res_r + str_r)
        ):
            recomputed = max(
                (res_r[2 * i] + res_r[2 * i + 1])
                / (str_r[2 * i] + str_r[2 * i + 1])
                for i in range(len(res_r) // 2)
            )
        else:
            res = tgt0.get("resident_ev_s")
            st = tgt0.get("streaming_ev_s")
            if _finite(res) and _finite(st) and res > 0:
                recomputed = st / res
        if recomputed is not None and (
            abs(recomputed - ratio) > 0.02 * max(recomputed, 1e-9)
        ):
            errors.append(
                f"{where}: declared streaming_vs_resident_ratio "
                f"{ratio} != recomputed {recomputed:.3f} from "
                "fusion_target's paired round times"
            )
    tgt = doc.get("fusion_target")
    if not isinstance(tgt, dict):
        errors.append(
            f"{where}: fusion_target block missing (schema v5 requires "
            "the streaming-vs-resident verdict)"
        )
    else:
        if tgt.get("verdict") != "met":
            errors.append(
                f"{where}: fusion_target.verdict "
                f"{tgt.get('verdict')!r} — streaming ev/s "
                f"{tgt.get('streaming_ev_s')} is below 80% of resident "
                f"{tgt.get('resident_ev_s')}: still dispatch-bound"
            )
        else:
            INFO.append(
                f"{where}: fusion target met — streaming/resident "
                f"ratio {tgt.get('ratio')} at segment_len "
                f"{tgt.get('segment_len')}"
            )


DISORDER_SKEWS_MS = (0, 1_000, 10_000)


def validate_disorder(dis, errors: List[str], where: str) -> None:
    """The schema-v6 ``disorder`` block: ev/s + p99 per skew, with the
    late/dup/idle accounting EXACT against the injected schedule — a
    disorder line whose counters drift from what was injected is a
    silently-wrong engine, not a benchmark."""
    where = f"{where}:disorder"
    if not isinstance(dis, dict):
        errors.append(f"{where}: must be an object")
        return
    runs = dis.get("runs")
    if not isinstance(runs, list) or not runs:
        errors.append(f"{where}: runs missing/empty")
        return
    seen = set()
    for run in runs:
        if not isinstance(run, dict):
            errors.append(f"{where}: run entries must be objects")
            continue
        skew = run.get("skew_ms")
        rw = f"{where}:skew={skew}"
        if not isinstance(skew, int) or isinstance(skew, bool) or skew < 0:
            errors.append(f"{rw}: skew_ms missing/non-int ({skew!r})")
            continue
        seen.add(skew)
        ev = run.get("events_per_sec")
        if not _finite(ev) or ev <= 0:
            errors.append(
                f"{rw}: events_per_sec missing/non-positive ({ev!r})"
            )
        p99 = run.get("p99_ms")
        if not _finite(p99):
            errors.append(f"{rw}: p99_ms missing/non-finite ({p99!r})")
        inj = run.get("injected")
        if not isinstance(inj, dict):
            errors.append(f"{rw}: injected block missing")
            continue
        for key in ("duplicates", "late", "idle_gaps"):
            v = inj.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(
                    f"{rw}: injected.{key} missing/non-int ({v!r})"
                )
        for key in (
            "events", "late_dropped", "idle_marked", "processed_events",
        ):
            v = run.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{rw}: {key} missing/non-int ({v!r})")
        if run.get("late_dropped") != inj.get("late"):
            errors.append(
                f"{rw}: late_dropped {run.get('late_dropped')!r} != "
                f"injected.late {inj.get('late')!r} — the late account "
                "drifted from the injected schedule"
            )
        if run.get("idle_marked") != inj.get("idle_gaps"):
            errors.append(
                f"{rw}: idle_marked {run.get('idle_marked')!r} != "
                f"injected.idle_gaps {inj.get('idle_gaps')!r} — idle "
                "gaps the engine never marked (or marked twice)"
            )
        if (
            isinstance(run.get("events"), int)
            and isinstance(inj.get("duplicates"), int)
            and isinstance(run.get("late_dropped"), int)
            and run.get("processed_events")
            != run["events"] + inj["duplicates"] - run["late_dropped"]
        ):
            errors.append(
                f"{rw}: processed_events {run.get('processed_events')!r}"
                f" != events {run['events']} + duplicates "
                f"{inj['duplicates']} - late_dropped "
                f"{run['late_dropped']} — duplicate accounting drifted"
            )
        if run.get("counts_exact") is not True:
            errors.append(
                f"{rw}: counts_exact must be true — the engine's "
                "late/dup/idle counters must reconcile exactly with "
                "the injected schedule"
            )
    missing = set(DISORDER_SKEWS_MS) - seen
    if missing:
        errors.append(
            f"{where}: runs missing skew(s) {sorted(missing)} — the "
            "contract is ev/s + p99 at 0/1s/10s skew"
        )


def validate_v6(doc, errors: List[str], where: str) -> None:
    """The event-time disorder contract (on top of v3/v4/v5)."""
    dis = doc.get("disorder")
    if dis is None:
        errors.append(
            f"{where}: disorder block missing (schema v6 requires the "
            "0/1s/10s-skew disorder sweep)"
        )
    else:
        validate_disorder(dis, errors, where)


def validate_control(ctrl, errors: List[str], where: str) -> None:
    """The schema-v7 ``control`` block: the dynamic query control
    plane's sustained-load claims. A control line whose admit rate is
    unmeasured, whose load dropped rows at a mutation boundary, or
    whose hostile tenant slipped through is a failed claim, not a
    benchmark."""
    where = f"{where}:control"
    if not isinstance(ctrl, dict):
        errors.append(f"{where}: must be an object")
        return
    rate = ctrl.get("admit_rate_qps")
    if not _finite(rate) or rate <= 0:
        errors.append(
            f"{where}: admit_rate_qps missing/non-finite ({rate!r}) — "
            "the admit rate must be a measured number"
        )
    ev_s = ctrl.get("steady_state_events_per_sec")
    if not _finite(ev_s) or ev_s <= 0:
        errors.append(
            f"{where}: steady_state_events_per_sec missing/non-finite "
            f"({ev_s!r})"
        )
    cq = ctrl.get("concurrent_queries")
    if not isinstance(cq, int) or isinstance(cq, bool) or cq < 1:
        errors.append(
            f"{where}: concurrent_queries missing/non-int/zero ({cq!r})"
        )
    for key in ("added_latency_p99_ms", "baseline_p99_ms"):
        if not _finite(ctrl.get(key)):
            errors.append(
                f"{where}: {key} missing/non-finite "
                f"({ctrl.get(key)!r})"
            )
    if ctrl.get("dropped_events") != 0:
        errors.append(
            f"{where}: dropped_events={ctrl.get('dropped_events')!r} "
            "— a control-plane mutation lost rows (epoch-boundary "
            "apply must never tear a segment)"
        )
    rej = ctrl.get("admission_rejected")
    if not isinstance(rej, int) or isinstance(rej, bool) or rej < 1:
        errors.append(
            f"{where}: admission_rejected={rej!r} — the hostile "
            "tenant query was not refused"
        )
    rule = ctrl.get("hostile_refused_rule")
    if not (
        isinstance(rule, str)
        and (rule.startswith("ADM") or rule.startswith("PLC"))
    ):
        errors.append(
            f"{where}: hostile_refused_rule={rule!r} — the refusal "
            "must name an exact ADM/PLC rule id"
        )
    sj = ctrl.get("stack_joins")
    if not isinstance(sj, int) or isinstance(sj, bool) or sj < 0:
        errors.append(
            f"{where}: stack_joins missing/non-int ({sj!r})"
        )
    cache = ctrl.get("cache")
    if not isinstance(cache, dict):
        errors.append(f"{where}: cache block missing")
    else:
        for key in ("hits", "misses", "evictions"):
            v = cache.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(
                    f"{where}: cache.{key} missing/non-int ({v!r})"
                )


def validate_v7(doc, errors: List[str], where: str) -> None:
    """The dynamic-control-plane contract (on top of v3..v6)."""
    ctrl = doc.get("control")
    if ctrl is None:
        errors.append(
            f"{where}: control block missing (schema v7 requires the "
            "sustained-load control-plane run)"
        )
    else:
        validate_control(ctrl, errors, where)


def validate_attribution(att, errors: List[str], where: str) -> None:
    """The schema-v8 ``attribution`` block: per-plan scoped row counts
    that must conserve against the job total, and the admitted-vs-
    measured footprint meter. An attribution whose rows do not sum, or
    whose meter never compared a measured footprint to an admission
    prediction, is a failed claim."""
    where = f"{where}:attribution"
    if not isinstance(att, dict):
        errors.append(f"{where}: must be an object")
        return
    plans = att.get("plans")
    total = att.get("rows_emitted_total")
    if not isinstance(plans, dict) or not plans:
        errors.append(
            f"{where}: plans missing/empty — per-plan attribution is "
            "the point of the block"
        )
    else:
        attributed = 0
        ok = True
        for pid, ent in plans.items():
            if not isinstance(ent, dict):
                errors.append(f"{where}: plans[{pid!r}] not an object")
                ok = False
                continue
            n = ent.get("rows_emitted")
            if not isinstance(n, int) or isinstance(n, bool) or n < 0:
                errors.append(
                    f"{where}: plans[{pid!r}].rows_emitted "
                    f"missing/non-int ({n!r})"
                )
                ok = False
                continue
            attributed += n
        if not isinstance(total, int) or isinstance(total, bool):
            errors.append(
                f"{where}: rows_emitted_total missing/non-int "
                f"({total!r})"
            )
        elif ok and attributed != total:
            errors.append(
                f"{where}: per-plan rows do not CONSERVE — scoped sum "
                f"{attributed} != job total {total} (attribution "
                "dropped or double-counted rows)"
            )
    if att.get("conserved") is not True:
        errors.append(f"{where}: conserved must be true")
    fp = att.get("footprint")
    if not isinstance(fp, dict) or not fp:
        errors.append(
            f"{where}: footprint map missing/empty — the "
            "admitted-vs-measured meter never polled"
        )
        return
    n_compared = 0
    for rid, ent in fp.items():
        if not isinstance(ent, dict):
            errors.append(f"{where}: footprint[{rid!r}] not an object")
            continue
        m = ent.get("measured_bytes")
        if not _finite(m) or m <= 0:
            errors.append(
                f"{where}: footprint[{rid!r}].measured_bytes "
                f"missing/non-positive ({m!r})"
            )
        if "admitted_bytes" in ent or "utilization" in ent:
            a = ent.get("admitted_bytes")
            u = ent.get("utilization")
            if not _finite(a) or a <= 0:
                errors.append(
                    f"{where}: footprint[{rid!r}].admitted_bytes "
                    f"non-finite/non-positive ({a!r})"
                )
            elif not _finite(u) or u <= 0:
                errors.append(
                    f"{where}: footprint[{rid!r}].utilization "
                    f"non-finite/non-positive ({u!r}) — utilization "
                    "must be a finite measured/admitted ratio"
                )
            else:
                n_compared += 1
    if n_compared == 0:
        errors.append(
            f"{where}: no runtime carries an admitted-vs-measured "
            "utilization — the meter never compared a prediction to "
            "device reality"
        )


def validate_limiting_leg(ll, errors: List[str], where: str) -> None:
    """The schema-v9 ``limiting_leg`` block: per-leg seconds/shares
    over the mode's measured wall-clock window, with the verdict
    RE-DERIVED here — the non-overlapped legs must attribute >= 95%
    of the window, and the named leg must be the argmax of the
    published per-leg seconds over the candidate set (everything but
    ``setup`` and the overlapped fetch-lane legs). A verdict that
    contradicts its own numbers is a failed claim."""
    where = f"{where}:limiting_leg"
    if not isinstance(ll, dict):
        errors.append(f"{where}: not an object")
        return
    if ll.get("telemetry") == "off":
        return  # explicit BENCH_TELEMETRY=0 opt-out: no contract
    for key in ("elapsed_s", "coverage", "limiting_share"):
        if not _finite(ll.get(key)):
            errors.append(f"{where}: {key} missing/non-numeric")
            return
    if ll["elapsed_s"] <= 0:
        errors.append(f"{where}: elapsed_s must be > 0")
        return
    legs = ll.get("legs")
    if not isinstance(legs, dict) or not legs:
        errors.append(f"{where}: legs missing/empty")
        return
    from flink_siddhi_tpu.telemetry.attribution import (
        CANDIDATE_LEGS,
        LEG_STAGES,
        OVERLAPPED_LEGS,
    )

    expected = set(LEG_STAGES) | set(OVERLAPPED_LEGS)
    missing = sorted(expected - set(legs))
    if missing:
        errors.append(f"{where}: legs missing from the cover: {missing}")
        return
    cover_s = 0.0
    for name, leg in legs.items():
        if not isinstance(leg, dict) or not _finite(
            leg.get("seconds")
        ) or leg["seconds"] < 0:
            errors.append(
                f"{where}: legs[{name!r}].seconds missing/negative"
            )
            return
        if not leg.get("overlapped"):
            cover_s += leg["seconds"]
    cov = cover_s / ll["elapsed_s"]
    if abs(cov - ll["coverage"]) > 0.02:
        errors.append(
            f"{where}: declared coverage {ll['coverage']:.4f} != "
            f"recomputed {cov:.4f} from per-leg seconds"
        )
    if cov < MIN_COVERAGE:
        errors.append(
            f"{where}: leg cover attributes only {cov:.1%} of the "
            f"measured window (< {MIN_COVERAGE:.0%}): unattributed "
            "wall-clock"
        )
    named = ll.get("limiting_leg")
    candidates = {
        name: legs[name]["seconds"]
        for name in CANDIDATE_LEGS
        if name in legs
    }
    if named not in candidates:
        errors.append(
            f"{where}: limiting_leg {named!r} is not a candidate leg "
            f"({sorted(candidates)})"
        )
        return
    best = max(candidates.values())
    # argmax with a rounding-tie tolerance (per-leg seconds are
    # published rounded to 4 decimals)
    if candidates[named] < best - max(1e-3, 0.001 * best):
        top = max(candidates, key=lambda k: candidates[k])
        errors.append(
            f"{where}: declared limiting leg {named!r} "
            f"({candidates[named]}s) is not the argmax — "
            f"{top!r} measured {candidates[top]}s"
        )
    share = candidates[named] / ll["elapsed_s"]
    if abs(share - ll["limiting_share"]) > 0.02:
        errors.append(
            f"{where}: limiting_share {ll['limiting_share']:.4f} != "
            f"recomputed {share:.4f}"
        )


def validate_v9(doc, errors: List[str], where: str) -> None:
    """The measured-attribution contract (on top of v3..v8): every
    mode section carries a gated ``limiting_leg`` block."""
    modes = doc.get("modes")
    if not isinstance(modes, dict):
        return  # v3 validation already reported the missing object
    for name in V3_MODES:
        sec = modes.get(name)
        if not isinstance(sec, dict):
            continue  # v3 validation already reported it
        mwhere = f"{where}:modes.{name}"
        sb = sec.get("stage_breakdown")
        telemetry_off = (
            isinstance(sb, dict) and sb.get("telemetry") == "off"
        )
        ll = sec.get("limiting_leg")
        if ll is None:
            if not telemetry_off:
                errors.append(
                    f"{mwhere}: limiting_leg block missing (schema v9 "
                    "requires the measured bottleneck verdict per mode)"
                )
        else:
            validate_limiting_leg(ll, errors, mwhere)


def validate_v8(doc, errors: List[str], where: str) -> None:
    """The per-tenant observability contract (on top of v3..v7). The
    control block itself is validated by validate_v7; here only its
    attribution rider is required."""
    ctrl = doc.get("control")
    if not isinstance(ctrl, dict):
        return  # v7 validation already reported the missing block
    att = ctrl.get("attribution")
    if att is None:
        errors.append(
            f"{where}:control: attribution block missing (schema v8 "
            "requires per-plan attribution + the footprint meter)"
        )
    else:
        validate_attribution(att, errors, f"{where}:control")


def validate_txn_recovery(txn, errors: List[str], where: str) -> None:
    """The v10 ``recovery.transactional`` sub-block: exactly-once
    measured at the external read-committed boundary of a KIP-98
    transactional sink. Duplicates or losses visible to a
    read-committed consumer are a failed claim, not a benchmark."""
    where = f"{where}.transactional"
    if not isinstance(txn, dict):
        errors.append(f"{where}: must be an object")
        return
    for key in (
        "events",
        "crashes",
        "restarts",
        "rows_emitted",
        "read_committed_duplicates",
        "read_committed_lost",
        "read_uncommitted_rows",
    ):
        v = txn.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{where}: {key} missing/non-int/negative ({v!r})"
            )
    rt = txn.get("recovery_time_ms")
    if not _finite(rt) or rt <= 0:
        errors.append(
            f"{where}: recovery_time_ms missing/non-positive ({rt!r}) "
            "— transactional recovery must be a measured number"
        )
    if txn.get("crashes") == 0:
        errors.append(
            f"{where}: crashes == 0 — a transactional recovery block "
            "with no injected crash measures nothing"
        )
    if txn.get("kill_mid_transaction") is not True:
        errors.append(
            f"{where}: kill_mid_transaction must be true — the new "
            "failure mode (death between snapshot and EndTxn) is the "
            "point of the block"
        )
    if txn.get("read_committed_duplicates") != 0:
        errors.append(
            f"{where}: read_committed_duplicates="
            f"{txn.get('read_committed_duplicates')!r} — exactly-once "
            "violated at the external boundary (a read-committed "
            "consumer saw repeated rows)"
        )
    if txn.get("read_committed_lost") != 0:
        errors.append(
            f"{where}: read_committed_lost="
            f"{txn.get('read_committed_lost')!r} — exactly-once "
            "violated at the external boundary (a read-committed "
            "consumer is missing oracle rows)"
        )
    if txn.get("exactly_once") is not True:
        errors.append(f"{where}: exactly_once must be true")
    if txn.get("aborted_rows_invisible") is not True:
        errors.append(
            f"{where}: aborted_rows_invisible must be true — either "
            "the kills never hit a data-bearing transaction (the "
            "block measured nothing) or aborted rows leaked to "
            "read_committed"
        )


def validate_recovery(
    rec, errors: List[str], where: str, version: int = 1
) -> None:
    """The ``--fault`` recovery block (optional in every version; when
    present it must carry real measurements and the exactly-once
    numbers must actually be exact — a recovery claim with duplicates
    or losses is a failed claim, not a benchmark). From v10 the block
    must additionally carry the ``transactional`` sub-block; pre-v10
    lines are exempt, but a present sub-block is always validated."""
    where = f"{where}:recovery"
    if not isinstance(rec, dict):
        errors.append(f"{where}: must be an object")
        return
    for key in (
        "crashes",
        "restarts",
        "checkpoints",
        "events_replayed",
        "rows_emitted",
        "duplicate_rows",
        "lost_rows",
    ):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{where}: {key} missing/non-int/negative ({v!r})"
            )
    rt = rec.get("recovery_time_ms")
    if not _finite(rt) or rt <= 0:
        errors.append(
            f"{where}: recovery_time_ms missing/non-positive ({rt!r}) "
            "— recovery must be a measured number"
        )
    if rec.get("crashes") == 0:
        errors.append(
            f"{where}: crashes == 0 — a recovery block with no "
            "injected crash measures nothing"
        )
    if rec.get("duplicate_rows") != 0:
        errors.append(
            f"{where}: duplicate_rows="
            f"{rec.get('duplicate_rows')!r} — exactly-once violated "
            "(committed output repeated rows the oracle emitted once)"
        )
    if rec.get("lost_rows") != 0:
        errors.append(
            f"{where}: lost_rows={rec.get('lost_rows')!r} — "
            "exactly-once violated (committed output is missing "
            "oracle rows)"
        )
    if rec.get("exactly_once") is not True:
        errors.append(f"{where}: exactly_once must be true")
    if rec.get("stale_tmp_swept") is not True:
        errors.append(
            f"{where}: stale_tmp_swept must be true — the "
            "kill-mid-checkpoint debris was not cleaned up"
        )
    if "transactional" in rec:
        validate_txn_recovery(rec["transactional"], errors, where)
    elif version >= 10:
        errors.append(
            f"{where}: schema v10 recovery block lacks the "
            "transactional sub-block — exactly-once must be measured "
            "at the external read-committed boundary, not only "
            "against internal committed results"
        )


def validate_serving(srv, errors: List[str], where: str) -> None:
    """The schema-v11 ``serving`` block: the open-loop multi-tenant
    serving claims, every one re-derived from the numbers published
    next to it so a declared verdict cannot lie."""
    where = f"{where}:serving"
    if not isinstance(srv, dict):
        errors.append(f"{where}: must be an object")
        return
    ev_s = srv.get("sustained_events_per_sec")
    if not _finite(ev_s) or ev_s <= 0:
        errors.append(
            f"{where}: sustained_events_per_sec missing/non-positive "
            f"({ev_s!r}) — the sustained rate must be a measured number"
        )
    nt = srv.get("tenants")
    if not isinstance(nt, int) or isinstance(nt, bool) or nt < 2:
        errors.append(
            f"{where}: tenants missing/non-int/<2 ({nt!r}) — a "
            "single-tenant run cannot claim isolation"
        )
    # -- the search ledger ------------------------------------------
    search = srv.get("search")
    if not isinstance(search, dict):
        errors.append(f"{where}: search block missing")
    else:
        sr = search.get("sustained_rate_ev_s")
        if not _finite(sr) or sr <= 0:
            errors.append(
                f"{where}: search.sustained_rate_ev_s "
                f"missing/non-positive ({sr!r})"
            )
        tried = search.get("rates_tried")
        if not isinstance(tried, list) or not tried:
            errors.append(
                f"{where}: search.rates_tried missing/empty — the "
                "rate ladder must be a published ledger"
            )
    # -- per-tenant tails -------------------------------------------
    pt = srv.get("per_tenant_p99_ms")
    if not isinstance(pt, dict) or not pt:
        errors.append(f"{where}: per_tenant_p99_ms missing/empty")
    else:
        bad = [t for t, v in pt.items() if not _finite(v) or v <= 0]
        if bad:
            errors.append(
                f"{where}: per_tenant_p99_ms non-finite/non-positive "
                f"for {sorted(bad)}"
            )
    # -- the isolation verdict, re-derived --------------------------
    iso = srv.get("isolation")
    if not isinstance(iso, dict):
        errors.append(f"{where}: isolation block missing")
    else:
        iwhere = f"{where}:isolation"
        gate = iso.get("gate_ratio")
        victims = iso.get("victims")
        if not _finite(gate) or gate <= 0:
            errors.append(
                f"{iwhere}: gate_ratio missing/non-positive ({gate!r})"
            )
        if not isinstance(victims, dict) or not victims:
            errors.append(
                f"{iwhere}: victims missing/empty — the storm run "
                "must publish per-victim pre/post tails"
            )
        else:
            recomputed_max = None
            for t, ent in victims.items():
                vwhere = f"{iwhere}:victims[{t!r}]"
                if not isinstance(ent, dict):
                    errors.append(f"{vwhere}: not an object")
                    continue
                pre, post = ent.get("pre_ms"), ent.get("post_ms")
                ratio = ent.get("ratio")
                if (
                    not _finite(pre) or pre <= 0
                    or not _finite(post) or post <= 0
                    or not _finite(ratio)
                ):
                    errors.append(
                        f"{vwhere}: pre_ms/post_ms/ratio "
                        "missing/non-positive"
                    )
                    continue
                rr = post / pre
                if abs(rr - ratio) > 0.02 * max(rr, 1.0):
                    errors.append(
                        f"{vwhere}: declared ratio {ratio} != "
                        f"recomputed {rr:.3f} from post_ms/pre_ms"
                    )
                recomputed_max = (
                    rr if recomputed_max is None
                    else max(recomputed_max, rr)
                )
            mr = iso.get("max_ratio")
            if recomputed_max is not None:
                if not _finite(mr) or (
                    abs(mr - recomputed_max)
                    > 0.02 * max(recomputed_max, 1.0)
                ):
                    errors.append(
                        f"{iwhere}: declared max_ratio {mr!r} != "
                        f"recomputed {recomputed_max:.3f} from victims"
                    )
                elif _finite(gate):
                    derived = (
                        "pass" if recomputed_max <= gate else "fail"
                    )
                    if iso.get("verdict") != derived:
                        errors.append(
                            f"{iwhere}: verdict "
                            f"{iso.get('verdict')!r} contradicts its "
                            f"own numbers (max_ratio "
                            f"{recomputed_max:.3f} vs gate {gate})"
                        )
        if iso.get("verdict") != "pass":
            errors.append(
                f"{iwhere}: verdict {iso.get('verdict')!r} — the "
                "storm tenant blew victims' p99 beyond the gate"
            )
    # -- the SLO account, reconciled exactly ------------------------
    slo = srv.get("slo")
    if not isinstance(slo, dict):
        errors.append(f"{where}: slo block missing")
    else:
        swhere = f"{where}:slo"
        for key in (
            "violations_total", "recoveries_total",
            "journal_violations", "journal_recoveries",
        ):
            v = slo.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(
                    f"{swhere}: {key} missing/non-int ({v!r})"
                )
        if slo.get("violations_total") != slo.get("journal_violations"):
            errors.append(
                f"{swhere}: violations_total "
                f"{slo.get('violations_total')!r} != journal replay "
                f"{slo.get('journal_violations')!r} — the watchdog's "
                "account drifted from the flight-recorder journal"
            )
        if slo.get("recoveries_total") != slo.get("journal_recoveries"):
            errors.append(
                f"{swhere}: recoveries_total "
                f"{slo.get('recoveries_total')!r} != journal replay "
                f"{slo.get('journal_recoveries')!r}"
            )
        if slo.get("reconciled") is not True:
            errors.append(f"{swhere}: reconciled must be true")
    # -- the sustainability verdict, re-derived ---------------------
    sus = srv.get("sustainable")
    if not isinstance(sus, dict):
        errors.append(f"{where}: sustainable block missing")
    else:
        uwhere = f"{where}:sustainable"
        checks = {}
        lag, lagb = sus.get("lag_p90_s"), sus.get("lag_budget_s")
        if _finite(lag) and _finite(lagb):
            checks["lag_ok"] = lag <= lagb
        loss, lossb = sus.get("loss_ratio"), sus.get("loss_budget")
        if _finite(loss) and _finite(lossb):
            checks["loss_ok"] = loss <= lossb
        pp, tp = sus.get("probe_p99_ms"), sus.get("telemetry_p99_ms")
        tol, slack = (
            sus.get("probe_tolerance"), sus.get("probe_slack_ms"),
        )
        if all(_finite(v) for v in (pp, tp, tol, slack)):
            checks["probe_ok"] = pp <= tol * tp + slack
            INFO.append(
                f"{uwhere}: prober p99 {pp}ms vs telemetry p99 "
                f"{tp}ms under serving load"
            )
        missing = [
            k for k in ("lag_ok", "loss_ok", "probe_ok")
            if k not in checks
        ]
        if missing:
            errors.append(
                f"{uwhere}: cannot re-derive {missing} — the inputs "
                "(measured value + budget) must be published"
            )
        for key, want in checks.items():
            if sus.get(key) is not want:
                errors.append(
                    f"{uwhere}: declared {key}={sus.get(key)!r} "
                    f"contradicts its own inputs (re-derived {want})"
                )
        if not isinstance(sus.get("health_ok"), bool):
            errors.append(f"{uwhere}: health_ok missing/non-bool")
        derived = (
            all(checks.values())
            and not missing
            and sus.get("health_ok") is True
        )
        if sus.get("verdict") is not True:
            errors.append(
                f"{uwhere}: verdict must be true — the published "
                "sustained rate was not actually sustained"
            )
        elif not derived:
            errors.append(
                f"{uwhere}: verdict true contradicts its own inputs"
            )
    # -- the limiting leg, same re-derivation gate as v9 ------------
    ll = srv.get("limiting_leg")
    if ll is None:
        errors.append(
            f"{where}: limiting_leg block missing (the serving line "
            "must name its measured bottleneck)"
        )
    else:
        validate_limiting_leg(ll, errors, where)
    # -- live churn under load --------------------------------------
    churn = srv.get("churn")
    if not isinstance(churn, dict):
        errors.append(f"{where}: churn block missing")
    else:
        cwhere = f"{where}:churn"
        for key in ("admitted", "retired", "disabled", "enabled"):
            v = churn.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                errors.append(
                    f"{cwhere}: {key}={v!r} — the churn really must "
                    "have happened mid-measurement"
                )
        rules = churn.get("hostile_refused_rules")
        if not (
            isinstance(rules, list)
            and rules
            and all(
                isinstance(r, str)
                and (r.startswith("ADM") or r.startswith("PLC"))
                for r in rules
            )
        ):
            errors.append(
                f"{cwhere}: hostile_refused_rules={rules!r} — the "
                "hostile admit must be refused with exact rule ids"
            )
    # -- the scrape ledger ------------------------------------------
    sc = srv.get("scrapes")
    if not isinstance(sc, dict):
        errors.append(f"{where}: scrapes block missing")
    else:
        n = sc.get("count")
        if not isinstance(n, int) or isinstance(n, bool) or n < 3:
            errors.append(
                f"{where}: scrapes.count={n!r} — the verdicts need a "
                "scraped series, not a single look"
            )
        if sc.get("source") != "rest":
            errors.append(
                f"{where}: scrapes.source={sc.get('source')!r} — "
                "serving verdicts must be read off the public REST "
                "surface"
            )


def validate_fleet(flt, errors: List[str], where: str) -> None:
    """The schema-v12 ``fleet`` block: the cold-vs-warm replica
    bootstrap claims (module docstring) — warm must beat cold, the
    warm boot must be lowering-free, and the commit-log exactly-once
    account across the handoff must be clean."""
    where = f"{where}:fleet"
    if not isinstance(flt, dict):
        errors.append(f"{where}: must be an object")
        return
    nt = flt.get("tenants")
    if not isinstance(nt, int) or isinstance(nt, bool) or nt < 2:
        errors.append(
            f"{where}: tenants missing/non-int/<2 ({nt!r}) — a "
            "single-tenant boot cannot claim executable sharing"
        )
    boots = {}
    for name in ("cold", "warm"):
        blk = flt.get(name)
        if not isinstance(blk, dict):
            errors.append(f"{where}: {name} boot block missing")
            continue
        frs = blk.get("first_row_s")
        if not _finite(frs) or frs <= 0:
            errors.append(
                f"{where}: {name}.first_row_s missing/non-positive "
                f"({frs!r}) — cold-start-to-first-row is the headline"
            )
        boots[name] = blk
    cold, warm = boots.get("cold"), boots.get("warm")
    if cold and warm:
        cf, wf = cold.get("first_row_s"), warm.get("first_row_s")
        if _finite(cf) and _finite(wf) and not wf < cf:
            errors.append(
                f"{where}: warm.first_row_s ({wf}) must beat "
                f"cold.first_row_s ({cf}) — a store that does not pay "
                "for itself is a failed claim"
            )
    if warm:
        if warm.get("compiles") != 0:
            errors.append(
                f"{where}: warm.compiles must be 0 "
                f"({warm.get('compiles')!r}) — the warm boot must "
                "lower nothing"
            )
        if warm.get("warm_misses") != 0:
            errors.append(
                f"{where}: warm.warm_misses must be 0 "
                f"({warm.get('warm_misses')!r})"
            )
        hits = warm.get("warm_hits")
        if not isinstance(hits, int) or isinstance(hits, bool) \
                or hits < 1:
            errors.append(
                f"{where}: warm.warm_hits missing/<1 ({hits!r}) — a "
                "warm boot that read nothing from the store proves "
                "nothing"
            )
    if cold:
        persists = cold.get("persists")
        if not isinstance(persists, int) or isinstance(persists, bool) \
                or persists < 1:
            errors.append(
                f"{where}: cold.persists missing/<1 ({persists!r}) — "
                "the cold boot must have populated the store"
            )
    committed = flt.get("committed")
    if not isinstance(committed, dict):
        errors.append(f"{where}: committed block missing")
    else:
        if committed.get("duplicate_epochs") != 0:
            errors.append(
                f"{where}: committed.duplicate_epochs must be 0 "
                f"({committed.get('duplicate_epochs')!r})"
            )
        if committed.get("lost") != 0:
            errors.append(
                f"{where}: committed.lost must be 0 "
                f"({committed.get('lost')!r})"
            )
        rows = committed.get("rows")
        if not isinstance(rows, int) or isinstance(rows, bool) \
                or rows < 1:
            errors.append(
                f"{where}: committed.rows missing/<1 ({rows!r}) — an "
                "exactly-once account over zero rows proves nothing"
            )


def validate_subplan_share(blk, errors: List[str], where: str) -> None:
    """The schema-v13 ``subplan_share`` block: the shared-vs-unshared
    A/B over a mixed non-constants-only tenant fleet. The gate
    RE-DERIVES the speedup from the two sides' published ev/s, holds
    both sides to zero dropped events, requires the shared side's
    per-tenant attribution to conserve, and requires per-host compile
    spend to be SUB-LINEAR in members (< 1 lowering per member — the
    point of sharing the prefix)."""
    where = f"{where}:subplan_share"
    if not isinstance(blk, dict):
        errors.append(f"{where}: must be an object")
        return
    nt = blk.get("tenants")
    if not isinstance(nt, int) or isinstance(nt, bool) or nt < 2:
        errors.append(
            f"{where}: tenants missing/non-int/<2 ({nt!r}) — a "
            "single-tenant fleet cannot claim cross-tenant sharing"
        )
    sides = {}
    for name in ("unshared", "shared"):
        sec = blk.get(name)
        if not isinstance(sec, dict):
            errors.append(f"{where}: {name} side missing")
            continue
        evs = sec.get("events_per_sec")
        if not _finite(evs) or evs <= 0:
            errors.append(
                f"{where}: {name}.events_per_sec missing/non-positive "
                f"({evs!r})"
            )
        if sec.get("dropped_events") != 0:
            errors.append(
                f"{where}: {name}.dropped_events must be 0 "
                f"({sec.get('dropped_events')!r}) — a side that sheds "
                "load wins its A/B by cheating"
            )
        sides[name] = sec
    shared = sides.get("shared")
    if shared:
        if shared.get("conserved") is not True:
            errors.append(
                f"{where}: shared.conserved must be true — per-plan "
                "scoped rows must still sum exactly to the job total "
                "when tenants ride a shared host"
            )
        hosts = shared.get("hosts")
        if not isinstance(hosts, dict) or not hosts:
            errors.append(
                f"{where}: shared.hosts missing/empty — an A/B where "
                "no prefix host formed measured nothing"
            )
        else:
            for hid, h in hosts.items():
                if not isinstance(h, dict):
                    errors.append(f"{where}: hosts[{hid}] not an object")
                    continue
                members = h.get("members")
                lows = h.get("lowerings")
                if not isinstance(members, int) \
                        or isinstance(members, bool) or members < 2:
                    errors.append(
                        f"{where}: hosts[{hid}].members missing/<2 "
                        f"({members!r}) — a host with one member "
                        "shares nothing"
                    )
                if not isinstance(lows, int) or isinstance(lows, bool) \
                        or lows < 0:
                    errors.append(
                        f"{where}: hosts[{hid}].lowerings "
                        f"missing/negative ({lows!r})"
                    )
                elif isinstance(members, int) and members >= 2 \
                        and lows >= members:
                    errors.append(
                        f"{where}: hosts[{hid}].lowerings ({lows}) must "
                        f"be sub-linear in members ({members}) — "
                        "one-lowering-per-tenant is the unshared cost"
                    )
        shares = shared.get("subplan_shares")
        if not isinstance(shares, int) or isinstance(shares, bool) \
                or shares < 2:
            errors.append(
                f"{where}: shared.subplan_shares missing/<2 "
                f"({shares!r})"
            )
    speedup = blk.get("speedup")
    if not _finite(speedup) or speedup <= 0:
        errors.append(
            f"{where}: speedup missing/non-positive ({speedup!r})"
        )
    else:
        un = sides.get("unshared", {}).get("events_per_sec")
        sh = sides.get("shared", {}).get("events_per_sec")
        if _finite(un) and _finite(sh) and un > 0:
            derived = sh / un
            if abs(derived - speedup) > max(0.011, derived * 0.01):
                errors.append(
                    f"{where}: speedup ({speedup}) does not re-derive "
                    f"from the published sides ({derived:.3f}) — a "
                    "declared ratio cannot lie"
                )
        # the headline claim: sharing must actually WIN. The dryrun
        # fleet is small (its closing-drain fixed costs weigh more),
        # so it gets a regression backstop instead of the full bar —
        # the broken states this gate exists to catch (per-payload
        # suffix dispatch, in-window re-lowering) measured <= 0.5
        floor = 0.8 if blk.get("dryrun") else 1.0
        if speedup < floor:
            errors.append(
                f"{where}: speedup ({speedup}) below {floor} — the "
                "shared fleet must not lose to the unshared one"
            )
        INFO.append(
            f"{where}: shared/unshared speedup {speedup} "
            f"({'dryrun' if blk.get('dryrun') else 'full'} fleet)"
        )


def validate_v13(doc, errors: List[str], where: str) -> None:
    """The cross-tenant subplan-sharing contract: a v13 replay line
    must carry the shared-vs-unshared A/B block."""
    blk = doc.get("subplan_share")
    if blk is None:
        errors.append(
            f"{where}: subplan_share block missing (schema v13 "
            "requires the shared-vs-unshared fleet A/B)"
        )
    else:
        validate_subplan_share(blk, errors, where)


def validate_doc(
    doc, errors: List[str], where: str, require_stages: bool = False
) -> None:
    if not isinstance(doc, dict):
        errors.append(f"{where}: not a JSON object")
        return
    for key, types in (
        ("metric", str),
        ("value", _NUM),
        ("unit", str),
    ):
        if not isinstance(doc.get(key), types):
            errors.append(f"{where}: {key} missing or wrong type")
    for key in (
        "vs_baseline",
        "vs_jvm_estimate",
        "p50_match_latency_ms",
        "p99_match_latency_ms",
        "p50_visibility_latency_ms",
        "p99_visibility_latency_ms",
        "stage_seconds",
    ):
        if key in doc and not isinstance(doc[key], _NUM):
            errors.append(f"{where}: {key} non-numeric")
    version = doc.get("schema_version", 1)
    if "fleet" in doc:
        validate_fleet(doc["fleet"], errors, where)
        if not isinstance(doc.get("modes"), dict):
            # a --fleet line carries fleet INSTEAD of modes (same
            # shape as the serving exemption below); an optional
            # recovery block present on it is still held to its
            # contract
            if "recovery" in doc:
                validate_recovery(
                    doc["recovery"], errors, where, version
                )
            return
    if "serving" in doc:
        validate_serving(doc["serving"], errors, where)
        if not isinstance(doc.get("modes"), dict):
            # a --serve line carries serving INSTEAD of modes: its
            # limiting_leg/latency claims live inside the serving
            # block, so the replay-mode contracts (v2 stage_breakdown
            # through v10 recovery-requirement) do not apply — but an
            # optional recovery block present on it is still held to
            # its contract
            if "recovery" in doc:
                validate_recovery(
                    doc["recovery"], errors, where, version
                )
            return
    if "stage_breakdown" in doc:
        validate_stage_breakdown(doc["stage_breakdown"], errors, where)
    elif version >= 2 or require_stages:
        errors.append(
            f"{where}: schema v{max(version, 2)} output lacks "
            "stage_breakdown"
        )
    if version >= 3:
        validate_v3(doc, errors, where)
    if version >= 4:
        validate_v4(doc, errors, where)
    if version >= 5:
        validate_v5(doc, errors, where)
    if version >= 6:
        validate_v6(doc, errors, where)
    elif "disorder" in doc:
        # pre-v6 lines are exempt from requiring the block, but one
        # that IS present must hold to its contract
        validate_disorder(doc["disorder"], errors, where)
    if version >= 7:
        validate_v7(doc, errors, where)
    elif "control" in doc:
        # same exemption shape as disorder: v6-era lines need not
        # carry the block, but a present one is held to its contract
        validate_control(doc["control"], errors, where)
    if version >= 9:
        validate_v9(doc, errors, where)
    elif isinstance(doc.get("modes"), dict):
        # pre-v9 exemption (same shape as disorder/control): a
        # limiting_leg block present in an older line is still held
        # to its contract
        for name, sec in doc["modes"].items():
            if isinstance(sec, dict) and "limiting_leg" in sec:
                validate_limiting_leg(
                    sec["limiting_leg"], errors,
                    f"{where}:modes.{name}",
                )
    if version >= 8:
        validate_v8(doc, errors, where)
    elif (
        isinstance(doc.get("control"), dict)
        and "attribution" in doc["control"]
    ):
        # pre-v8 exemption, but a present attribution block is held
        # to its contract
        validate_attribution(
            doc["control"]["attribution"], errors, f"{where}:control"
        )
    if version >= 13:
        validate_v13(doc, errors, where)
    elif "subplan_share" in doc:
        # pre-v13 exemption (same shape as disorder/control): a block
        # present in an older line is still held to its contract
        validate_subplan_share(doc["subplan_share"], errors, where)
    if "recovery" in doc:
        validate_recovery(doc["recovery"], errors, where, version)


def extract_docs(text: str, errors: List[str], path: str):
    """Bench-output JSON objects from either format:

    * raw bench stdout — one JSON object per line (mixed with logging
      noise, which is skipped);
    * a driver-harvest wrapper — one pretty-printed object with the
      bench stdout embedded in its ``tail`` string (BENCH_r01..r05).
    """
    try:
        wrapper = json.loads(text)
    except ValueError:
        wrapper = None
    if isinstance(wrapper, dict) and "tail" in wrapper:
        text = str(wrapper.get("tail") or "")
    docs = []
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue  # non-bench JSON-ish noise
        if isinstance(doc, dict) and "metric" in doc:
            docs.append((f"{path}:{i + 1}", doc))
    if not docs:
        # applies to wrapper files too: a harvest whose run crashed
        # before printing its JSON line (tail empty / noise only) must
        # FAIL the gate, not slide through as trivially valid
        errors.append(f"{path}: no bench JSON lines found")
    return docs


def validate_file(path: str, require_stages: bool = False) -> List[str]:
    errors: List[str] = []
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    if not text.strip():
        return [f"{path}: empty"]
    for where, doc in extract_docs(text, errors, path):
        validate_doc(doc, errors, where, require_stages)
    return errors


def main(argv: List[str]) -> int:
    require = "--require-stages" in argv
    files = [a for a in argv if not a.startswith("--")]
    if not files:
        files = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    if not files:
        print("no BENCH_*.json files found")
        return 1
    all_errors: List[str] = []
    for path in files:
        all_errors.extend(validate_file(path, require))
    for note in INFO:
        print(f"PROBER: {note}")
    for err in all_errors:
        print(f"SCHEMA ERROR: {err}")
    print(
        f"checked {len(files)} file(s): "
        + ("FAIL" if all_errors else "ok")
    )
    return 1 if all_errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
