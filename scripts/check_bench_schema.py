#!/usr/bin/env python
"""Validate BENCH_*.json lines against the bench output schema.

Schema v2 (telemetry rounds, bench.py ``schema_version: 2``) adds the
honest-wall-clock contract: a ``stage_breakdown`` section whose
top-level stages (flink_siddhi_tpu.telemetry.TOP_LEVEL_STAGES) must
sum to >= 95% of the end-to-end elapsed wall clock — the gate that
keeps "unattributed off-clock time" from ever reappearing in a
headline number. Pre-v2 files (BENCH_r01..r05) validate against the
legacy subset only.

Schema v3 (falsifiable-latency round, bench.py ``schema_version: 3``)
adds the multi-mode + independent-measurement contract:

* ``modes`` must contain ALL of resident, streaming, sink — one bench
  run tracks the engine path, the unbounded path, and the
  rows-materialized data path together (a ``"partial": true`` subset
  run is rejected: headline numbers must carry all three);
* every mode section carries its own ``stage_breakdown`` (same >= 95%
  coverage contract as v2) and a ``latency`` block whose
  ``telemetry_p99_ms`` AND out-of-process ``prober_p50_ms`` /
  ``prober_p99_ms`` are present and finite — a bench line whose
  side-channel prober failed does not validate;
* the prober-vs-telemetry ``discrepancy_ratio`` is reported per mode
  (printed, not just stored), and a declared ``prober_contradiction``
  fails validation outright.

Schema v4 (columnar-sink + tail-aware-drain round, bench.py
``schema_version: 4``) adds the rows-materialized + p99-target
contract:

* ``modes.sink`` must carry a finite positive ``rows_materialized_ev_s``
  (events/sec through the path where every emitted row reaches a
  consumer), a ``rows_emitted`` count, and ``columnar: true`` — the
  sink mode must drive the columnar fast lane, not the row fallback;
* ``p99_target.verdict`` must be ``p99_le_500ms`` (p99 <= 500 ms at a
  >= 1M ev/s offered load) or ``p99_le_2x_prober`` (p99 <= 2x the
  out-of-process prober's under-load p99). ``missed`` — failing BOTH
  targets — is rejected loudly, as is a line missing the block;
* ``drain_staleness.p50_ms`` / ``p99_ms`` must be present and finite:
  the deadline drain scheduler's staleness leg must actually have
  recorded (a scheduler that never ran produces no line).

Optional ``recovery`` block (``bench.py --fault``, any version): when
present it must carry a finite positive measured ``recovery_time_ms``,
at least one injected crash, ``stale_tmp_swept: true``, and EXACT
exactly-once numbers — ``duplicate_rows`` and ``lost_rows`` (counted
against an unfaulted oracle, not assumed) must both be 0.

Usage:
    python scripts/check_bench_schema.py [FILES...]
    python scripts/check_bench_schema.py --require-stages FILES...

With no FILES, validates every BENCH_*.json in the repo root. Exit
status 0 = all valid. ``--require-stages`` additionally fails any file
that lacks a stage_breakdown (used for freshly-produced bench output,
where telemetry is expected on).

Runs in the tier-1 lane via tests/test_bench_schema.py.
"""

from __future__ import annotations

import glob
import json
import math
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MIN_COVERAGE = 0.95
V3_MODES = ("resident", "streaming", "sink")

_NUM = (int, float)

# informational lines (prober-vs-telemetry discrepancy ratios etc.)
# collected during validation and printed by main()
INFO: List[str] = []


def _finite(v) -> bool:
    return isinstance(v, _NUM) and math.isfinite(v)


def _stage_names():
    from flink_siddhi_tpu.telemetry import TOP_LEVEL_STAGES

    return TOP_LEVEL_STAGES


def validate_stage_breakdown(sb, errors: List[str], where: str) -> None:
    if not isinstance(sb, dict):
        errors.append(f"{where}: stage_breakdown is not an object")
        return
    if sb.get("telemetry") == "off":
        return  # explicit opt-out run (BENCH_TELEMETRY=0): no contract
    for key in ("elapsed_s", "attributed_s", "coverage"):
        if not isinstance(sb.get(key), _NUM):
            errors.append(
                f"{where}: stage_breakdown.{key} missing/non-numeric"
            )
            return
    stages = sb.get("stages")
    if not isinstance(stages, dict) or not stages:
        errors.append(f"{where}: stage_breakdown.stages missing/empty")
        return
    bad = [
        k for k, v in stages.items() if not isinstance(v, _NUM) or v < 0
    ]
    if bad:
        errors.append(
            f"{where}: non-numeric/negative stage seconds: {bad}"
        )
        return
    if sb["elapsed_s"] <= 0:
        errors.append(f"{where}: elapsed_s must be > 0")
        return
    top = _stage_names()
    top_sum = sum(v for k, v in stages.items() if k in top)
    cov = top_sum / sb["elapsed_s"]
    # the declared coverage must match a recompute from the stages map
    if abs(cov - sb["coverage"]) > 0.02:
        errors.append(
            f"{where}: declared coverage {sb['coverage']:.4f} != "
            f"recomputed {cov:.4f} from top-level stages"
        )
    if cov < MIN_COVERAGE:
        errors.append(
            f"{where}: top-level stages attribute only {cov:.1%} of "
            f"elapsed wall-clock (< {MIN_COVERAGE:.0%}): "
            "unattributed off-clock time"
        )
    unknown = [
        k
        for k in stages
        if k not in top and not k.startswith("nested.")
    ]
    if unknown:
        errors.append(
            f"{where}: unknown stage names (not in TOP_LEVEL_STAGES, "
            f"not nested.*): {unknown}"
        )


def validate_mode_latency(
    lat, errors: List[str], where: str, telemetry_off: bool = False
) -> None:
    """The v3 falsifiability contract per mode: an in-process number
    AND an out-of-process prober number, both finite. A
    ``BENCH_TELEMETRY=0`` overhead-A/B run is exempt from the
    in-process half only — the prober is external and must still
    report."""
    if not isinstance(lat, dict):
        errors.append(f"{where}: latency is not an object")
        return
    required = ["prober_p50_ms", "prober_p99_ms"]
    if not telemetry_off:
        required.append("telemetry_p99_ms")
    for key in required:
        if not _finite(lat.get(key)):
            errors.append(
                f"{where}: latency.{key} missing/non-finite (a failed "
                "side-channel prober run does not validate)"
            )
    for key in ("prober_pid", "prober_parent_pid"):
        if not isinstance(lat.get(key), int):
            errors.append(f"{where}: latency.{key} missing/non-int")
    if (
        isinstance(lat.get("prober_pid"), int)
        and isinstance(lat.get("prober_parent_pid"), int)
        and lat["prober_pid"] == lat["prober_parent_pid"]
    ):
        errors.append(
            f"{where}: prober_pid == prober_parent_pid — the prober "
            "did not run in a separate OS process"
        )
    ratio = lat.get("discrepancy_ratio")
    if not _finite(ratio):
        if not telemetry_off:
            errors.append(
                f"{where}: latency.discrepancy_ratio missing/non-finite"
            )
    else:
        INFO.append(
            f"{where}: prober p99 {lat.get('prober_p99_ms')}ms vs "
            f"telemetry p99 {lat.get('telemetry_p99_ms')}ms — "
            f"discrepancy ratio {ratio}"
        )


def validate_v3(doc, errors: List[str], where: str) -> None:
    if doc.get("partial"):
        errors.append(
            f"{where}: partial mode subset (BENCH_MODES) — headline "
            "bench lines must carry all of "
            + ", ".join(V3_MODES)
        )
    modes = doc.get("modes")
    if not isinstance(modes, dict):
        errors.append(f"{where}: schema v3 output lacks modes object")
        return
    for name in V3_MODES:
        sec = modes.get(name)
        if not isinstance(sec, dict):
            errors.append(f"{where}: modes.{name} missing")
            continue
        mwhere = f"{where}:modes.{name}"
        if not _finite(sec.get("events_per_sec")) or (
            sec.get("events_per_sec", 0) <= 0
        ):
            errors.append(
                f"{mwhere}: events_per_sec missing/non-positive"
            )
        sb = sec.get("stage_breakdown")
        if sb is None:
            errors.append(f"{mwhere}: stage_breakdown missing")
        else:
            validate_stage_breakdown(sb, errors, mwhere)
        telemetry_off = (
            isinstance(sb, dict) and sb.get("telemetry") == "off"
        )
        lat = sec.get("latency")
        if lat is None:
            errors.append(f"{mwhere}: latency block missing")
        else:
            validate_mode_latency(lat, errors, mwhere, telemetry_off)
    if "prober_contradiction" in doc:
        errors.append(
            f"{where}: prober contradicts the in-process claims: "
            f"{doc['prober_contradiction']}"
        )


V4_VERDICTS = ("p99_le_500ms", "p99_le_2x_prober")


def validate_v4(doc, errors: List[str], where: str) -> None:
    """The columnar-sink + tail-aware-drain contract (on top of v3)."""
    sink = (doc.get("modes") or {}).get("sink")
    if isinstance(sink, dict):
        swhere = f"{where}:modes.sink"
        rm = sink.get("rows_materialized_ev_s")
        if not _finite(rm) or rm <= 0:
            errors.append(
                f"{swhere}: rows_materialized_ev_s missing/non-positive "
                "(schema v4 requires the measured data-path ev/s)"
            )
        if not isinstance(sink.get("rows_emitted"), int):
            errors.append(f"{swhere}: rows_emitted missing/non-int")
        if sink.get("columnar") is not True:
            errors.append(
                f"{swhere}: columnar must be true — the sink mode must "
                "drive the columnar fast lane, not the row fallback"
            )
    tgt = doc.get("p99_target")
    if not isinstance(tgt, dict):
        errors.append(
            f"{where}: p99_target block missing (schema v4 requires "
            "the latency-target verdict)"
        )
    else:
        verdict = tgt.get("verdict")
        if verdict not in V4_VERDICTS:
            errors.append(
                f"{where}: p99_target.verdict {verdict!r} — the line "
                f"fails BOTH latency targets (need one of "
                f"{', '.join(V4_VERDICTS)}: p99 "
                f"{tgt.get('p99_ms')}ms at "
                f"{tgt.get('offered_load_events_per_sec')} ev/s, "
                f"prober p99 {tgt.get('prober_p99_ms')}ms)"
            )
        elif not _finite(tgt.get("p99_ms")):
            errors.append(f"{where}: p99_target.p99_ms missing/non-finite")
        else:
            INFO.append(
                f"{where}: p99 target met via {verdict} — p99 "
                f"{tgt.get('p99_ms')}ms at "
                f"{tgt.get('offered_load_events_per_sec')} ev/s offered"
            )
    st = doc.get("drain_staleness")
    if not isinstance(st, dict):
        errors.append(
            f"{where}: drain_staleness block missing (schema v4 "
            "requires the deadline drain scheduler's staleness stats)"
        )
    else:
        for key in ("p50_ms", "p99_ms"):
            if not _finite(st.get(key)):
                errors.append(
                    f"{where}: drain_staleness.{key} missing/non-finite"
                )


def validate_recovery(rec, errors: List[str], where: str) -> None:
    """The ``--fault`` recovery block (optional in every version; when
    present it must carry real measurements and the exactly-once
    numbers must actually be exact — a recovery claim with duplicates
    or losses is a failed claim, not a benchmark)."""
    where = f"{where}:recovery"
    if not isinstance(rec, dict):
        errors.append(f"{where}: must be an object")
        return
    for key in (
        "crashes",
        "restarts",
        "checkpoints",
        "events_replayed",
        "rows_emitted",
        "duplicate_rows",
        "lost_rows",
    ):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{where}: {key} missing/non-int/negative ({v!r})"
            )
    rt = rec.get("recovery_time_ms")
    if not _finite(rt) or rt <= 0:
        errors.append(
            f"{where}: recovery_time_ms missing/non-positive ({rt!r}) "
            "— recovery must be a measured number"
        )
    if rec.get("crashes") == 0:
        errors.append(
            f"{where}: crashes == 0 — a recovery block with no "
            "injected crash measures nothing"
        )
    if rec.get("duplicate_rows") != 0:
        errors.append(
            f"{where}: duplicate_rows="
            f"{rec.get('duplicate_rows')!r} — exactly-once violated "
            "(committed output repeated rows the oracle emitted once)"
        )
    if rec.get("lost_rows") != 0:
        errors.append(
            f"{where}: lost_rows={rec.get('lost_rows')!r} — "
            "exactly-once violated (committed output is missing "
            "oracle rows)"
        )
    if rec.get("exactly_once") is not True:
        errors.append(f"{where}: exactly_once must be true")
    if rec.get("stale_tmp_swept") is not True:
        errors.append(
            f"{where}: stale_tmp_swept must be true — the "
            "kill-mid-checkpoint debris was not cleaned up"
        )


def validate_doc(
    doc, errors: List[str], where: str, require_stages: bool = False
) -> None:
    if not isinstance(doc, dict):
        errors.append(f"{where}: not a JSON object")
        return
    for key, types in (
        ("metric", str),
        ("value", _NUM),
        ("unit", str),
    ):
        if not isinstance(doc.get(key), types):
            errors.append(f"{where}: {key} missing or wrong type")
    for key in (
        "vs_baseline",
        "vs_jvm_estimate",
        "p50_match_latency_ms",
        "p99_match_latency_ms",
        "p50_visibility_latency_ms",
        "p99_visibility_latency_ms",
        "stage_seconds",
    ):
        if key in doc and not isinstance(doc[key], _NUM):
            errors.append(f"{where}: {key} non-numeric")
    version = doc.get("schema_version", 1)
    if "stage_breakdown" in doc:
        validate_stage_breakdown(doc["stage_breakdown"], errors, where)
    elif version >= 2 or require_stages:
        errors.append(
            f"{where}: schema v{max(version, 2)} output lacks "
            "stage_breakdown"
        )
    if version >= 3:
        validate_v3(doc, errors, where)
    if version >= 4:
        validate_v4(doc, errors, where)
    if "recovery" in doc:
        validate_recovery(doc["recovery"], errors, where)


def extract_docs(text: str, errors: List[str], path: str):
    """Bench-output JSON objects from either format:

    * raw bench stdout — one JSON object per line (mixed with logging
      noise, which is skipped);
    * a driver-harvest wrapper — one pretty-printed object with the
      bench stdout embedded in its ``tail`` string (BENCH_r01..r05).
    """
    try:
        wrapper = json.loads(text)
    except ValueError:
        wrapper = None
    if isinstance(wrapper, dict) and "tail" in wrapper:
        text = str(wrapper.get("tail") or "")
    docs = []
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue  # non-bench JSON-ish noise
        if isinstance(doc, dict) and "metric" in doc:
            docs.append((f"{path}:{i + 1}", doc))
    if not docs:
        # applies to wrapper files too: a harvest whose run crashed
        # before printing its JSON line (tail empty / noise only) must
        # FAIL the gate, not slide through as trivially valid
        errors.append(f"{path}: no bench JSON lines found")
    return docs


def validate_file(path: str, require_stages: bool = False) -> List[str]:
    errors: List[str] = []
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    if not text.strip():
        return [f"{path}: empty"]
    for where, doc in extract_docs(text, errors, path):
        validate_doc(doc, errors, where, require_stages)
    return errors


def main(argv: List[str]) -> int:
    require = "--require-stages" in argv
    files = [a for a in argv if not a.startswith("--")]
    if not files:
        files = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    if not files:
        print("no BENCH_*.json files found")
        return 1
    all_errors: List[str] = []
    for path in files:
        all_errors.extend(validate_file(path, require))
    for note in INFO:
        print(f"PROBER: {note}")
    for err in all_errors:
        print(f"SCHEMA ERROR: {err}")
    print(
        f"checked {len(files)} file(s): "
        + ("FAIL" if all_errors else "ok")
    )
    return 1 if all_errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
