#!/usr/bin/env python
"""Validate BENCH_*.json lines against the bench output schema.

Schema v2 (telemetry rounds, bench.py ``schema_version: 2``) adds the
honest-wall-clock contract: a ``stage_breakdown`` section whose
top-level stages (flink_siddhi_tpu.telemetry.TOP_LEVEL_STAGES) must
sum to >= 95% of the end-to-end elapsed wall clock — the gate that
keeps "unattributed off-clock time" from ever reappearing in a
headline number. Pre-v2 files (BENCH_r01..r05) validate against the
legacy subset only.

Usage:
    python scripts/check_bench_schema.py [FILES...]
    python scripts/check_bench_schema.py --require-stages FILES...

With no FILES, validates every BENCH_*.json in the repo root. Exit
status 0 = all valid. ``--require-stages`` additionally fails any file
that lacks a stage_breakdown (used for freshly-produced bench output,
where telemetry is expected on).

Runs in the tier-1 lane via tests/test_bench_schema.py.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MIN_COVERAGE = 0.95

_NUM = (int, float)


def _stage_names():
    from flink_siddhi_tpu.telemetry import TOP_LEVEL_STAGES

    return TOP_LEVEL_STAGES


def validate_stage_breakdown(sb, errors: List[str], where: str) -> None:
    if not isinstance(sb, dict):
        errors.append(f"{where}: stage_breakdown is not an object")
        return
    if sb.get("telemetry") == "off":
        return  # explicit opt-out run (BENCH_TELEMETRY=0): no contract
    for key in ("elapsed_s", "attributed_s", "coverage"):
        if not isinstance(sb.get(key), _NUM):
            errors.append(
                f"{where}: stage_breakdown.{key} missing/non-numeric"
            )
            return
    stages = sb.get("stages")
    if not isinstance(stages, dict) or not stages:
        errors.append(f"{where}: stage_breakdown.stages missing/empty")
        return
    bad = [
        k for k, v in stages.items() if not isinstance(v, _NUM) or v < 0
    ]
    if bad:
        errors.append(
            f"{where}: non-numeric/negative stage seconds: {bad}"
        )
        return
    if sb["elapsed_s"] <= 0:
        errors.append(f"{where}: elapsed_s must be > 0")
        return
    top = _stage_names()
    top_sum = sum(v for k, v in stages.items() if k in top)
    cov = top_sum / sb["elapsed_s"]
    # the declared coverage must match a recompute from the stages map
    if abs(cov - sb["coverage"]) > 0.02:
        errors.append(
            f"{where}: declared coverage {sb['coverage']:.4f} != "
            f"recomputed {cov:.4f} from top-level stages"
        )
    if cov < MIN_COVERAGE:
        errors.append(
            f"{where}: top-level stages attribute only {cov:.1%} of "
            f"elapsed wall-clock (< {MIN_COVERAGE:.0%}): "
            "unattributed off-clock time"
        )
    unknown = [
        k
        for k in stages
        if k not in top and not k.startswith("nested.")
    ]
    if unknown:
        errors.append(
            f"{where}: unknown stage names (not in TOP_LEVEL_STAGES, "
            f"not nested.*): {unknown}"
        )


def validate_doc(
    doc, errors: List[str], where: str, require_stages: bool = False
) -> None:
    if not isinstance(doc, dict):
        errors.append(f"{where}: not a JSON object")
        return
    for key, types in (
        ("metric", str),
        ("value", _NUM),
        ("unit", str),
    ):
        if not isinstance(doc.get(key), types):
            errors.append(f"{where}: {key} missing or wrong type")
    for key in (
        "vs_baseline",
        "vs_jvm_estimate",
        "p50_match_latency_ms",
        "p99_match_latency_ms",
        "p50_visibility_latency_ms",
        "p99_visibility_latency_ms",
        "stage_seconds",
    ):
        if key in doc and not isinstance(doc[key], _NUM):
            errors.append(f"{where}: {key} non-numeric")
    v2 = doc.get("schema_version", 1) >= 2
    if "stage_breakdown" in doc:
        validate_stage_breakdown(doc["stage_breakdown"], errors, where)
    elif v2 or require_stages:
        errors.append(
            f"{where}: schema v2 output lacks stage_breakdown"
        )


def extract_docs(text: str, errors: List[str], path: str):
    """Bench-output JSON objects from either format:

    * raw bench stdout — one JSON object per line (mixed with logging
      noise, which is skipped);
    * a driver-harvest wrapper — one pretty-printed object with the
      bench stdout embedded in its ``tail`` string (BENCH_r01..r05).
    """
    try:
        wrapper = json.loads(text)
    except ValueError:
        wrapper = None
    if isinstance(wrapper, dict) and "tail" in wrapper:
        text = str(wrapper.get("tail") or "")
    docs = []
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue  # non-bench JSON-ish noise
        if isinstance(doc, dict) and "metric" in doc:
            docs.append((f"{path}:{i + 1}", doc))
    if not docs and wrapper is None:
        errors.append(f"{path}: no bench JSON lines found")
    return docs


def validate_file(path: str, require_stages: bool = False) -> List[str]:
    errors: List[str] = []
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    if not text.strip():
        return [f"{path}: empty"]
    for where, doc in extract_docs(text, errors, path):
        validate_doc(doc, errors, where, require_stages)
    return errors


def main(argv: List[str]) -> int:
    require = "--require-stages" in argv
    files = [a for a in argv if not a.startswith("--")]
    if not files:
        files = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    if not files:
        print("no BENCH_*.json files found")
        return 1
    all_errors: List[str] = []
    for path in files:
        all_errors.extend(validate_file(path, require))
    for err in all_errors:
        print(f"SCHEMA ERROR: {err}")
    print(
        f"checked {len(files)} file(s): "
        + ("FAIL" if all_errors else "ok")
    )
    return 1 if all_errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
