#!/usr/bin/env python
"""The multiquery64 predicate-class-bucketing experiment (ROADMAP 6).

``docs/multiquery64.md`` claims the 64-query stack's throughput bound
is the per-(event, query) state advance — pure HBM traffic LINEAR in
Q — and that everything else amortizes. If that linear-HBM hypothesis
holds, two things must be measurably true:

1. **scaling**: one Q-query stack's event rate satisfies
   ``rate(Q) * Q ~= const`` once Q is past the amortized per-event
   overhead (tape expansion, masking, ts reconstruction);
2. **bucketing is a wash**: splitting the 64 queries into B stacked
   plans of 64/B — bucketed by PREDICATE CLASS of the first element
   (first-literal id mod B), so each bucket is a narrower [Q/B] lane
   advance over the same events — does not beat the single 64-stack:
   the total lane-advances are identical, and bucketing only adds
   per-plan fixed overhead (B tape expansions, B dispatch chains).

If instead bucketing WINS, the per-event fixed costs — not the linear
[Q, E] advance — were the real bound and the doc's analysis is wrong.

This script measures both, resident-replay mode, counts-only, identical
synthetic stream (bench.make_batches), and prints one JSON line per
variant. Verdict and measured numbers are recorded in
docs/multiquery64.md.

Env knobs: EXP_EVENTS (default 500_000), EXP_BATCH (default 131_072),
EXP_RUNS (median-of-N replays, default 3), EXP_VARIANTS (comma subset).

Usage:
    JAX_PLATFORMS=cpu python scripts/experiment_mq64.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)
os.environ.setdefault(
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2"
)


def _queries():
    """The bench's exact 64 two-step patterns (bench._config_cql)."""
    out = []
    for q in range(64):
        a, b = q % 50, (q * 7 + 1) % 50
        out.append(
            (
                a,
                f"from every s1 = inputStream[id == {a}] -> "
                f"s2 = inputStream[id == {b}] "
                f"select s1.timestamp as t1, s2.timestamp as t2 "
                f"insert into m{q}",
            )
        )
    return out


def _variants():
    qs = _queries()
    v = {
        # scaling sweep: one stacked plan of the first Q queries
        "stack8": [[t for _, t in qs[:8]]],
        "stack16": [[t for _, t in qs[:16]]],
        "stack32": [[t for _, t in qs[:32]]],
        "stack64": [[t for _, t in qs]],
    }
    # predicate-class bucketing: first-element literal id mod B
    for buckets in (4, 8):
        groups = [[] for _ in range(buckets)]
        for a, text in qs:
            groups[a % buckets].append(text)
        v[f"bucketed{buckets}x{64 // buckets}"] = [
            g for g in groups if g
        ]
    return v


def run_variant(name, plan_texts, n_events, batch, n_runs):
    import bench
    from flink_siddhi_tpu import CEPEnvironment
    from flink_siddhi_tpu.compiler.config import EngineConfig
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.replay import ResidentReplay
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    env = CEPEnvironment(batch_size=batch, time_mode="processing")
    schema = StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ],
        shared_strings=env.shared_strings,
    )
    batches = bench.make_batches(
        n_events, batch, schema, "inputStream", n_ids=50
    )
    src = BatchSource("inputStream", schema, iter(batches))
    # fixed compile-window cap across ALL variants (the 64-stack picks
    # this cap automatically at Q>=16; pinning it keeps the per-chunk
    # dispatch count comparable between stack and bucket variants)
    ecfg = EngineConfig(max_tape_capacity=131_072)
    t0 = time.perf_counter()
    plans = [
        compile_plan(
            "; ".join(texts), {"inputStream": schema},
            plan_id=f"{name}:{i}", config=ecfg,
        )
        for i, texts in enumerate(plan_texts)
    ]
    compile_s = time.perf_counter() - t0
    job = Job(
        plans, [src], batch_size=batch, time_mode="processing",
        retain_results=False,
    )
    rep = ResidentReplay(job)
    rep.stage()
    t0 = time.perf_counter()
    rep.run()
    job.flush()
    runs = [time.perf_counter() - t0]
    for _ in range(n_runs - 1):
        runs.append(rep.rerun())
    elapsed = float(np.median(runs))
    n_queries = sum(len(t) for t in plan_texts)
    rate = rep.total_events / max(elapsed, 1e-9)
    return {
        "variant": name,
        "plans": len(plan_texts),
        "queries": n_queries,
        "events": n_events,
        "elapsed_s": round(elapsed, 3),
        "runs_elapsed_s": [round(t, 3) for t in runs],
        "events_per_sec": round(rate, 1),
        "query_events_per_sec": round(rate * n_queries, 1),
        "compile_s": round(compile_s, 2),
        "stage_s": round(rep.stage_seconds, 2),
        "emitted_total": int(sum(job.emitted_counts.values())),
    }


def main() -> int:
    n_events = int(os.environ.get("EXP_EVENTS", 500_000))
    batch = int(os.environ.get("EXP_BATCH", 131_072))
    n_runs = max(int(os.environ.get("EXP_RUNS", 3)), 1)
    variants = _variants()
    want = os.environ.get("EXP_VARIANTS")
    if want:
        keys = [k for k in want.split(",") if k in variants]
    else:
        keys = list(variants)
    results = []
    for name in keys:
        r = run_variant(name, variants[name], n_events, batch, n_runs)
        results.append(r)
        print(json.dumps(r), flush=True)
    # cross-variant sanity: every variant advancing all 64 queries over
    # the same stream must produce the same match counts
    full = [r for r in results if r["queries"] == 64]
    if len(full) > 1:
        counts = {r["emitted_total"] for r in full}
        if len(counts) != 1:
            print(
                f"MATCH-COUNT MISMATCH across 64-query variants: "
                f"{sorted((r['variant'], r['emitted_total']) for r in full)}",
                file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
