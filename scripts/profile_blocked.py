"""Sweep the blocked window step's tile/chunk knobs on the real chip:
time the jitted step_acc (piped) for the window_groupby bench shape."""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

import jax

from bench import build_job


def run_one(tile, chunk, batch=524288):
    os.environ["FST_BLOCKED_TILE"] = str(tile)
    os.environ["FST_BLOCKED_CHUNK"] = str(chunk)
    job = build_job("window_groupby", batch, batch)
    rt = list(job._plans.values())[0]
    job._pull_sources()
    ready = job._release_ready()
    job._epoch_ms = min(int(b.timestamps.min()) for b in ready)
    # the SAME staging half the streaming/resident paths use (capacity
    # bucketing, interning side effects), so the sweep times the tape
    # shape the benchmark actually compiles against
    wire = job._stage_tape(rt, ready)
    states, acc = rt.states, rt.acc
    states = rt.plan.grow_state(states)
    states, acc = rt.jitted_acc(states, acc, wire)  # compile+warm
    jax.block_until_ready(states)
    N = 8
    t0 = time.perf_counter()
    for _ in range(N):
        states, acc = rt.jitted_acc(states, acc, wire)
    jax.block_until_ready(states)
    piped = (time.perf_counter() - t0) / N
    print(
        f"tile={tile:5d} chunk={chunk:3d}: {piped*1e3:7.1f}ms/step "
        f"({batch/piped/1e6:5.2f}M ev/s)"
    )


def main():
    for tile, chunk in (
        (512, 16), (512, 64), (512, 128), (1024, 16), (1024, 64),
        (2048, 16), (2048, 32), (256, 64),
    ):
        run_one(tile, chunk)


if __name__ == "__main__":
    main()
