#!/usr/bin/env python
"""Dispatch-leg microprofiler: enqueue vs device-wall, per dispatch.

The round-6 measurement named ``dispatch`` the limiting leg (the
jitted device step: 2.289 s of the 2.56 s data-path window). This
profiler decomposes that leg WITHOUT a full bench run, for both the
historical per-batch loop and the fused scan-of-microbatches segment
dispatch (``Job.fused_segment_len``):

* ``enqueue``     — host time to hand one dispatch to the device
                    (segment stack + H2D device_put + jit-call
                    return), from the runtime's own
                    ``dispatch.enqueue`` histogram;
* ``device_wall`` — residual device execution measured by the driver
                    blocking on the dispatch ticket right after the
                    cycle that enqueued it (the serialization is the
                    point: the leg is isolated, pipelining is off).

A warm pass runs the whole stream first (every XLA executable —
fused scan shapes, padded trailing partial, drain packs — compiles
there), then engine state resets rerun-style and the measured pass
reports per-leg p50/p99 plus dispatches-per-1k-batches, so a
per-batch vs fused A/B is two invocations of this script.

Env knobs:
  PROF_CONFIG    bench config (default: headline; bench._config_cql)
  PROF_EVENTS    total events staged (default 2_000_000)
  PROF_BATCH     micro-batch size (default 65_536)
  PROF_SEGMENT   fused segment length (default 8; 0/1 = per-batch)

Usage:
    JAX_PLATFORMS=cpu python scripts/profile_dispatch.py
    JAX_PLATFORMS=cpu PROF_SEGMENT=0 python scripts/profile_dispatch.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)


def main() -> int:
    config = os.environ.get("PROF_CONFIG", "headline")
    n_events = int(os.environ.get("PROF_EVENTS", 2_000_000))
    batch = int(os.environ.get("PROF_BATCH", 65_536))
    seg = int(os.environ.get("PROF_SEGMENT", 8))

    import jax

    import bench
    from flink_siddhi_tpu.telemetry import (
        LatencyHistogram,
        MetricsRegistry,
    )
    from flink_siddhi_tpu.telemetry.tracing import TraceSampler

    job = bench.build_job(config, n_events, batch)
    job.fused_segment_len = seg if seg > 1 else None
    job.drain_interval_ms = None  # isolate dispatch: no interval drains
    batches = bench.drain_source_batches(job)

    # warm pass: compiles land here, off the profile
    bench.re_source(job, batches)
    while not job.finished:
        job.run_cycle()
    job.flush()
    job.reset_engine_state()  # the shared rerun recipe
    job.telemetry = MetricsRegistry()
    job.tracer = TraceSampler(job.telemetry, sample_every=0)

    # measured pass: block on every dispatch ticket as it appears —
    # device_wall is what the pipeline normally hides
    wall = LatencyHistogram()
    rts = list(job._plans.values())
    bench.re_source(job, batches)
    t0 = time.perf_counter()
    while not job.finished:
        job.run_cycle()
        for rt in rts:
            while rt.tickets:
                t1 = time.perf_counter()
                jax.block_until_ready(rt.tickets.popleft())
                wall.record_seconds(time.perf_counter() - t1)
    job.flush()
    elapsed = time.perf_counter() - t0

    snap = job.telemetry.snapshot()
    counters = snap["counters"]
    enq = job.telemetry.histogram("dispatch.enqueue")
    dispatches = enq.count
    n_batches = counters.get("fusion.batches", 0) or dispatches
    out = {
        "config": config,
        "events": n_events,
        "batch": batch,
        "segment_len": seg,
        "mode": "fused" if seg > 1 else "per-batch",
        "dispatches": dispatches,
        "batches": n_batches,
        "dispatches_per_1k_batches": round(
            1000.0 * dispatches / max(n_batches, 1), 1
        ),
        "h2d_uploads": counters.get("fusion.h2d_uploads", 0),
        "h2d_overlapped": counters.get("fusion.h2d_overlapped", 0),
        "elapsed_s": round(elapsed, 3),
        "legs": {},
    }
    for name, h in (("enqueue", enq), ("device_wall", wall)):
        if not h.count:
            continue
        out["legs"][name] = {
            "count": h.count,
            "p50_ms": h.percentile_ms(50),
            "p99_ms": h.percentile_ms(99),
        }
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
