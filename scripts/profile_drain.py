#!/usr/bin/env python
"""Drain-leg microprofiler: iterate on tail latency WITHOUT a bench run.

Runs N drains against a live job under a configurable device backlog
and prints per-leg p50/p99 from the runtime's own ``drain.*``
histograms (runtime/executor.py records every completed drain's
wait_ready / queue / fetch_meta / fetch / decode / emit_lag / total /
staleness / transport legs) — the decomposition the tail-aware drain
scheduler attacks, produced in seconds instead of a full bench cycle.

Each profiled drain: dispatch ``PROF_BACKLOG_CYCLES`` device cycles
WITHOUT draining (the backlog the count-prefix readiness gate must
ride behind), then issue one drain request and poll it to completion.

Env knobs:
  PROF_CONFIG          bench config (default: filter — a row-heavy
                       data path; see bench._config_cql)
  PROF_EVENTS          total events staged (default 2_000_000)
  PROF_BATCH           micro-batch size (default 65_536)
  PROF_DRAINS          profiled drains (default 30)
  PROF_BACKLOG_CYCLES  device cycles dispatched per drain (default 2)
  PROF_SINK            1 = attach a columnar sink (data-path drains,
                       the default); 0 = counts-only drains

Usage:
    JAX_PLATFORMS=cpu python scripts/profile_drain.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)

LEGS = (
    "drain.total",
    "drain.staleness",
    "drain.wait_ready",
    "drain.queue",
    "drain.fetch_meta",
    "drain.fetch",
    "drain.decode",
    "drain.emit_lag",
    "drain.transport",
)


def main() -> int:
    config = os.environ.get("PROF_CONFIG", "filter")
    n_events = int(os.environ.get("PROF_EVENTS", 2_000_000))
    batch = int(os.environ.get("PROF_BATCH", 65_536))
    n_drains = int(os.environ.get("PROF_DRAINS", 30))
    backlog = int(os.environ.get("PROF_BACKLOG_CYCLES", 2))
    want_sink = os.environ.get("PROF_SINK", "1") == "1"

    import bench

    job = bench.build_job(config, n_events, batch)
    job.drain_interval_ms = None  # manual drains only: we ARE the pacer
    rows = {"n": 0}
    if want_sink:
        class _Sink:
            def accept_columns(self, ts, cols):
                rows["n"] += len(ts)

        for rt in job._plans.values():
            for sid in rt.plan.output_streams():
                job.add_sink(sid, _Sink())

    # warm: a couple of cycles + one full drain compiles every program
    for _ in range(2):
        job.run_cycle()
    job.drain_outputs(wait=True)
    job.telemetry = type(job.telemetry)()  # fresh registry: warm excluded
    from flink_siddhi_tpu.telemetry.tracing import TraceSampler

    job.tracer = TraceSampler(job.telemetry, sample_every=0)

    done = 0
    t0 = time.perf_counter()
    while done < n_drains and not job.finished:
        for _ in range(backlog):
            if job.finished:
                break
            job.run_cycle()
        for rt in job._plans.values():
            job._drain_request(rt)
            job._drain_poll(rt, block=True)
        done += 1
    elapsed = time.perf_counter() - t0

    out = {
        "config": config,
        "drains": done,
        "backlog_cycles": backlog,
        "batch": batch,
        "data_path": want_sink,
        "rows_emitted": rows["n"],
        "elapsed_s": round(elapsed, 3),
        "legs": {},
    }
    for name in LEGS:
        h = job.telemetry.histogram(name)
        if not h.count:
            continue
        out["legs"][name] = {
            "count": h.count,
            "p50_ms": h.percentile_ms(50),
            "p99_ms": h.percentile_ms(99),
        }
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
