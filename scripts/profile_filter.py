"""Phase-level steady-state profile of a bench config's hot loop.

Times, per cycle: source pull + reorder, wire-tape build, lazy-ring push,
jit dispatch, ticket backpressure wait, drain poll — the components of
Job.run_cycle — plus the end flush. Prints a per-phase ms/cycle table so
the host-vs-device split is visible.

Usage: BENCH_CONFIG=filter BENCH_EVENTS=4000000 python scripts/profile_filter.py
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

import numpy as np  # noqa: E402

import bench  # noqa: E402


def main():
    config = os.environ.get("BENCH_CONFIG", "filter")
    n_events = int(os.environ.get("BENCH_EVENTS", 4_000_000))
    batch = int(os.environ.get("BENCH_BATCH", 524_288))
    job = bench.build_job(config, n_events, batch)

    import jax

    from flink_siddhi_tpu.runtime import executor as ex

    phases = {}

    def timed(name, fn):
        def wrapper(*a, **kw):
            t0 = time.perf_counter()
            out = fn(*a, **kw)
            phases[name] = phases.get(name, 0.0) + (time.perf_counter() - t0)
            return out
        return wrapper

    # instrument the job's phases
    job._pull_sources = timed("pull_sources", job._pull_sources)
    job._release_ready = timed("release_ready", job._release_ready)
    orig_step = job._step_plan

    tape_t = {"t": 0.0}
    orig_tape = ex.build_wire_tape

    def tape_timed(*a, **kw):
        t0 = time.perf_counter()
        out = orig_tape(*a, **kw)
        tape_t["t"] += time.perf_counter() - t0
        return out

    ex.build_wire_tape = tape_timed

    dispatch_t = {"t": 0.0}
    wait_t = {"t": 0.0}

    def step_timed(rt, ready):
        # wrap jitted_acc & ticket wait
        orig_acc = rt.jitted_acc

        def acc_timed(*a, **kw):
            t0 = time.perf_counter()
            out = orig_acc(*a, **kw)
            dispatch_t["t"] += time.perf_counter() - t0
            return out

        rt.jitted_acc = acc_timed
        orig_block = jax.block_until_ready

        def block_timed(x):
            t0 = time.perf_counter()
            out = orig_block(x)
            wait_t["t"] += time.perf_counter() - t0
            return out

        jax.block_until_ready = block_timed
        t0 = time.perf_counter()
        out = orig_step(rt, ready)
        phases["step_plan_total"] = (
            phases.get("step_plan_total", 0.0) + (time.perf_counter() - t0)
        )
        jax.block_until_ready = orig_block
        rt.jitted_acc = orig_acc
        return out

    job._step_plan = step_timed
    orig_poll = job._drain_poll

    def poll_timed(*a, **kw):
        t0 = time.perf_counter()
        out = orig_poll(*a, **kw)
        phases["drain_poll"] = (
            phases.get("drain_poll", 0.0) + (time.perf_counter() - t0)
        )
        return out

    job._drain_poll = poll_timed

    warmup = 3
    cycles = 0
    t0 = time.perf_counter()
    counted_at = 0
    t_meas = t0
    while not job.finished:
        job.run_cycle()
        cycles += 1
        if cycles == warmup:
            phases.clear()
            tape_t["t"] = 0.0
            dispatch_t["t"] = 0.0
            wait_t["t"] = 0.0
            t_meas = time.perf_counter()
            counted_at = job.processed_events
    tf0 = time.perf_counter()
    job.flush()
    flush_t = time.perf_counter() - tf0
    elapsed = time.perf_counter() - t_meas
    measured = job.processed_events - counted_at
    n_cyc = max(cycles - warmup, 1)
    print(f"config={config} events={measured} cycles={n_cyc} "
          f"elapsed={elapsed:.3f}s  ev/s={measured/elapsed:,.0f}")
    print(f"{'phase':24s} {'total_s':>9s} {'ms/cycle':>9s}")
    rows = dict(phases)
    rows["wire_tape"] = tape_t["t"]
    rows["jit_dispatch"] = dispatch_t["t"]
    rows["ticket_wait"] = wait_t["t"]
    rows["flush_end"] = flush_t
    for k, v in sorted(rows.items(), key=lambda kv: -kv[1]):
        print(f"{k:24s} {v:9.3f} {1e3*v/n_cyc:9.2f}")
    acct = sum(v for k, v in rows.items()
               if k not in ("step_plan_total",))
    print(f"{'accounted':24s} {acct:9.3f}  (wall {elapsed:.3f})")


if __name__ == "__main__":
    main()
