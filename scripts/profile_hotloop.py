"""Profile the headline bench hot loop on the real TPU: where does each
cycle's wall time go? (host tape build vs transfer vs device step vs
fetches). Run: python scripts/profile_hotloop.py [n_events] [batch]."""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

import jax
import numpy as np

from bench import build_job, make_batches


def main():
    n_events = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 131_072

    config = os.environ.get("BENCH_CONFIG", "headline")
    t0 = time.perf_counter()
    job = build_job(config, n_events, batch)
    print(f"build_job: {time.perf_counter() - t0:.2f}s")

    # phase timers, monkeypatched around the executor internals
    from flink_siddhi_tpu.runtime import executor as ex
    from flink_siddhi_tpu.runtime import tape as tp

    timers = {"pull": 0.0, "release": 0.0, "tape": 0.0, "step": 0.0,
              "drain": 0.0, "decode": 0.0}
    orig_pull = job._pull_sources
    orig_release = job._release_ready
    orig_tape = tp.build_wire_tape
    orig_drain = job._drain_plan

    orig_req, orig_poll = job._drain_request, job._drain_poll

    def timed_drain(rt):
        t = time.perf_counter()
        r = orig_drain(rt)
        timers["drain"] += time.perf_counter() - t
        return r

    def timed_req(rt):
        t = time.perf_counter()
        r = orig_req(rt)
        timers["drain"] += time.perf_counter() - t
        return r

    def timed_poll(rt, block=False, limit=0):
        t = time.perf_counter()
        r = orig_poll(rt, block=block, limit=limit)
        timers["drain"] += time.perf_counter() - t
        return r

    job._drain_plan = timed_drain
    job._drain_request = timed_req
    job._drain_poll = timed_poll

    def timed_pull():
        t = time.perf_counter(); r = orig_pull(); timers["pull"] += time.perf_counter() - t; return r

    def timed_release():
        t = time.perf_counter(); r = orig_release(); timers["release"] += time.perf_counter() - t; return r

    def timed_tape(*a, **k):
        t = time.perf_counter(); r = orig_tape(*a, **k); timers["tape"] += time.perf_counter() - t; return r

    job._pull_sources = timed_pull
    job._release_ready = timed_release
    ex.build_wire_tape = timed_tape

    rt = list(job._plans.values())[0]
    orig_decode = rt.plan.drain_decode

    def timed_decode(counts, data, **kw):
        t = time.perf_counter()
        r = orig_decode(counts, data, **kw)
        timers["decode"] += time.perf_counter() - t
        return r

    rt.plan.drain_decode = timed_decode
    orig_acc = rt.jitted_acc

    def timed_acc(states, acc, wire):
        t = time.perf_counter()
        out = orig_acc(states, acc, wire)
        timers["step"] += time.perf_counter() - t  # dispatch (async) time
        return out

    rt.jitted_acc = timed_acc

    sync_each = bool(os.environ.get("PROF_SYNC"))
    warmup = 3
    cycles = 0
    t_start = time.perf_counter()
    t_meas = t_start
    counted = 0
    cycle_walls = []
    while not job.finished:
        c0 = time.perf_counter()
        job.run_cycle()
        if sync_each:
            jax.block_until_ready(rt.states)
        dt = time.perf_counter() - c0
        cycle_walls.append(dt)
        if sync_each and cycles < 20:
            print(f"  cycle {cycles}: {dt*1e3:.1f}ms")
        cycles += 1
        if cycles == warmup:
            t_meas = time.perf_counter()
            counted = job.processed_events
            for k in timers:
                timers[k] = 0.0
    t_sync0 = time.perf_counter()
    jax.block_until_ready(rt.states)
    sync_tail = time.perf_counter() - t_sync0
    t_flush0 = time.perf_counter()
    job.flush()
    flush_t = time.perf_counter() - t_flush0
    elapsed = time.perf_counter() - t_meas
    measured = job.processed_events - counted
    walls = np.array(cycle_walls[warmup:])
    print(f"cycles: {cycles}, measured events: {measured}")
    print(f"elapsed (post-warmup): {elapsed:.3f}s -> {measured/elapsed:,.0f} ev/s")
    print(f"device sync tail: {sync_tail:.3f}s  flush: {flush_t:.3f}s")
    print("phase totals (post-warmup):",
          {k: round(v, 3) for k, v in timers.items()})
    print(f"cycle wall: mean {walls.mean()*1e3:.1f}ms p50 "
          f"{np.percentile(walls,50)*1e3:.1f}ms p99 "
          f"{np.percentile(walls,99)*1e3:.1f}ms max {walls.max()*1e3:.1f}ms")
    print("matches:", len(job.results("matches")))


if __name__ == "__main__":
    main()
