"""Attribute resident-replay wall time: per-segment scan execution,
drain request/poll, final drain, flush. Run on the real chip."""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

import jax

from bench import build_job
from flink_siddhi_tpu.runtime.replay import ResidentReplay


def main():
    config = os.environ.get("BENCH_CONFIG", "headline")
    n = int(os.environ.get("BENCH_EVENTS", 10_485_760))
    batch = int(os.environ.get("BENCH_BATCH", 1_048_576))
    seg = os.environ.get("BENCH_SEGMENT_CYCLES")
    job = build_job(config, n, batch)
    rep = ResidentReplay(job, segment_cycles=int(seg) if seg else None)
    t0 = time.perf_counter()
    rep.stage()
    print(f"stage: {time.perf_counter()-t0:.2f}s "
          f"(events={rep.total_events})")
    for pid, st in rep._staged.items():
        rt = job._plans[pid]
        print(f"plan {pid}: {len(st['segments'])} segments")
        for i, s in enumerate(st["segments"]):
            t0 = time.perf_counter()
            rt.states, rt.acc = st["scan"](rt.states, rt.acc, s)
            t_disp = time.perf_counter() - t0
            jax.block_until_ready(rt.states)
            t_exec = time.perf_counter() - t0
            rt.acc_dirty = True
            t0 = time.perf_counter()
            job._drain_request(rt)
            job._drain_poll(rt)
            t_drain = time.perf_counter() - t0
            print(f"  seg {i}: dispatch {t_disp*1e3:7.1f}ms  "
                  f"exec {t_exec*1e3:7.1f}ms  drainreq {t_drain*1e3:6.1f}ms")
        t0 = time.perf_counter()
        job._drain_poll(rt, block=True)
        print(f"  final drain: {(time.perf_counter()-t0)*1e3:.1f}ms")
    t0 = time.perf_counter()
    job.flush()
    print(f"flush: {(time.perf_counter()-t0)*1e3:.1f}ms")


if __name__ == "__main__":
    main()
