"""Isolate the device step cost: compute vs tunnel latency.

Times the jitted step_acc at several tape capacities, both per-call-synced
(compute + RTT) and pipelined-chain (N async calls, one final sync).
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

import jax
import numpy as np

from bench import build_job


def bench_capacity(batch):
    job = build_job("headline", batch, batch)
    rt = list(job._plans.values())[0]
    job._pull_sources()
    ready = job._release_ready()
    from flink_siddhi_tpu.runtime.tape import build_wire_tape

    wire, _ = build_wire_tape(
        rt.plan.spec, ready, int(ready[0].timestamps.min()), rt.wire_kinds
    )
    states, acc = rt.states, rt.acc
    # warm compile
    t0 = time.perf_counter()
    states, acc = rt.jitted_acc(states, acc, wire)
    jax.block_until_ready(states)
    compile_or_warm = time.perf_counter() - t0

    # synced: each call waits
    N = 10
    t0 = time.perf_counter()
    for _ in range(N):
        states, acc = rt.jitted_acc(states, acc, wire)
        jax.block_until_ready(states)
    synced = (time.perf_counter() - t0) / N

    # pipelined: N dispatches, one sync
    t0 = time.perf_counter()
    for _ in range(N):
        states, acc = rt.jitted_acc(states, acc, wire)
    jax.block_until_ready(states)
    piped = (time.perf_counter() - t0) / N

    print(
        f"E={batch:>7}: warm {compile_or_warm*1e3:7.1f}ms  "
        f"synced {synced*1e3:7.1f}ms/step ({batch/synced/1e6:5.2f}M ev/s)  "
        f"piped {piped*1e3:7.1f}ms/step ({batch/piped/1e6:5.2f}M ev/s)"
    )


def main():
    for batch in (16384, 65536, 131072, 262144, 524288):
        bench_capacity(batch)


if __name__ == "__main__":
    main()
