#!/usr/bin/env python3
"""Tier-1 static analysis gate: fstlint + plancheck over the query zoo.

Runs alongside scripts/check_bench_schema.py in the tier-1 lane
(tests/test_static_analysis.py imports and invokes this; CI can also
call it directly). Exits nonzero on:

* any unsuppressed fstlint finding over the repo surface
  (flink_siddhi_tpu/, bench.py, scripts/),
* any stale / reason-less / REVIEWME baseline.toml suppression,
* any plancheck issue over the window/pattern/join/multiquery zoo
  (full tier: static NFA/stack checks + eval_shape schema/donation
  checks + the deep inert-tape execution; ``--fast`` skips deep).

docs/static_analysis.md is the rule and invariant reference.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--skip-lint", action="store_true")
    ap.add_argument("--skip-plancheck", action="store_true")
    ap.add_argument(
        "--fast",
        action="store_true",
        help="skip the deep inert-tape zoo execution (trace checks "
        "still run; the tier-1 lane uses this to protect its wall-"
        "clock budget — CI outside the lane runs full deep)",
    )
    args = ap.parse_args(argv)
    failed = False

    if not args.skip_lint:
        from flink_siddhi_tpu.analysis import fstlint

        print("== fstlint ==", flush=True)
        rc = fstlint.main([])
        if rc != 0:
            failed = True
            print(f"fstlint: FAILED (exit {rc})")
        else:
            print("fstlint: clean")

    if not args.skip_plancheck:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from flink_siddhi_tpu.analysis.plancheck import (
            PlanCheckError,
            verify_plan,
        )
        from flink_siddhi_tpu.analysis.zoo import compile_zoo

        print("== plancheck (query zoo) ==", flush=True)
        try:
            plans = compile_zoo()
        except Exception as e:  # noqa: BLE001 — a zoo compile failure IS the finding
            print(f"zoo compile FAILED: {type(e).__name__}: {e}")
            return 1
        for name, plan in plans:
            try:
                verify_plan(plan, trace=True, deep=not args.fast)
                print(f"  {name}: ok")
            except PlanCheckError as e:
                failed = True
                print(f"  {name}: FAILED")
                for issue in e.issues:
                    print(f"    {issue.render()}")

    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
