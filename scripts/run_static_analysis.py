#!/usr/bin/env python3
"""Tier-1 static analysis gate: fstlint + plancheck + admission.

Runs alongside scripts/check_bench_schema.py in the tier-1 lane
(tests/test_static_analysis.py imports and invokes this; CI can also
call it directly). Exits nonzero on:

* any unsuppressed fstlint finding over the repo surface
  (flink_siddhi_tpu/, bench.py, scripts/),
* any stale / reason-less / REVIEWME baseline.toml suppression,
* any plancheck issue over the window/pattern/join/multiquery zoo
  (full tier: static NFA/stack checks + eval_shape schema/donation
  checks + the deep inert-tape execution; ``--fast`` skips deep),
* any admission failure (analysis/admit.py): a legitimate zoo entry
  NOT admitted with finite bounds under the default budgets, or a
  HOSTILE zoo entry not rejected with its exact ADM rule id.

docs/static_analysis.md is the rule and invariant reference.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--skip-lint", action="store_true")
    ap.add_argument("--skip-plancheck", action="store_true")
    ap.add_argument("--skip-admission", action="store_true")
    ap.add_argument(
        "--fast",
        action="store_true",
        help="skip the deep inert-tape zoo execution (trace checks "
        "still run; the tier-1 lane uses this to protect its wall-"
        "clock budget — CI outside the lane runs full deep)",
    )
    args = ap.parse_args(argv)
    failed = False

    if not args.skip_lint:
        from flink_siddhi_tpu.analysis import fstlint

        print("== fstlint ==", flush=True)
        rc = fstlint.main([])
        if rc != 0:
            failed = True
            print(f"fstlint: FAILED (exit {rc})")
        else:
            print("fstlint: clean")

    plans = None  # zoo compiled once, shared by plancheck + admission

    def _zoo():
        nonlocal plans
        if plans is None:
            from flink_siddhi_tpu.analysis.zoo import compile_zoo

            plans = compile_zoo()
        return plans

    if not args.skip_plancheck:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from flink_siddhi_tpu.analysis.plancheck import (
            PlanCheckError,
            verify_plan,
        )

        print("== plancheck (query zoo) ==", flush=True)
        try:
            plans = _zoo()
        except Exception as e:  # noqa: BLE001 — a zoo compile failure IS the finding
            print(f"zoo compile FAILED: {type(e).__name__}: {e}")
            return 1
        for name, plan in plans:
            try:
                verify_plan(plan, trace=True, deep=not args.fast)
                print(f"  {name}: ok")
            except PlanCheckError as e:
                failed = True
                print(f"  {name}: FAILED")
                for issue in e.issues:
                    print(f"    {issue.render()}")

    if not args.skip_admission:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from flink_siddhi_tpu.analysis.admit import (
            DEFAULT_BUDGETS,
            analyze_plan,
        )

        # --fast (the tier-1 lane): static tier only — every zoo
        # entry's cost_info() hooks must collect clean, no eval_shape,
        # no hostile compiles (tests/test_admit.py carries the full
        # budget/signature/hostile contract in tier-1 already). Direct
        # runs add the deep tier + the full hostile zoo.
        tier = "static tier" if args.fast else "full, default budgets"
        print(f"== admission (query zoo, {tier}) ==", flush=True)
        try:
            plans = _zoo()
        except Exception as e:  # noqa: BLE001
            print(f"zoo compile FAILED: {type(e).__name__}: {e}")
            return 1
        for name, plan in plans:
            rep = analyze_plan(
                plan,
                budgets=None if args.fast else DEFAULT_BUDGETS,
                deep=not args.fast,
            )
            if not rep.admitted:
                failed = True
                print(f"  {name}: NOT ADMITTED")
                for issue in rep.findings:
                    print(f"    {issue.render()}")
            elif args.fast:
                print(f"  {name}: ok (amp={rep.amplification})")
            else:
                print(
                    f"  {name}: admitted (state={rep.state_bytes}B "
                    f"acc={rep.acc_bytes}B amp={rep.amplification} "
                    f"sig={rep.signature[:12]})"
                )

        if not args.fast:
            from flink_siddhi_tpu.analysis.zoo import (
                compile_hostile,
                hostile_budgets,
            )

            print("== admission (hostile zoo) ==", flush=True)
            try:
                hostile = compile_hostile()
            except Exception as e:  # noqa: BLE001
                print(
                    f"hostile zoo compile FAILED: "
                    f"{type(e).__name__}: {e}"
                )
                return 1
            for name, plan, rule, profile in hostile:
                rep = analyze_plan(
                    plan, budgets=hostile_budgets(profile)
                )
                got = [i.rule for i in rep.findings]
                if not rep.admitted and rule in got:
                    print(f"  {name}: rejected by {rule} ({profile})")
                else:
                    failed = True
                    print(
                        f"  {name}: FAILED — expected rejection by "
                        f"{rule} under {profile} budgets, got "
                        f"{got or 'ADMITTED'}"
                    )

    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
