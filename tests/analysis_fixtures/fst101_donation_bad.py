"""KNOWN-BAD fixture: the PR 7 checkpoint-restore donation-aliasing
bug, reconstructed. On CPU, ``jax.device_put`` zero-copies aligned
numpy, so device state silently aliases the unpickled snapshot's
buffers; the donated step then frees them in place and the retained
alias reads garbage. fstlint must flag the post-donation read (FST101).

Lint fixture only — never imported by tests, only parsed.
"""

import jax


def step(states, batch):
    return {"w": states["w"] + batch}


jitted_step = jax.jit(step, donate_argnums=(0,))


def restore_and_run(snapshot_arrays, batches):
    states = jax.device_put(snapshot_arrays)
    snap = states  # alias captured BEFORE the donating call
    for b in batches:
        states = jitted_step(states, b)
    # BAD: snap still points at the donated (freed/reused) buffers
    return snap["w"]


def donate_put(x, batches):
    y = jax.device_put(x, donate=True)
    # BAD: x's buffer was donated to the transfer above
    return x + y
