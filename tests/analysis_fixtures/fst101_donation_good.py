"""Corrected twin of fst101_donation_bad.py: the restore path makes an
OWNED copy before the donating step ever runs (the actual PR 7 fix in
runtime/checkpoint.py), so no binding outlives its buffer. fstlint must
stay quiet."""

import jax
import jax.numpy as jnp


def step(states, batch):
    return {"w": states["w"] + batch}


jitted_step = jax.jit(step, donate_argnums=(0,))


def restore_and_run(snapshot_arrays, batches):
    states = jax.device_put(snapshot_arrays)
    # owned on-device copy: nothing aliases the snapshot's numpy
    states = jax.tree.map(lambda a: a + 0, states)
    snap = jax.device_get(states)  # host copy, not an alias
    for b in batches:
        states = jitted_step(states, b)
    return states["w"], snap


def donate_put(x, batches):
    y = jax.device_put(jnp.asarray(x), donate=True)
    return y + 1
