"""KNOWN-BAD fixture: host syncs inside an annotated hot-path function
— every one is a blocking device round trip per micro-batch (or a
TracerBoolConversionError once the function is jitted). fstlint must
flag all four (FST102). Lint fixture only."""

import numpy as np


# fst:hotpath device=state,tape
def step(state, tape):
    total = state["acc"] + tape["vals"]
    if total > 0:  # BAD: branching on a device value
        total = total + 1
    rate = float(total)  # BAD: float() forces a fetch
    dump = np.asarray(total)  # BAD: implicit device->host transfer
    one = total.item()  # BAD: per-call round trip
    return {"acc": total}, (rate, dump, one)
