"""Corrected twin of fst102_hostsync_bad.py: the branch becomes a
device-side ``jnp.where``, host materialization happens OUTSIDE the
hot path (the drain boundary), and static shape metadata reads stay
legal. fstlint must stay quiet."""

import jax.numpy as jnp
import numpy as np


# fst:hotpath device=state,tape
def step(state, tape):
    total = state["acc"] + tape["vals"]
    total = jnp.where(total > 0, total + 1, total)
    width = int(total.shape[0])  # static metadata: no sync
    return {"acc": total}, width


def drain(acc):
    # the ONE intended sync point, outside any hot-path annotation
    return np.asarray(acc), float(acc.sum())
