"""KNOWN-BAD fixture: the PR 8 ``drain_interval_ms=0`` bug,
reconstructed — ``x or default`` on a numeric config where 0 is a
legitimate value ("tightest visibility") silently rounds 0 up to the
default. fstlint must flag both sites (FST103). Lint fixture only."""


class Job:
    def __init__(self):
        self.drain_interval_ms = None
        self.fused_segment_len = None


def partial_age_budget_s(job):
    # BAD: drain_interval_ms=0 means "dispatch immediately" but `or`
    # rounds it up to 500ms
    age_ms = job.drain_interval_ms or 500.0
    return age_ms / 1e3


def segment_depth(job):
    # BAD: a 0 segment length silently becomes 8
    return job.fused_segment_len or 8
