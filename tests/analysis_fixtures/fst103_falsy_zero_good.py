"""Corrected twin of fst103_falsy_zero_bad.py: explicit ``is None``
defaulting (the actual PR 8 review fix) — 0 stays 0. fstlint must stay
quiet."""


class Job:
    def __init__(self):
        self.drain_interval_ms = None
        self.fused_segment_len = None


def partial_age_budget_s(job):
    age_ms = (
        500.0
        if job.drain_interval_ms is None
        else job.drain_interval_ms
    )
    return age_ms / 1e3


def segment_depth(job):
    if job.fused_segment_len is None:
        return 8
    return job.fused_segment_len
