"""KNOWN-BAD fixture: values derived inside jit/scan bodies stored
onto ``self`` and a module global — the tracer (or a stale concrete
value from trace time) escapes the trace. fstlint must flag both
(FST104). Lint fixture only."""

import jax

_LAST_BATCH = None


class Engine:
    def make_step(self):
        def body(carry, x):
            y = carry + x
            self.debug_last = y  # BAD: tracer stored on self
            return y, y

        return jax.jit(body)


def traced(x):
    global _LAST_BATCH
    _LAST_BATCH = x * 2  # BAD: tracer stored in a module global
    return x + 1


jitted = jax.jit(traced)
