"""Corrected twin of fst104_tracer_leak_bad.py: trace-time python
counters (host ints, the executor's ``traces["n"] += 1`` idiom) and
pure returns are legal; debug state is captured OUTSIDE the jitted
function from its outputs. fstlint must stay quiet."""

import jax


class Engine:
    def make_step(self):
        traces = {"n": 0}

        def body(carry, x):
            traces["n"] += 1  # host int bump at TRACE time: fine
            y = carry + x
            return y, y

        self.step = jax.jit(body)
        return self.step


def run(engine, carry, xs):
    step = engine.make_step()
    for x in xs:
        carry, out = step(carry, x)
    engine.debug_last = out  # captured from the OUTPUT, outside jit
    return carry
