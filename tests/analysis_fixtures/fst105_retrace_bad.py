"""KNOWN-BAD fixture: a jitted call site fed argument shapes that
derive from the raw batch length — every distinct length compiles a
fresh executable (the sticky wire-kind widening retrace-explosion
class). fstlint must flag both call sites (FST105). Lint fixture
only."""

import jax
import numpy as np

step = jax.jit(lambda t: t * 2)


def dispatch_sliced(events):
    n = len(events)
    tape = np.asarray(events, dtype=np.int32)
    # BAD: n takes any value -> one executable per batch size
    return step(tape[:n])


def dispatch_fresh(events):
    n = len(events)
    # BAD: freshly built array sized by the raw length
    return step(np.zeros(n, dtype=np.int32))
