"""Corrected twin of fst105_retrace_bad.py: sizes route through the
named shape-bucketing helper (``bucket_size``, runtime/tape.py), so
the jitted callee sees a handful of power-of-two shapes. fstlint must
stay quiet."""

import jax
import numpy as np


def bucket_size(n, minimum=128):
    b = minimum
    while b < n:
        b *= 2
    return b


step = jax.jit(lambda t: t * 2)


def dispatch(events):
    cap = bucket_size(len(events))
    tape = np.zeros(cap, dtype=np.int32)
    tape[: len(events)] = events
    return step(tape)
