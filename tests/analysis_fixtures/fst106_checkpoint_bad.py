"""KNOWN-BAD fixture: the PR 10 event-time-gate bug class,
reconstructed — a checkpoint-covered class (it has ``state_dict``)
grows mutable run-loop state that never joins the snapshot. The gate
horizon advanced per cycle but died on restore, so a restarted job
re-admitted rows it had already released past. fstlint must flag both
uncovered attributes (FST106). Lint fixture only."""


class Gate:
    def __init__(self):
        self._source_wm = 0
        self._released_wm = 0
        self._gate_wm = 0

    def release(self, wm):
        # BAD: mutated every cycle, absent from state_dict below and
        # not annotated ephemeral — silently dies on restore
        self._released_wm = max(self._released_wm, wm)
        # BAD: same class of forgotten state
        self._gate_wm = max(self._gate_wm, self._released_wm)
        return self._gate_wm

    def observe(self, wm):
        self._source_wm = max(self._source_wm, wm)

    def state_dict(self):
        # covers _source_wm only; the gate horizons were forgotten
        return {"source_wm": self._source_wm}

    def load_state_dict(self, d):
        self._source_wm = int(d["source_wm"])
