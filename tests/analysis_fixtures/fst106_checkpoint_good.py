"""Corrected twin of fst106_checkpoint_bad.py: the gate horizons join
``state_dict``/``load_state_dict`` (the actual PR 10 fix), and a
genuinely un-checkpointable monotonic clock carries an explicit
``# fst:ephemeral`` annotation with its reason. fstlint must stay
quiet."""


class Gate:
    def __init__(self):
        self._source_wm = 0
        self._released_wm = 0
        self._gate_wm = 0
        # fst:ephemeral warning rate-limit clock (monotonic); restore re-arms it
        self._warned_at = -1e9

    def release(self, wm, now=0.0):
        self._released_wm = max(self._released_wm, wm)
        self._gate_wm = max(self._gate_wm, self._released_wm)
        self._warned_at = now
        return self._gate_wm

    def observe(self, wm):
        self._source_wm = max(self._source_wm, wm)

    def state_dict(self):
        return {
            "source_wm": self._source_wm,
            "released_wm": self._released_wm,
            "gate_wm": self._gate_wm,
        }

    def load_state_dict(self, d):
        self._source_wm = int(d["source_wm"])
        self._released_wm = int(d["released_wm"])
        self._gate_wm = int(d["gate_wm"])
