"""Known-bad FST201: the PR 12 control-plane contract violated — a
REST handler mutates run-loop-owned Job state directly instead of
pushing a control event for the run loop to apply at a micro-batch
boundary (the shipped-bug class the fstrace ownership pass exists
for)."""


class Job:
    def __init__(self):
        self._routes = {}
        self._queue = []

    # fst:thread-root name=run-loop
    def run_cycle(self):
        for ev in self._queue:
            self._routes[ev] = True
        self._queue = []


class Service:
    def __init__(self, job):
        self.job = job

    # fst:thread-root name=service
    def do_POST(self, plan_id):
        # BAD: direct off-thread write to run-loop-owned state
        self.job._routes[plan_id] = True

    # fst:thread-root name=service
    def do_DELETE(self, plan_id):
        # BAD: off-thread structural mutation, same class
        self.job._routes.pop(plan_id, None)
