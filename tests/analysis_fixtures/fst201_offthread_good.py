"""Corrected twin of fst201_offthread_bad: the service thread pushes
control events onto a locked queue; ONLY the run loop mutates Job
state, applying drained events at the micro-batch boundary."""


class ControlQueue:
    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._pending = []

    def push(self, ev):
        with self._lock:
            self._pending.append(ev)

    def drain(self):
        with self._lock:
            out = list(self._pending)
            self._pending = []
        return out


class Job:
    def __init__(self, control):
        self.control = control
        self._routes = {}

    # fst:thread-root name=run-loop
    def run_cycle(self):
        for ev in self.control.drain():
            if ev[0] == "add":
                self._routes[ev[1]] = True
            else:
                self._routes.pop(ev[1], None)


class Service:
    def __init__(self, job):
        self.job = job

    # fst:thread-root name=service
    def do_POST(self, plan_id):
        self.job.control.push(("add", plan_id))

    # fst:thread-root name=service
    def do_DELETE(self, plan_id):
        self.job.control.push(("remove", plan_id))
