"""Known-bad FST202: two worker threads mutate shared container
attributes with the class's own lock sitting unused — racy dict/list
mutation the GIL does not make safe (concurrent iteration raises,
interleaved read-modify-write drops counts)."""


class Collector:
    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self.stats = {}
        self.errors = []

    # fst:thread-root name=decode-worker
    def decode_loop(self):
        # BAD: unlocked read-modify-write on a shared dict
        self.stats["decoded"] = self.stats.get("decoded", 0) + 1

    # fst:thread-root name=upload-worker
    def upload_loop(self):
        self.stats["uploaded"] = self.stats.get("uploaded", 0) + 1
        # BAD: unlocked append on a shared list read by the other root
        self.errors.append("late")

    # fst:thread-root name=decode-worker
    def report(self):
        return list(self.errors)
