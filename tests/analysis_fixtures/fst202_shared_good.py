"""Corrected twin of fst202_shared_bad: every access to the shared
containers holds the one lock that guards them."""


class Collector:
    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self.stats = {}
        self.errors = []

    # fst:thread-root name=decode-worker
    def decode_loop(self):
        with self._lock:
            self.stats["decoded"] = self.stats.get("decoded", 0) + 1

    # fst:thread-root name=upload-worker
    def upload_loop(self):
        with self._lock:
            self.stats["uploaded"] = self.stats.get("uploaded", 0) + 1
            self.errors.append("late")

    # fst:thread-root name=decode-worker
    def report(self):
        with self._lock:
            return list(self.errors)
