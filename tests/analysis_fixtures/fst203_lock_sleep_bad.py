"""Known-bad FST203: the PR 7 ApiVersions bug reconstructed — the
version-negotiation retry loop sleeps its (exponential!) backoff while
the client lock is held, so every other thread queuing on the client
waits out the whole backoff sequence; and the probe helper, reachable
only from under the lock, blocks in recv."""

import time


class Client:
    def __init__(self, sock):
        import threading

        self._lock = threading.Lock()
        self._sock = sock
        self._versions = None

    def negotiate(self):
        with self._lock:
            for attempt in range(5):
                try:
                    self._versions = self._probe_locked()
                    return self._versions
                except OSError:
                    # BAD: exponential backoff under the client lock
                    time.sleep(0.02 * (2 ** attempt))
        return None

    def _probe_locked(self):
        # BAD: blocking recv; *_locked names run under the lock by
        # convention (and every call site above holds it)
        return self._sock.recv(4)
