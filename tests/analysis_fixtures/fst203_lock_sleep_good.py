"""Corrected twin of fst203_lock_sleep_bad: the blocking probe and the
backoff sleep both run with the lock RELEASED — only the state update
holds it — and the one deliberate wait-under-lock carries a reasoned
`# fst:blocking-ok` annotation."""

import time


class Client:
    def __init__(self, sock):
        import threading

        self._lock = threading.Lock()
        self._sock = sock
        self._versions = None

    def negotiate(self):
        for _attempt in range(5):
            try:
                versions = self._probe()
            except OSError:
                time.sleep(0.02)  # lock not held: others proceed
                continue
            with self._lock:
                self._versions = versions
            return versions
        return None

    def _probe(self):
        # called with the lock released; only the result is stored
        # under it
        return self._sock.recv(4)

    def close_grace(self):
        with self._lock:
            # fst:blocking-ok constant 10ms teardown grace so in-flight frames flush; close() callers already serialize on this lock by design
            time.sleep(0.01)
