"""Known-bad FST204: check-then-act on a lock-guarded attribute from
outside the lock — the emptiness check can be stale by the time the
pop lands (classic TOCTOU against the class's own lock discipline)."""


class Ring:
    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._items = []

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def pop_if_any(self):
        # BAD: `_items` is guarded by _lock in push(), but this test
        # and the mutation it gates hold no lock
        if self._items:
            return self._items.pop()
        return None
