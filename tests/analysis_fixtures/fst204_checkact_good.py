"""Corrected twin of fst204_checkact_bad: the lock is held across the
test AND the act, so the decision cannot go stale."""


class Ring:
    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._items = []

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def pop_if_any(self):
        with self._lock:
            if self._items:
                return self._items.pop()
        return None
