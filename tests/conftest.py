"""Test harness: run everything on a virtual 8-device CPU mesh.

The analog of the reference's in-process Flink MiniCluster
(SiddhiCEPITCase.java:63 extends AbstractTestBase): real multi-device sharding
and collectives, single process, no TPU required.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
