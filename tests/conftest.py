"""Test harness: run everything on a virtual 8-device CPU mesh.

The analog of the reference's in-process Flink MiniCluster
(SiddhiCEPITCase.java:63 extends AbstractTestBase): real multi-device sharding
and collectives, single process, no TPU required.

The environment may pre-register an accelerator PJRT plugin whose lazy
initialization dials a remote tunnel; tests must never depend on that tunnel
being alive, so non-CPU backend factories are dropped before any backend
initializes (``jax.backends()`` would otherwise try to init them all).
"""

import os

# Persistent XLA compilation cache, shared with bench.py: the sharded
# (shard_map) and resident-replay tests cost minutes of XLA CPU
# compile per cold run on the 2-core tier-1 lane; with the cache warm,
# repeat suite runs skip every unchanged compile. Same knobs bench.py
# sets — one cache, both consumers.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO, ".jax_cache")
)
os.environ.setdefault(
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2"
)

# Compiled-plan verification on EVERY compile in the test lane
# (analysis/plancheck.py): static NFA/stack invariants at ~zero cost.
# The eval_shape tier runs over the full query zoo in
# tests/test_plancheck.py + scripts/run_static_analysis.py; =1 keeps
# per-compile overhead out of the suite's 870s budget while still
# rejecting malformed transition tables anywhere a test compiles one.
os.environ.setdefault("FST_VERIFY_PLANS", "1")

# TPU smoke lane (`FST_TPU_SMOKE=1 python -m pytest -m tpu tests/`):
# keep the real accelerator backend alive instead of pinning CPU —
# the only configuration under which the real chip runs result-asserting
# tests (bench.py asserts nothing; round-3 verdict item 8)
_TPU_SMOKE = os.environ.get("FST_TPU_SMOKE") == "1"

if not _TPU_SMOKE:
    os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

if not _TPU_SMOKE:
    # jax may already be imported (an interpreter-startup hook importing
    # it captures JAX_PLATFORMS before this file runs), so set the
    # config directly.
    jax.config.update("jax_platforms", "cpu")

    for _name in list(_xb._backend_factories):
        if _name != "cpu":
            del _xb._backend_factories[_name]


import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _pallas_fallback_gate():
    """Tier-1 gate: on this CPU lane Pallas is unavailable and every
    kernel entry point must fall back CLEANLY — warmup() returns False
    without raising, no kernel reports active, and the fallback paths
    compute. If this gate fires, the XLA fallbacks the whole suite
    runs on are broken, so no test may silently skip past it (the
    kernel-vs-fallback equivalence itself runs under the Pallas
    interpreter in tests/test_pallas_ops.py subprocesses — those
    tests FAIL, never skip, when the kernels regress)."""
    if _TPU_SMOKE:
        yield
        return
    import numpy as _np

    import jax.numpy as _jnp
    from flink_siddhi_tpu.compiler import pallas_ops

    assert not pallas_ops.available(), (
        "CPU lane unexpectedly reports Pallas available"
    )
    assert pallas_ops.warmup() is False, (
        "warmup() must fall back cleanly when Pallas is unavailable"
    )
    assert pallas_ops.chain_kernel_active() is False
    assert pallas_ops.fold_kernel_active() is False
    assert pallas_ops.chain_advance(
        (0, 1), ((), ()), False, {}, _jnp.zeros(5, _jnp.int32),
        _jnp.zeros(4, bool), _jnp.zeros(4, _jnp.int32),
        _jnp.zeros(4, _jnp.int32), _jnp.zeros(4, _jnp.int32), 0,
    ) is None
    assert pallas_ops.unique_window_fold(
        _jnp.zeros(128, bool), _jnp.zeros(128, _jnp.int32), [],
        _jnp.zeros(128, bool), [], (("count", -1),),
    ) is None
    out = pallas_ops.multi_reverse_cummin(
        [_jnp.asarray(_np.array([4, 2, 9, 1], _np.int32))]
    )
    assert _np.asarray(out[0]).tolist() == [1, 1, 1, 1]
    yield


# The permanent compile-telemetry surface (telemetry/compile_events.py)
# is the suite's ONE jax.monitoring registration: tests that count XLA
# lowerings use compile_events.watch() instead of registering private
# listeners — the historical per-test register +
# clear_event_listeners() teardown clobbered every other listener in
# the process (the footgun the old test comments flagged). install()
# is idempotent AND self-healing (re-registers if something cleared
# the global list), so asserting it here keeps the guarantee live for
# the whole session.
@pytest.fixture(scope="session", autouse=True)
def _compile_events_surface():
    from flink_siddhi_tpu.telemetry import compile_events

    compile_events.install()
    yield
    # a test that calls jax.monitoring.clear_event_listeners() has
    # reintroduced the footgun this surface replaced — fail loudly
    assert compile_events.installed(), (
        "the permanent compile-events listener was cleared mid-session"
        " (use compile_events.watch() instead of private listeners + "
        "jax.monitoring.clear_event_listeners())"
    )


# The jitted-step suites run the engine hot loop under jax's transfer
# guard (runtime/executor.py HOTLOOP_TRANSFER_GUARD): an IMPLICIT
# host<->device transfer inside run_cycle — a numpy array silently
# riding a jit call where the design says "one explicit async
# device_put per segment" — fails loudly. The per-batch path's
# intended staging upload is re-allowed at its one call site
# (_staging_allow); everything else the guard catches is a regression
# of the staging contract (docs/static_analysis.md). Scoped to the
# hot loop, not the whole test: plan compilation legitimately builds
# eager device constants.
_TRANSFER_GUARD_FILES = {"test_fused_stream.py", "test_checkpoint.py"}


@pytest.fixture(autouse=True)
def _hotloop_transfer_guard(request, monkeypatch):
    fname = os.path.basename(str(request.node.fspath))
    if _TPU_SMOKE or fname not in _TRANSFER_GUARD_FILES:
        yield
        return
    from flink_siddhi_tpu.runtime import executor as _executor

    monkeypatch.setattr(_executor, "HOTLOOP_TRANSFER_GUARD", True)
    yield


# Run-loop ownership guard (runtime/executor.py
# RUNLOOP_OWNERSHIP_GUARD): the dynamic half of the fstrace FST201
# invariant. In the control-plane / service / fault lanes — exactly
# the suites where the REST thread, supervisor restarts, and control
# events interleave with the run loop — every state-mutating control
# entry point asserts it runs on the stamped run-loop thread, so the
# invariant the linter proves statically is also EXECUTED by the
# tests (tests/test_control_plane.py injects a deliberate off-thread
# mutation and expects OwnershipViolation).
_OWNERSHIP_GUARD_FILES = {
    "test_control_plane.py",
    "test_control_e2e.py",
    "test_app.py",
    "test_faults.py",
    "test_prober.py",
}


@pytest.fixture(autouse=True)
def _runloop_ownership_guard(request, monkeypatch):
    fname = os.path.basename(str(request.node.fspath))
    if _TPU_SMOKE or fname not in _OWNERSHIP_GUARD_FILES:
        yield
        return
    from flink_siddhi_tpu.runtime import executor as _executor

    monkeypatch.setattr(_executor, "RUNLOOP_OWNERSHIP_GUARD", True)
    yield


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest

    if _TPU_SMOKE:
        # the smoke lane runs ONLY tpu-marked tests (everything else
        # assumes the CPU mesh)
        skip = _pytest.mark.skip(reason="non-tpu test in TPU smoke lane")
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip)
    else:
        skip = _pytest.mark.skip(
            reason="TPU smoke test (FST_TPU_SMOKE=1 -m tpu to run)"
        )
        for item in items:
            if "tpu" in item.keywords:
                item.add_marker(skip)
