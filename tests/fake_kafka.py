"""In-process fake Kafka broker for protocol-level tests.

Two dialects, selected at construction:

* default ("modern", a >=2.x broker): answers ApiVersions (api 18) and
  advertises Produce up to v5 / Fetch up to v6 so the client's
  negotiation exercises real intersection (it implements 3 and 4);
  Produce v3 accepts v2 record batches (CRC32C validated, gzip
  inflated — a corrupt batch gets error code 2, CORRUPT_MESSAGE);
  Fetch v4 serves v2 batches re-encoded with ``fetch_codec`` (gzip by
  default) and — like a real broker — returns *whole batches*: a fetch
  offset landing mid-batch returns the batch containing it, and at
  least one batch is always returned regardless of max_bytes (KIP-74).
* ``legacy=True`` (a pre-0.10 broker): v0 apis only; an ApiVersions
  request slams the connection, which is exactly how old brokers
  answered and what the client's fallback-to-v0 path keys off.

Single node, in-memory logs. Batch boundaries are remembered per
produce/append call so whole-batch fetch semantics are honest.
``mangle_batch`` (a bytes->bytes hook applied to every served v2
batch) lets tests inject corruption or foreign codec flags on the
wire without touching the log.

Fault injection (``fault_hook``): a callable ``(api, seq) -> action``
consulted once per request, in arrival order (``seq`` is a
broker-lifetime request counter — deterministic schedules replay
exactly). Actions:

* ``None``             — serve normally
* ``"drop"``           — close the connection without answering (an
                         outage / crashed broker)
* ``"drop_mid_frame"`` — send the size header + half the response,
                         then close (the exact failure the client's
                         ``_read_frame`` sees as mid-frame close)
* ``"error"``          — answer Fetch/Produce/ListOffsets with the
                         transient NOT_LEADER_FOR_PARTITION code (6)
                         instead of data (other apis: like ``drop``)
* ``"corrupt"``        — serve THIS fetch's v2 batches mangled
                         (bit-flip => CRC32C mismatch); the log is
                         untouched, the next fetch is clean (other
                         apis: like ``drop``)
* ``"delay"``          — serve normally after ``fault_delay_s``
                         (default 2 ms; bounded, never a test clock)
* ``"fence"``          — bump the requesting producer's epoch
                         coordinator-side BEFORE handling, so this and
                         every later request from the old incarnation
                         answers INVALID_PRODUCER_EPOCH (the zombie-
                         producer shape; txn/produce apis only, other
                         apis: like ``drop``). Opt-in: NOT in
                         ``FaultSchedule.ACTIONS`` (seeded draws of
                         existing schedules must not shift).
* ``"abort_txn"``      — abort the requester's ongoing transaction
                         server-side (the transaction-timeout shape:
                         markers written, data becomes invisible to
                         read-committed) then handle the request
                         normally against the now-empty txn state.
                         Opt-in, like ``"fence"``.

Transaction coordinator (KIP-98, single node): InitProducerId (22)
grants ``(producer_id, epoch)`` per transactional id — re-running it
bumps the epoch, fences older holders, and aborts any transaction
they left open; AddPartitionsToTxn (24) registers marker targets;
EndTxn (26) appends a COMMIT/ABORT control batch to every registered
partition. Produce v3 validates the batch header's
producer_id/epoch/sequence: a stale epoch is fenced (47), the
expected next sequence appends, a re-send of the last appended batch
acks as DUPLICATE_SEQUENCE_NUMBER (46 — the client treats it as
success, closing the retry-duplicates hole), anything else is
OUT_OF_ORDER_SEQUENCE_NUMBER (45). Fetch v4 honors
``isolation_level``: read_committed (1) is capped at the last stable
offset and carries the aborted-transactions index for the served
range. Transactions never time out here — tests are exact where real
brokers are ambiguous (docs/fault_tolerance.md).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from bisect import bisect_right
from typing import Callable, Dict, List, Optional, Tuple

from flink_siddhi_tpu.connectors.kafka.protocol import (
    API_ADD_PARTITIONS_TO_TXN,
    API_END_TXN,
    API_FETCH,
    API_INIT_PRODUCER_ID,
    API_LIST_OFFSETS,
    API_METADATA,
    API_PRODUCE,
    API_VERSIONS,
    Reader,
    Writer,
    encode_api_versions_response,
)
from flink_siddhi_tpu.connectors.kafka.records import (
    CorruptBatchError,
    decode_batch_meta,
    decode_record_set,
    encode_control_batch,
    encode_message_set,
    encode_record_batch,
)

ERR_CORRUPT_MESSAGE = 2
ERR_UNKNOWN_TOPIC = 3
ERR_NOT_LEADER = 6  # transient: the client's retry taxonomy retries it
ERR_OUT_OF_ORDER_SEQ = 45
ERR_DUPLICATE_SEQ = 46  # client's idempotent path treats as success
ERR_INVALID_EPOCH = 47  # fenced: fatal client-side
ERR_INVALID_TXN_STATE = 48
ERR_INVALID_PID_MAPPING = 49

# per-batch producer metadata for non-idempotent appends (the shape
# ``FakeBroker.append`` and legacy produce record per bound)
_PLAIN_META = {
    "pid": -1, "epoch": -1, "base_seq": -1, "txn": False, "control": None,
}

# what the modern dialect advertises (intentionally wider than the
# client implements: negotiation must intersect, not parrot)
MODERN_API_VERSIONS: Dict[int, Tuple[int, int]] = {
    API_PRODUCE: (0, 5),
    API_FETCH: (0, 6),
    API_LIST_OFFSETS: (0, 2),
    API_METADATA: (0, 5),
    API_VERSIONS: (0, 1),
    API_INIT_PRODUCER_ID: (0, 1),
    API_ADD_PARTITIONS_TO_TXN: (0, 1),
    API_END_TXN: (0, 1),
}


class FakeBroker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        legacy: bool = False,
        fetch_codec: str = "gzip",
        api_versions: Optional[Dict[int, Tuple[int, int]]] = None,
    ) -> None:
        # (topic, partition) -> list of (ts, value)
        self.logs: Dict[Tuple[str, int], List] = {}
        # (topic, partition) -> sorted batch start offsets; batch i
        # covers [starts[i], starts[i+1]) (last runs to len(log))
        self.bounds: Dict[Tuple[str, int], List[int]] = {}
        # (topic, partition) -> per-bound producer metadata (parallel
        # to ``bounds``): pid/epoch/base_seq/txn/control — what a
        # served batch's header must carry back
        self.batch_meta: Dict[Tuple[str, int], List[dict]] = {}
        # -- transaction coordinator state (all under self._lock) ----
        self._next_pid = 1000
        # transactional_id -> {pid, epoch, state: "empty"|"ongoing",
        #                      partitions: set of (topic, partition)}
        self._txns: Dict[str, dict] = {}
        # producer_id -> current epoch (the fencing source of truth,
        # covers idempotent-only producers with no transactional id)
        self._pid_epoch: Dict[int, int] = {}
        # (topic, partition) -> {pid: (next_seq, last_base_seq,
        #                              last_base_off, epoch)}
        self._seqs: Dict[Tuple[str, int], Dict[int, tuple]] = {}
        # (topic, partition) -> {pid: first data offset of the OPEN
        # transaction} — what caps the last stable offset
        self._open_txn: Dict[Tuple[str, int], Dict[int, int]] = {}
        # (topic, partition) -> [(pid, first_offset, marker_offset)]
        # for every ABORTED transaction (the Fetch v4 index)
        self.aborted: Dict[Tuple[str, int], List[Tuple[int, int, int]]] = {}
        self.legacy = legacy
        self.fetch_codec = fetch_codec
        self.api_versions = dict(
            MODERN_API_VERSIONS if api_versions is None else api_versions
        )
        self.mangle_batch: Optional[Callable[[bytes], bytes]] = None
        # fault injection: (api, request_seq) -> action (see module
        # docstring); None = no faults. Request seq is broker-lifetime
        # and monotonic, so a seeded schedule replays deterministically.
        self.fault_hook: Optional[Callable[[int, int], Optional[str]]] = None
        self.fault_delay_s = 0.002
        self._req_seq = 0
        self._lock = threading.Lock()
        self._server = socket.create_server((host, port))
        self._server.settimeout(0.2)
        self.host, self.port = self._server.getsockname()[:2]
        self._closed = False
        threading.Thread(target=self._accept, daemon=True).start()

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            for p in range(partitions):
                self.logs.setdefault((topic, p), [])
                self.bounds.setdefault((topic, p), [])
                self.batch_meta.setdefault((topic, p), [])

    def append(self, topic: str, partition: int, values, ts_ms=0):
        """Append values as ONE batch (one bound) — a v4 fetch of any
        offset inside it returns the whole thing."""
        with self._lock:
            log = self.logs[(topic, partition)]
            self.bounds.setdefault((topic, partition), []).append(len(log))
            self.batch_meta.setdefault((topic, partition), []).append(
                dict(_PLAIN_META)
            )
            for v in values:
                if isinstance(v, str):
                    v = v.encode()
                log.append((ts_ms, v))

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass

    # -- server loop ------------------------------------------------------
    def _accept(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._closed:
                head = b""
                while len(head) < 4:
                    chunk = conn.recv(4 - len(head))
                    if not chunk:
                        return
                    head += chunk
                (size,) = struct.unpack(">i", head)
                data = bytearray()
                while len(data) < size:
                    chunk = conn.recv(min(1 << 16, size - len(data)))
                    if not chunk:
                        return
                    data += chunk
                resp = self._handle(bytes(data))
                if resp is None:  # legacy broker / drop fault: hang up
                    return
                if isinstance(resp, tuple):  # ("partial", payload)
                    _, payload = resp
                    conn.sendall(
                        struct.pack(">i", len(payload))
                        + payload[: max(len(payload) // 2, 1)]
                    )
                    return  # close mid-frame
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        finally:
            conn.close()

    # -- request dispatch -------------------------------------------------
    def _handle(self, data: bytes):
        r = Reader(data)
        api, version, corr = r.i16(), r.i16(), r.i32()
        r.string()  # client_id
        # fault injection happens here, per request, in arrival order
        fault = None
        if self.fault_hook is not None:
            with self._lock:
                seq = self._req_seq
                self._req_seq += 1
            fault = self.fault_hook(api, seq)
        if fault == "drop":
            return None
        if fault == "delay":
            time.sleep(self.fault_delay_s)
            fault = None
        _txn_apis = (
            API_PRODUCE, API_ADD_PARTITIONS_TO_TXN, API_END_TXN,
        )
        forced_err = 0
        corrupt = False
        fence = False
        abort_txn = False
        if fault == "error":
            if api in (
                API_FETCH, API_PRODUCE, API_LIST_OFFSETS,
                API_INIT_PRODUCER_ID, API_ADD_PARTITIONS_TO_TXN,
                API_END_TXN,
            ):
                forced_err = ERR_NOT_LEADER
            else:
                return None  # no error slot in these responses: drop
        elif fault == "corrupt":
            if api == API_FETCH:
                corrupt = True
            else:
                return None
        elif fault == "fence":
            if api in _txn_apis:
                fence = True
            else:
                return None  # no producer identity to fence: drop
        elif fault == "abort_txn":
            if api in _txn_apis:
                abort_txn = True
            else:
                return None
        w = Writer().i32(corr)
        if api == API_VERSIONS:
            if self.legacy:
                return None  # pre-0.10: slam the connection
            w.raw(encode_api_versions_response(self.api_versions))
        elif api == API_METADATA:
            self._metadata(r, w)
        elif api == API_LIST_OFFSETS:
            self._list_offsets(r, w, forced_err)
        elif api == API_FETCH:
            if version not in (0, 4):
                raise AssertionError(f"fake broker: Fetch v{version}")
            self._fetch(r, w, version, forced_err, corrupt)
        elif api == API_PRODUCE:
            if version not in (0, 3):
                raise AssertionError(f"fake broker: Produce v{version}")
            self._produce(r, w, version, forced_err, fence, abort_txn)
        elif api == API_INIT_PRODUCER_ID:
            if self.legacy:
                return None
            self._init_producer_id(r, w, forced_err)
        elif api == API_ADD_PARTITIONS_TO_TXN:
            if self.legacy:
                return None
            self._add_partitions_to_txn(
                r, w, forced_err, fence, abort_txn
            )
        elif api == API_END_TXN:
            if self.legacy:
                return None
            self._end_txn(r, w, forced_err, fence, abort_txn)
        else:
            if self.legacy:
                return None
            raise AssertionError(f"fake broker: unsupported api {api}")
        if fault == "drop_mid_frame":
            return ("partial", w.done())
        return w.done()

    def _metadata(self, r: Reader, w: Writer) -> None:
        n = r.i32()
        topics = [r.string() for _ in range(n)]
        with self._lock:
            if not topics:
                topics = sorted({t for t, _ in self.logs})
            w.i32(1).i32(0).string(self.host).i32(self.port)
            w.i32(len(topics))
            for t in topics:
                parts = sorted(p for (tt, p) in self.logs if tt == t)
                w.i16(0 if parts else ERR_UNKNOWN_TOPIC).string(t)
                w.i32(len(parts))
                for p in parts:
                    w.i16(0).i32(p).i32(0)
                    w.i32(1).i32(0)  # replicas [0]
                    w.i32(1).i32(0)  # isr [0]

    def _list_offsets(
        self, r: Reader, w: Writer, forced_err: int = 0
    ) -> None:
        r.i32()  # replica
        w.i32(r_topics := r.i32())
        for _ in range(r_topics):
            t = r.string()
            np_ = r.i32()
            w.string(t).i32(np_)
            for _ in range(np_):
                pid, time_, _maxn = r.i32(), r.i64(), r.i32()
                if forced_err:
                    w.i32(pid).i16(forced_err).i32(0)
                    continue
                with self._lock:
                    log = self.logs.get((t, pid))
                if log is None:
                    w.i32(pid).i16(ERR_UNKNOWN_TOPIC).i32(0)
                    continue
                off = 0 if time_ == -2 else len(log)
                w.i32(pid).i16(0).i32(1).i64(off)

    # -- fetch ------------------------------------------------------------
    def _fetch(
        self, r: Reader, w: Writer, version: int,
        forced_err: int = 0, corrupt: bool = False,
    ) -> None:
        r.i32(), r.i32(), r.i32()  # replica, max_wait, min_bytes
        isolation = 0
        if version >= 4:
            r.i32()  # total max_bytes
            isolation = r.i8()  # 0 = read_uncommitted, 1 = read_committed
            w.i32(0)  # throttle_time_ms
        nt = r.i32()
        w.i32(nt)
        for _ in range(nt):
            t = r.string()
            np_ = r.i32()
            w.string(t).i32(np_)
            for _ in range(np_):
                pid, off, maxb = r.i32(), r.i64(), r.i32()
                with self._lock:
                    log = list(self.logs.get((t, pid), ()))
                    bounds = list(self.bounds.get((t, pid), ()))
                    meta = list(self.batch_meta.get((t, pid), ()))
                    open_firsts = list(
                        self._open_txn.get((t, pid), {}).values()
                    )
                    aborted = list(self.aborted.get((t, pid), ()))
                hw = len(log)
                # last stable offset: everything below it is decided
                # (committed or aborted-with-marker); an OPEN
                # transaction's first data offset pins it down
                lso = min(open_firsts) if open_firsts else hw
                if forced_err:
                    w.i32(pid).i16(forced_err).i64(hw)
                    if version >= 4:
                        w.i64(hw).i32(0)
                    w.bytes_(b"")
                    continue
                if version >= 4:
                    cap = lso if isolation == 1 else hw
                    rset, end = self._serve_batches(
                        log, bounds, meta, off, maxb,
                        corrupt=corrupt, cap=cap,
                    )
                    w.i32(pid).i16(0).i64(hw)
                    w.i64(lso if isolation == 1 else hw)
                    if isolation == 1:
                        # aborted transactions overlapping the served
                        # range: first data offset <= served end and
                        # marker at/after the fetch offset (KIP-98's
                        # index; the client clears each pid at its
                        # control batch)
                        rel = [
                            (apid, first)
                            for apid, first, marker in aborted
                            if first < end and marker >= off
                        ]
                        w.i32(len(rel))
                        for apid, first in rel:
                            w.i64(apid).i64(first)
                    else:
                        w.i32(0)  # aborted_transactions
                    w.bytes_(rset)
                else:
                    rset = self._serve_messages(log, off, maxb)
                    w.i32(pid).i16(0).i64(hw).bytes_(rset)

    @staticmethod
    def _serve_messages(log, off: int, maxb: int) -> bytes:
        """v0 dialect: one legacy message per record, byte-capped."""
        mset = b""
        o = off
        while o < len(log) and len(mset) < maxb:
            ts, v = log[o]
            one = encode_message_set([v], ts_ms=ts)
            # stamp the real offset into the entry header
            one = struct.pack(">q", o) + one[8:]
            mset += one
            o += 1
        return mset

    def _serve_batches(
        self, log, bounds, meta, off: int, maxb: int,
        corrupt: bool = False, cap: Optional[int] = None,
    ) -> Tuple[bytes, int]:
        """v4 dialect: whole v2 batches, starting with the batch that
        CONTAINS the fetch offset; always at least one batch. Returns
        ``(record_set, end_offset_served)``. Batches are re-encoded
        with their recorded producer metadata (id/epoch/sequence, the
        transactional bit) so a consumer can attribute each batch to
        its transaction; control bounds re-encode as real control
        batches. ``cap`` (the last stable offset under
        read_committed) stops serving at the first batch that starts
        at or beyond it. ``corrupt=True`` (one fetch's fault action)
        flips a payload bit in every served batch — CRC32C fails
        client-side, the log itself stays clean."""
        if off >= len(log) or not bounds:
            return b"", off
        from flink_siddhi_tpu.connectors.kafka.codecs import codec_id

        if cap is None:
            cap = len(log)
        i = max(bisect_right(bounds, off) - 1, 0)
        out = b""
        served_end = off
        while i < len(bounds) and (not out or len(out) < maxb):
            start = bounds[i]
            if start >= cap:
                break  # open-transaction data: above the LSO
            end = bounds[i + 1] if i + 1 < len(bounds) else len(log)
            m = meta[i] if i < len(meta) else _PLAIN_META
            if m["control"] is not None:
                batch = encode_control_batch(
                    start,
                    m["pid"],
                    m["epoch"],
                    commit=(m["control"] == "commit"),
                    ts_ms=log[start][0],
                )
            else:
                entries = [(ts, None, v) for ts, v in log[start:end]]
                batch = encode_record_batch(
                    entries,
                    base_offset=start,
                    codec=codec_id(self.fetch_codec),
                    producer_id=m["pid"],
                    producer_epoch=m["epoch"],
                    base_sequence=m["base_seq"],
                    transactional=m["txn"],
                )
            if self.mangle_batch is not None:
                batch = self.mangle_batch(batch)
            if corrupt:
                b = bytearray(batch)
                b[-1] ^= 0x04  # payload bit: breaks the batch CRC32C
                batch = bytes(b)
            out += batch
            served_end = end
            i += 1
        return out, served_end

    # -- produce ----------------------------------------------------------
    def _produce(
        self, r: Reader, w: Writer, version: int, forced_err: int = 0,
        fence: bool = False, abort_txn: bool = False,
    ) -> None:
        txn_id = None
        if version >= 3:
            txn_id = r.string()  # transactional_id
        r.i16(), r.i32()  # acks, timeout
        nt = r.i32()
        w.i32(nt)
        for _ in range(nt):
            t = r.string()
            np_ = r.i32()
            w.string(t).i32(np_)
            for _ in range(np_):
                pid = r.i32()
                rset = r.bytes_() or b""
                if forced_err:
                    # transient refusal: NOTHING is appended — the
                    # client's retry re-sends the whole batch (same
                    # base_sequence, so the idempotent path dedupes)
                    w.i32(pid).i16(forced_err).i64(-1)
                    if version >= 2:
                        w.i64(-1)
                    continue
                try:
                    # magic sits at byte 16 in BOTH wire formats;
                    # only v2 batches carry producer metadata
                    is_v2 = len(rset) > 16 and rset[16] >= 2
                    bm = decode_batch_meta(rset) if is_v2 else None
                    msgs = decode_record_set(rset)
                    err = 0
                except CorruptBatchError:
                    bm, msgs, err = None, [], ERR_CORRUPT_MESSAGE
                with self._lock:
                    if fence and bm is not None and bm["producer_id"] >= 0:
                        self._fence_pid_locked(bm["producer_id"])
                    if abort_txn and txn_id is not None:
                        self._abort_ongoing_locked(txn_id)
                    base = len(self.logs.setdefault((t, pid), []))
                    if err == 0 and bm is not None:
                        err, base = self._validate_append_locked(
                            t, pid, txn_id, bm, msgs
                        )
                    elif err == 0 and msgs:
                        # batch-less entries (legacy v0 payloads in a
                        # v3 request don't occur; defensive)
                        self._append_locked(t, pid, msgs, _PLAIN_META)
                w.i32(pid).i16(err).i64(base)
                if version >= 2:
                    w.i64(-1)  # log_append_time
        if version >= 1:
            w.i32(0)  # throttle_time_ms

    def _append_locked(self, t, pid, msgs, meta: dict) -> int:
        """Append one decoded batch as one bound; -> base offset."""
        log = self.logs.setdefault((t, pid), [])
        base = len(log)
        if msgs:
            self.bounds.setdefault((t, pid), []).append(base)
            self.batch_meta.setdefault((t, pid), []).append(dict(meta))
            for _off, ts, _k, v in msgs:
                log.append((ts or 0, v))
        return base

    def _validate_append_locked(
        self, t, pid, txn_id, bm: dict, msgs
    ) -> Tuple[int, int]:
        """KIP-98 produce-side validation -> (error_code, base_offset).

        Epoch fencing first (a zombie's data must never land), then
        sequence idempotence (expected next appends; a re-send of the
        LAST appended batch acks as DUPLICATE_SEQUENCE_NUMBER with its
        original base offset — success client-side; anything else is
        OUT_OF_ORDER), then transaction membership (data for a
        transaction that is not ongoing on this partition is
        INVALID_TXN_STATE)."""
        ppid = bm["producer_id"]
        epoch = bm["producer_epoch"]
        base_seq = bm["base_sequence"]
        key = (t, pid)
        if ppid < 0:
            # non-idempotent classic batch
            if bm["transactional"]:
                return ERR_INVALID_TXN_STATE, -1
            return 0, self._append_locked(t, pid, msgs, _PLAIN_META)
        cur = self._pid_epoch.get(ppid)
        if cur is None:
            return ERR_INVALID_PID_MAPPING, -1
        if epoch != cur:
            return ERR_INVALID_EPOCH, -1
        entry = self._txns.get(txn_id) if txn_id is not None else None
        if bm["transactional"]:
            if (
                entry is None
                or entry["pid"] != ppid
                or entry["state"] != "ongoing"
                or key not in entry["partitions"]
            ):
                return ERR_INVALID_TXN_STATE, -1
        st = self._seqs.setdefault(key, {}).get(ppid)
        if st is not None and st[3] == epoch:
            next_seq, last_base_seq, last_base_off, _ = st
            if base_seq == last_base_seq:
                # the retry-after-append shape: already holding this
                # batch, ack it without a second append
                return ERR_DUPLICATE_SEQ, last_base_off
            if base_seq != next_seq:
                return ERR_OUT_OF_ORDER_SEQ, -1
        else:
            # new producer session on this partition: sequences
            # restart at 0 (the epoch scopes them)
            if base_seq != 0:
                return ERR_OUT_OF_ORDER_SEQ, -1
        meta = {
            "pid": ppid,
            "epoch": epoch,
            "base_seq": base_seq,
            "txn": bm["transactional"],
            "control": None,
        }
        base = self._append_locked(t, pid, msgs, meta)
        self._seqs[key][ppid] = (
            base_seq + len(msgs), base_seq, base, epoch
        )
        if bm["transactional"]:
            self._open_txn.setdefault(key, {}).setdefault(ppid, base)
        return 0, base

    # -- transaction coordinator ------------------------------------------
    def _fence_pid_locked(self, ppid: int) -> None:
        """Server-side epoch bump: the current holder of ``ppid``
        becomes a zombie (its next request answers 47)."""
        if ppid in self._pid_epoch:
            self._pid_epoch[ppid] += 1
            for entry in self._txns.values():
                if entry["pid"] == ppid:
                    entry["epoch"] = self._pid_epoch[ppid]

    def _abort_ongoing_locked(self, txn_id: str) -> None:
        """Abort ``txn_id``'s ongoing transaction (markers written,
        aborted index updated) — the transaction-timeout shape."""
        entry = self._txns.get(txn_id)
        if entry is not None and entry["state"] == "ongoing":
            self._complete_txn_locked(entry, commit=False)

    def _complete_txn_locked(self, entry: dict, commit: bool) -> None:
        """Write a COMMIT/ABORT control batch to every partition the
        transaction registered, update the aborted index, close the
        transaction server-side."""
        verdict = "commit" if commit else "abort"
        for key in sorted(entry["partitions"]):
            log = self.logs.setdefault(key, [])
            marker_off = len(log)
            self.bounds.setdefault(key, []).append(marker_off)
            self.batch_meta.setdefault(key, []).append({
                "pid": entry["pid"],
                "epoch": entry["epoch"],
                "base_seq": -1,
                "txn": True,
                "control": verdict,
            })
            log.append((0, b""))  # the marker occupies one offset
            first = self._open_txn.get(key, {}).pop(entry["pid"], None)
            if not commit and first is not None:
                self.aborted.setdefault(key, []).append(
                    (entry["pid"], first, marker_off)
                )
        entry["state"] = "empty"
        entry["partitions"] = set()

    def _init_producer_id(
        self, r: Reader, w: Writer, forced_err: int = 0
    ) -> None:
        txn_id = r.string()
        r.i32()  # transaction_timeout_ms (never enforced here)
        if forced_err:
            w.i32(0).i16(forced_err).i64(-1).i16(-1)
            return
        with self._lock:
            if txn_id is None:
                # idempotence-only producer: fresh pid, epoch 0
                ppid, epoch = self._next_pid, 0
                self._next_pid += 1
            else:
                entry = self._txns.get(txn_id)
                if entry is None:
                    entry = {
                        "pid": self._next_pid,
                        "epoch": 0,
                        "state": "empty",
                        "partitions": set(),
                    }
                    self._next_pid += 1
                    self._txns[txn_id] = entry
                else:
                    # the fencing moment: older holders of this id
                    # are zombies from here on, and whatever they
                    # left open is aborted
                    if entry["state"] == "ongoing":
                        self._complete_txn_locked(entry, commit=False)
                    entry["epoch"] += 1
                ppid, epoch = entry["pid"], entry["epoch"]
            self._pid_epoch[ppid] = epoch
        w.i32(0).i16(0).i64(ppid).i16(epoch)

    def _add_partitions_to_txn(
        self, r: Reader, w: Writer, forced_err: int = 0,
        fence: bool = False, abort_txn: bool = False,
    ) -> None:
        txn_id = r.string()
        ppid = r.i64()
        epoch = r.i16()
        topics = []
        for _ in range(r.i32()):
            t = r.string()
            parts = [r.i32() for _ in range(r.i32())]
            topics.append((t, parts))
        with self._lock:
            if fence:
                self._fence_pid_locked(ppid)
            if abort_txn and txn_id is not None:
                self._abort_ongoing_locked(txn_id)
            entry = self._txns.get(txn_id)
            if forced_err:
                err = forced_err
            elif entry is None or entry["pid"] != ppid:
                err = ERR_INVALID_PID_MAPPING
            elif epoch != entry["epoch"]:
                err = ERR_INVALID_EPOCH
            else:
                err = 0
                entry["state"] = "ongoing"
                for t, parts in topics:
                    for p in parts:
                        entry["partitions"].add((t, p))
                        self.logs.setdefault((t, p), [])
        w.i32(0).i32(len(topics))
        for t, parts in topics:
            w.string(t).i32(len(parts))
            for p in parts:
                w.i32(p).i16(err)

    def _end_txn(
        self, r: Reader, w: Writer, forced_err: int = 0,
        fence: bool = False, abort_txn: bool = False,
    ) -> None:
        txn_id = r.string()
        ppid = r.i64()
        epoch = r.i16()
        commit = bool(r.i8())
        with self._lock:
            if fence:
                self._fence_pid_locked(ppid)
            if abort_txn and txn_id is not None:
                self._abort_ongoing_locked(txn_id)
            entry = self._txns.get(txn_id)
            if forced_err:
                err = forced_err
            elif entry is None or entry["pid"] != ppid:
                err = ERR_INVALID_PID_MAPPING
            elif epoch != entry["epoch"]:
                err = ERR_INVALID_EPOCH
            elif entry["state"] != "ongoing":
                # nothing open: for a RESUMED commit this is the
                # already-completed signal (see runtime/kafka.py)
                err = ERR_INVALID_TXN_STATE
            else:
                err = 0
                self._complete_txn_locked(entry, commit=commit)
        w.i32(0).i16(err)


def read_topic(
    bootstrap: str,
    topic: str,
    partition: int = 0,
    committed: bool = True,
) -> List[bytes]:
    """Drain one partition through the REAL client and return its data
    record values — the external observer the exactly-once claims are
    asserted against. ``committed=True`` consumes read_committed
    (isolation 1: capped at the LSO, aborted transactions filtered by
    the client from the wire index); ``committed=False`` consumes
    read_uncommitted, where aborted/open transactional data is still
    visible. Control-batch and aborted-record offsets advance without
    contributing values. Assumes a quiescent broker (post-run): stops
    at the first fetch that makes no progress."""
    from flink_siddhi_tpu.runtime.kafka import KafkaClient

    host, _, port = bootstrap.partition(":")
    client = KafkaClient(host, int(port or 9092))
    try:
        off = client.list_offsets(topic, [partition], -2)[partition]
        values: List[bytes] = []
        while True:
            res = client.fetch(
                topic, {partition: off},
                isolation=1 if committed else 0,
            )
            _hw, records, _raw = res[partition]
            progressed = False
            for o, _ts, _k, v in records:
                if o < off:
                    continue  # whole-batch resend below the position
                if v is not None:
                    values.append(v)
                off = o + 1
                progressed = True
            if not progressed:
                return values
    finally:
        client.close()
