"""In-process fake Kafka broker for protocol-level tests.

Two dialects, selected at construction:

* default ("modern", a >=2.x broker): answers ApiVersions (api 18) and
  advertises Produce up to v5 / Fetch up to v6 so the client's
  negotiation exercises real intersection (it implements 3 and 4);
  Produce v3 accepts v2 record batches (CRC32C validated, gzip
  inflated — a corrupt batch gets error code 2, CORRUPT_MESSAGE);
  Fetch v4 serves v2 batches re-encoded with ``fetch_codec`` (gzip by
  default) and — like a real broker — returns *whole batches*: a fetch
  offset landing mid-batch returns the batch containing it, and at
  least one batch is always returned regardless of max_bytes (KIP-74).
* ``legacy=True`` (a pre-0.10 broker): v0 apis only; an ApiVersions
  request slams the connection, which is exactly how old brokers
  answered and what the client's fallback-to-v0 path keys off.

Single node, in-memory logs. Batch boundaries are remembered per
produce/append call so whole-batch fetch semantics are honest.
``mangle_batch`` (a bytes->bytes hook applied to every served v2
batch) lets tests inject corruption or foreign codec flags on the
wire without touching the log.

Fault injection (``fault_hook``): a callable ``(api, seq) -> action``
consulted once per request, in arrival order (``seq`` is a
broker-lifetime request counter — deterministic schedules replay
exactly). Actions:

* ``None``             — serve normally
* ``"drop"``           — close the connection without answering (an
                         outage / crashed broker)
* ``"drop_mid_frame"`` — send the size header + half the response,
                         then close (the exact failure the client's
                         ``_read_frame`` sees as mid-frame close)
* ``"error"``          — answer Fetch/Produce/ListOffsets with the
                         transient NOT_LEADER_FOR_PARTITION code (6)
                         instead of data (other apis: like ``drop``)
* ``"corrupt"``        — serve THIS fetch's v2 batches mangled
                         (bit-flip => CRC32C mismatch); the log is
                         untouched, the next fetch is clean (other
                         apis: like ``drop``)
* ``"delay"``          — serve normally after ``fault_delay_s``
                         (default 2 ms; bounded, never a test clock)
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from bisect import bisect_right
from typing import Callable, Dict, List, Optional, Tuple

from flink_siddhi_tpu.connectors.kafka.protocol import (
    API_FETCH,
    API_LIST_OFFSETS,
    API_METADATA,
    API_PRODUCE,
    API_VERSIONS,
    Reader,
    Writer,
    encode_api_versions_response,
)
from flink_siddhi_tpu.connectors.kafka.records import (
    CorruptBatchError,
    decode_record_set,
    encode_message_set,
    encode_record_batch,
)

ERR_CORRUPT_MESSAGE = 2
ERR_UNKNOWN_TOPIC = 3
ERR_NOT_LEADER = 6  # transient: the client's retry taxonomy retries it

# what the modern dialect advertises (intentionally wider than the
# client implements: negotiation must intersect, not parrot)
MODERN_API_VERSIONS: Dict[int, Tuple[int, int]] = {
    API_PRODUCE: (0, 5),
    API_FETCH: (0, 6),
    API_LIST_OFFSETS: (0, 2),
    API_METADATA: (0, 5),
    API_VERSIONS: (0, 1),
}


class FakeBroker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        legacy: bool = False,
        fetch_codec: str = "gzip",
        api_versions: Optional[Dict[int, Tuple[int, int]]] = None,
    ) -> None:
        # (topic, partition) -> list of (ts, value)
        self.logs: Dict[Tuple[str, int], List] = {}
        # (topic, partition) -> sorted batch start offsets; batch i
        # covers [starts[i], starts[i+1]) (last runs to len(log))
        self.bounds: Dict[Tuple[str, int], List[int]] = {}
        self.legacy = legacy
        self.fetch_codec = fetch_codec
        self.api_versions = dict(
            MODERN_API_VERSIONS if api_versions is None else api_versions
        )
        self.mangle_batch: Optional[Callable[[bytes], bytes]] = None
        # fault injection: (api, request_seq) -> action (see module
        # docstring); None = no faults. Request seq is broker-lifetime
        # and monotonic, so a seeded schedule replays deterministically.
        self.fault_hook: Optional[Callable[[int, int], Optional[str]]] = None
        self.fault_delay_s = 0.002
        self._req_seq = 0
        self._lock = threading.Lock()
        self._server = socket.create_server((host, port))
        self._server.settimeout(0.2)
        self.host, self.port = self._server.getsockname()[:2]
        self._closed = False
        threading.Thread(target=self._accept, daemon=True).start()

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            for p in range(partitions):
                self.logs.setdefault((topic, p), [])
                self.bounds.setdefault((topic, p), [])

    def append(self, topic: str, partition: int, values, ts_ms=0):
        """Append values as ONE batch (one bound) — a v4 fetch of any
        offset inside it returns the whole thing."""
        with self._lock:
            log = self.logs[(topic, partition)]
            self.bounds.setdefault((topic, partition), []).append(len(log))
            for v in values:
                if isinstance(v, str):
                    v = v.encode()
                log.append((ts_ms, v))

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass

    # -- server loop ------------------------------------------------------
    def _accept(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._closed:
                head = b""
                while len(head) < 4:
                    chunk = conn.recv(4 - len(head))
                    if not chunk:
                        return
                    head += chunk
                (size,) = struct.unpack(">i", head)
                data = bytearray()
                while len(data) < size:
                    chunk = conn.recv(min(1 << 16, size - len(data)))
                    if not chunk:
                        return
                    data += chunk
                resp = self._handle(bytes(data))
                if resp is None:  # legacy broker / drop fault: hang up
                    return
                if isinstance(resp, tuple):  # ("partial", payload)
                    _, payload = resp
                    conn.sendall(
                        struct.pack(">i", len(payload))
                        + payload[: max(len(payload) // 2, 1)]
                    )
                    return  # close mid-frame
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        finally:
            conn.close()

    # -- request dispatch -------------------------------------------------
    def _handle(self, data: bytes):
        r = Reader(data)
        api, version, corr = r.i16(), r.i16(), r.i32()
        r.string()  # client_id
        # fault injection happens here, per request, in arrival order
        fault = None
        if self.fault_hook is not None:
            with self._lock:
                seq = self._req_seq
                self._req_seq += 1
            fault = self.fault_hook(api, seq)
        if fault == "drop":
            return None
        if fault == "delay":
            time.sleep(self.fault_delay_s)
            fault = None
        forced_err = 0
        corrupt = False
        if fault == "error":
            if api in (API_FETCH, API_PRODUCE, API_LIST_OFFSETS):
                forced_err = ERR_NOT_LEADER
            else:
                return None  # no error slot in these responses: drop
        elif fault == "corrupt":
            if api == API_FETCH:
                corrupt = True
            else:
                return None
        w = Writer().i32(corr)
        if api == API_VERSIONS:
            if self.legacy:
                return None  # pre-0.10: slam the connection
            w.raw(encode_api_versions_response(self.api_versions))
        elif api == API_METADATA:
            self._metadata(r, w)
        elif api == API_LIST_OFFSETS:
            self._list_offsets(r, w, forced_err)
        elif api == API_FETCH:
            if version not in (0, 4):
                raise AssertionError(f"fake broker: Fetch v{version}")
            self._fetch(r, w, version, forced_err, corrupt)
        elif api == API_PRODUCE:
            if version not in (0, 3):
                raise AssertionError(f"fake broker: Produce v{version}")
            self._produce(r, w, version, forced_err)
        else:
            if self.legacy:
                return None
            raise AssertionError(f"fake broker: unsupported api {api}")
        if fault == "drop_mid_frame":
            return ("partial", w.done())
        return w.done()

    def _metadata(self, r: Reader, w: Writer) -> None:
        n = r.i32()
        topics = [r.string() for _ in range(n)]
        with self._lock:
            if not topics:
                topics = sorted({t for t, _ in self.logs})
            w.i32(1).i32(0).string(self.host).i32(self.port)
            w.i32(len(topics))
            for t in topics:
                parts = sorted(p for (tt, p) in self.logs if tt == t)
                w.i16(0 if parts else ERR_UNKNOWN_TOPIC).string(t)
                w.i32(len(parts))
                for p in parts:
                    w.i16(0).i32(p).i32(0)
                    w.i32(1).i32(0)  # replicas [0]
                    w.i32(1).i32(0)  # isr [0]

    def _list_offsets(
        self, r: Reader, w: Writer, forced_err: int = 0
    ) -> None:
        r.i32()  # replica
        w.i32(r_topics := r.i32())
        for _ in range(r_topics):
            t = r.string()
            np_ = r.i32()
            w.string(t).i32(np_)
            for _ in range(np_):
                pid, time_, _maxn = r.i32(), r.i64(), r.i32()
                if forced_err:
                    w.i32(pid).i16(forced_err).i32(0)
                    continue
                with self._lock:
                    log = self.logs.get((t, pid))
                if log is None:
                    w.i32(pid).i16(ERR_UNKNOWN_TOPIC).i32(0)
                    continue
                off = 0 if time_ == -2 else len(log)
                w.i32(pid).i16(0).i32(1).i64(off)

    # -- fetch ------------------------------------------------------------
    def _fetch(
        self, r: Reader, w: Writer, version: int,
        forced_err: int = 0, corrupt: bool = False,
    ) -> None:
        r.i32(), r.i32(), r.i32()  # replica, max_wait, min_bytes
        if version >= 4:
            r.i32(), r.i8()  # total max_bytes, isolation_level
            w.i32(0)  # throttle_time_ms
        nt = r.i32()
        w.i32(nt)
        for _ in range(nt):
            t = r.string()
            np_ = r.i32()
            w.string(t).i32(np_)
            for _ in range(np_):
                pid, off, maxb = r.i32(), r.i64(), r.i32()
                with self._lock:
                    log = list(self.logs.get((t, pid), ()))
                    bounds = list(self.bounds.get((t, pid), ()))
                hw = len(log)
                if forced_err:
                    w.i32(pid).i16(forced_err).i64(hw)
                    if version >= 4:
                        w.i64(hw).i32(0)
                    w.bytes_(b"")
                    continue
                if version >= 4:
                    rset = self._serve_batches(
                        log, bounds, off, maxb, corrupt=corrupt
                    )
                    w.i32(pid).i16(0).i64(hw)
                    w.i64(hw)  # last_stable_offset
                    w.i32(0)  # aborted_transactions
                    w.bytes_(rset)
                else:
                    rset = self._serve_messages(log, off, maxb)
                    w.i32(pid).i16(0).i64(hw).bytes_(rset)

    @staticmethod
    def _serve_messages(log, off: int, maxb: int) -> bytes:
        """v0 dialect: one legacy message per record, byte-capped."""
        mset = b""
        o = off
        while o < len(log) and len(mset) < maxb:
            ts, v = log[o]
            one = encode_message_set([v], ts_ms=ts)
            # stamp the real offset into the entry header
            one = struct.pack(">q", o) + one[8:]
            mset += one
            o += 1
        return mset

    def _serve_batches(
        self, log, bounds, off: int, maxb: int, corrupt: bool = False
    ) -> bytes:
        """v4 dialect: whole v2 batches, starting with the batch that
        CONTAINS the fetch offset; always at least one batch.
        ``corrupt=True`` (one fetch's fault action) flips a payload
        bit in every served batch — CRC32C fails client-side, the log
        itself stays clean."""
        if off >= len(log) or not bounds:
            return b""
        from flink_siddhi_tpu.connectors.kafka.codecs import codec_id

        i = max(bisect_right(bounds, off) - 1, 0)
        out = b""
        while i < len(bounds) and (not out or len(out) < maxb):
            start = bounds[i]
            end = bounds[i + 1] if i + 1 < len(bounds) else len(log)
            entries = [(ts, None, v) for ts, v in log[start:end]]
            batch = encode_record_batch(
                entries,
                base_offset=start,
                codec=codec_id(self.fetch_codec),
            )
            if self.mangle_batch is not None:
                batch = self.mangle_batch(batch)
            if corrupt:
                b = bytearray(batch)
                b[-1] ^= 0x04  # payload bit: breaks the batch CRC32C
                batch = bytes(b)
            out += batch
            i += 1
        return out

    # -- produce ----------------------------------------------------------
    def _produce(
        self, r: Reader, w: Writer, version: int, forced_err: int = 0
    ) -> None:
        if version >= 3:
            r.string()  # transactional_id
        r.i16(), r.i32()  # acks, timeout
        nt = r.i32()
        w.i32(nt)
        for _ in range(nt):
            t = r.string()
            np_ = r.i32()
            w.string(t).i32(np_)
            for _ in range(np_):
                pid = r.i32()
                rset = r.bytes_() or b""
                if forced_err:
                    # transient refusal: NOTHING is appended — the
                    # client's retry re-sends the whole batch
                    w.i32(pid).i16(forced_err).i64(-1)
                    if version >= 2:
                        w.i64(-1)
                    continue
                try:
                    msgs = decode_record_set(rset)
                    err = 0
                except CorruptBatchError:
                    msgs, err = [], ERR_CORRUPT_MESSAGE
                with self._lock:
                    log = self.logs.setdefault((t, pid), [])
                    base = len(log)
                    if msgs:
                        self.bounds.setdefault((t, pid), []).append(base)
                    for _off, ts, _k, v in msgs:
                        log.append((ts or 0, v))
                w.i32(pid).i16(err).i64(base)
                if version >= 2:
                    w.i64(-1)  # log_append_time
        if version >= 1:
            w.i32(0)  # throttle_time_ms
