"""In-process fake Kafka broker speaking the v0 wire protocol subset the
engine's client uses (Metadata/ListOffsets/Fetch/Produce, MessageSet
magic 0/1). Single node, in-memory logs, enough fidelity to test
offset semantics: fetches honor offsets, produce appends and assigns
base offsets, ListOffsets reports earliest/latest."""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Tuple

from flink_siddhi_tpu.runtime.kafka import (
    _Reader,
    _Writer,
    decode_message_set,
    encode_message_set,
)


class FakeBroker:
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        # (topic, partition) -> list of (ts, value)
        self.logs: Dict[Tuple[str, int], List] = {}
        self._lock = threading.Lock()
        self._server = socket.create_server((host, port))
        self._server.settimeout(0.2)
        self.host, self.port = self._server.getsockname()[:2]
        self._closed = False
        threading.Thread(target=self._accept, daemon=True).start()

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            for p in range(partitions):
                self.logs.setdefault((topic, p), [])

    def append(self, topic: str, partition: int, values, ts_ms=0):
        with self._lock:
            log = self.logs[(topic, partition)]
            for v in values:
                if isinstance(v, str):
                    v = v.encode()
                log.append((ts_ms, v))

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass

    # -- server loop ------------------------------------------------------
    def _accept(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._closed:
                head = b""
                while len(head) < 4:
                    chunk = conn.recv(4 - len(head))
                    if not chunk:
                        return
                    head += chunk
                (size,) = struct.unpack(">i", head)
                data = bytearray()
                while len(data) < size:
                    chunk = conn.recv(min(1 << 16, size - len(data)))
                    if not chunk:
                        return
                    data += chunk
                resp = self._handle(bytes(data))
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        finally:
            conn.close()

    def _handle(self, data: bytes) -> bytes:
        r = _Reader(data)
        api, version, corr = r.i16(), r.i16(), r.i32()
        r.string()  # client_id
        w = _Writer().i32(corr)
        if api == 3:  # Metadata v0
            n = r.i32()
            topics = [r.string() for _ in range(n)]
            with self._lock:
                if not topics:
                    topics = sorted({t for t, _ in self.logs})
                w.i32(1).i32(0).string(self.host).i32(self.port)
                w.i32(len(topics))
                for t in topics:
                    parts = sorted(
                        p for (tt, p) in self.logs if tt == t
                    )
                    w.i16(0 if parts else 3).string(t)
                    w.i32(len(parts))
                    for p in parts:
                        w.i16(0).i32(p).i32(0)
                        w.i32(1).i32(0)  # replicas [0]
                        w.i32(1).i32(0)  # isr [0]
        elif api == 2:  # ListOffsets v0
            r.i32()  # replica
            w.i32(r_topics := r.i32())
            for _ in range(r_topics):
                t = r.string()
                np_ = r.i32()
                w.string(t).i32(np_)
                for _ in range(np_):
                    pid, time_, _maxn = r.i32(), r.i64(), r.i32()
                    with self._lock:
                        log = self.logs.get((t, pid))
                    if log is None:
                        w.i32(pid).i16(3).i32(0)
                        continue
                    off = 0 if time_ == -2 else len(log)
                    w.i32(pid).i16(0).i32(1).i64(off)
        elif api == 1:  # Fetch v0
            r.i32()
            r.i32()
            r.i32()  # replica, max_wait, min_bytes
            nt = r.i32()
            w.i32(nt)
            for _ in range(nt):
                t = r.string()
                np_ = r.i32()
                w.string(t).i32(np_)
                for _ in range(np_):
                    pid, off, maxb = r.i32(), r.i64(), r.i32()
                    with self._lock:
                        log = list(self.logs.get((t, pid), ()))
                    hw = len(log)
                    mset = b""
                    size = 0
                    o = off
                    while o < hw and size < maxb:
                        ts, v = log[o]
                        one = encode_message_set([v], ts_ms=ts)
                        # stamp the real offset into the entry header
                        one = struct.pack(">q", o) + one[8:]
                        mset += one
                        size += len(one)
                        o += 1
                    w.i32(pid).i16(0).i64(hw).bytes_(mset)
        elif api == 0:  # Produce v0
            r.i16()
            r.i32()  # acks, timeout
            nt = r.i32()
            w.i32(nt)
            for _ in range(nt):
                t = r.string()
                np_ = r.i32()
                w.string(t).i32(np_)
                for _ in range(np_):
                    pid = r.i32()
                    mset = r.bytes_() or b""
                    msgs = decode_message_set(mset)
                    with self._lock:
                        log = self.logs.setdefault((t, pid), [])
                        base = len(log)
                        for _off, ts, _k, v in msgs:
                            log.append((ts or 0, v))
                    w.i32(pid).i16(0).i64(base)
        else:
            raise AssertionError(f"fake broker: unsupported api {api}")
        return w.done()
