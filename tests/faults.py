"""Deterministic fault-injection harness.

Two injection axes, both seeded, both replayable:

* **wire faults** — :class:`FaultSchedule` plugs into
  ``FakeBroker.fault_hook`` and decides, per request (in the broker's
  deterministic arrival order), whether to drop the connection, close
  it mid-frame, answer with a transient broker error code, serve a
  corrupt batch, or delay. Consecutive faults are capped below the
  client's retry budget, so a bounded RetryPolicy always eventually
  gets through — the schedule injects pain, not livelock.
* **process faults** — :class:`CrashPlan` + :func:`wrap_job`
  (re-exported from ``flink_siddhi_tpu.runtime.faultinject``, the one
  shared implementation that ``bench.py --fault`` also drives) inject
  crashes into a SUPERVISED job at scheduled source-pull boundaries
  and killed-mid-checkpoint; see that module's docstring.

No wall-clock sleeps anywhere (the only sleep is the broker's bounded
2 ms ``delay`` action and the client's own milliseconds-scale test
backoff); every decision is a function of (seed, sequence number).
"""

from __future__ import annotations

import random
import threading
from typing import Optional, Sequence

from flink_siddhi_tpu.runtime.faultinject import (  # noqa: F401
    CrashPlan,
    InjectedCrash,
    wrap_job,
)


class FaultSchedule:
    """Seeded per-request wire-fault decisions for FakeBroker.

    ``p_fault`` is the per-request fault probability; ``actions`` the
    pool drawn from. ``max_consecutive`` caps the run of consecutive
    faulted requests (default 2 — safely below the client's default
    5-attempt budget)."""

    ACTIONS = ("drop", "drop_mid_frame", "error", "corrupt", "delay")

    def __init__(
        self,
        seed: int,
        p_fault: float = 0.2,
        actions: Sequence[str] = ACTIONS,
        max_consecutive: int = 2,
    ) -> None:
        self._rng = random.Random(seed)
        self.p_fault = float(p_fault)
        self.actions = tuple(actions)
        self.max_consecutive = int(max_consecutive)
        self._consecutive = 0
        self.injected = []  # [(seq, api, action)] — the audit trail
        # the broker serves connections from multiple threads; the
        # schedule must stay an ordered, race-free decision sequence
        self._lock = threading.Lock()

    def __call__(self, api: int, seq: int) -> Optional[str]:
        with self._lock:
            fault = (
                self._consecutive < self.max_consecutive
                and self._rng.random() < self.p_fault
            )
            if not fault:
                self._consecutive = 0
                return None
            action = self.actions[
                self._rng.randrange(len(self.actions))
            ]
            self._consecutive += 1
            self.injected.append((seq, api, action))
            return action
