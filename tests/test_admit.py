"""Admission-time query analysis (analysis/admit.py).

Pins the three tentpole contracts: (1) every legitimate zoo entry is
admitted under default budgets with FINITE reported bounds, and the
footprint bound dominates the actually-materialized state; (2) every
hostile zoo entry is rejected with its exact ADM rule id; (3) the
shape-bucket plan signature collides on constants-only changes, splits
across shape/bucket boundaries, and is stable across process restarts
— the AOT executable-cache key contract (docs/static_analysis.md).
"""

import dataclasses
import os
import subprocess
import sys

import numpy as np
import pytest

from flink_siddhi_tpu.analysis.admit import (
    ADM_RULES,
    AdmissionBudgets,
    AdmissionError,
    DEFAULT_BUDGETS,
    STRICT_BUDGETS,
    admit_plan,
    analyze_plan,
    plan_signature,
    segment_signatures,
)
from flink_siddhi_tpu.analysis.zoo import (
    HOSTILE_ZOO,
    PLAN_ZOO,
    compile_zoo,
    hostile_budgets,
    zoo_schemas,
)
from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def zoo():
    return dict(compile_zoo())


def _sig(cql, capacity=128, plan_id="p", **schemas_kw):
    plan = compile_plan(cql, zoo_schemas(), plan_id=plan_id)
    return plan_signature(plan, capacity=capacity)


# -- resource bounds --------------------------------------------------------


def test_all_zoo_entries_admitted_with_finite_bounds(zoo):
    for name, plan in zoo.items():
        rep = analyze_plan(plan, budgets=DEFAULT_BUDGETS)
        assert rep.admitted, (name, [i.render() for i in rep.findings])
        assert isinstance(rep.state_bytes, int) and rep.state_bytes >= 0
        assert isinstance(rep.acc_bytes, int) and rep.acc_bytes > 0
        assert 0 <= rep.amplification < 1 << 20
        assert rep.signature is not None
        # per-artifact cost rows surfaced for every artifact
        assert len(rep.per_artifact) == len(plan.artifacts)


@pytest.mark.parametrize(
    "entry", sorted(HOSTILE_ZOO), ids=sorted(HOSTILE_ZOO)
)
def test_hostile_zoo_rejected_by_exact_rule(entry):
    cql, expected_rule, profile = HOSTILE_ZOO[entry]
    plan = compile_plan(cql, zoo_schemas(), plan_id=f"hostile:{entry}")
    rep = analyze_plan(plan, budgets=hostile_budgets(profile))
    assert not rep.admitted, entry
    assert expected_rule in {i.rule for i in rep.findings}, (
        entry, [i.render() for i in rep.findings],
    )
    assert expected_rule in ADM_RULES
    # and the SAME entry under no-residency default budgets still
    # rejects for the default-profile entries (they are hostile per
    # se, not just under the strict profile)
    if profile == "default":
        with pytest.raises(AdmissionError) as ei:
            admit_plan(plan, budgets=DEFAULT_BUDGETS)
        assert expected_rule in {i.rule for i in ei.value.issues}


@pytest.mark.parametrize(
    "name",
    ["length_window_agg", "chain_pattern", "multiquery_stack6"],
)
def test_footprint_bound_dominates_measured_state(name, zoo):
    """The reported worst-case state footprint must be >= the nbytes
    the plan ACTUALLY materializes at init (the bound is the
    admission-time bucket shapes, which is exactly what init builds)."""
    plan = zoo[name]
    rep = analyze_plan(plan, budgets=DEFAULT_BUDGETS)
    states = plan.init_state()
    import jax

    actual = sum(
        np.asarray(leaf).nbytes
        for leaf in jax.tree_util.tree_leaves(states)
    )
    assert actual > 0
    assert rep.state_bytes >= actual, (name, rep.state_bytes, actual)


def test_missing_cost_info_hook_is_adm001(zoo, monkeypatch):
    from flink_siddhi_tpu.compiler.select import SelectArtifact

    monkeypatch.delattr(SelectArtifact, "cost_info")
    rep = analyze_plan(zoo["filter_select"], budgets=DEFAULT_BUDGETS)
    assert [i.rule for i in rep.findings] == ["ADM001"]


def test_malformed_cost_info_is_adm002(zoo, monkeypatch):
    from flink_siddhi_tpu.compiler.select import SelectArtifact

    monkeypatch.setattr(
        SelectArtifact, "cost_info", lambda self: {"name": self.name}
    )
    rep = analyze_plan(zoo["filter_select"], budgets=DEFAULT_BUDGETS)
    assert [i.rule for i in rep.findings] == ["ADM002"]


def test_residency_budget_passes_bounded_patterns(zoo):
    """STRICT (bounded-residency) budgets admit the 'within'-bounded
    chain while rejecting its unbounded twin — the knob rejects the
    hazard, not the feature."""
    ok = analyze_plan(zoo["chain_pattern_within"], budgets=STRICT_BUDGETS)
    assert ok.admitted, [i.render() for i in ok.findings]
    bad = analyze_plan(zoo["chain_pattern"], budgets=STRICT_BUDGETS)
    assert {i.rule for i in bad.findings} == {"ADM110"}


# -- compile_plan wiring ----------------------------------------------------


def test_engineconfig_budgets_reject_at_compile(monkeypatch):
    cql, expected_rule, _ = HOSTILE_ZOO["hostile_length_window_1m"]
    cfg = EngineConfig(admission_budgets=DEFAULT_BUDGETS)
    with pytest.raises(AdmissionError) as ei:
        compile_plan(cql, zoo_schemas(), plan_id="p", config=cfg)
    assert expected_rule in {i.rule for i in ei.value.issues}
    # FST_VERIFY_PLANS=0 is the bench escape hatch: even explicit
    # budgets are skipped (same contract as plancheck)
    monkeypatch.setenv("FST_VERIFY_PLANS", "0")
    plan = compile_plan(cql, zoo_schemas(), plan_id="p", config=cfg)
    assert plan.plan_id == "p"


def test_budget_knobs_are_enforced_individually(zoo):
    plan = zoo["length_window_agg"]
    tight_state = AdmissionBudgets(max_state_bytes=16)
    assert {
        i.rule
        for i in analyze_plan(plan, budgets=tight_state).findings
    } == {"ADM101"}
    tight_acc = AdmissionBudgets(max_acc_bytes=1024)
    assert {
        i.rule
        for i in analyze_plan(plan, budgets=tight_acc).findings
    } == {"ADM102"}
    tight_amp = AdmissionBudgets(max_amplification=0)
    got = {
        i.rule
        for i in analyze_plan(plan, budgets=tight_amp).findings
    }
    assert got == {"ADM120"}


# -- shape-bucket plan signatures -------------------------------------------


def test_signature_constants_only_change_collides():
    a = _sig("from S[id == 2] select id, name, price insert into out")
    b = _sig(
        "from S[id == 7] select id, name, price insert into out",
        plan_id="other-tenant",
    )
    assert a == b  # filter constants AND plan ids are not shape


def test_signature_time_span_constants_collide():
    a = _sig("from S#window.time(3 sec) select sum(price) as t "
             "insert into out")
    b = _sig("from S#window.time(5 sec) select sum(price) as t "
             "insert into out")
    assert a == b  # span is a literal operand; state shapes identical


def test_signature_within_constants_collide_presence_splits():
    p5 = _sig("from every s1 = S[id == 1] -> s2 = S[id == 2] "
              "within 5 sec select s1.price as a insert into out")
    p6 = _sig("from every s1 = S[id == 1] -> s2 = S[id == 2] "
              "within 6 sec select s1.price as a insert into out")
    p0 = _sig("from every s1 = S[id == 1] -> s2 = S[id == 2] "
              "select s1.price as a insert into out")
    assert p5 == p6
    assert p5 != p0  # with/without within are different programs


def test_signature_operator_change_splits():
    a = _sig("from S[id == 2] select id, name, price insert into out")
    c = _sig("from S[id > 2] select id, name, price insert into out")
    assert a != c  # == vs > is structure, not a constant


def test_signature_window_width_across_shape_boundary_splits():
    w16 = _sig("from S#window.length(16) select sum(price) as t "
               "insert into out")
    w17 = _sig("from S#window.length(17) select sum(price) as t "
               "insert into out")
    assert w16 != w17  # the ring shape IS the executable's shape


def test_signature_batch_capacity_buckets():
    q = "from S[id == 2] select id, name, price insert into out"
    assert _sig(q, capacity=100) == _sig(q, capacity=128)
    assert _sig(q, capacity=128) != _sig(q, capacity=129)


def test_signature_stable_across_process_restart(zoo):
    """The AOT-cache key must be reproducible in a FRESH process (no
    Python hash(), no id()s, no iteration-order dependence) — a
    restart that recomputed different keys would cold-compile every
    running tenant's plan again."""
    names = ["filter_select", "chain_pattern_within", "window_join"]
    here = {n: plan_signature(zoo[n]) for n in names}
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['FST_VERIFY_PLANS'] = '0'\n"
        "from flink_siddhi_tpu.analysis.zoo import PLAN_ZOO, zoo_schemas\n"
        "from flink_siddhi_tpu.analysis.admit import plan_signature\n"
        "from flink_siddhi_tpu.compiler.plan import compile_plan\n"
        f"for n in {names!r}:\n"
        "    p = compile_plan(PLAN_ZOO[n], zoo_schemas(),\n"
        "                     plan_id=f'zoo:{n}')\n"
        "    print(n, plan_signature(p))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=REPO, timeout=240,
        check=True,
    ).stdout
    fresh = dict(line.split() for line in out.strip().splitlines())
    assert fresh == here


# -- per-segment prefix signatures (the subplan-share key space) -------------


def _segsigs(cql, plan_id="p"):
    plan = compile_plan(cql, zoo_schemas(), plan_id=plan_id)
    return segment_signatures(plan)


def test_segment_signatures_share_prefix_split_at_divergence():
    """Two tenants whose queries agree on the leading filter bracket
    but diverge after it must agree on every prefix segment key up to
    the divergence and split exactly there — the property the
    control plane's subplan-share decision keys on."""
    a = _segsigs(
        "from S[price > 2.0][id == 1] select id insert into out"
    )[0]
    b = _segsigs(
        "from S[price > 2.0][id > 3] select name insert into o2",
        plan_id="other-tenant",
    )[0]
    assert len(a) == len(b) == 4  # source, filter, filter, select
    assert a[0] == b[0]  # same source stream
    assert a[1] == b[1]  # same shared leading filter
    assert a[2] != b[2]  # == vs > is structure: keys diverge here
    assert a[3] != b[3]  # cumulative: divergence never heals


def test_segment_signatures_constants_only_change_collides():
    a = _segsigs(
        "from S[price > 2.0][id == 1] select id insert into out"
    )[0]
    b = _segsigs(
        "from S[price > 9.0][id == 7] select id insert into out"
    )[0]
    assert a == b  # literals are masked, exactly like plan_signature


def test_segment_signatures_structural_prefix_change_splits():
    a = _segsigs("from S[price > 2.0] select id insert into out")[0]
    b = _segsigs("from S[price >= 2.0] select id insert into out")[0]
    assert a[0] == b[0]  # source segment agrees
    assert a[1] != b[1]  # operator change splits the filter segment


def test_segment_signatures_stable_across_process_restart():
    """Same contract as plan_signature: a fresh process must derive
    byte-identical segment keys, or a restarted control plane would
    stop recognizing live shared prefixes."""
    cqls = [
        "from S[price > 2.0][id == 1] select id insert into out",
        "from S[price > 2.0] select sum(price) as t insert into o2",
        "from every s1 = S[id == 1] -> s2 = S[id == 2] "
        "within 5 sec select s1.price as a insert into o3",
    ]
    here = [_segsigs(c, plan_id=f"q{i}") for i, c in enumerate(cqls)]
    code = (
        "import os, json\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['FST_VERIFY_PLANS'] = '0'\n"
        "from flink_siddhi_tpu.analysis.zoo import zoo_schemas\n"
        "from flink_siddhi_tpu.analysis.admit import "
        "segment_signatures\n"
        "from flink_siddhi_tpu.compiler.plan import compile_plan\n"
        f"for i, c in enumerate({cqls!r}):\n"
        "    p = compile_plan(c, zoo_schemas(), plan_id=f'q{i}')\n"
        "    print(json.dumps(segment_signatures(p)))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=REPO, timeout=240,
        check=True,
    ).stdout
    import json

    fresh = [json.loads(line) for line in out.strip().splitlines()]
    assert fresh == here


def test_disk_tier_store_keys_stable_across_processes(zoo):
    """The PR 11 property extended to the persistent warm-start store
    (fleet/warmstore.py): the on-disk directory name derived from the
    AOT cache key — and the platform namespace above it — must come
    out identical in TWO independent fresh processes, or two replicas
    sharing one store directory would miss each other's executables.
    (That a store written by process A yields ZERO new lowerings in
    process B is pinned end-to-end in tests/test_fleet.py.)"""
    from flink_siddhi_tpu.control.aotcache import cache_key
    from flink_siddhi_tpu.fleet.warmstore import (
        store_key_dir,
        store_namespace,
    )

    names = ["filter_select", "chain_pattern_within", "window_join"]
    here = {}
    for n in names:
        key = cache_key(zoo[n])
        if key is not None:
            here[n] = f"{store_namespace()}/{store_key_dir(key)}"
    assert here, "no zoo plan produced a cacheable store key"
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['FST_VERIFY_PLANS'] = '0'\n"
        "from flink_siddhi_tpu.analysis.zoo import PLAN_ZOO, zoo_schemas\n"
        "from flink_siddhi_tpu.compiler.plan import compile_plan\n"
        "from flink_siddhi_tpu.control.aotcache import cache_key\n"
        "from flink_siddhi_tpu.fleet.warmstore import (\n"
        "    store_key_dir, store_namespace)\n"
        f"for n in {names!r}:\n"
        "    p = compile_plan(PLAN_ZOO[n], zoo_schemas(),\n"
        "                     plan_id=f'zoo:{n}')\n"
        "    key = cache_key(p)\n"
        "    if key is not None:\n"
        "        print(n, store_namespace() + '/' + store_key_dir(key))\n"
    )
    results = []
    for _ in range(2):
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, cwd=REPO, timeout=240,
            check=True,
        ).stdout
        results.append(
            dict(line.split() for line in out.strip().splitlines())
        )
    assert results[0] == here
    assert results[1] == here


# -- verdicts on the control plane ------------------------------------------


def test_admission_summary_rides_metadata_events_json():
    from flink_siddhi_tpu.control.events import (
        MetadataControlEvent,
        control_event_from_json,
        control_event_to_json,
    )

    plan = compile_plan(
        PLAN_ZOO["filter_select"], zoo_schemas(), plan_id="t1"
    )
    rep = analyze_plan(plan, budgets=DEFAULT_BUDGETS)
    b = MetadataControlEvent.builder()
    pid = b.add_execution_plan(
        PLAN_ZOO["filter_select"], admission=rep.summary()
    )
    ev = control_event_from_json(control_event_to_json(b.build()))
    assert ev.admission[pid]["admitted"] is True
    assert ev.admission[pid]["signature"] == rep.signature
    assert ev.admission[pid]["state_bytes"] == rep.state_bytes


def test_rejected_admission_verdict_refuses_control_add():
    """An add whose carried verdict says admitted=False must never
    reach the compiler/runtime — counted, logged, the rest of the
    event still applies (the control-plane groundwork)."""
    import dataclasses as dc

    from flink_siddhi_tpu import CEPEnvironment, MetadataControlEvent, SiddhiCEP

    @dc.dataclass
    class Event:
        id: int
        price: float
        timestamp: int

    events = [Event(1, float(i), 1000 * (i + 1)) for i in range(6)]
    b = MetadataControlEvent.builder()
    pid_ok = b.add_execution_plan(
        "from S select id, price insert into ok"
    )
    pid_bad = b.add_execution_plan(
        "from S select id, price insert into bad",
        admission={
            "admitted": False,
            "findings": [{"rule": "ADM110", "where": "x", "message": "m"}],
        },
    )
    env = CEPEnvironment(batch_size=2)
    es = SiddhiCEP.define(
        "S", events, ["id", "price", "timestamp"], env=env
    ).cql([(0, b.build())])
    job = es.execute()
    assert len(job.results("ok")) == len(events)
    assert job.results("bad") == []
    assert pid_bad not in job.plan_ids and pid_ok in job.plan_ids
    snap = job.telemetry.snapshot()
    assert snap["counters"]["control.admission_rejected"] == 1
