"""App layer: deployable pipeline + REST control service.

Reference parity: experimental/CEPPipeline.scala:33-78 (checkpointed,
restartable ingest->CEP->sink job) and CEPService.scala:43-95 (the
/api/v1/queries REST API the reference never implemented).
"""

import http.client
import json
import threading
import time

import pytest

from flink_siddhi_tpu.app import (
    CEPPipeline,
    ControlQueueSource,
    PipelineConfig,
    QueryControlService,
)


def write_events(path, n=120):
    with open(path, "w") as f:
        for i in range(n):
            f.write(
                json.dumps(
                    {
                        "id": i % 4,
                        "name": f"n{i % 3}",
                        "price": float(i),
                        "timestamp": 1000 + i,
                    }
                )
                + "\n"
            )


FIELDS = [
    ("id", "int"),
    ("name", "string"),
    ("price", "double"),
    ("timestamp", "long"),
]


def test_pipeline_end_to_end(tmp_path):
    inp, outp = tmp_path / "in.jsonl", tmp_path / "out.jsonl"
    write_events(inp)
    cfg = PipelineConfig(
        stream_id="S",
        fields=FIELDS,
        cql="from S[id == 2] select name, price insert into matches",
        input_path=str(inp),
        output_path=str(outp),
        ts_field="timestamp",
        batch_size=32,
    )
    pipe = CEPPipeline(cfg)
    pipe.run()
    pipe.close()
    rows = [json.loads(l) for l in open(outp)]
    assert len(rows) == 30
    assert rows[0]["stream"] == "matches"
    assert rows[0]["name"] == "n2" and rows[0]["price"] == 2.0
    assert rows[0]["ts"] == 1002


def test_pipeline_restart_resumes_from_checkpoint(tmp_path):
    inp, outp = tmp_path / "in.jsonl", tmp_path / "out.jsonl"
    ckpt = tmp_path / "job.ckpt"
    write_events(inp, n=100)
    cfg = PipelineConfig(
        stream_id="S",
        fields=FIELDS,
        cql="from S[id == 1] select price insert into m",
        input_path=str(inp),
        output_path=str(outp),
        ts_field="timestamp",
        batch_size=16,
        chunk_bytes=512,  # several ingest cycles so the crash hits mid-run
        checkpoint_path=str(ckpt),
        checkpoint_interval_s=0.0,  # checkpoint every cycle
        restart_attempts=2,
        restart_delay_s=0.0,
    )
    pipe = CEPPipeline(cfg, sleep=lambda s: None)
    # crash injection: fail once partway through the stream
    crashed = {"done": False}
    orig = CEPPipeline._run_once

    def flaky(self):
        cfg_ = self.config
        job = self.build()
        import os as _os

        if ckpt.exists():
            job.restore(str(ckpt))
        cycles = 0
        while not job.finished:
            job.run_cycle()
            job.save_checkpoint(str(ckpt))
            cycles += 1
            if cycles == 3 and not crashed["done"]:
                crashed["done"] = True
                raise RuntimeError("injected fault")
        job.flush()
        job.drain_outputs()
        self.job = job

    CEPPipeline._run_once = flaky
    try:
        pipe.run()
    finally:
        CEPPipeline._run_once = orig
    pipe.close()
    assert crashed["done"]
    rows = [json.loads(l) for l in open(outp)]
    # exactly-once per emission is not claimed across the crash boundary,
    # but every expected match must appear at least once and the tail
    # (post-restore) must not be lost
    prices = [r["price"] for r in rows]
    expected = [float(i) for i in range(100) if i % 4 == 1]
    assert set(expected) <= set(prices)


def test_pipeline_restart_exhaustion_raises(tmp_path):
    inp, outp = tmp_path / "in.jsonl", tmp_path / "out.jsonl"
    write_events(inp, n=10)
    cfg = PipelineConfig(
        stream_id="S",
        fields=FIELDS,
        cql="from S select id insert into m",
        input_path=str(inp),
        output_path=str(outp),
        restart_attempts=2,
        restart_delay_s=0.0,
    )
    pipe = CEPPipeline(cfg, sleep=lambda s: None)
    calls = {"n": 0}

    def always_fail(self):
        calls["n"] += 1
        raise RuntimeError("boom")

    orig = CEPPipeline._run_once
    CEPPipeline._run_once = always_fail
    try:
        with pytest.raises(RuntimeError):
            pipe.run()
    finally:
        CEPPipeline._run_once = orig
    assert calls["n"] == 3  # initial + 2 restarts (parity: 4x10s policy)


def test_control_service_rest_roundtrip(tmp_path):
    """Add/disable/enable/remove queries over HTTP against a running job."""
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import CallbackSource
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [
            ("id", AttributeType.INT),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )
    src = CallbackSource("S", schema)
    control = ControlQueueSource()
    plan0 = compile_plan(
        "from S[id == 0] select price insert into base",
        {"S": schema},
        plan_id="base",
    )
    job = Job(
        [plan0],
        [src],
        batch_size=8,
        time_mode="processing",
        control_sources=[control],
        plan_compiler=lambda cql, plan_id: compile_plan(
            cql, {"S": schema}, plan_id=plan_id
        ),
    )
    svc = QueryControlService(
        control,
        job=job,
        validate=lambda cql: compile_plan(cql, {"S": schema}),
    ).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)

        def call(method, path, body=None):
            conn.request(
                method, path,
                body=json.dumps(body) if body else None,
                headers={"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            return r.status, json.loads(r.read() or b"{}")

        # add a query over REST
        status, resp = call(
            "POST", "/api/v1/queries",
            {"cql": "from S[id == 1] select price insert into ones"},
        )
        assert status == 201
        qid = resp["id"]

        class Rec:
            def __init__(self, id, price, timestamp):
                self.id, self.price, self.timestamp = id, price, timestamp

        for i in range(8):
            src.emit(Rec(i % 2, float(i), 1000 + i), 1000 + i)
        job.run_cycle()  # applies the control event, steps both plans
        for i in range(8, 16):
            src.emit(Rec(i % 2, float(i), 1000 + i), 1000 + i)
        job.run_cycle()
        assert qid in job.plan_ids
        ones_so_far = len(job.results("ones"))
        assert ones_so_far > 0

        # disable, feed more, count must not grow
        status, _ = call("POST", f"/api/v1/queries/{qid}/disable")
        assert status == 200
        for i in range(16, 24):
            src.emit(Rec(i % 2, float(i), 1000 + i), 1000 + i)
        job.run_cycle()
        job.run_cycle()
        assert len(job.results("ones")) == ones_so_far

        # re-enable, feed, count grows
        call("POST", f"/api/v1/queries/{qid}/enable")
        for i in range(24, 32):
            src.emit(Rec(i % 2, float(i), 1000 + i), 1000 + i)
        job.run_cycle()
        job.run_cycle()
        assert len(job.results("ones")) > ones_so_far

        # listing + delete: one poll shows the whole fleet (id,
        # tenant, enabled, fold host/slot per entry)
        status, resp = call("GET", "/api/v1/queries")
        assert status == 200
        by_id = {q["id"]: q for q in resp["queries"]}
        assert qid in by_id
        assert by_id[qid]["enabled"] is True
        assert by_id[qid]["tenant"] == "default"
        assert "folded" in by_id[qid]
        status, _ = call("DELETE", f"/api/v1/queries/{qid}")
        assert status == 200
        src.emit(Rec(1, 99.0, 2000), 2000)
        job.run_cycle()
        job.run_cycle()
        assert qid not in job.plan_ids

        # metrics endpoint
        status, m = call("GET", "/api/v1/metrics")
        assert status == 200
        assert m["processed_events"] > 0
        assert "ones" in m["emitted"]
        # the per-event trace view rides the metrics snapshot...
        trace = m["telemetry"]["trace"]
        assert trace["sample_every"] > 0

        # ...and has its own endpoint (full payload incl. recent ring)
        status, t = call("GET", "/api/v1/traces")
        assert status == 200
        assert t["sample_every"] == trace["sample_every"]
        for key in ("sampled", "completed", "pending", "e2e", "recent"):
            assert key in t

        # 404 + 400 paths
        status, _ = call("GET", "/api/v1/nope")
        assert status == 404
        status, _ = call("POST", "/api/v1/queries", {})
        assert status == 400
        # invalid CQL is rejected at the REST boundary, job stays alive
        status, resp = call(
            "POST", "/api/v1/queries", {"cql": "this is not cql"}
        )
        assert status == 400 and "error" in resp

        # defense in depth: a bad control event that slips past
        # validation must not kill the running job either
        from flink_siddhi_tpu.control.events import MetadataControlEvent

        b = MetadataControlEvent.builder()
        b.add_execution_plan("nor is this")
        control.push(b.build())
        src.emit(Rec(0, 5.0, 3000), 3000)
        before = len(job.results("base"))
        job.run_cycle()  # must not raise
        job.run_cycle()
        assert len(job.results("base")) > before
    finally:
        svc.stop()


# -- round-5: Kafka-protocol source/sink (CEPPipeline.scala:49-56) -------

def _kafka_events(n, start=0):
    return [
        json.dumps(
            {
                "id": (start + i) % 4,
                "name": f"n{(start + i) % 3}",
                "price": float(start + i),
                "timestamp": 1000 + start + i,
            }
        )
        for i in range(n)
    ]


@pytest.mark.parametrize("legacy", [False, True], ids=["v2", "v0"])
def test_kafka_pipeline_roundtrip(legacy):
    """kafka://in -> filter CEP -> kafka://out, the reference's only
    deployable job shape, against the in-process broker in BOTH
    dialects: modern (negotiated Fetch v4 / Produce v3, gzip'd v2
    record batches both ways) and legacy (pre-0.10 v0 message sets,
    ApiVersions slams the connection)."""
    from tests.fake_kafka import FakeBroker

    broker = FakeBroker(legacy=legacy)
    try:
        broker.create_topic("in")
        broker.create_topic("out")
        n = 200
        broker.append("in", 0, _kafka_events(n))
        cfg = PipelineConfig(
            stream_id="inputStream",
            fields=FIELDS,
            cql=(
                "from inputStream[id == 2] select name, price "
                "insert into out"
            ),
            input_path=f"kafka://{broker.bootstrap}/in",
            output_path=f"kafka://{broker.bootstrap}/out",
            ts_field="timestamp",
            time_mode="processing",
            batch_size=64,
            compression="none" if legacy else "gzip",
        )
        pipe = CEPPipeline(cfg)
        job = pipe.build()
        src = job._sources[0]
        while job.processed_events < n:
            job.run_cycle()
        src.close()
        while not job.finished:
            job.run_cycle()
        job.flush()
        job.drain_outputs()
        for sink in pipe._kafka_sinks:
            sink.flush()
        out_rows = [
            json.loads(v.decode())
            for _, v in broker.logs[("out", 0)]
        ]
        assert len(out_rows) == n // 4
        assert all(r["stream"] == "out" for r in out_rows)
        assert [r["name"] for r in out_rows] == [
            f"n{i % 3}" for i in range(2, n, 4)
        ]
        assert [r["price"] for r in out_rows] == [
            float(i) for i in range(2, n, 4)
        ]
    finally:
        broker.close()


def test_kafka_offsets_resume_across_restart(tmp_path):
    """Offsets are checkpointed source positions: a job restarted from
    a checkpoint resumes fetching exactly where the snapshot was taken
    — every event processed exactly once across the two runs."""
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.kafka import KafkaSource
    from tests.fake_kafka import FakeBroker

    broker = FakeBroker()
    try:
        broker.create_topic("t")
        broker.append("t", 0, _kafka_events(100))
        schema = PipelineConfig(
            stream_id="S", fields=FIELDS, cql="", input_path="x",
            output_path="x",
        ).schema()
        cql = "from S select id, price insert into o"
        seen = []

        def build_job():
            src = KafkaSource(
                "S", schema, broker.bootstrap, "t",
                ts_field="timestamp",
            )
            plan = compile_plan(cql, {"S": schema})
            job = Job(
                [plan], [src], batch_size=32,
                time_mode="processing", retain_results=False,
            )
            job.add_sink("o", lambda ts, row: seen.append(row))
            return job, src

        ckpt = str(tmp_path / "ckpt")
        job1, src1 = build_job()
        while job1.processed_events < 48:
            job1.run_cycle()
        job1.save_checkpoint(ckpt)
        taken_at = len(seen)
        # events appended after the snapshot belong to the next run
        broker.append("t", 0, _kafka_events(40, start=100))
        # simulate the failure: everything after the checkpoint is lost
        del seen[taken_at:]

        job2, src2 = build_job()
        job2.restore(ckpt)
        assert src2.offsets == src1.offsets  # resumed, not re-read
        src2.close()
        while not job2.finished:
            job2.run_cycle()
        job2.flush()
        job2.drain_outputs()
        # exactly once: 140 events total, no duplicates, no gaps
        assert len(seen) == 140
        prices = sorted(p for _, p in seen)
        assert prices == [float(i) for i in range(140)]
    finally:
        broker.close()


def test_kafka_v2_gzip_resume_mid_batch(tmp_path):
    """Checkpointed-offset resume over v2+gzip with the committed
    offset landing MID-BATCH: the topic holds one 100-record gzip'd
    record batch, the checkpoint commits at offset 64, and a v4 fetch
    from 64 returns the WHOLE batch — the restarted source must skip
    the 64 already-consumed records, not re-emit or drop them."""
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.connectors.kafka.protocol import API_FETCH
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.kafka import KafkaClient, KafkaSource
    from tests.fake_kafka import FakeBroker

    broker = FakeBroker()  # serves fetches as gzip'd v2 batches
    try:
        broker.create_topic("t")
        producer = KafkaClient(broker.host, broker.port)
        # one produce call = ONE v2 batch of 100 records on the log
        producer.produce(
            "t", 0, [e.encode() for e in _kafka_events(100)],
            compression="gzip",
        )
        producer.close()
        assert broker.bounds[("t", 0)] == [0]
        schema = PipelineConfig(
            stream_id="S", fields=FIELDS, cql="", input_path="x",
            output_path="x",
        ).schema()
        cql = "from S select id, price insert into o"
        seen = []

        def build_job():
            src = KafkaSource(
                "S", schema, broker.bootstrap, "t",
                ts_field="timestamp",
            )
            plan = compile_plan(cql, {"S": schema})
            job = Job(
                [plan], [src], batch_size=32,
                time_mode="processing", retain_results=False,
            )
            job.add_sink("o", lambda ts, row: seen.append(row))
            return job, src

        ckpt = str(tmp_path / "ckpt")
        job1, src1 = build_job()
        assert src1.client.api_versions()[API_FETCH] == 4
        while job1.processed_events < 48:
            job1.run_cycle()
        job1.save_checkpoint(ckpt)
        committed = src1.offsets[0]
        assert 0 < committed < 100  # the point of the test: mid-batch
        taken_at = len(seen)
        # a second gzip'd batch lands after the snapshot
        producer2 = KafkaClient(broker.host, broker.port)
        producer2.produce(
            "t", 0, [e.encode() for e in _kafka_events(40, start=100)],
            compression="gzip",
        )
        producer2.close()
        # simulate the failure: everything after the checkpoint is lost
        del seen[taken_at:]

        job2, src2 = build_job()
        job2.restore(ckpt)
        assert src2.offsets == {0: committed}
        src2.close()
        while not job2.finished:
            job2.run_cycle()
        job2.flush()
        job2.drain_outputs()
        # exactly once across the batch boundary AND the mid-batch seam
        assert len(seen) == 140
        prices = sorted(p for _, p in seen)
        assert prices == [float(i) for i in range(140)]
    finally:
        broker.close()
