"""Engine vs measured-baseline interpreter: the two implementations of
the benchmark semantics (the vectorized device engine and the per-event
Python reference) must agree on the SAME stream — this is what makes
``vs_baseline`` an apples-to-apples ratio.

Coverage: all FIVE bench configs. filter and headline additionally
compare ROW CONTENTS + timestamps as sorted multisets (float fields at
f32 tolerance — the device computes in f32, the interpreter in f64), so
compensating row-level bugs cannot hide behind equal counts (ADVICE
round 4). multiquery64 compares per-output-stream counts, pinning each
of the 64 stacked queries individually.
"""

import numpy as np
import pytest

import bench
from flink_siddhi_tpu.baseline import BaselineEngine
from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType


def _schema():
    return StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )


def _norm_row(ts, row):
    """f32-tolerant canonical form: the engine's DOUBLE columns compute
    and ship as f32; compare at that precision."""
    return (
        int(ts),
        tuple(
            np.float32(v).item() if isinstance(v, float) else v
            for v in row
        ),
    )


@pytest.mark.parametrize(
    "config",
    ["headline", "filter", "pattern2", "window_groupby", "multiquery64"],
)
def test_engine_matches_baseline_interpreter(config):
    n, batch = 100_000, 16_384
    if config == "multiquery64":
        n = 50_000  # the interpreter fans every event through 64 NFAs
    compare_rows = config in ("headline", "filter")
    schema = _schema()
    n_ids = 1000 if config == "window_groupby" else 50
    batches = bench.make_batches(n, batch, schema, "inputStream", n_ids)
    cql = bench._config_cql(config)
    plan = compile_plan(
        cql, {"inputStream": schema},
        config=EngineConfig(lazy_projection=True, pred_pushdown=True),
    )
    eng_rows = []
    eng_counts = {}
    job = Job(
        [plan],
        [BatchSource("inputStream", schema,
                     iter(bench.make_batches(n, batch, schema,
                                             "inputStream", n_ids)))],
        batch_size=batch, time_mode="processing", retain_results=False,
    )
    for rt in job._plans.values():
        for out_stream in rt.plan.output_streams():
            def sink(ts, row, _sid=out_stream):
                eng_counts[_sid] = eng_counts.get(_sid, 0) + 1
                if compare_rows:
                    eng_rows.append(_norm_row(ts, row))

            job.add_sink(out_stream, sink)
    job.run()

    eng = BaselineEngine(cql, ["id", "name", "price", "timestamp"])
    base_rows = []
    base_counts = {}

    def base_emit(out, ts, row):
        eng.emitted += 1
        base_counts[out] = base_counts.get(out, 0) + 1
        if compare_rows:
            base_rows.append(_norm_row(ts, row))

    eng._emit = base_emit
    cols = {
        "id": np.concatenate(
            [b.columns["id"] for b in batches]
        ).tolist(),
        "name": ["test_event"] * n,
        "price": np.concatenate(
            [b.columns["price"] for b in batches]
        ).tolist(),
        "timestamp": np.concatenate(
            [b.timestamps for b in batches]
        ).tolist(),
    }
    eng.run_columns(cols, cols["timestamp"])

    assert sum(eng_counts.values()) == eng.emitted
    assert eng_counts == base_counts  # per-output-stream agreement
    if compare_rows:
        assert eng.emitted > 0
        assert sorted(eng_rows) == sorted(base_rows)
