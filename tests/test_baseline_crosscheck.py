"""Engine vs measured-baseline interpreter: the two implementations of
the benchmark semantics (the vectorized device engine and the per-event
Python reference) must agree on the SAME stream — this is what makes
``vs_baseline`` an apples-to-apples ratio."""

import numpy as np
import pytest

import bench
from flink_siddhi_tpu.baseline import BaselineEngine
from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType


def _schema():
    return StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )


@pytest.mark.parametrize(
    "config", ["headline", "filter", "pattern2", "window_groupby"]
)
def test_engine_matches_baseline_interpreter(config):
    n, batch = 100_000, 16_384
    schema = _schema()
    n_ids = 1000 if config == "window_groupby" else 50
    batches = bench.make_batches(n, batch, schema, "inputStream", n_ids)
    cql = bench._config_cql(config)
    plan = compile_plan(
        cql, {"inputStream": schema},
        config=EngineConfig(lazy_projection=True, pred_pushdown=True),
    )
    counts = {"n": 0}
    job = Job(
        [plan],
        [BatchSource("inputStream", schema,
                     iter(bench.make_batches(n, batch, schema,
                                             "inputStream", n_ids)))],
        batch_size=batch, time_mode="processing", retain_results=False,
    )
    for rt in job._plans.values():
        for out_stream in rt.plan.output_streams():
            job.add_sink(
                out_stream,
                lambda ts, row: counts.__setitem__("n", counts["n"] + 1),
            )
    job.run()

    eng = BaselineEngine(
        cql, ["id", "name", "price", "timestamp"]
    )
    cols = {
        "id": np.concatenate(
            [b.columns["id"] for b in batches]
        ).tolist(),
        "name": ["test_event"] * n,
        "price": np.concatenate(
            [b.columns["price"] for b in batches]
        ).tolist(),
        "timestamp": np.concatenate(
            [b.timestamps for b in batches]
        ).tolist(),
    }
    eng.run_columns(cols, cols["timestamp"])
    assert counts["n"] == eng.emitted
