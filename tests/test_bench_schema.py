"""scripts/check_bench_schema.py in the tier-1 lane: the BENCH JSON
schema gate (stage_breakdown present and attributing >= 95% of elapsed
wall-clock) validates both synthetic documents and the repo's real
BENCH_*.json harvest files."""

import glob
import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_bench_schema",
        os.path.join(REPO, "scripts", "check_bench_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


CHECK = _checker()


def _v2_doc(coverage=0.97, elapsed=10.0, extra_stages=None):
    stages = {
        "plan_compile": 0.5,
        "stage.compile": elapsed * coverage - 1.0,
        "replay.dispatch": 0.3,
        "drain": 0.1,
        "flush": 0.1,
        "nested.sink": 0.05,  # drill-down: excluded from the sum
    }
    if extra_stages:
        stages.update(extra_stages)
    top = CHECK._stage_names()
    attributed = sum(v for k, v in stages.items() if k in top)
    return {
        "metric": "events/sec (headline, 1000 events)",
        "value": 1234.5,
        "unit": "events/sec",
        "vs_baseline": 2.0,
        "schema_version": 2,
        "stage_breakdown": {
            "telemetry": "on",
            "window": "build_job..final_flush",
            "elapsed_s": elapsed,
            "attributed_s": round(attributed, 3),
            "coverage": round(attributed / elapsed, 4),
            "stages": stages,
        },
    }


def test_valid_v2_doc_passes():
    errors = []
    CHECK.validate_doc(_v2_doc(), errors, "doc")
    assert errors == []


def test_v2_without_stage_breakdown_fails():
    doc = _v2_doc()
    del doc["stage_breakdown"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("stage_breakdown" in e for e in errors)


def test_low_coverage_fails():
    doc = _v2_doc(coverage=0.80)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("unattributed off-clock" in e for e in errors)


def test_declared_coverage_must_match_stages():
    doc = _v2_doc()
    doc["stage_breakdown"]["coverage"] = 0.99  # lies about the stages
    doc["stage_breakdown"]["stages"]["stage.compile"] = 1.0
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors


def test_unknown_stage_names_fail():
    doc = _v2_doc(extra_stages={"mystery_stage": 1.0})
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("unknown stage names" in e for e in errors)


def test_telemetry_off_run_is_exempt():
    doc = _v2_doc()
    doc["stage_breakdown"] = {"telemetry": "off"}
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []


def test_legacy_doc_passes_without_stages():
    doc = {
        "metric": "events/sec (headline, 10000000 events)",
        "value": 16881096.6,
        "unit": "events/sec",
    }
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
    errors = []
    CHECK.validate_doc(doc, errors, "doc", require_stages=True)
    assert errors  # unless the caller demands the new contract


def test_repo_bench_files_validate():
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    assert files, "no BENCH_*.json harvest files in repo root"
    for path in files:
        assert CHECK.validate_file(path) == []


def test_wrapper_format_extraction(tmp_path):
    inner = json.dumps(_v2_doc())
    wrapper = json.dumps(
        {"n": 6, "cmd": "python bench.py", "rc": 0,
         "tail": "WARNING: noise\n" + inner + "\n"}
    )
    p = tmp_path / "BENCH_x.json"
    p.write_text(wrapper)
    assert CHECK.validate_file(str(p)) == []
    # and a broken inner doc is caught through the wrapper
    bad = _v2_doc(coverage=0.5)
    p.write_text(
        json.dumps({"rc": 0, "tail": json.dumps(bad)})
    )
    assert CHECK.validate_file(str(p))
