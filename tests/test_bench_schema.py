"""scripts/check_bench_schema.py in the tier-1 lane: the BENCH JSON
schema gate (stage_breakdown present and attributing >= 95% of elapsed
wall-clock; schema v3: all three execution modes present, each with a
finite out-of-process prober p99 next to the telemetry p99) validates
synthetic documents, the repo's real BENCH_*.json harvest files, AND a
live ``bench.py --dryrun`` — the dryrun must stay schema-complete:
three modes + a real prober child process, under the tier-1 timeout."""

import glob
import importlib.util
import json
import math
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_bench_schema",
        os.path.join(REPO, "scripts", "check_bench_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


CHECK = _checker()


def _v2_doc(coverage=0.97, elapsed=10.0, extra_stages=None):
    stages = {
        "plan_compile": 0.5,
        "stage.compile": elapsed * coverage - 1.0,
        "replay.dispatch": 0.3,
        "drain": 0.1,
        "flush": 0.1,
        "nested.sink": 0.05,  # drill-down: excluded from the sum
    }
    if extra_stages:
        stages.update(extra_stages)
    top = CHECK._stage_names()
    attributed = sum(v for k, v in stages.items() if k in top)
    return {
        "metric": "events/sec (headline, 1000 events)",
        "value": 1234.5,
        "unit": "events/sec",
        "vs_baseline": 2.0,
        "schema_version": 2,
        "stage_breakdown": {
            "telemetry": "on",
            "window": "build_job..final_flush",
            "elapsed_s": elapsed,
            "attributed_s": round(attributed, 3),
            "coverage": round(attributed / elapsed, 4),
            "stages": stages,
        },
    }


def test_valid_v2_doc_passes():
    errors = []
    CHECK.validate_doc(_v2_doc(), errors, "doc")
    assert errors == []


def test_v2_without_stage_breakdown_fails():
    doc = _v2_doc()
    del doc["stage_breakdown"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("stage_breakdown" in e for e in errors)


def test_low_coverage_fails():
    doc = _v2_doc(coverage=0.80)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("unattributed off-clock" in e for e in errors)


def test_declared_coverage_must_match_stages():
    doc = _v2_doc()
    doc["stage_breakdown"]["coverage"] = 0.99  # lies about the stages
    doc["stage_breakdown"]["stages"]["stage.compile"] = 1.0
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors


def test_unknown_stage_names_fail():
    doc = _v2_doc(extra_stages={"mystery_stage": 1.0})
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("unknown stage names" in e for e in errors)


def test_telemetry_off_run_is_exempt():
    doc = _v2_doc()
    doc["stage_breakdown"] = {"telemetry": "off"}
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []


def test_legacy_doc_passes_without_stages():
    doc = {
        "metric": "events/sec (headline, 10000000 events)",
        "value": 16881096.6,
        "unit": "events/sec",
    }
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
    errors = []
    CHECK.validate_doc(doc, errors, "doc", require_stages=True)
    assert errors  # unless the caller demands the new contract


# -- schema v3: multi-mode + out-of-process prober contract ---------------


def _v3_latency(**over):
    lat = {
        "telemetry_p50_ms": 60.0,
        "telemetry_p99_ms": 95.0,
        "telemetry_source": "trace_histogram (paced latency job)",
        "prober_p50_ms": 76.0,
        "prober_p99_ms": 122.0,
        "prober_pid": 4242,
        "prober_parent_pid": 4241,
        "prober_n_sent": 120,
        "prober_n_received": 119,
        "prober_lost": 1,
        "prober_clock": "child-monotonic",
        "prober_path": "paced-socket-ingest",
        "discrepancy_ratio": 1.284,
    }
    lat.update(over)
    return lat


def _v3_doc(**over):
    base = _v2_doc()
    sb = base["stage_breakdown"]
    modes = {}
    for name in ("resident", "streaming", "sink"):
        modes[name] = {
            "events": 200_000,
            "elapsed_s": 1.0,
            "events_per_sec": 200_000.0,
            "vs_baseline": 0.4,
            "stage_breakdown": json.loads(json.dumps(sb)),
            "latency": _v3_latency(),
        }
    base["schema_version"] = 3
    base["modes"] = modes
    base.update(over)
    return base


def test_valid_v3_doc_passes():
    errors = []
    CHECK.validate_doc(_v3_doc(), errors, "doc")
    assert errors == []


def test_v3_requires_all_three_modes():
    doc = _v3_doc()
    del doc["modes"]["streaming"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("modes.streaming missing" in e for e in errors)


def test_v3_partial_subset_fails():
    doc = _v3_doc(partial=True)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("partial" in e for e in errors)


def test_v3_missing_or_nonfinite_prober_fields_fail():
    for bad in (
        {"prober_p99_ms": None},
        {"prober_p99_ms": float("nan")},
        {"prober_p50_ms": None},
        {"telemetry_p99_ms": None},
        {"discrepancy_ratio": None},
        {"discrepancy_ratio": float("inf")},
    ):
        doc = _v3_doc()
        doc["modes"]["sink"]["latency"] = _v3_latency(**bad)
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert errors, bad


def test_v3_same_pid_means_no_separate_process():
    doc = _v3_doc()
    doc["modes"]["resident"]["latency"] = _v3_latency(
        prober_pid=7, prober_parent_pid=7
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("separate OS process" in e for e in errors)


def test_v3_mode_coverage_still_enforced():
    doc = _v3_doc()
    doc["modes"]["sink"]["stage_breakdown"]["stages"][
        "stage.compile"
    ] = 1.0
    doc["modes"]["sink"]["stage_breakdown"]["coverage"] = 0.5
    doc["modes"]["sink"]["stage_breakdown"]["attributed_s"] = 5.0
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "modes.sink" in e and "unattributed off-clock" in e
        for e in errors
    )


def test_v3_telemetry_off_exempts_internal_half_only():
    """A BENCH_TELEMETRY=0 overhead-A/B run has no in-process
    histograms, but the prober is external: its fields stay
    mandatory."""
    doc = _v3_doc()
    sec = doc["modes"]["streaming"]
    sec["stage_breakdown"] = {"telemetry": "off"}
    sec["latency"] = _v3_latency(
        telemetry_p50_ms=None,
        telemetry_p99_ms=None,
        discrepancy_ratio=None,
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
    sec["latency"]["prober_p99_ms"] = None
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors


def test_v3_prober_contradiction_fails():
    doc = _v3_doc(
        prober_contradiction="prober p99 5000ms > 3x internal claims"
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("contradicts" in e for e in errors)


def test_v3_reports_discrepancy_ratio():
    CHECK.INFO.clear()
    errors = []
    CHECK.validate_doc(_v3_doc(), errors, "doc")
    assert errors == []
    assert any("discrepancy ratio" in n for n in CHECK.INFO)


# -- schema v4: columnar sink + tail-aware drain contract ------------------


def _v4_doc(**over):
    doc = _v3_doc()
    doc["schema_version"] = 4
    doc["modes"]["sink"].update(
        rows_materialized_ev_s=200_000.0,
        rows_emitted=4096,
        rows_per_sec=4096.0,
        columnar=True,
    )
    doc["p99_target"] = {
        "p99_ms": 120.0,
        "offered_load_events_per_sec": 1_000_000,
        "p99_le_500ms_at_1M": True,
        "p99_le_2x_prober": True,
        "prober_p99_ms": 122.0,
        "verdict": "p99_le_500ms",
    }
    doc["drain_staleness"] = {
        "p50_ms": 80.0, "p99_ms": 140.0, "count": 33,
    }
    doc.update(over)
    return doc


def test_valid_v4_doc_passes():
    errors = []
    CHECK.validate_doc(_v4_doc(), errors, "doc")
    assert errors == []


def test_v4_requires_rows_materialized_and_columnar():
    for strip in (
        "rows_materialized_ev_s", "rows_emitted", "columnar",
    ):
        doc = _v4_doc()
        del doc["modes"]["sink"][strip]
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert errors, strip
    doc = _v4_doc()
    doc["modes"]["sink"]["columnar"] = False  # row fallback: rejected
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("columnar" in e for e in errors)


def test_v4_missed_verdict_fails_loudly():
    doc = _v4_doc()
    doc["p99_target"]["verdict"] = "missed"
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("fails BOTH latency targets" in e for e in errors)
    doc = _v4_doc()
    del doc["p99_target"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("p99_target" in e for e in errors)


def test_v4_requires_finite_drain_staleness():
    doc = _v4_doc()
    del doc["drain_staleness"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("drain_staleness" in e for e in errors)
    doc = _v4_doc()
    doc["drain_staleness"]["p99_ms"] = None
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("drain_staleness.p99_ms" in e for e in errors)


def test_v3_era_docs_unaffected_by_v4_gate():
    """BENCH_r01..r05 harvests predate v4; the new requirements apply
    from schema_version 4 only."""
    errors = []
    CHECK.validate_doc(_v3_doc(), errors, "doc")
    assert errors == []


# -- schema v5: fused dispatch + streaming-vs-resident contract ------------


def _v5_fusion(**over):
    fu = {
        "segment_len": 8,
        "dispatches": 13,
        "batches": 100,
        "dispatches_per_1k_batches": 130.0,
        "h2d_overlap_frac": 0.75,
    }
    fu.update(over)
    return fu


def _v5_doc(**over):
    doc = _v4_doc()
    doc["schema_version"] = 5
    for name in ("resident", "streaming", "sink"):
        doc["modes"][name]["fusion"] = _v5_fusion()
    doc["modes"]["resident"]["fusion"].update(
        h2d_overlap_frac=0.0, prestaged=True
    )
    doc["streaming_vs_resident_ratio"] = 1.0
    doc["fusion_target"] = {
        "streaming_ev_s": 200_000.0,
        "resident_ev_s": 200_000.0,
        "basis": "best of 2 ABBA rounds",
        "rounds": 2,
        "resident_runs_s": [0.1, 0.12, 0.11, 0.1],
        "streaming_runs_s": [0.1, 0.12, 0.11, 0.1],
        "ratio": 1.0,
        "target": 0.8,
        "segment_len": 8,
        "verdict": "met",
    }
    doc.update(over)
    return doc


def test_valid_v5_doc_passes():
    errors = []
    CHECK.validate_doc(_v5_doc(), errors, "doc")
    assert errors == []


def test_v5_requires_fusion_block_per_mode():
    doc = _v5_doc()
    del doc["modes"]["streaming"]["fusion"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "modes.streaming" in e and "fusion block missing" in e
        for e in errors
    )


def test_v5_fusion_field_bounds():
    for bad in (
        {"segment_len": 0},
        {"segment_len": None},
        {"dispatches_per_1k_batches": None},
        {"dispatches_per_1k_batches": -1.0},
        {"h2d_overlap_frac": 1.5},
        {"h2d_overlap_frac": None},
    ):
        doc = _v5_doc()
        doc["modes"]["sink"]["fusion"] = _v5_fusion(**bad)
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert errors, bad
    # a fused segment that did NOT collapse dispatches is a lie
    doc = _v5_doc()
    doc["modes"]["streaming"]["fusion"] = _v5_fusion(
        segment_len=8, dispatches_per_1k_batches=1001.0
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("did not collapse" in e for e in errors)


def test_v5_requires_consistent_ratio():
    doc = _v5_doc()
    del doc["streaming_vs_resident_ratio"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("streaming_vs_resident_ratio" in e for e in errors)
    # the declared ratio must match a recompute from the mode sections
    doc = _v5_doc(streaming_vs_resident_ratio=0.5)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("recomputed" in e for e in errors)


def test_v5_fusion_target_missed_fails_loudly():
    doc = _v5_doc()
    doc["fusion_target"]["verdict"] = "missed"
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("still dispatch-bound" in e for e in errors)
    doc = _v5_doc()
    del doc["fusion_target"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("fusion_target" in e for e in errors)


def test_v5_telemetry_off_fusion_exempt():
    doc = _v5_doc()
    doc["modes"]["streaming"]["fusion"] = {
        "telemetry": "off", "segment_len": 8,
    }
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []


def test_v4_era_docs_unaffected_by_v5_gate():
    """BENCH files predating v5 carry no fusion blocks; the new
    requirements apply from schema_version 5 only."""
    errors = []
    CHECK.validate_doc(_v4_doc(), errors, "doc")
    assert errors == []


# -- schema v6: the event-time disorder contract ---------------------------

def _v6_run(skew, **over):
    run = {
        "skew_ms": skew,
        "events": 60_000,
        "events_per_sec": 45_000.0,
        "p99_ms": 3.2,
        "p50_ms": 0.4,
        "elapsed_s": 1.3,
        "injected": {
            "duplicates": 124, "late": 20,
            "idle_gaps": 2, "idle_polls": 4,
        },
        "late_dropped": 20,
        "idle_marked": 2,
        "processed_events": 60_000 + 124 - 20,
        "counts_exact": True,
    }
    run.update(over)
    return run


def _v6_doc(**over):
    doc = _v5_doc()
    doc["schema_version"] = 6
    doc["disorder"] = {
        "config": "headline",
        "late_policy": "drop",
        "watermark": "BoundedDisorderWatermark(skew)",
        "runs": [_v6_run(s) for s in (0, 1_000, 10_000)],
    }
    doc.update(over)
    return doc


def test_valid_v6_doc_passes():
    errors = []
    CHECK.validate_doc(_v6_doc(), errors, "doc")
    assert errors == []


def test_v6_requires_disorder_block():
    doc = _v6_doc()
    del doc["disorder"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("disorder block missing" in e for e in errors)


def test_v6_requires_all_three_skews():
    doc = _v6_doc()
    doc["disorder"]["runs"] = doc["disorder"]["runs"][:2]  # drop 10s
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("missing skew" in e for e in errors)


def test_v6_requires_finite_throughput_and_p99():
    for bad in (
        {"events_per_sec": None},
        {"events_per_sec": 0},
        {"p99_ms": None},
        {"p99_ms": float("nan")},
    ):
        doc = _v6_doc()
        doc["disorder"]["runs"][1] = _v6_run(1_000, **bad)
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert errors, bad


def test_v6_accounting_must_match_injected_schedule():
    # late counter drifted from the injected stragglers
    doc = _v6_doc()
    doc["disorder"]["runs"][0] = _v6_run(0, late_dropped=19)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("late account drifted" in e for e in errors)
    # idle marks drifted from the injected gaps
    doc = _v6_doc()
    doc["disorder"]["runs"][2] = _v6_run(10_000, idle_marked=1)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("idle" in e and "never marked" in e for e in errors)
    # duplicate reconciliation: processed != events + dups - late
    doc = _v6_doc()
    doc["disorder"]["runs"][0] = _v6_run(0, processed_events=60_000)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("duplicate accounting drifted" in e for e in errors)
    # a declared counts_exact=false is itself a failure
    doc = _v6_doc()
    doc["disorder"]["runs"][0] = _v6_run(0, counts_exact=False)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("counts_exact" in e for e in errors)


def test_v5_era_docs_unaffected_by_v6_gate():
    """BENCH files predating v6 carry no disorder block; the
    requirement applies from schema_version 6 only — but a disorder
    block PRESENT in an older line is still held to its contract."""
    errors = []
    CHECK.validate_doc(_v5_doc(), errors, "doc")
    assert errors == []
    doc = _v5_doc()
    doc["disorder"] = {"runs": [_v6_run(0, late_dropped=1)]}
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("late account drifted" in e for e in errors)


# -- schema v7: the dynamic-control-plane contract --------------------------


def _control_blk(**over):
    blk = {
        "concurrent_queries": 23,
        "queries_admitted": 24,
        "queries_retired": 1,
        "admission_rejected": 1,
        "hostile_refused_rule": "ADM110",
        "stack_joins": 21,
        "admit_wall_ms": 940.0,
        "admit_rate_qps": 25.5,
        "steady_state_events_per_sec": 120_000,
        "events": 104_448,
        "dropped_events": 0,
        "baseline_p99_ms": 7.0,
        "added_latency_p99_ms": 940.0,
        "cache": {"entries": 1, "hits": 2, "misses": 1,
                  "evictions": 0},
        "dryrun": True,
    }
    blk.update(over)
    return blk


def _v7_doc(**over):
    doc = _v6_doc()
    doc["schema_version"] = 7
    doc["control"] = _control_blk()
    doc.update(over)
    return doc


def test_valid_v7_doc_passes():
    errors = []
    CHECK.validate_doc(_v7_doc(), errors, "doc")
    assert errors == []


def test_v7_requires_control_block():
    doc = _v7_doc()
    del doc["control"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("control block missing" in e for e in errors)


def test_v7_admit_rate_must_be_measured():
    for bad in (None, 0, -1.0, float("inf")):
        errors = []
        CHECK.validate_doc(
            _v7_doc(control=_control_blk(admit_rate_qps=bad)),
            errors, "doc",
        )
        assert any("admit_rate_qps" in e for e in errors), bad


def test_v7_dropped_events_gated_zero():
    errors = []
    CHECK.validate_doc(
        _v7_doc(control=_control_blk(dropped_events=3)), errors, "doc"
    )
    assert any("dropped_events" in e for e in errors)


def test_v7_hostile_must_be_refused_by_rule_id():
    errors = []
    CHECK.validate_doc(
        _v7_doc(control=_control_blk(admission_rejected=0)),
        errors, "doc",
    )
    assert any("not refused" in e for e in errors)
    errors = []
    CHECK.validate_doc(
        _v7_doc(control=_control_blk(hostile_refused_rule="nope")),
        errors, "doc",
    )
    assert any("rule id" in e for e in errors)


def test_v7_cache_counters_required():
    errors = []
    blk = _control_blk()
    del blk["cache"]
    CHECK.validate_doc(_v7_doc(control=blk), errors, "doc")
    assert any("cache block missing" in e for e in errors)
    errors = []
    CHECK.validate_doc(
        _v7_doc(control=_control_blk(cache={"hits": -1, "misses": 0})),
        errors, "doc",
    )
    assert any("cache." in e for e in errors)


def test_v6_era_docs_unaffected_by_v7_gate():
    """Pre-v7 lines need no control block, but one present is held to
    its contract (same exemption shape as the disorder block)."""
    errors = []
    CHECK.validate_doc(_v6_doc(), errors, "doc")
    assert errors == []
    doc = _v6_doc()
    doc["control"] = _control_blk(dropped_events=7)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("dropped_events" in e for e in errors)


# -- schema v8: per-plan attribution + footprint meter ----------------------


def _attribution_blk(**over):
    blk = {
        "plans": {
            "q0": {"tenant": "tenant0", "rows_emitted": 120,
                   "matches": 120},
            "q1": {"tenant": "tenant1", "rows_emitted": 80,
                   "matches": 80},
            "flat": {"tenant": "tenant0", "rows_emitted": 300,
                     "matches": 300},
        },
        "rows_emitted_total": 500,
        "conserved": True,
        "footprint": {
            "@dyn:q0": {"measured_bytes": 134_217_728},
            "flat": {
                "measured_bytes": 100_000_000,
                "admitted_bytes": 100_663_296,
                "utilization": 0.993,
            },
        },
    }
    blk.update(over)
    return blk


def _v8_doc(**att_over):
    doc = _v7_doc()
    doc["schema_version"] = 8
    doc["control"]["attribution"] = _attribution_blk(**att_over)
    return doc


def test_valid_v8_doc_passes():
    errors = []
    CHECK.validate_doc(_v8_doc(), errors, "doc")
    assert errors == []


def test_v8_requires_attribution_block():
    doc = _v8_doc()
    del doc["control"]["attribution"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("attribution block missing" in e for e in errors)


def test_v8_rows_must_conserve():
    # scoped sum != job total: attribution dropped rows
    errors = []
    CHECK.validate_doc(
        _v8_doc(rows_emitted_total=501), errors, "doc"
    )
    assert any("do not CONSERVE" in e for e in errors)
    # a declared conserved=false is itself a failure
    errors = []
    CHECK.validate_doc(_v8_doc(conserved=False), errors, "doc")
    assert any("conserved must be true" in e for e in errors)
    # empty plans map measures nothing
    errors = []
    CHECK.validate_doc(
        _v8_doc(plans={}, rows_emitted_total=0), errors, "doc"
    )
    assert any("plans missing/empty" in e for e in errors)


def test_v8_footprint_utilization_must_be_finite_and_compared():
    # a non-finite utilization is a failed claim
    errors = []
    CHECK.validate_doc(
        _v8_doc(footprint={
            "flat": {
                "measured_bytes": 1, "admitted_bytes": 1,
                "utilization": float("inf"),
            },
        }),
        errors, "doc",
    )
    assert any("utilization" in e for e in errors)
    # measured-only everywhere = the meter never compared anything
    errors = []
    CHECK.validate_doc(
        _v8_doc(footprint={"@dyn:q0": {"measured_bytes": 7}}),
        errors, "doc",
    )
    assert any("never compared" in e for e in errors)
    # an empty meter is a missing meter
    errors = []
    CHECK.validate_doc(_v8_doc(footprint={}), errors, "doc")
    assert any("footprint map missing/empty" in e for e in errors)
    # measured bytes must be positive finite
    errors = []
    CHECK.validate_doc(
        _v8_doc(footprint={
            "x": {"measured_bytes": 0},
            "flat": {
                "measured_bytes": 1, "admitted_bytes": 2,
                "utilization": 0.5,
            },
        }),
        errors, "doc",
    )
    assert any("measured_bytes" in e for e in errors)


def test_v7_era_docs_unaffected_by_v8_gate():
    """Pre-v8 lines need no attribution block, but one present is
    held to its contract (same exemption shape as disorder/control)."""
    errors = []
    CHECK.validate_doc(_v7_doc(), errors, "doc")
    assert errors == []
    doc = _v7_doc()
    doc["control"]["attribution"] = _attribution_blk(conserved=False)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("conserved must be true" in e for e in errors)


# -- schema v9: the measured limiting-leg verdict ---------------------------


def _limiting_leg_blk(mode="streaming", **over):
    blk = {
        "mode": mode,
        "elapsed_s": 10.0,
        "coverage": 0.98,
        "legs": {
            "setup": {"seconds": 1.0, "share": 0.1,
                      "overlapped": False, "stages": ["prewarm"]},
            "host_staging": {"seconds": 2.0, "share": 0.2,
                             "overlapped": False,
                             "stages": ["ingest", "tape_build"]},
            "h2d": {"seconds": 0.3, "share": 0.03,
                    "overlapped": False,
                    "stages": ["stage.h2d_overlap"]},
            "dispatch": {"seconds": 5.0, "share": 0.5,
                         "overlapped": False, "stages": ["dispatch"]},
            "device_compute": {"seconds": 0.5, "share": 0.05,
                               "overlapped": False,
                               "stages": ["backpressure_wait"]},
            "drain_fetch": {"seconds": 1.0, "share": 0.1,
                            "overlapped": False, "stages": ["drain"]},
            "decode": {"seconds": 0.4, "share": 0.04,
                       "overlapped": True,
                       "stages": ["drain.decode (histogram mass)"]},
            "sink": {"seconds": 0.1, "share": 0.01,
                     "overlapped": True, "stages": ["sink"]},
        },
        "limiting_leg": "dispatch",
        "limiting_share": 0.5,
        "basis": "test fixture",
    }
    blk.update(over)
    return blk


def _v9_doc(**over):
    doc = _v8_doc()
    doc["schema_version"] = 9
    for name, sec in doc["modes"].items():
        sec["limiting_leg"] = _limiting_leg_blk(mode=name)
    doc.update(over)
    return doc


def test_valid_v9_doc_passes():
    errors = []
    CHECK.validate_doc(_v9_doc(), errors, "doc")
    assert errors == []


def test_v9_requires_limiting_leg_per_mode():
    doc = _v9_doc()
    del doc["modes"]["streaming"]["limiting_leg"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "modes.streaming: limiting_leg block missing" in e
        for e in errors
    )


def test_v9_named_leg_must_be_argmax():
    """A verdict contradicting its own published seconds is rejected —
    the gate re-derives the argmax, a declared name cannot lie."""
    doc = _v9_doc()
    doc["modes"]["sink"]["limiting_leg"]["limiting_leg"] = (
        "host_staging"  # dispatch measured 5.0s, host_staging 2.0s
    )
    doc["modes"]["sink"]["limiting_leg"]["limiting_share"] = 0.2
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("is not the argmax" in e for e in errors)
    # setup and the overlapped legs are never nameable, however large
    doc = _v9_doc()
    doc["modes"]["sink"]["limiting_leg"]["limiting_leg"] = "setup"
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("not a candidate leg" in e for e in errors)


def test_v9_cover_must_reach_95_percent():
    blk = _limiting_leg_blk()
    blk["legs"]["dispatch"]["seconds"] = 1.0  # cover drops to 58%
    blk["coverage"] = 0.58
    blk["limiting_leg"] = "host_staging"
    blk["limiting_share"] = 0.2
    doc = _v9_doc()
    doc["modes"]["resident"]["limiting_leg"] = blk
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("attributes only" in e for e in errors)
    # and a declared coverage that disagrees with the per-leg seconds
    blk2 = _limiting_leg_blk(coverage=0.99)
    blk2["legs"]["dispatch"]["seconds"] = 4.0
    doc = _v9_doc()
    doc["modes"]["resident"]["limiting_leg"] = blk2
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("declared coverage" in e for e in errors)


def test_v9_overlapped_legs_outside_cover():
    """decode/sink (fetch-lane) seconds must not rescue a failing
    cover: only non-overlapped legs sum into coverage."""
    blk = _limiting_leg_blk()
    blk["legs"]["dispatch"]["seconds"] = 1.0
    blk["legs"]["decode"]["seconds"] = 6.0  # overlapped: not cover
    blk["coverage"] = 0.58
    blk["limiting_leg"] = "host_staging"
    blk["limiting_share"] = 0.2
    doc = _v9_doc()
    doc["modes"]["streaming"]["limiting_leg"] = blk
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("attributes only" in e for e in errors)


def test_v9_telemetry_off_exempt():
    doc = _v9_doc()
    doc["modes"]["sink"]["stage_breakdown"] = {"telemetry": "off"}
    doc["modes"]["sink"]["limiting_leg"] = {"telemetry": "off"}
    # the latency block keeps only the external half under
    # telemetry-off (same exemption as v3)
    doc["modes"]["sink"]["latency"].pop("telemetry_p99_ms", None)
    doc["modes"]["sink"]["latency"]["discrepancy_ratio"] = None
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []


def test_v8_era_docs_unaffected_by_v9_gate():
    """Pre-v9 lines need no limiting_leg, but a present one is held
    to its contract (same exemption shape as disorder/control)."""
    errors = []
    CHECK.validate_doc(_v8_doc(), errors, "doc")
    assert errors == []
    doc = _v8_doc()
    doc["modes"]["streaming"]["limiting_leg"] = _limiting_leg_blk(
        limiting_leg="h2d", limiting_share=0.03
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("is not the argmax" in e for e in errors)


# -- optional recovery block (bench.py --fault) ----------------------------


def _recovery_block(**over):
    rec = {
        "events": 40_000,
        "crash_pulls": [2, 6],
        "kill_mid_checkpoint": True,
        "crashes": 3,
        "restarts": 3,
        "checkpoints": 3,
        "recovery_time_ms": 412.7,
        "events_replayed": 24_576,
        "rows_discarded_uncommitted": 8_192,
        "rows_emitted": 40_000,
        "duplicate_rows": 0,
        "lost_rows": 0,
        "exactly_once": True,
        "stale_tmp_swept": True,
        "elapsed_s": 9.3,
    }
    rec.update(over)
    return rec


def test_recovery_block_valid_passes():
    errors = []
    CHECK.validate_doc(_v5_doc(recovery=_recovery_block()), errors, "doc")
    assert errors == []


def test_recovery_block_absent_is_fine():
    """--fault is optional: a line without the block validates."""
    errors = []
    CHECK.validate_doc(_v4_doc(), errors, "doc")
    assert errors == []


def test_recovery_duplicates_or_losses_fail():
    for key in ("duplicate_rows", "lost_rows"):
        doc = _v4_doc(recovery=_recovery_block(**{key: 3}))
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert any(
            key in e and "exactly-once violated" in e for e in errors
        ), key


def test_recovery_time_must_be_measured():
    for bad in (None, 0, -1.0, float("nan")):
        doc = _v4_doc(
            recovery=_recovery_block(recovery_time_ms=bad)
        )
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert any("recovery_time_ms" in e for e in errors), bad


def test_recovery_requires_a_real_crash_and_clean_tmp():
    doc = _v4_doc(recovery=_recovery_block(crashes=0))
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("measures nothing" in e for e in errors)
    doc = _v4_doc(recovery=_recovery_block(stale_tmp_swept=False))
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("stale_tmp_swept" in e for e in errors)


# -- v10: transactional sub-block of the recovery block --------------------


def _txn_block(**over):
    txn = {
        "events": 8_192,
        "crash_pulls": [3],
        "kill_mid_checkpoint": True,
        "kill_mid_transaction": True,
        "crashes": 3,
        "restarts": 3,
        "recovery_time_ms": 101.4,
        "rows_emitted": 8_192,
        "read_committed_duplicates": 0,
        "read_committed_lost": 0,
        "exactly_once": True,
        "read_uncommitted_rows": 9_001,
        "aborted_rows_invisible": True,
        "elapsed_s": 4.2,
    }
    txn.update(over)
    return txn


def _v10_doc(**over):
    doc = _v9_doc()
    doc["schema_version"] = 10
    doc.update(over)
    return doc


def test_valid_v10_doc_passes():
    """v10 without --fault is fine (the block stays optional), and
    with the full recovery + transactional pair it validates."""
    errors = []
    CHECK.validate_doc(_v10_doc(), errors, "doc")
    assert errors == []
    errors = []
    CHECK.validate_doc(
        _v10_doc(
            recovery=_recovery_block(transactional=_txn_block())
        ),
        errors, "doc",
    )
    assert errors == []


def test_v10_recovery_requires_transactional_subblock():
    """From v10, a recovery block that only diffed INTERNAL results is
    an incomplete exactly-once claim — the external read-committed
    boundary must be measured."""
    doc = _v10_doc(recovery=_recovery_block())
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "transactional sub-block" in e and "read-committed" in e
        for e in errors
    )


def test_v9_era_recovery_exempt_but_present_txn_block_validated():
    """Pre-v10 lines need no transactional sub-block, but one that IS
    present is held to its contract (the disorder/control exemption
    shape)."""
    errors = []
    CHECK.validate_doc(
        _v9_doc(recovery=_recovery_block()), errors, "doc"
    )
    assert errors == []
    doc = _v9_doc(
        recovery=_recovery_block(
            transactional=_txn_block(read_committed_duplicates=2)
        )
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("read_committed_duplicates" in e for e in errors)


def test_txn_subblock_external_duplicates_or_losses_fail():
    for key in ("read_committed_duplicates", "read_committed_lost"):
        doc = _v10_doc(
            recovery=_recovery_block(
                transactional=_txn_block(**{key: 1})
            )
        )
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert any(
            key in e and "external boundary" in e for e in errors
        ), key


def test_txn_subblock_must_be_a_real_measurement():
    """recovery_time_ms must be finite-positive, the
    kill-mid-transaction must actually have fired, and the aborted
    debris must have stayed invisible — otherwise the block measured
    nothing (or worse, leaked)."""
    for bad in (None, 0, -1.0, float("nan")):
        doc = _v10_doc(
            recovery=_recovery_block(
                transactional=_txn_block(recovery_time_ms=bad)
            )
        )
        errors = []
        CHECK.validate_doc(doc, errors, "doc")
        assert any(
            "transactional" in e and "recovery_time_ms" in e
            for e in errors
        ), bad
    doc = _v10_doc(
        recovery=_recovery_block(
            transactional=_txn_block(kill_mid_transaction=False)
        )
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("kill_mid_transaction" in e for e in errors)
    doc = _v10_doc(
        recovery=_recovery_block(
            transactional=_txn_block(aborted_rows_invisible=False)
        )
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("aborted_rows_invisible" in e for e in errors)
    doc = _v10_doc(
        recovery=_recovery_block(transactional=_txn_block(crashes=0))
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "transactional" in e and "measures nothing" in e
        for e in errors
    )


# -- schema v11: the serving observatory ------------------------------------


def _serving_blk(**over):
    """A self-consistent serving block modeled on a real
    ``bench.py --serve --dryrun`` line (victim ratios, max_ratio and
    every verdict re-derivable from the numbers published next to
    them)."""
    blk = {
        "tenants": 4,
        "offered_events_per_sec": 1200.0,
        "sustained_events_per_sec": 1176.6,
        "seconds": 6.0,
        "search": {
            "mode": "fixed",
            "rates_tried": [[1200.0, True]],
            "sustained_rate_ev_s": 1200.0,
        },
        "per_tenant_p99_ms": {
            "t0": 61.2, "t1": 58.6, "t2": 69.8, "t3": 55.2,
        },
        "isolation": {
            "storm_tenant": "t0",
            "window": "storm",
            "gate_ratio": 4.0,
            "victims": {
                "t1": {"pre_ms": 49.3, "post_ms": 58.6,
                       "ratio": 1.189},
                "t2": {"pre_ms": 50.0, "post_ms": 69.8,
                       "ratio": 1.396},
                "t3": {"pre_ms": 48.0, "post_ms": 55.2,
                       "ratio": 1.15},
            },
            "max_ratio": 1.396,
            "verdict": "pass",
        },
        "slo": {
            "policies": 4,
            "violations_total": 45,
            "recoveries_total": 4,
            "journal_violations": 45,
            "journal_recoveries": 4,
            "reconciled": True,
            "active_violations": 4,
            "worst_burning_tenant": "t0",
        },
        "sustainable": {
            "lag_p90_s": 0.674,
            "lag_budget_s": 2.5,
            "lag_ok": True,
            "loss_ratio": 0.0017,
            "loss_budget": 0.005,
            "loss_ok": True,
            "probe_p99_ms": 1519.3,
            "telemetry_p99_ms": 946.2,
            "probe_tolerance": 4.0,
            "probe_slack_ms": 500.0,
            "probe_ok": True,
            "health_ok": True,
            "verdict": True,
        },
        "limiting_leg": _limiting_leg_blk(mode="serve"),
        "churn": {
            "admitted": 1, "retired": 1, "disabled": 1, "enabled": 1,
            "hostile_refused_rules": ["ADM110"],
        },
        "scrapes": {
            "count": 21, "failures": 0, "cadence_s": 0.35,
            "source": "rest",
        },
    }
    blk.update(over)
    return blk


def _v11_doc(**over):
    doc = {
        "metric": "events/sec (serving mix, 4 tenants, open-loop)",
        "value": 1176.6,
        "unit": "events/sec",
        "schema_version": 11,
        "serving": _serving_blk(),
    }
    doc.update(over)
    return doc


def test_valid_v11_serving_only_doc_passes():
    """A --serve line carries ``serving`` INSTEAD of ``modes``: the
    replay-mode contracts (stage_breakdown through the v10 recovery
    requirement) must NOT fire against it — errors == [] proves the
    early-return, not just the serving gate."""
    errors = []
    CHECK.validate_doc(_v11_doc(), errors, "doc")
    assert errors == []


def test_v11_isolation_ratios_rederived():
    # a declared victim ratio that disagrees with its own pre/post
    doc = _v11_doc()
    doc["serving"]["isolation"]["victims"]["t2"]["ratio"] = 1.05
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("!= recomputed" in e and "t2" in e for e in errors)
    # a declared max_ratio that is not the max of its victims
    doc = _v11_doc()
    doc["serving"]["isolation"]["max_ratio"] = 1.15
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("max_ratio" in e and "recomputed" in e for e in errors)


def test_v11_isolation_verdict_cannot_lie_and_fail_fails():
    # verdict "pass" contradicting a gate the numbers blow through
    doc = _v11_doc()
    doc["serving"]["isolation"]["gate_ratio"] = 1.2
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("contradicts its own numbers" in e for e in errors)
    # an HONEST fail verdict still fails the line — the serving claim
    # requires isolation to hold, not merely to be reported
    doc = _v11_doc()
    doc["serving"]["isolation"]["gate_ratio"] = 1.2
    doc["serving"]["isolation"]["verdict"] = "fail"
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "verdict 'fail'" in e and "blew victims" in e for e in errors
    )


def test_v11_slo_account_must_reconcile_with_journal():
    # watchdog counters drifting from the flight-recorder replay
    doc = _v11_doc()
    doc["serving"]["slo"]["journal_violations"] = 44
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "journal replay" in e and "drifted" in e for e in errors
    )
    doc = _v11_doc()
    doc["serving"]["slo"]["reconciled"] = False
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("reconciled must be true" in e for e in errors)


def test_v11_sustainable_verdict_rederived_from_inputs():
    # a declared lag_ok=True contradicting the published lag vs budget
    doc = _v11_doc()
    doc["serving"]["sustainable"]["lag_p90_s"] = 3.1
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "lag_ok" in e and "contradicts its own inputs" in e
        for e in errors
    )
    # verdict false = not sustained = the line's headline is a lie
    doc = _v11_doc()
    doc["serving"]["sustainable"]["verdict"] = False
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("verdict must be true" in e for e in errors)
    # missing inputs: the check cannot be re-derived, so it fails
    doc = _v11_doc()
    del doc["serving"]["sustainable"]["probe_p99_ms"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("cannot re-derive" in e and "probe_ok" in e
               for e in errors)


def test_v11_churn_really_happened_with_rule_ids():
    doc = _v11_doc()
    doc["serving"]["churn"]["admitted"] = 0
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "admitted=0" in e and "really must have happened" in e
        for e in errors
    )
    doc = _v11_doc()
    doc["serving"]["churn"]["hostile_refused_rules"] = []
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("hostile_refused_rules" in e for e in errors)
    doc = _v11_doc()
    doc["serving"]["churn"]["hostile_refused_rules"] = ["nope"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("exact rule ids" in e for e in errors)


def test_v11_requires_limiting_leg_and_rest_scrapes():
    doc = _v11_doc()
    del doc["serving"]["limiting_leg"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "limiting_leg block missing" in e and "bottleneck" in e
        for e in errors
    )
    doc = _v11_doc()
    doc["serving"]["scrapes"]["source"] = "in-process"
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("public REST surface" in e for e in errors)
    doc = _v11_doc()
    doc["serving"]["scrapes"]["count"] = 2
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("scraped series" in e for e in errors)


def test_v11_search_ledger_required():
    doc = _v11_doc()
    doc["serving"]["search"]["rates_tried"] = []
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("rates_tried" in e and "ledger" in e for e in errors)
    doc = _v11_doc()
    doc["serving"]["search"]["sustained_rate_ev_s"] = 0.0
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("sustained_rate_ev_s" in e for e in errors)


def test_v10_era_docs_unaffected_by_v11_gate():
    """Replay-mode lines need no serving block, but one attached to a
    modes-carrying line is held to its contract AND the replay
    contracts still apply (no early-return when modes is present) —
    same exemption shape as disorder/control/attribution."""
    errors = []
    CHECK.validate_doc(_v10_doc(), errors, "doc")
    assert errors == []
    doc = _v10_doc()
    doc["serving"] = _serving_blk()
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
    doc = _v10_doc()
    doc["serving"] = _serving_blk()
    doc["serving"]["slo"]["reconciled"] = False
    del doc["modes"]["streaming"]["limiting_leg"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("reconciled must be true" in e for e in errors)
    assert any(
        "modes.streaming: limiting_leg block missing" in e
        for e in errors
    )


def test_v11_serving_line_recovery_block_still_gated():
    """The early-return exempts a --serve line from the replay
    contracts, NOT from the recovery contract: an attached recovery
    block is still validated (at v11 that includes the transactional
    sub-block requirement)."""
    doc = _v11_doc(
        recovery=_recovery_block(transactional=_txn_block())
    )
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
    doc = _v11_doc(recovery=_recovery_block(transactional=None))
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("transactional" in e for e in errors)


def test_fault_block_live_and_gate_accepts():
    """The live --fault contract: bench._fault_recovery_block runs the
    supervised crash schedule (two pull-kills + one
    kill-mid-checkpoint) at dryrun scale and the resulting block — a
    MEASURED recovery_time_ms, replayed events, and oracle-diffed
    exactly-once counts — passes the schema gate attached to a v4
    line. Run in a SUBPROCESS, not in-process: bench's supervised
    jobs sharing this pytest process's XLA runtime corrupted later
    sharded tests' device state nondeterministically (garbage
    accumulator values); process isolation is the same boundary
    ``bench.py --fault`` itself runs behind. (A full ``bench.py
    --dryrun --fault`` subprocess line was gate-validated when this
    landed; this test keeps the block's producer and validator honest
    against each other at a fraction of a full dryrun's cost.)"""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable, "-c",
            "import json, bench; "
            "print(json.dumps(bench._fault_recovery_block(True)))",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    block = json.loads(proc.stdout.splitlines()[-1])
    assert block["crashes"] >= 2  # pull kills + mid-checkpoint kill
    assert block["kill_mid_checkpoint"] is True
    assert math.isfinite(block["recovery_time_ms"])
    assert block["recovery_time_ms"] > 0
    assert block["events_replayed"] > 0
    assert block["duplicate_rows"] == 0
    assert block["lost_rows"] == 0
    assert block["exactly_once"] is True
    assert block["stale_tmp_swept"] is True
    # v10: the transactional leg rode the same producer run — its
    # exactly-once numbers are EXTERNAL (read-committed topic vs
    # oracle) and the kill-mid-transaction really fired
    txn = block["transactional"]
    assert txn["kill_mid_transaction"] is True
    assert txn["crashes"] >= 2
    assert math.isfinite(txn["recovery_time_ms"])
    assert txn["recovery_time_ms"] > 0
    assert txn["read_committed_duplicates"] == 0
    assert txn["read_committed_lost"] == 0
    assert txn["exactly_once"] is True
    assert txn["read_uncommitted_rows"] > txn["rows_emitted"]
    assert txn["aborted_rows_invisible"] is True
    errors = []
    CHECK.validate_doc(_v4_doc(recovery=block), errors, "doc")
    assert errors == []
    # and attached to a v10 line it satisfies the REQUIRED contract
    errors = []
    CHECK.validate_doc(_v10_doc(recovery=block), errors, "doc")
    assert errors == []


def test_dryrun_emits_schema_complete_v13(tmp_path):
    """The live contract: ``bench.py --dryrun`` (small events, one
    replay, short paced phase) exercises resident + streaming + sink,
    the out-of-process prober, the small-skew disorder sweep, the
    control-plane sustained-load run (with the v8 per-plan
    attribution block), AND the v9 measured limiting-leg verdict per
    mode, and its JSON line passes the schema gate — in the tier-1
    lane, under its timeout. (The --fault recovery block — which v10
    gates the transactional sub-block inside of — has its own live
    subprocess test above, and the v11 serving line has its own
    --serve --dryrun test below; this replay line stays at its
    historical cost and simply stamps the current schema version.)"""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        # the production batch shape scaled down: per-event staging
        # amortizes as it does at 10M/524k, so the gated
        # streaming_vs_resident_ratio measures dispatch overhead, not
        # tiny-batch fixed costs; ~0.4s per measured run keeps the
        # shared host's ±20ms scheduler jitter at the few-percent
        # level instead of flipping the verdict
        BENCH_EVENTS="2097152",
        BENCH_BATCH="65536",
        # 32 micro-batches -> 4 fused segments per run
        BENCH_SEGMENT="8",
        BENCH_LAT_SECONDS="1.0",
        BENCH_RUNS="3",
        # the gated ratio is the median of ABBA rounds (resident,
        # streaming, streaming, resident — linear host drift cancels
        # out of each round's quotient)
        BENCH_PAIR_ROUNDS="2",
    )
    out = tmp_path / "BENCH_dryrun.json"
    for attempt in (1, 2):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--dryrun"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=560,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out.write_text(proc.stdout)
        errors = CHECK.validate_file(str(out))
        # ONE retry, only when the sole failure is the perf-ratio
        # verdict: the fusion target is a hardware measurement on a
        # shared 2-core host whose round quotients still spread under
        # co-tenant load even with the drift-cancelling ABBA design —
        # a second independent window distinguishes "engine regressed"
        # (fails twice) from "the box was busy" (passes clean)
        if attempt == 1 and errors and all(
            "fusion_target" in e for e in errors
        ):
            continue
        break
    assert errors == []
    doc = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ][-1]
    assert doc["schema_version"] == 13
    assert set(doc["modes"]) == {"resident", "streaming", "sink"}
    for name, sec in doc["modes"].items():
        lat = sec["latency"]
        # the prober demonstrably ran out of process, and its numbers
        # are real finite measurements
        assert lat["prober_pid"] != lat["prober_parent_pid"]
        assert math.isfinite(lat["prober_p99_ms"])
        assert math.isfinite(lat["telemetry_p99_ms"])
        assert math.isfinite(lat["discrepancy_ratio"])
        assert sec["stage_breakdown"]["coverage"] >= 0.95
        # the v9 additions: the LIVE limiting-leg block — coverage,
        # a named leg that is the argmax of its own published
        # numbers, and the overlapped decode/sink detail legs
        ll = sec["limiting_leg"]
        assert ll["mode"] == name
        assert ll["coverage"] >= 0.95
        cands = {
            k: v["seconds"]
            for k, v in ll["legs"].items()
            if not v["overlapped"] and k != "setup"
        }
        assert ll["limiting_leg"] == max(cands, key=cands.get)
        assert {"decode", "sink"} <= set(ll["legs"])
        assert all(
            ll["legs"][k]["overlapped"] for k in ("decode", "sink")
        )
    assert "prober_contradiction" not in doc
    # the v4 additions ride the same dryrun line: the columnar sink
    # lane really materialized rows, the latency verdict passed one of
    # the two targets, and the deadline scheduler recorded staleness
    sink = doc["modes"]["sink"]
    assert sink["columnar"] is True
    assert sink["rows_materialized_ev_s"] > 0
    assert sink["rows_emitted"] > 0
    assert doc["p99_target"]["verdict"] in (
        "p99_le_500ms", "p99_le_2x_prober",
    )
    assert math.isfinite(doc["drain_staleness"]["p99_ms"])
    # the v5 additions: fused dispatch really collapsed the streaming
    # dispatch chain, H2D uploads really overlapped in-flight compute,
    # and streaming reached the gated >= 80%-of-resident target
    for name in ("resident", "streaming", "sink"):
        fu = doc["modes"][name]["fusion"]
        assert fu["segment_len"] >= 1
        assert math.isfinite(fu["dispatches_per_1k_batches"])
    stream_fu = doc["modes"]["streaming"]["fusion"]
    assert stream_fu["segment_len"] > 1
    assert stream_fu["dispatches_per_1k_batches"] < 1000.0
    # on the 2-core CPU lane segment compute retires inside the
    # dispatch call itself, so the between-dispatch overlap fraction
    # can honestly be 0 here; the busy-window overlap proof is the
    # heavy-stack unit test (tests/test_fused_stream.py)
    assert 0.0 <= stream_fu["h2d_overlap_frac"] <= 1.0
    assert math.isfinite(doc["streaming_vs_resident_ratio"])
    assert doc["fusion_target"]["verdict"] == "met"
    # the v6 additions: the disorder sweep really ran at all three
    # skews in event-time mode with EXACT late/dup/idle accounting
    runs = {r["skew_ms"]: r for r in doc["disorder"]["runs"]}
    assert set(runs) == {0, 1_000, 10_000}
    for skew, run in runs.items():
        assert run["counts_exact"] is True, (skew, run)
        assert run["late_dropped"] == run["injected"]["late"] > 0
        assert run["idle_marked"] == run["injected"]["idle_gaps"] > 0
        assert run["events_per_sec"] > 0
        assert math.isfinite(run["p99_ms"])
    # the v7 additions: the control plane really admitted a stack of
    # tenant queries at epoch boundaries under load, refused the
    # hostile one by rule id, dropped nothing, and the AOT executable
    # cache served hosts 2..N without recompiling
    ctrl = doc["control"]
    assert ctrl["dropped_events"] == 0
    assert ctrl["concurrent_queries"] >= 8
    assert ctrl["stack_joins"] > 0
    assert ctrl["hostile_refused_rule"].startswith("ADM")
    assert ctrl["cache"]["hits"] >= 1
    assert math.isfinite(ctrl["admit_rate_qps"])
    assert ctrl["admit_rate_qps"] > 0
    # the v8 additions: per-plan scoped row counts really conserve
    # against the job total, every plan carries its tenant, and the
    # footprint meter compared at least one admission prediction to
    # live device bytes (see also the unit v8 cases above)
    att = ctrl["attribution"]
    assert att["conserved"] is True
    assert sum(
        p["rows_emitted"] for p in att["plans"].values()
    ) == att["rows_emitted_total"] > 0
    assert all("tenant" in p for p in att["plans"].values())
    assert any(
        math.isfinite(ent.get("utilization", float("nan")))
        for ent in att["footprint"].values()
    )
    # the v13 additions: the shared-vs-unshared fleet A/B really ran —
    # hosts formed, each serving >= 2 members with sub-linear compile
    # spend, attribution conserved with tenants riding shared prefixes,
    # and neither side shed load (the gate re-derives the speedup and
    # holds the dryrun fleet to its regression backstop)
    shr = doc["subplan_share"]
    assert shr["tenants"] >= 12
    assert shr["dryrun"] is True
    assert shr["shared"]["conserved"] is True
    assert shr["shared"]["subplan_shares"] >= shr["tenants"]
    assert shr["unshared"]["dropped_events"] == 0
    assert shr["shared"]["dropped_events"] == 0
    for h in shr["shared"]["hosts"].values():
        assert h["members"] >= 2
        assert h["lowerings"] < h["members"]


def test_serve_dryrun_emits_valid_serving_line(tmp_path):
    """The live --serve contract: ``bench.py --serve --dryrun`` runs
    ONE fixed-load open-loop pass of the full serving observatory —
    mixed-tenant stack over shared ingest, disorder, mid-run broker
    faults, admit/retire churn, the noisy-neighbor storm, the
    out-of-process prober, the SLO watchdog — with every verdict read
    off the public REST surface, and its serving-only JSON line
    passes the v11 schema gate in the tier-1 lane."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu")
    out = tmp_path / "BENCH_serve_dryrun.json"
    for attempt in (1, 2):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--serve", "--dryrun"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=560,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out.write_text(proc.stdout)
        errors = CHECK.validate_file(str(out))
        # ONE retry, only when every failure is a serving-block
        # verdict: the isolation ratios and sustainability gates are
        # hardware measurements of tail latency on a shared 2-core
        # host — a second independent window distinguishes "the
        # observatory regressed" (fails twice) from "the box was
        # busy" (passes clean)
        if attempt == 1 and errors and all(
            ":serving" in e for e in errors
        ):
            continue
        break
    assert errors == []
    doc = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ][-1]
    assert doc["schema_version"] == 13
    srv = doc["serving"]
    # the headline number is the measured aggregate, sustained
    assert doc["value"] == srv["sustained_events_per_sec"] > 0
    assert srv["tenants"] >= 2
    assert srv["search"]["mode"] == "fixed"
    assert srv["search"]["rates_tried"] == [
        [srv["search"]["sustained_rate_ev_s"], True]
    ]
    # every tenant published a finite positive tail
    assert len(srv["per_tenant_p99_ms"]) == srv["tenants"]
    assert all(
        math.isfinite(v) and v > 0
        for v in srv["per_tenant_p99_ms"].values()
    )
    # the verdicts the gate re-derived really came out green
    assert srv["isolation"]["verdict"] == "pass"
    assert srv["sustainable"]["verdict"] is True
    assert srv["slo"]["reconciled"] is True
    assert srv["slo"]["policies"] >= srv["tenants"]
    # churn really happened mid-measurement, hostile refused by rule
    churn = srv["churn"]
    assert all(
        churn[k] >= 1
        for k in ("admitted", "retired", "disabled", "enabled")
    )
    assert churn["hostile_refused_rules"]
    # the mix's shared-prefix family (two structurally distinct
    # residues behind one exact bracket) was admitted AND actually
    # rode the subplan-share path under churn/faults — real coverage
    # of the share ladder rung on the serving line, no new gate
    assert srv["mix"].get("shared") == 2
    assert churn["subplan_shares"] >= 2
    # the prober ran out of process under serving load
    sus = srv["sustainable"]
    assert math.isfinite(sus["probe_p99_ms"])
    assert math.isfinite(sus["telemetry_p99_ms"])
    # the verdicts were read off the REST plane, as a series
    assert srv["scrapes"]["source"] == "rest"
    assert srv["scrapes"]["count"] >= 3
    assert srv["scrapes"]["failures"] == 0
    # the serving line names its measured bottleneck
    assert srv["limiting_leg"]["limiting_leg"] in srv[
        "limiting_leg"
    ]["legs"]


@pytest.mark.slow
def test_serve_full_binary_search_publishes_rate_ladder(tmp_path):
    """The full (non-dryrun) --serve mode: binary search on the
    open-loop offered rate. Scaled down via the BENCH_SERVE_* knobs
    so it terminates in minutes, but the search itself is real: the
    published ledger must show more than one rate tried, the mode
    must be "binary", and the sustained rate must be the highest
    rate whose pass verdict was true."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        # dryrun-scale passes: the disorder schedule needs rate *
        # seconds events to span several 2048-event chunks so its
        # stragglers have room to release before the stream ends
        BENCH_SERVE_RATE="1200",
        BENCH_SERVE_SECONDS="6.0",
        BENCH_SERVE_PASSES="3",
        BENCH_SERVE_TENANTS="4",
    )
    out = tmp_path / "BENCH_serve.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--serve"],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out.write_text(proc.stdout)
    assert CHECK.validate_file(str(out)) == []
    doc = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ][-1]
    search = doc["serving"]["search"]
    assert search["mode"] == "binary"
    assert len(search["rates_tried"]) > 1
    passed = [r for r, ok in search["rates_tried"] if ok]
    assert passed, search["rates_tried"]
    assert search["sustained_rate_ev_s"] == max(passed)


def test_repo_bench_files_validate():
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    assert files, "no BENCH_*.json harvest files in repo root"
    for path in files:
        assert CHECK.validate_file(path) == []


def test_wrapper_format_extraction(tmp_path):
    inner = json.dumps(_v2_doc())
    wrapper = json.dumps(
        {"n": 6, "cmd": "python bench.py", "rc": 0,
         "tail": "WARNING: noise\n" + inner + "\n"}
    )
    p = tmp_path / "BENCH_x.json"
    p.write_text(wrapper)
    assert CHECK.validate_file(str(p)) == []
    # and a broken inner doc is caught through the wrapper
    bad = _v2_doc(coverage=0.5)
    p.write_text(
        json.dumps({"rc": 0, "tail": json.dumps(bad)})
    )
    assert CHECK.validate_file(str(p))
    # a wrapper whose run crashed before printing its JSON line
    # (noise-only / empty tail) must fail, not trivially validate
    p.write_text(json.dumps({"rc": 1, "tail": "Traceback ...\n"}))
    assert any(
        "no bench JSON lines" in e for e in CHECK.validate_file(str(p))
    )


# -- schema v12: the fleet block (bench.py --fleet) --------------------------


def _fleet_doc():
    """A valid fleet-only v12 line (the shape ``bench.py --fleet
    --dryrun`` prints; numbers from a real run)."""
    return {
        "metric": "cold-start to first row (warm store, 8 tenants)",
        "value": 1.65,
        "unit": "seconds",
        "schema_version": 12,
        "fleet": {
            "tenants": 8,
            "events_per_boot": 200,
            "store_namespace": "cpu-cpu-n1-jax0.4.37",
            "cold": {
                "first_row_s": 4.68, "ready_s": 0.03, "compiles": 1,
                "warm_hits": 0, "warm_misses": 2, "persists": 3,
                "store_errors": 0,
            },
            "warm": {
                "first_row_s": 1.65, "ready_s": 0.03, "compiles": 0,
                "warm_hits": 3, "warm_misses": 0, "persists": 0,
                "store_errors": 0,
            },
            "cold_to_warm_speedup": 2.84,
            "handoff": {
                "replica": "fleet-warm", "reason": "drain",
                "boundary": "final_checkpoint",
            },
            "committed": {
                "rows": 798, "epochs": 8, "duplicate_epochs": 0,
                "lost": 0,
            },
            "wall_seconds": 9.8,
        },
    }


def test_fleet_block_valid_line_passes(tmp_path):
    p = tmp_path / "BENCH_fleet.json"
    p.write_text(json.dumps(_fleet_doc()) + "\n")
    assert CHECK.validate_file(str(p)) == []


def test_fleet_line_exempt_from_replay_contracts(tmp_path):
    """A --fleet line carries ``fleet`` INSTEAD of ``modes``: the v2
    stage_breakdown .. v10 recovery-requirement contracts must not
    fire on it (same early-return shape as the serving exemption)."""
    doc = _fleet_doc()
    assert "modes" not in doc and "stage_breakdown" not in doc
    p = tmp_path / "BENCH_fleet.json"
    p.write_text(json.dumps(doc) + "\n")
    errors = CHECK.validate_file(str(p))
    assert errors == []


@pytest.mark.parametrize(
    "mutate, needle",
    [
        (lambda f: f["warm"].__setitem__("compiles", 2),
         "warm.compiles must be 0"),
        (lambda f: f["warm"].__setitem__("warm_misses", 1),
         "warm.warm_misses must be 0"),
        (lambda f: f["warm"].__setitem__("warm_hits", 0),
         "warm.warm_hits missing/<1"),
        (lambda f: f["warm"].__setitem__("first_row_s", 9.9),
         "must beat cold.first_row_s"),
        (lambda f: f["cold"].__setitem__("persists", 0),
         "cold.persists missing/<1"),
        (lambda f: f["cold"].pop("first_row_s"),
         "cold.first_row_s missing"),
        (lambda f: f.pop("warm"), "warm boot block missing"),
        (lambda f: f["committed"].__setitem__("duplicate_epochs", 1),
         "duplicate_epochs must be 0"),
        (lambda f: f["committed"].__setitem__("lost", 5),
         "committed.lost must be 0"),
        (lambda f: f["committed"].__setitem__("rows", 0),
         "committed.rows missing/<1"),
        (lambda f: f.pop("committed"), "committed block missing"),
        (lambda f: f.__setitem__("tenants", 1), "tenants missing"),
    ],
)
def test_fleet_block_rejects_broken_claims(tmp_path, mutate, needle):
    doc = _fleet_doc()
    mutate(doc["fleet"])
    p = tmp_path / "BENCH_fleet.json"
    p.write_text(json.dumps(doc) + "\n")
    errors = CHECK.validate_file(str(p))
    assert errors, "mutation should have failed the gate"
    assert any(needle in e for e in errors), errors


def test_fleet_block_validated_on_old_versions_when_present(tmp_path):
    """Pre-v12 exemption shape: an old line need not carry the block,
    but one that IS present is held to its contract regardless of the
    stamped version."""
    doc = _fleet_doc()
    doc["schema_version"] = 11
    doc["fleet"]["warm"]["compiles"] = 3
    p = tmp_path / "BENCH_fleet.json"
    p.write_text(json.dumps(doc) + "\n")
    assert any(
        "warm.compiles must be 0" in e
        for e in CHECK.validate_file(str(p))
    )


def test_fleet_dryrun_emits_valid_v12_fleet_line(tmp_path):
    """The live --fleet contract: ``bench.py --fleet --dryrun`` boots
    a replica subprocess cold behind the key-hash router, admits the
    tenant stack through the fan-out control plane, rolling-restarts
    it into a warm successor booted from the persistent store + the
    supervisor checkpoint, and the fleet-only JSON line passes the v12
    gate in the tier-1 lane: warm first-row beats cold, the warm boot
    lowered NOTHING, and the commit-log exactly-once account across
    the handoff is clean."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu")
    out = tmp_path / "BENCH_fleet_dryrun.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--fleet", "--dryrun"],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out.write_text(proc.stdout)
    assert CHECK.validate_file(str(out)) == []
    doc = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ][-1]
    assert doc["schema_version"] == 13
    flt = doc["fleet"]
    # the headline number is the WARM boot's cold-start-to-first-row
    assert doc["value"] == flt["warm"]["first_row_s"] > 0
    assert flt["warm"]["first_row_s"] < flt["cold"]["first_row_s"]
    # the successor lowered nothing: every executable came off disk
    assert flt["warm"]["compiles"] == 0
    assert flt["warm"]["warm_hits"] >= 1
    assert flt["warm"]["warm_misses"] == 0
    assert flt["cold"]["persists"] >= 1
    # the handoff was journaled and the committed account is exact
    assert flt["handoff"]["reason"] == "drain"
    assert flt["committed"]["rows"] >= 1
    assert flt["committed"]["duplicate_epochs"] == 0
    assert flt["committed"]["lost"] == 0


# -- schema v13: the subplan_share block (cross-tenant sharing A/B) ----------


def _share_blk(**over):
    """A valid v13 ``subplan_share`` block (the shape bench.py's
    replay line carries; numbers from a real dryrun)."""
    blk = {
        "tenants": 12,
        "families": 2,
        "members_per_family": 6,
        "mix": "non-constants-only structurally-distinct suffixes",
        "unshared": {
            "events_per_sec": 100_000, "events": 196_608,
            "concurrent_plans": 12, "lowerings": 11,
            "dropped_events": 0, "stack_joins": 1,
        },
        "shared": {
            "events_per_sec": 180_000, "events": 196_608,
            "concurrent_plans": 12, "lowerings": 14,
            "dropped_events": 0,
            "hosts": {
                "@shr:aaaa0000aaaa0000": {"members": 6, "lowerings": 1},
                "@shr:bbbb1111bbbb1111": {"members": 6, "lowerings": 1},
            },
            "subplan_shares": 12,
            "conserved": True,
            "rows_emitted_total": 27_258,
        },
        "speedup": 1.8,
        "dryrun": False,
    }
    blk.update(over)
    return blk


def _v13_doc(**over):
    doc = _v10_doc()
    doc["schema_version"] = 13
    doc["subplan_share"] = _share_blk(**over)
    return doc


def test_valid_v13_doc_passes():
    errors = []
    CHECK.validate_doc(_v13_doc(), errors, "doc")
    assert errors == []


def test_v13_requires_subplan_share_block():
    doc = _v13_doc()
    del doc["subplan_share"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("subplan_share block missing" in e for e in errors)


def test_pre_v13_exempt_but_present_block_validated():
    # a v12-era replay line need not carry the block...
    doc = _v10_doc()
    doc["schema_version"] = 12
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
    # ...but one that IS present is held to its contract
    doc["subplan_share"] = _share_blk(speedup=9.9)
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("does not re-derive" in e for e in errors)


def test_v13_speedup_must_rederive_from_sides():
    errors = []
    CHECK.validate_doc(_v13_doc(speedup=2.5), errors, "doc")
    assert any("does not re-derive" in e for e in errors)


def test_v13_sharing_must_not_lose():
    # a full-fleet line below 1.0 fails outright
    doc = _v13_doc()
    doc["subplan_share"]["unshared"]["events_per_sec"] = 200_000
    doc["subplan_share"]["speedup"] = 0.9
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("must not lose" in e for e in errors)
    # the dryrun fleet gets the 0.8 regression backstop: 0.9 passes...
    doc["subplan_share"]["dryrun"] = True
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
    # ...but the broken-coalescing regime (<= 0.5) still fails
    doc["subplan_share"]["unshared"]["events_per_sec"] = 400_000
    doc["subplan_share"]["speedup"] = 0.45
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("must not lose" in e for e in errors)


def test_v13_shared_side_must_conserve():
    doc = _v13_doc()
    doc["subplan_share"]["shared"]["conserved"] = False
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("conserved must be true" in e for e in errors)


def test_v13_host_lowerings_must_be_sublinear():
    # one lowering per member is exactly the unshared cost: rejected
    doc = _v13_doc()
    doc["subplan_share"]["shared"]["hosts"][
        "@shr:aaaa0000aaaa0000"]["lowerings"] = 6
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("sub-linear" in e for e in errors)
    # a host nobody shares proves nothing
    doc = _v13_doc()
    doc["subplan_share"]["shared"]["hosts"][
        "@shr:aaaa0000aaaa0000"]["members"] = 1
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("shares nothing" in e for e in errors)


def test_v13_dropped_events_fail_either_side():
    doc = _v13_doc()
    doc["subplan_share"]["shared"]["dropped_events"] = 17
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("cheating" in e for e in errors)


def test_v13_nonfinite_throughput_rejected():
    doc = _v13_doc()
    doc["subplan_share"]["shared"]["events_per_sec"] = float("nan")
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "shared.events_per_sec missing/non-positive" in e
        for e in errors
    )
    doc = _v13_doc()
    del doc["subplan_share"]["unshared"]["events_per_sec"]
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any(
        "unshared.events_per_sec missing/non-positive" in e
        for e in errors
    )


def test_v13_missing_hosts_rejected():
    doc = _v13_doc()
    doc["subplan_share"]["shared"]["hosts"] = {}
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert any("hosts missing/empty" in e for e in errors)


@pytest.mark.slow
def test_subplan_share_block_live_and_gate_accepts():
    """The live producer: bench._subplan_share_block(True) runs the
    real shared-vs-unshared A/B (two families x six structurally-
    distinct members over one Job each) and the resulting block
    passes the v13 gate. Subprocess-isolated like the --fault live
    test, and slow-marked: the block also rides the main --dryrun
    line, whose live test gate-validates it in the tier-1 lane — this
    test exists to debug the producer in isolation."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable, "-c",
            "import json, bench; "
            "print(json.dumps(bench._subplan_share_block(True)))",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    block = json.loads(proc.stdout.splitlines()[-1])
    assert block["tenants"] >= 12
    assert block["shared"]["conserved"] is True
    assert block["shared"]["subplan_shares"] >= block["tenants"]
    for h in block["shared"]["hosts"].values():
        assert h["members"] >= 2
        assert h["lowerings"] < h["members"]
    # attached to a v13 replay line the REQUIRED contract holds
    doc = _v10_doc()
    doc["schema_version"] = 13
    doc["subplan_share"] = block
    errors = []
    CHECK.validate_doc(doc, errors, "doc")
    assert errors == []
