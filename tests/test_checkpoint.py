"""Checkpoint / restore equivalence: run-half + restore == full run.

The reference never restored engine state (AbstractSiddhiOperator.java:341
TODO); these tests pin that this engine restores EVERYTHING: window rings,
partial NFA matches, group tables, string dictionaries, event tables."""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP


@dataclasses.dataclass
class Event:
    id: int
    name: str
    price: float
    timestamp: int


FIELDS = ["id", "name", "price", "timestamp"]


def make_events(n, start_ts=1000):
    return [
        Event(i % 4, f"name_{i % 3}", float(i), start_ts + 1000 * i)
        for i in range(n)
    ]


def run_full(events, cql, out="out"):
    env = CEPEnvironment(batch_size=5)
    return (
        SiddhiCEP.define("S", events, FIELDS, env=env).cql(cql).returns(out)
    )


def run_split(events, cql, k, out="out"):
    """Run the first k events, snapshot, then resume in a fresh process
    analog: a new environment over the SAME stream, where the restored
    source position skips the already-consumed prefix."""
    env1 = CEPEnvironment(batch_size=5)
    es1 = SiddhiCEP.define("S", events[:k], FIELDS, env=env1).cql(cql)
    job1 = es1.execute()
    snap = job1.snapshot()

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events[:k] + events[k:], FIELDS, env=env2).cql(cql)
    job2 = es2.job
    job2.restore(snap)
    job2.run()
    return job1.results(out) + job2.results(out)


CASES = [
    # sliding window ring must survive
    "from S#window.length(6) select sum(price) as t, min(price) as lo "
    "insert into out",
    # cumulative group table + encoder
    "from S select id, sum(price) as t, count() as c group by id "
    "insert into out",
    # string-keyed groups: dictionary + encoder round-trip
    "from S select name, count() as c group by name insert into out",
    # partial pattern matches must survive the boundary
    "from every s1 = S[id == 2] -> s2 = S[id == 3] "
    "select s1.price as p1, s2.price as p2 insert into out",
    # tumbling window carry
    "from S#window.lengthBatch(7) select sum(price) as t insert into out",
]


@pytest.mark.parametrize("cql", CASES)
@pytest.mark.parametrize("k", [9, 13])
def test_restore_equivalence(cql, k):
    events = make_events(30)
    assert run_split(events, cql, k) == run_full(events, cql)


def test_restore_event_table():
    events = make_events(20)
    cql = (
        "define table T (tid int, total double);"
        "from S[id == 0] select id as tid, price as total insert into T;"
        "from S[id == 1] join T on S.id == T.tid + 1 "
        "select S.price, T.total insert into out"
    )
    assert run_split(events, cql, 11) == run_full(events, cql)


def test_save_load_file(tmp_path):
    events = make_events(24)
    cql = "from S#window.length(5) select sum(price) as t insert into out"
    env1 = CEPEnvironment(batch_size=5)
    es1 = SiddhiCEP.define("S", events[:12], FIELDS, env=env1).cql(cql)
    job1 = es1.execute()
    path = str(tmp_path / "ckpt.bin")
    job1.save_checkpoint(path)

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events, FIELDS, env=env2).cql(cql)
    job2 = es2.job
    job2.restore(path)
    job2.run()
    assert job1.results("out") + job2.results("out") == run_full(
        events, cql
    )


def test_restore_rejects_changed_plan():
    events = make_events(10)
    env1 = CEPEnvironment(batch_size=5)
    job1 = (
        SiddhiCEP.define("S", events, FIELDS, env=env1)
        .cql("from S#window.length(5) select sum(price) as t insert into out")
        .execute()
    )
    snap = job1.snapshot()

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events, FIELDS, env=env2).cql(
        "from every s1 = S[id == 2] -> s2 = S[id == 3] "
        "select s1.price as p insert into out"
    )
    with pytest.raises(ValueError):
        es2.job.restore(snap)
