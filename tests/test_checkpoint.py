"""Checkpoint / restore equivalence: run-half + restore == full run.

The reference never restored engine state (AbstractSiddhiOperator.java:341
TODO); these tests pin that this engine restores EVERYTHING: window rings,
partial NFA matches, group tables, string dictionaries, event tables."""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP


@dataclasses.dataclass
class Event:
    id: int
    name: str
    price: float
    timestamp: int


FIELDS = ["id", "name", "price", "timestamp"]


def make_events(n, start_ts=1000):
    return [
        Event(i % 4, f"name_{i % 3}", float(i), start_ts + 1000 * i)
        for i in range(n)
    ]


def run_full(events, cql, out="out"):
    env = CEPEnvironment(batch_size=5)
    return (
        SiddhiCEP.define("S", events, FIELDS, env=env).cql(cql).returns(out)
    )


def run_split(events, cql, k, out="out"):
    """Run the first k events, snapshot, then resume in a fresh process
    analog: a new environment over the SAME stream, where the restored
    source position skips the already-consumed prefix."""
    env1 = CEPEnvironment(batch_size=5)
    es1 = SiddhiCEP.define("S", events[:k], FIELDS, env=env1).cql(cql)
    job1 = es1.execute()
    snap = job1.snapshot()

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events[:k] + events[k:], FIELDS, env=env2).cql(cql)
    job2 = es2.job
    job2.restore(snap)
    job2.run()
    return job1.results(out) + job2.results(out)


CASES = [
    # sliding window ring must survive
    "from S#window.length(6) select sum(price) as t, min(price) as lo "
    "insert into out",
    # cumulative group table + encoder
    "from S select id, sum(price) as t, count() as c group by id "
    "insert into out",
    # string-keyed groups: dictionary + encoder round-trip
    "from S select name, count() as c group by name insert into out",
    # partial pattern matches must survive the boundary
    "from every s1 = S[id == 2] -> s2 = S[id == 3] "
    "select s1.price as p1, s2.price as p2 insert into out",
    # tumbling window carry
    "from S#window.lengthBatch(7) select sum(price) as t insert into out",
]


@pytest.mark.parametrize("cql", CASES)
@pytest.mark.parametrize("k", [9, 13])
def test_restore_equivalence(cql, k):
    events = make_events(30)
    assert run_split(events, cql, k) == run_full(events, cql)


def test_restore_event_table():
    events = make_events(20)
    cql = (
        "define table T (tid int, total double);"
        "from S[id == 0] select id as tid, price as total insert into T;"
        "from S[id == 1] join T on S.id == T.tid + 1 "
        "select S.price, T.total insert into out"
    )
    assert run_split(events, cql, 11) == run_full(events, cql)


def test_save_load_file(tmp_path):
    events = make_events(24)
    cql = "from S#window.length(5) select sum(price) as t insert into out"
    env1 = CEPEnvironment(batch_size=5)
    es1 = SiddhiCEP.define("S", events[:12], FIELDS, env=env1).cql(cql)
    job1 = es1.execute()
    path = str(tmp_path / "ckpt.bin")
    job1.save_checkpoint(path)

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events, FIELDS, env=env2).cql(cql)
    job2 = es2.job
    job2.restore(path)
    job2.run()
    assert job1.results("out") + job2.results("out") == run_full(
        events, cql
    )


def test_restore_rejects_changed_plan():
    events = make_events(10)
    env1 = CEPEnvironment(batch_size=5)
    job1 = (
        SiddhiCEP.define("S", events, FIELDS, env=env1)
        .cql("from S#window.length(5) select sum(price) as t insert into out")
        .execute()
    )
    snap = job1.snapshot()

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events, FIELDS, env=env2).cql(
        "from every s1 = S[id == 2] -> s2 = S[id == 3] "
        "select s1.price as p insert into out"
    )
    with pytest.raises(ValueError):
        es2.job.restore(snap)


def test_restore_rejects_changed_window_size():
    # same pytree structure, different ring capacity -> must be rejected
    # (shape validation, not just key paths)
    events = make_events(12)
    env1 = CEPEnvironment(batch_size=5)
    job1 = (
        SiddhiCEP.define("S", events, FIELDS, env=env1)
        .cql("from S#window.length(5) select sum(price) as t insert into out")
        .execute()
    )
    snap = job1.snapshot()

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events, FIELDS, env=env2).cql(
        "from S#window.length(9) select sum(price) as t insert into out"
    )
    with pytest.raises(ValueError, match="shape|dtype|CQL"):
        es2.job.restore(snap)


def test_restore_rejects_time_mode_mismatch():
    events = make_events(12)
    env1 = CEPEnvironment(batch_size=5)
    cql = "from S select id, price insert into out"
    job1 = SiddhiCEP.define("S", events, FIELDS, env=env1).cql(cql).execute()
    snap = job1.snapshot()

    env2 = CEPEnvironment(batch_size=5, time_mode="processing")
    es2 = SiddhiCEP.define("S", events, FIELDS, env=env2).cql(cql)
    with pytest.raises(ValueError, match="time mode"):
        es2.job.restore(snap)


def test_restore_accepts_pathlike(tmp_path):
    events = make_events(12)
    cql = "from S#window.length(5) select sum(price) as t insert into out"
    env1 = CEPEnvironment(batch_size=5)
    job1 = SiddhiCEP.define("S", events, FIELDS, env=env1).cql(cql).execute()
    path = tmp_path / "ckpt.bin"  # pathlib.Path, not str
    job1.save_checkpoint(str(path))

    env2 = CEPEnvironment(batch_size=5)
    es2 = SiddhiCEP.define("S", events, FIELDS, env=env2).cql(cql)
    es2.job.restore(path)


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_sharded_job_checkpoint_roundtrip():
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.parallel import ShardedJob, make_cep_mesh

    events = make_events(40)
    cql = (
        "from S select id, sum(price) as total, count() as c "
        "group by id insert into out"
    )

    def build(evs):
        env = CEPEnvironment(batch_size=8)
        env.register_stream("S", evs, FIELDS)
        plan = compile_plan(
            cql, {"S": env.schemas["S"]}, extensions=env.extensions
        )
        return ShardedJob(
            [plan], [env.sources["S"]], mesh=make_cep_mesh(8), batch_size=8
        )

    full = build(events)
    full.run()

    j1 = build(events[:20])
    j1.run()
    snap = j1.snapshot()
    j2 = build(events)
    j2.restore(snap)
    # skip the consumed prefix (source position was restored)
    j2.run()
    assert sorted(j1.results_with_ts("out") + j2.results_with_ts("out")) == sorted(
        full.results_with_ts("out")
    )


def test_sharded_job_double_recovery_roundtrip(tmp_path):
    """Checkpoint -> kill -> restore -> SECOND kill -> SECOND restore:
    two full generations of file-based recovery on a ShardedJob (the
    second restore starts from a checkpoint written by an
    already-restored job, so restored state must itself checkpoint
    losslessly), with row-exact oracle agreement across all three
    lifetimes. The save path runs with keep=2 rotation, so the round
    trip also pins that rotated generations stay readable.

    Mesh 4, deliberately: this test stays in the tier-1 lane, and on
    the 2-core CPU lane a mesh-8 shard_map compile costs minutes (the
    mesh-8 suites carry @pytest.mark.slow)."""
    import glob
    import os

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.parallel import ShardedJob, make_cep_mesh

    events = make_events(48)
    cql = (
        "from S select id, sum(price) as total, count() as c "
        "group by id insert into out"
    )

    def build(evs):
        env = CEPEnvironment(batch_size=8)
        env.register_stream("S", evs, FIELDS)
        plan = compile_plan(
            cql, {"S": env.schemas["S"]}, extensions=env.extensions
        )
        return ShardedJob(
            [plan], [env.sources["S"]], mesh=make_cep_mesh(4), batch_size=8
        )

    full = build(events)
    full.run()
    oracle = sorted(full.results_with_ts("out"))

    path = str(tmp_path / "ckpt")

    # lifetime 1: consume a third, checkpoint, "die"
    j1 = build(events[:16])
    j1.run()
    j1.save_checkpoint(path, keep=2)

    # lifetime 2: restore, consume to two-thirds, checkpoint, "die".
    # This save rotates lifetime 1's checkpoint to ckpt.1.
    j2 = build(events[:32])
    j2.restore(path)
    j2.run()
    j2.save_checkpoint(path, keep=2)
    assert os.path.exists(f"{path}.1")  # the rotated generation

    # lifetime 3: restore the SECOND-generation checkpoint, finish
    j3 = build(events)
    j3.restore(path)
    j3.run()

    got = sorted(
        j1.results_with_ts("out")
        + j2.results_with_ts("out")
        + j3.results_with_ts("out")
    )
    assert got == oracle  # no loss, no duplicates, across two recoveries
    assert glob.glob(f"{path}.tmp.*") == []  # no temp debris left

    # the ROTATED generation is itself restorable (the fallback the
    # supervisor walks when the newest file is unreadable): restoring
    # ckpt.1 replays lifetime 2 exactly
    j2b = build(events[:32])
    j2b.restore(f"{path}.1")
    j2b.run()
    assert sorted(j2b.results_with_ts("out")) == sorted(
        j2.results_with_ts("out")
    )
