"""connectors/kafka wire-format layer: varints, CRC-32C known-answer
vectors, v2 record batches (codecs, corruption, unknown magic), and
API-version negotiation against both fake-broker dialects.

Everything here is tier-1 ("not slow"): bounded batch sizes, no
sleeps — the fake broker answers in-process.
"""

import gzip
import struct

import pytest

from flink_siddhi_tpu.connectors.kafka.codecs import (
    CODEC_GZIP,
    CODEC_LZ4,
    CODEC_NONE,
    CODEC_SNAPPY,
    CODEC_ZSTD,
    UnsupportedCodecError,
    codec_id,
    compress,
    decompress,
)
from flink_siddhi_tpu.connectors.kafka.crc32c import crc32c
from flink_siddhi_tpu.connectors.kafka.records import (
    CorruptBatchError,
    decode_message_set,
    decode_record_batch,
    decode_record_set,
    encode_message_set,
    encode_record_batch,
)
from flink_siddhi_tpu.connectors.kafka.errors import (
    BrokerErrorResponse,
    ProducerFencedError,
    is_retryable,
)
from flink_siddhi_tpu.connectors.kafka.protocol import (
    API_END_TXN,
    API_FETCH,
    API_INIT_PRODUCER_ID,
    API_PRODUCE,
    ProtocolError,
    Reader,
    Writer,
    negotiate,
)
from flink_siddhi_tpu.connectors.kafka.txn import (
    decode_add_partitions_response,
    decode_end_txn_response,
    decode_init_producer_id_response,
)
from flink_siddhi_tpu.connectors.kafka.varint import (
    VarintError,
    decode_varint,
    decode_varlong,
    encode_varint,
    encode_varlong,
)
from flink_siddhi_tpu.runtime.kafka import KafkaClient, KafkaError
from tests.fake_kafka import FakeBroker, read_topic


# -- varints ---------------------------------------------------------------

def test_varint_zigzag_known_answers():
    # protobuf/Kafka zigzag: 0,-1,1,-2,2 -> 0,1,2,3,4
    assert encode_varint(0) == b"\x00"
    assert encode_varint(-1) == b"\x01"
    assert encode_varint(1) == b"\x02"
    assert encode_varint(-2) == b"\x03"
    assert encode_varint(2) == b"\x04"
    assert encode_varint(150) == b"\xac\x02"  # zigzag 300 = 0b10_0101100
    assert encode_varint(2**31 - 1) == b"\xfe\xff\xff\xff\x0f"
    assert encode_varint(-(2**31)) == b"\xff\xff\xff\xff\x0f"


@pytest.mark.parametrize(
    "n", [0, 1, -1, 63, -64, 300, -301, 2**31 - 1, -(2**31)]
)
def test_varint_roundtrip(n):
    v, pos = decode_varint(encode_varint(n))
    assert (v, pos) == (n, len(encode_varint(n)))


@pytest.mark.parametrize(
    "n", [0, -1, 2**31, -(2**31) - 1, 2**63 - 1, -(2**63), 10**15]
)
def test_varlong_roundtrip(n):
    v, pos = decode_varlong(encode_varlong(n))
    assert (v, pos) == (n, len(encode_varlong(n)))


def test_varint_errors():
    with pytest.raises(VarintError):
        encode_varint(2**31)  # int32 overflow
    with pytest.raises(VarintError):
        decode_varint(b"\x80\x80")  # truncated continuation
    with pytest.raises(VarintError):
        decode_varint(b"\x80\x80\x80\x80\x80\x80")  # > 5 bytes


# -- CRC-32C (RFC 3720 appendix B.4 known answers) -------------------------

_ISCSI_READ_PDU = bytes(
    [0x01, 0xC0, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
     0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
     0x14, 0x00, 0x00, 0x00, 0x00, 0x00, 0x04, 0x00,
     0x00, 0x00, 0x00, 0x14, 0x00, 0x00, 0x00, 0x18,
     0x28, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
     0x02, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00]
)


@pytest.mark.parametrize(
    "data,expect",
    [
        (bytes(32), 0x8A9136AA),  # 32 zeros
        (b"\xff" * 32, 0x62A8AB43),  # 32 ones
        (bytes(range(32)), 0x46DD794E),  # ascending
        (bytes(range(31, -1, -1)), 0x113FDB5C),  # descending
        (_ISCSI_READ_PDU, 0xD9963A56),  # SCSI Read(10) command PDU
        (b"123456789", 0xE3069283),  # classic CRC check string
    ],
)
def test_crc32c_known_answers(data, expect):
    assert crc32c(data) == expect


def test_crc32c_incremental():
    data = bytes(range(256)) * 3
    split = crc32c(data[100:], crc32c(data[:100]))
    assert split == crc32c(data)


# -- codecs ----------------------------------------------------------------

def test_codec_gzip_roundtrip_and_determinism():
    payload = b"x" * 1000 + bytes(range(256))
    blob = compress(CODEC_GZIP, payload)
    assert decompress(CODEC_GZIP, blob) == payload
    assert gzip.decompress(blob) == payload  # honest gzip framing
    assert blob == compress(CODEC_GZIP, payload)  # mtime pinned


@pytest.mark.parametrize(
    "codec,name",
    [(CODEC_SNAPPY, "snappy"), (CODEC_LZ4, "lz4"), (CODEC_ZSTD, "zstd")],
)
def test_codec_rejections_name_the_codec(codec, name):
    with pytest.raises(UnsupportedCodecError, match=name):
        compress(codec, b"data")
    with pytest.raises(UnsupportedCodecError, match=name):
        decompress(codec, b"data")
    assert codec_id(name) == codec


# -- v2 record batches -----------------------------------------------------

def _entries(n, base_ts=1000):
    return [
        (base_ts + i, None if i % 2 else b"k%d" % i, b"value-%d" % i)
        for i in range(n)
    ]


@pytest.mark.parametrize("codec", [CODEC_NONE, CODEC_GZIP])
def test_record_batch_roundtrip(codec):
    entries = _entries(17)
    batch = encode_record_batch(entries, base_offset=42, codec=codec)
    records, end = decode_record_batch(batch)
    assert end == len(batch)
    assert [r[0] for r in records] == list(range(42, 42 + 17))
    assert [r[1] for r in records] == [ts for ts, _, _ in entries]
    assert [r[2] for r in records] == [k for _, k, _ in entries]
    assert [r[3] for r in records] == [v for _, _, v in entries]


def test_record_batch_headers_roundtrip():
    entries = [(5, b"k", b"v", [(b"hk", b"hv"), (b"null", None)])]
    records, _ = decode_record_batch(encode_record_batch(entries))
    assert records == [(0, 5, b"k", b"v")]  # headers parsed, not kept


def test_record_batch_crc_corruption_is_loud():
    batch = bytearray(encode_record_batch(_entries(5), base_offset=9))
    batch[len(batch) // 2] ^= 0x40  # flip one bit mid-records
    with pytest.raises(CorruptBatchError, match="CRC-32C"):
        decode_record_batch(bytes(batch))
    # and the batch's identity (base offset) is in the message
    with pytest.raises(CorruptBatchError, match="offset 9"):
        decode_record_batch(bytes(batch))


def _reflag_codec(batch: bytes, codec: int) -> bytes:
    """Flip a valid batch's attributes to claim ``codec``, recomputing
    the CRC so the codec check (not the CRC check) fires."""
    b = bytearray(batch)
    attrs = struct.unpack_from(">h", b, 21)[0]
    struct.pack_into(">h", b, 21, (attrs & ~0x07) | codec)
    struct.pack_into(">I", b, 17, crc32c(bytes(b[21:])))
    return bytes(b)


@pytest.mark.parametrize(
    "codec,name",
    [(CODEC_SNAPPY, "snappy"), (CODEC_LZ4, "lz4"), (CODEC_ZSTD, "zstd")],
)
def test_foreign_codec_batch_rejected_by_name(codec, name):
    batch = _reflag_codec(encode_record_batch(_entries(3)), codec)
    with pytest.raises(UnsupportedCodecError, match=name):
        decode_record_set(batch)


def test_control_batch_advances_offsets_without_data():
    """A control batch (transaction marker) must not wedge consumers:
    its records come back with null payloads but REAL offsets, so the
    fetch position can advance past the batch."""
    batch = bytearray(encode_record_batch(_entries(3), base_offset=10))
    attrs = struct.unpack_from(">h", batch, 21)[0]
    struct.pack_into(">h", batch, 21, attrs | 0x20)  # isControlBatch
    struct.pack_into(">I", batch, 17, crc32c(bytes(batch[21:])))
    records = decode_record_set(bytes(batch))
    assert [(r[0], r[2], r[3]) for r in records] == [
        (10, None, None), (11, None, None), (12, None, None),
    ]


def test_unknown_magic_rejected_by_value():
    batch = bytearray(encode_record_batch(_entries(3)))
    batch[16] = 3  # future magic
    with pytest.raises(CorruptBatchError, match="magic 3"):
        decode_record_set(bytes(batch))


def test_record_set_mixed_formats_and_partial_tail():
    legacy = encode_message_set([b"old-0", b"old-1"])
    # stamp real offsets into the two legacy entries
    l0_len = 12 + struct.unpack_from(">i", legacy, 8)[0]
    legacy = (
        struct.pack(">q", 0) + legacy[8:l0_len]
        + struct.pack(">q", 1) + legacy[l0_len + 8:]
    )
    v2 = encode_record_batch(
        [(7, None, b"new-0"), (8, None, b"new-1")],
        base_offset=2, codec=CODEC_GZIP,
    )
    blob = legacy + v2
    records = decode_record_set(blob + v2[: len(v2) - 5])  # partial tail
    assert [(r[0], r[3]) for r in records] == [
        (0, b"old-0"), (1, b"old-1"), (2, b"new-0"), (3, b"new-1"),
    ]


def test_legacy_compressed_wrapper_rejected_by_name():
    mset = bytearray(encode_message_set([b"inner"]))
    mset[17] |= CODEC_GZIP  # wrapper attributes: gzip
    # re-frame the CRC so the codec rejection (the real guard) fires
    import zlib

    struct.pack_into(
        ">I", mset, 12, zlib.crc32(bytes(mset[16:])) & 0xFFFFFFFF
    )
    with pytest.raises(CorruptBatchError, match="gzip"):
        decode_message_set(bytes(mset))


def test_legacy_crc_corruption_is_loud():
    mset = bytearray(encode_message_set([b"payload"]))
    mset[-1] ^= 0x01
    with pytest.raises(CorruptBatchError, match="CRC-32"):
        decode_message_set(bytes(mset))


# -- version negotiation ---------------------------------------------------

def test_negotiate_intersects_and_falls_back():
    picks = negotiate({API_PRODUCE: (0, 5), API_FETCH: (0, 6)})
    assert picks[API_PRODUCE] == 3  # newest implemented, not newest offered
    assert picks[API_FETCH] == 4
    assert negotiate(None) == {api: 0 for api in negotiate(None)}
    # broker supports only a window above ours: loud, not silent v0
    with pytest.raises(ProtocolError, match="no overlap"):
        negotiate({API_PRODUCE: (5, 7)})


def test_client_negotiates_modern_dialect():
    broker = FakeBroker()
    try:
        client = KafkaClient(broker.host, broker.port)
        picks = client.api_versions()
        assert picks[API_PRODUCE] == 3
        assert picks[API_FETCH] == 4
        client.close()
    finally:
        broker.close()


def test_transient_connect_failure_does_not_pin_v0():
    """Only an established-then-slammed connection means 'pre-0.10
    broker'. A connection REFUSED during negotiation must propagate
    and leave the dialect undecided, not silently pin v0 forever."""
    import socket as _socket

    probe = _socket.create_server(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()  # nothing listens here now
    client = KafkaClient("127.0.0.1", dead_port, timeout_s=2.0)
    with pytest.raises(KafkaError, match="io error"):
        client.api_versions()
    assert client.negotiated is None  # undecided, will renegotiate
    client.close()


def test_client_falls_back_to_v0_for_legacy_broker():
    broker = FakeBroker(legacy=True)
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        assert client.api_versions()[API_FETCH] == 0
        # and the v0 dialect actually works end to end
        client.produce("t", 0, [b"a", b"b"])
        hw, records, _ = client.fetch("t", {0: 0})[0]
        assert hw == 2
        assert [r[3] for r in records] == [b"a", b"b"]
        with pytest.raises(KafkaError, match="Produce >= 3"):
            client.produce("t", 0, [b"c"], compression="gzip")
        client.close()
    finally:
        broker.close()


# -- client <-> fake broker over v2+gzip -----------------------------------

def test_produce_fetch_v2_gzip_roundtrip():
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        values = [b"ev-%03d" % i for i in range(50)]
        base = client.produce("t", 0, values, compression="gzip", ts_ms=77)
        assert base == 0
        # broker stored decoded records (inflated server-side)
        assert [v for _, v in broker.logs[("t", 0)]] == values
        hw, records, _ = client.fetch("t", {0: 0})[0]
        assert hw == 50
        assert [r[3] for r in records] == values
        assert all(r[1] == 77 for r in records)
        client.close()
    finally:
        broker.close()


def test_fetch_mid_batch_returns_whole_batch():
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        broker.append("t", 0, [b"r%d" % i for i in range(20)])
        client = KafkaClient(broker.host, broker.port)
        _, records, _ = client.fetch("t", {0: 13})[0]
        # v2 semantics: the batch containing offset 13 comes back whole
        assert [r[0] for r in records] == list(range(20))
        client.close()
    finally:
        broker.close()


def test_corrupt_batch_on_the_wire_rejected_not_skipped():
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        broker.append("t", 0, [b"a", b"b", b"c"])

        def flip(batch: bytes) -> bytes:
            b = bytearray(batch)
            b[-2] ^= 0x10
            return bytes(b)

        broker.mangle_batch = flip
        client = KafkaClient(broker.host, broker.port)
        with pytest.raises(CorruptBatchError, match="CRC-32C"):
            client.fetch("t", {0: 0})
        client.close()
    finally:
        broker.close()


def test_broker_rejects_corrupt_produced_batch():
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        good = encode_record_batch([(0, None, b"x")])
        bad = bytearray(good)
        bad[-1] ^= 0x01
        # bypass client-side encode: ship the corrupt bytes verbatim
        from flink_siddhi_tpu.connectors.kafka.protocol import Writer

        w = Writer()
        w.string(None).i16(1).i32(1000).i32(1).string("t").i32(1)
        w.i32(0).bytes_(bytes(bad))
        with pytest.raises(KafkaError, match="error 2"):
            client.api_versions()  # pin v3 produce
            r = client._call(API_PRODUCE, 3, w.done())
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    pid, err, off = r.i32(), r.i16(), r.i64()
                    r.i64()
                    if err:
                        raise KafkaError(f"Produce t/{pid}: error {err}")
        assert broker.logs[("t", 0)] == []  # nothing appended
        client.close()
    finally:
        broker.close()

# -- KIP-98 transactions: codecs, coordinator, fencing, visibility ----------

def test_txn_response_codecs_and_error_mapping():
    """Pure wire codecs: happy-path decode plus the error taxonomy —
    47 surfaces as ProducerFencedError (fatal), 51 stays retryable
    (CONCURRENT_TRANSACTIONS), 48 is the resume-commit signal."""
    r = Reader(Writer().i32(0).i16(0).i64(900).i16(7).done())
    assert decode_init_producer_id_response(r) == (900, 7)
    r = Reader(Writer().i32(0).i16(47).i64(-1).i16(-1).done())
    with pytest.raises(ProducerFencedError) as ei:
        decode_init_producer_id_response(r)
    assert ei.value.code == 47 and not is_retryable(ei.value)
    # AddPartitions: the first per-partition error surfaces, located
    w = Writer().i32(0).i32(1).string("t").i32(2)
    w.i32(0).i16(0).i32(1).i16(51)
    with pytest.raises(BrokerErrorResponse, match=r"t\[1\]") as ei:
        decode_add_partitions_response(Reader(w.done()))
    assert ei.value.code == 51 and is_retryable(ei.value)
    with pytest.raises(BrokerErrorResponse) as ei:
        decode_end_txn_response(Reader(Writer().i32(0).i16(48).done()))
    assert ei.value.code == 48 and not is_retryable(ei.value)


def test_init_producer_id_fencing_matrix():
    """Re-running InitProducerId on one transactional id keeps the
    producer id but bumps the epoch; every transactional api then
    refuses the older epoch with 47 (ProducerFencedError, fatal)."""
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        pid, e0 = client.init_producer_id("tx-a")
        pid2, e1 = client.init_producer_id("tx-a")
        assert pid2 == pid and e1 == e0 + 1
        other, oe = client.init_producer_id("tx-b")
        assert other != pid and oe == 0  # distinct id, fresh mapping
        with pytest.raises(ProducerFencedError):
            client.add_partitions_to_txn("tx-a", pid, e0, [("t", 0)])
        client.add_partitions_to_txn("tx-a", pid, e1, [("t", 0)])
        with pytest.raises(ProducerFencedError):
            client.produce(
                "t", 0, [b"zombie"], transactional_id="tx-a",
                producer_id=pid, producer_epoch=e0,
                base_sequence=0, transactional=True,
            )
        with pytest.raises(ProducerFencedError):
            client.end_txn("tx-a", pid, e0, commit=True)
        # the zombie's data never landed
        assert broker.logs[("t", 0)] == []
        # unknown producer id: INVALID_PRODUCER_ID_MAPPING, fatal
        with pytest.raises(BrokerErrorResponse) as ei:
            client.end_txn("tx-a", 424242, e1, commit=True)
        assert ei.value.code == 49 and not is_retryable(ei.value)
        client.close()
    finally:
        broker.close()


def test_transactional_visibility_and_control_batch_placement():
    """Open transaction: invisible read_committed, visible
    read_uncommitted. EndTxn(commit) writes the control batch at the
    offset AFTER the data (hw includes it; consumers get a null-value
    record there so positions advance), and a second EndTxn answers
    INVALID_TXN_STATE — the resume-commit 'already done' signal."""
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        pid, ep = client.init_producer_id("tx")
        client.add_partitions_to_txn("tx", pid, ep, [("t", 0)])
        client.produce(
            "t", 0, [b"a", b"b"], transactional_id="tx",
            producer_id=pid, producer_epoch=ep,
            base_sequence=0, transactional=True,
        )
        assert read_topic(broker.bootstrap, "t", committed=True) == []
        assert read_topic(broker.bootstrap, "t", committed=False) == [
            b"a", b"b",
        ]
        client.end_txn("tx", pid, ep, commit=True)
        assert read_topic(broker.bootstrap, "t", committed=True) == [
            b"a", b"b",
        ]
        hw, records, _ = client.fetch("t", {0: 0})[0]
        assert hw == 3  # two data offsets + the commit marker
        assert [(o, v) for o, _ts, _k, v in records] == [
            (0, b"a"), (1, b"b"), (2, None),
        ]
        with pytest.raises(BrokerErrorResponse) as ei:
            client.end_txn("tx", pid, ep, commit=True)
        assert ei.value.code == 48
        client.close()
    finally:
        broker.close()


def test_aborted_transaction_stays_invisible_forever():
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        pid, ep = client.init_producer_id("tx")
        client.add_partitions_to_txn("tx", pid, ep, [("t", 0)])
        client.produce(
            "t", 0, [b"discarded"], transactional_id="tx",
            producer_id=pid, producer_epoch=ep,
            base_sequence=0, transactional=True,
        )
        client.end_txn("tx", pid, ep, commit=False)
        assert read_topic(broker.bootstrap, "t", committed=True) == []
        # a later committed transaction interleaves cleanly: only ITS
        # rows surface read_committed, both surface read_uncommitted
        pid, ep = client.init_producer_id("tx")
        client.add_partitions_to_txn("tx", pid, ep, [("t", 0)])
        client.produce(
            "t", 0, [b"kept"], transactional_id="tx",
            producer_id=pid, producer_epoch=ep,
            base_sequence=0, transactional=True,
        )
        client.end_txn("tx", pid, ep, commit=True)
        assert read_topic(broker.bootstrap, "t", committed=True) == [
            b"kept",
        ]
        assert read_topic(broker.bootstrap, "t", committed=False) == [
            b"discarded", b"kept",
        ]
        client.close()
    finally:
        broker.close()


def test_fetch_wire_carries_aborted_transactions_index():
    """Raw v4 read_committed Fetch: the last_stable_offset and the
    (producer_id, first_offset) aborted-transactions index are on the
    wire — the KIP-98 contract the client-side filter consumes."""
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        pid, ep = client.init_producer_id("tx")
        client.add_partitions_to_txn("tx", pid, ep, [("t", 0)])
        client.produce(
            "t", 0, [b"dead"], transactional_id="tx",
            producer_id=pid, producer_epoch=ep,
            base_sequence=0, transactional=True,
        )
        client.end_txn("tx", pid, ep, commit=False)
        client.api_versions()  # pin the modern dialect
        w = Writer()
        w.i32(-1).i32(0).i32(0)  # replica, max_wait, min_bytes
        w.i32(1 << 20).i8(1)  # max_bytes, isolation=read_committed
        w.i32(1).string("t").i32(1)
        w.i32(0).i64(0).i32(1 << 20)
        r = client._call(API_FETCH, 4, w.done())
        r.i32()  # throttle
        assert r.i32() == 1 and r.string() == "t" and r.i32() == 1
        part, err, hw = r.i32(), r.i16(), r.i64()
        lso = r.i64()
        aborted = [(r.i64(), r.i64()) for _ in range(r.i32())]
        assert (part, err) == (0, 0)
        assert hw == 2 and lso == 2  # data + marker, all decided
        assert aborted == [(pid, 0)]
        client.close()
    finally:
        broker.close()


def test_idempotent_produce_dedupes_and_rejects_gaps():
    """Produce-side idempotence without a transaction: a re-send of
    the last appended batch acks with its ORIGINAL base offset and
    appends nothing (DUPLICATE_SEQUENCE_NUMBER, success client-side);
    a sequence gap is OUT_OF_ORDER (45, fatal); a fresh producer
    session must restart sequences at 0."""
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        client = KafkaClient(broker.host, broker.port)
        pid, ep = client.init_producer_id(None)  # idempotence-only
        kw = dict(producer_id=pid, producer_epoch=ep)
        assert client.produce("t", 0, [b"a", b"b"],
                              base_sequence=0, **kw) == 0
        # the wire-retry shape: identical re-send, same base back
        assert client.produce("t", 0, [b"a", b"b"],
                              base_sequence=0, **kw) == 0
        assert [v for _, v in broker.logs[("t", 0)]] == [b"a", b"b"]
        with pytest.raises(BrokerErrorResponse) as ei:
            client.produce("t", 0, [b"gap"], base_sequence=5, **kw)
        assert ei.value.code == 45 and not is_retryable(ei.value)
        assert client.produce("t", 0, [b"c"],
                              base_sequence=2, **kw) == 2
        # new session on the same partition: epoch scopes sequences
        pid2, ep2 = client.init_producer_id(None)
        with pytest.raises(BrokerErrorResponse) as ei:
            client.produce("t", 0, [b"x"], base_sequence=3,
                           producer_id=pid2, producer_epoch=ep2)
        assert ei.value.code == 45
        assert client.produce("t", 0, [b"x"], base_sequence=0,
                              producer_id=pid2,
                              producer_epoch=ep2) == 3
        client.close()
    finally:
        broker.close()


def test_fault_hook_fence_action_turns_holder_into_zombie():
    """The seeded-fault 'fence' action (opt-in, never in the default
    FaultSchedule draw): the broker bumps the requester's epoch
    server-side, so the request itself answers 47 — the shape of a
    competing restart racing the running producer. Re-running
    InitProducerId recovers with a fresh epoch."""
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        armed = {"on": False}

        def hook(api, seq):
            if armed["on"] and api == API_PRODUCE:
                armed["on"] = False
                return "fence"
            return None

        broker.fault_hook = hook
        client = KafkaClient(broker.host, broker.port)
        pid, ep = client.init_producer_id("tx")
        client.add_partitions_to_txn("tx", pid, ep, [("t", 0)])
        armed["on"] = True
        with pytest.raises(ProducerFencedError):
            client.produce(
                "t", 0, [b"z"], transactional_id="tx",
                producer_id=pid, producer_epoch=ep,
                base_sequence=0, transactional=True,
            )
        assert broker.logs[("t", 0)] == []  # fenced data never lands
        pid2, ep2 = client.init_producer_id("tx")
        assert pid2 == pid and ep2 > ep
        client.add_partitions_to_txn("tx", pid2, ep2, [("t", 0)])
        client.produce(
            "t", 0, [b"ok"], transactional_id="tx",
            producer_id=pid2, producer_epoch=ep2,
            base_sequence=0, transactional=True,
        )
        client.end_txn("tx", pid2, ep2, commit=True)
        assert read_topic(broker.bootstrap, "t", committed=True) == [
            b"ok",
        ]
        client.close()
    finally:
        broker.close()


def test_fault_hook_abort_txn_action_is_the_timeout_shape():
    """The 'abort_txn' action aborts the requester's ongoing
    transaction server-side before serving — the transaction-timeout
    shape real brokers add. The commit then answers 48 (nothing open)
    and the rows stay invisible read_committed: exactly the ambiguity
    docs/fault_tolerance.md documents for resumed commits."""
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        armed = {"on": False}

        def hook(api, seq):
            if armed["on"] and api == API_END_TXN:
                armed["on"] = False
                return "abort_txn"
            return None

        broker.fault_hook = hook
        client = KafkaClient(broker.host, broker.port)
        pid, ep = client.init_producer_id("tx")
        client.add_partitions_to_txn("tx", pid, ep, [("t", 0)])
        client.produce(
            "t", 0, [b"timed-out"], transactional_id="tx",
            producer_id=pid, producer_epoch=ep,
            base_sequence=0, transactional=True,
        )
        armed["on"] = True
        with pytest.raises(BrokerErrorResponse) as ei:
            client.end_txn("tx", pid, ep, commit=True)
        assert ei.value.code == 48
        assert read_topic(broker.bootstrap, "t", committed=True) == []
        assert read_topic(broker.bootstrap, "t", committed=False) == [
            b"timed-out",
        ]
        client.close()
    finally:
        broker.close()


def test_transactional_apis_negotiation_and_legacy_refusal():
    """The modern fake broker advertises apis 22/24/26 at v0; a
    legacy broker does not, and because negotiate() blanket-falls-back
    to v0 for OMITTED apis, the transactional path must refuse loudly
    via its own preflight instead of trusting the fallback."""
    broker = FakeBroker()
    try:
        client = KafkaClient(broker.host, broker.port)
        picks = client.api_versions()
        assert picks[API_INIT_PRODUCER_ID] == 0
        client.close()
    finally:
        broker.close()
    legacy = FakeBroker(legacy=True)
    try:
        client = KafkaClient(legacy.host, legacy.port)
        with pytest.raises(KafkaError, match="advertise"):
            client.init_producer_id("tx")
        client.close()
    finally:
        legacy.close()
