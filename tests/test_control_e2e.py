"""Dynamic control plane end-to-end (SiddhiCEPITCase.java:466-533 analog:
plans added/updated/removed/enabled/disabled at runtime via control events
interleaved with data by event time)."""

import dataclasses

from flink_siddhi_tpu import (
    CEPEnvironment,
    MetadataControlEvent,
    OperationControlEvent,
    SiddhiCEP,
)


@dataclasses.dataclass
class Event:
    id: int
    price: float
    timestamp: int


FIELDS = ["id", "price", "timestamp"]


def make_events(n, start_ts=1000):
    return [Event(i % 4, float(i), start_ts + 1000 * i) for i in range(n)]


def dyn(events, control, batch_size=4096):
    env = CEPEnvironment(batch_size=batch_size)
    return SiddhiCEP.define("S", events, FIELDS, env=env).cql(control)


def test_add_plan_mid_stream():
    # plan installed at ts 5500: only events with ts > 5500 are processed
    events = make_events(10)  # ts 1000..10000
    ev = MetadataControlEvent.builder()
    ev.add_execution_plan("from S select id, price insert into out")
    es = dyn(events, [(5500, ev.build())], batch_size=1)
    out = es.returns("out")
    assert out == [(e.id, e.price) for e in events if e.timestamp > 5500]


def test_multiple_plans_fan_out():
    # two plans over the same stream: every event fans out to both
    events = make_events(8)
    b = MetadataControlEvent.builder()
    b.add_execution_plan("from S[id == 1] select id insert into ones")
    b.add_execution_plan("from S[id == 2] select id insert into twos")
    es = dyn(events, [(0, b.build())])
    job = es.execute()
    assert len(job.results("ones")) == 2
    assert len(job.results("twos")) == 2


def test_disable_enable_query():
    events = make_events(10)  # ts 1000..10000
    b = MetadataControlEvent.builder()
    pid = b.add_execution_plan("from S select id insert into out")
    control = [
        (0, b.build()),
        (4500, OperationControlEvent.disable_query(pid)),
        (7500, OperationControlEvent.enable_query(pid)),
    ]
    es = dyn(events, control, batch_size=1)
    out = es.returns("out")
    # events in (4500, 7500] are dropped while the plan is paused
    expected = [
        (e.id,)
        for e in events
        if e.timestamp <= 4500 or e.timestamp > 7500
    ]
    assert out == expected


def test_remove_plan():
    events = make_events(10)
    b = MetadataControlEvent.builder()
    pid = b.add_execution_plan("from S select id insert into out")
    drop = MetadataControlEvent.builder()
    drop.remove_execution_plan(pid)
    es = dyn(events, [(0, b.build()), (5500, drop.build())], batch_size=1)
    out = es.returns("out")
    assert out == [(e.id,) for e in events if e.timestamp <= 5500]


def test_update_plan():
    events = make_events(10)
    b = MetadataControlEvent.builder()
    pid = b.add_execution_plan("from S[id == 1] select id insert into out")
    upd = (
        MetadataControlEvent.builder()
        .update_execution_plan(
            pid, "from S[id == 2] select id insert into out"
        )
        .build()
    )
    es = dyn(events, [(0, b.build()), (5500, upd)], batch_size=1)
    out = es.returns("out")
    expected = [
        (e.id,)
        for e in events
        if (e.timestamp <= 5500 and e.id == 1)
        or (e.timestamp > 5500 and e.id == 2)
    ]
    assert out == expected


def test_dynamic_pattern_plan():
    # the ITCase dynamic test installs pattern queries at runtime
    events = [Event(2, 1.0, 1000), Event(3, 2.0, 2000), Event(2, 3.0, 3000),
              Event(3, 4.0, 4000)]
    b = MetadataControlEvent.builder()
    b.add_execution_plan(
        "from every s1 = S[id == 2] -> s2 = S[id == 3] "
        "select s1.price as p1, s2.price as p2 insert into outputStream"
    )
    es = dyn(events, [(0, b.build())])
    out = es.return_as_map("outputStream")
    assert out == [{"p1": 1.0, "p2": 2.0}, {"p1": 3.0, "p2": 4.0}]


def test_control_json_round_trip():
    from flink_siddhi_tpu.control.events import (
        control_event_from_json,
        control_event_to_json,
    )

    b = MetadataControlEvent.builder()
    pid = b.add_execution_plan("from S select id insert into out")
    ev = b.build()
    ev2 = control_event_from_json(control_event_to_json(ev))
    assert ev2.added_plans == {pid: "from S select id insert into out"}

    op = OperationControlEvent.disable_query("abc")
    op2 = control_event_from_json(control_event_to_json(op))
    assert (op2.action, op2.plan_id) == ("disable", "abc")
