"""The dynamic query control plane (flink_siddhi_tpu/control/):
epoch-boundary admit/retire, incremental multi-query stacking, the
shape-keyed AOT executable cache, admission gating on the REST/control
path, control-in-replay epoch parity, and control-event checkpointing.

docs/control_plane.md states the contracts these tests pin."""

import json
import urllib.request

import numpy as np
import pytest

from flink_siddhi_tpu.analysis.admit import STRICT_BUDGETS
from flink_siddhi_tpu.app.service import (
    ControlQueueSource,
    QueryControlService,
)
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control import (
    AdmissionGate,
    ControlPlane,
    ControlRejected,
    MetadataControlEvent,
    OperationControlEvent,
    control_event_from_json,
    control_event_to_json,
)
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.replay import ResidentReplay
from flink_siddhi_tpu.runtime.sources import (
    BatchSource,
    CallbackSource,
    ControlListSource,
)
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)

# the hostile-zoo unbounded-residency query (analysis/zoo.py
# hostile_pattern_no_within): plancheck-clean, refused under the
# strict multi-tenant budgets by exactly ADM110
HOSTILE_CQL = (
    "from every s1 = S[id == 1] -> s2 = S[id == 2] "
    "select s1.price as p1, s2.price as p2 insert into out"
)


class Rec:
    def __init__(self, id, price, timestamp):
        self.id, self.price, self.timestamp = id, price, timestamp


def compiler(cql, pid):
    return compile_plan(cql, {"S": SCHEMA}, plan_id=pid)


def chain_cql(a, b, out="out"):
    return (
        f"from every s1 = S[id == {a}] -> s2 = S[id == {b}] "
        "within 60 sec "
        f"select s1.timestamp as t1, s2.timestamp as t2 "
        f"insert into {out}"
    )


def make_job(src, ctrl, **kw):
    return Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[ctrl], plan_compiler=compiler, **kw,
    )


def feed(src, lo, hi):
    for i in range(lo, hi):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)


# -- admit / stack-join / retire-reclaim / status ---------------------------


def test_admit_stack_join_retire_reclaim_slot():
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    plane = ControlPlane(job, ctrl, gate=AdmissionGate(compiler))

    plane.admit(chain_cql(1, 2), plan_id="q1", tenant="acme")
    feed(src, 0, 8)
    job.run_cycle()
    assert job.results("out") == [(1001, 1002), (1005, 1006)]

    # second, constants-only tenant variant: joins the padded stack as
    # a data update (stack_join), not a new runtime
    plane.admit(chain_cql(2, 3), plan_id="q2")
    job.run_cycle()
    assert len(job._plans) == 1
    st = plane.status()
    assert st["counters"]["admitted"] == 2
    assert st["counters"]["stack_join"] == 1
    assert st["plans"]["q1"]["folded"]["slot"] == 0
    assert st["plans"]["q2"]["folded"]["slot"] == 1

    # retire q1: its slot goes row-inert; a later admit RECLAIMS it
    plane.retire("q1")
    n_before = len(job.results("out"))
    feed(src, 8, 16)
    job.run_cycle()
    rows = job.results("out")
    # only q2 (2 -> 3) matches land: (1010,1011), (1014,1015)
    assert rows[n_before:] == [(1010, 1011), (1014, 1015)]
    plane.admit(chain_cql(3, 0), plan_id="q3")
    job.run_cycle()
    st = plane.status()
    assert st["plans"]["q3"]["folded"]["slot"] == 0  # reclaimed
    assert st["counters"]["retired"] == 1
    assert st["counters"]["stack_join"] == 2


def test_ownership_guard_catches_off_thread_mutation():
    """The dynamic half of fstrace FST201 (docs/static_analysis.md):
    conftest flips RUNLOOP_OWNERSHIP_GUARD for this file, the first
    run_cycle stamps this thread as the run-loop owner, and a DIRECT
    Job mutation from another thread must raise OwnershipViolation —
    while the same intent routed through the control queue (the
    documented contract) applies cleanly at the next boundary."""
    import threading

    from flink_siddhi_tpu.runtime import executor as executor_mod
    from flink_siddhi_tpu.runtime.executor import OwnershipViolation

    assert executor_mod.RUNLOOP_OWNERSHIP_GUARD  # conftest lane flip
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    plane = ControlPlane(job, ctrl, gate=AdmissionGate(compiler))
    plane.admit(chain_cql(1, 2), plan_id="q1")
    feed(src, 0, 8)
    job.run_cycle()  # stamps the run-loop owner = this thread
    assert job.results("out") == [(1001, 1002), (1005, 1006)]

    caught: list = []

    def rogue():
        try:
            job.set_plan_enabled("q1", False)  # bypasses the queue
        except OwnershipViolation as e:
            caught.append(e)

    t = threading.Thread(target=rogue)
    t.start()
    t.join()
    assert len(caught) == 1
    msg = str(caught[0])
    assert "owns Job state" in msg and "control event" in msg
    # the rogue write never landed: q1 still emits
    feed(src, 8, 12)
    job.run_cycle()
    assert job.results("out")[-1] == (1009, 1010)

    # the sanctioned route from the same foreign thread: push a
    # disable CONTROL EVENT (plane.set_enabled), applied by the run
    # loop at the next micro-batch boundary
    t2 = threading.Thread(
        target=plane.set_enabled, args=("q1", False)
    )
    t2.start()
    t2.join()
    feed(src, 12, 20)
    n_before = len(job.results("out"))
    job.run_cycle()
    assert len(job.results("out")) == n_before  # disabled, no new rows

    # and the owner itself keeps full mutation rights
    job.set_plan_enabled("q1", True)


def test_aot_cache_hit_on_constants_variant_readmit():
    """The acceptance criterion: after full retire drops the group
    host, re-admitting a constants-only variant re-forms it from the
    AOT executable cache — a measured cache HIT with ZERO new XLA
    lowerings, counted via the PERMANENT compile-telemetry surface
    (telemetry/compile_events.py; the lowering event fires at the
    jaxpr->MLIR stage, so a warm persistent cache cannot mask it).
    Previously this test registered a private jax.monitoring listener
    and tore down with clear_event_listeners() — the footgun the
    surface replaced. The same pin now also rides
    ``Job.metrics()["compiles"]``: the first admit records >= 1
    attributed lowering with finite duration, the cache-hit re-admit
    adds ZERO."""
    from flink_siddhi_tpu.telemetry import compile_events

    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    plane = ControlPlane(job, ctrl)

    plane.admit(chain_cql(1, 2), plan_id="q1")
    feed(src, 0, 8)
    job.run_cycle()
    job.drain_outputs()
    assert job.aot_cache.stats()["misses"] == 1
    # first admit of the shape class: the permanent surface recorded
    # its compiles — attributed to the 'dyn:' signature label, with a
    # finite lowering-duration distribution
    comp0 = job.metrics()["compiles"]
    assert comp0["total_lowerings"] >= 1
    assert comp0["total_duration_s"] > 0
    assert any(
        label.startswith("dyn:") for label in comp0["by_signature"]
    ), comp0["by_signature"]

    plane.retire("q1")
    job.run_cycle()
    assert not job._plans  # host dropped; executables stay cached

    with compile_events.watch() as w:
        plane.admit(chain_cql(2, 3), plan_id="q2")
        feed(src, 8, 16)
        job.run_cycle()
        job.drain_outputs()
    assert job.results("out")[-2:] == [(1010, 1011), (1014, 1015)]
    assert w.count == 0, (
        f"{w.count} executables lowered on a cache-hit re-admit — "
        "the AOT cache is not serving the shape class"
    )
    # the job's own accounting agrees: zero new attributed lowerings
    comp1 = job.metrics()["compiles"]
    assert comp1["total_lowerings"] == comp0["total_lowerings"]
    stats = job.aot_cache.stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    # and the cache traffic is journaled: one miss then one hit for
    # the same shape-class signature (telemetry/flightrec.py)
    hits = job.flightrec.events(kind="aotcache.hit")
    misses = job.flightrec.events(kind="aotcache.miss")
    assert len(hits) == 1 and len(misses) == 1
    assert hits[0]["signature"] == misses[0]["signature"]


def test_cache_eviction_is_bounded_and_counted():
    from flink_siddhi_tpu.control.aotcache import (
        AOTExecutableCache,
        CachedExecutables,
    )

    cache = AOTExecutableCache(max_entries=2)
    mk = lambda: CachedExecutables(*([None] * 5))  # noqa: E731
    cache.insert(("exact", "a"), mk())
    cache.insert(("exact", "b"), mk())
    assert cache.lookup(("exact", "a")) is not None  # a now MRU
    cache.insert(("exact", "c"), mk())  # evicts b (LRU)
    assert cache.lookup(("exact", "b")) is None
    assert cache.lookup(("exact", "a")) is not None
    assert cache.stats()["evictions"] == 1
    assert len(cache) == 2


# -- admission gating: REST boundary + executor apply time ------------------


def test_hostile_refused_by_rule_id_rest_and_apply_time():
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    job.admission_budgets = STRICT_BUDGETS
    gate = AdmissionGate(compiler, budgets=STRICT_BUDGETS)
    svc = QueryControlService(
        ctrl, job=job, admission=gate
    ).start()
    try:
        base = f"http://127.0.0.1:{svc.port}/api/v1"
        # REST boundary: 422 with the exact ADM rule id
        req = urllib.request.Request(
            f"{base}/queries",
            data=json.dumps({"cql": HOSTILE_CQL}).encode(),
            method="POST",
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("hostile add returned 2xx")
        except urllib.error.HTTPError as e:
            assert e.code == 422
            body = json.loads(e.read())
            assert body["rules"] == ["ADM110"]
        # the boundary refusal is recorded too (source="service"):
        # observable from /health and per-query status even after the
        # 422 response is gone
        boundary_id = body["id"]
        assert (
            job.control_rejections[boundary_id]["source"] == "service"
        )
        with urllib.request.urlopen(
            f"{base}/queries/{boundary_id}"
        ) as resp:
            status = json.loads(resp.read())
        assert status["state"] == "rejected"
        assert status["rules"] == ["ADM110"]
        # a well-behaved add passes the same gate and applies
        req = urllib.request.Request(
            f"{base}/queries",
            data=json.dumps(
                {"cql": chain_cql(1, 2), "tenant": "acme"}
            ).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 201
            created = json.loads(resp.read())
        assert created["admission"]["admitted"] is True
        assert created["admission"]["signature"]
        feed(src, 0, 4)
        job.run_cycle()
        assert created["id"] in job.plan_ids

        # defense in depth: an event injected PAST the service (raw
        # control queue) is refused at apply time, counted, and
        # observable via /health and per-query status
        b = MetadataControlEvent.builder()
        hostile_id = b.add_execution_plan(
            HOSTILE_CQL, plan_id="hostile-1"
        )
        ctrl.push(b.build())
        job.run_cycle()
        assert hostile_id not in job.plan_ids
        rej = job.control_rejections[hostile_id]
        assert rej["rules"] == ["ADM110"]
        with urllib.request.urlopen(f"{base}/health") as resp:
            health = json.loads(resp.read())
        assert (
            health["control"]["counters"]["admission_rejected"] >= 1
        )
        assert hostile_id in health["control"]["rejections"]
        with urllib.request.urlopen(
            f"{base}/queries/{hostile_id}"
        ) as resp:
            status = json.loads(resp.read())
        assert status["state"] == "rejected"
        assert status["rules"] == ["ADM110"]
    finally:
        svc.stop()


def test_unparsable_cql_refused_not_fatal():
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    b = MetadataControlEvent.builder()
    bad_id = b.add_execution_plan("this is not siddhi ql at all")
    ctrl.push(b.build())
    feed(src, 0, 4)
    job.run_cycle()  # must not raise
    assert bad_id not in job.plan_ids
    assert job.control_rejections[bad_id]["rules"] == ["CQL000"]


def test_gate_rejects_before_event_ever_pushed():
    ctrl = ControlQueueSource()
    plane = ControlPlane(
        None, ctrl, gate=AdmissionGate(compiler, budgets=STRICT_BUDGETS)
    )
    with pytest.raises(ControlRejected) as ei:
        plane.admit(HOSTILE_CQL)
    assert ei.value.rules == ["ADM110"]
    assert ctrl.poll(16)[0] == []  # nothing reached the stream


# -- service-level sustained load (tier-1 dryrun subset; see the slow
# sweep below for the full-scale version) -----------------------------------


def _sustained_streaming(n_queries, cycles_between, events_per_cycle):
    """Admit/disable/enable/retire through the REST service while the
    load keeps flowing; returns (job, fed, per-cycle seconds)."""
    import time as _t

    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    # batch_size must cover one cycle's feed, or unpulled events linger
    # in the source and the fed==processed reconciliation lies
    job = Job(
        [], [src], batch_size=4096, time_mode="processing",
        control_sources=[ctrl], plan_compiler=compiler,
        retain_results=False,
    )
    svc = QueryControlService(
        ctrl, job=job, admission=AdmissionGate(compiler)
    ).start()
    fed = 0
    cyc = []
    try:
        base = f"http://127.0.0.1:{svc.port}/api/v1"

        def post(path, body=None):
            req = urllib.request.Request(
                f"{base}/{path}",
                data=json.dumps(body).encode() if body else None,
                method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                return json.loads(resp.read())

        def run_cycles(n):
            nonlocal fed
            for _ in range(n):
                feed(src, fed, fed + events_per_cycle)
                fed += events_per_cycle
                t0 = _t.perf_counter()
                job.run_cycle()
                cyc.append(_t.perf_counter() - t0)

        ids = []
        for q in range(n_queries):
            ids.append(
                post("queries", {"cql": chain_cql(q % 4, (q + 1) % 4)})[
                    "id"
                ]
            )
            run_cycles(cycles_between)
        post(f"queries/{ids[0]}/disable")
        run_cycles(cycles_between)
        post(f"queries/{ids[0]}/enable")
        req = urllib.request.Request(
            f"{base}/queries/{ids[1]}", method="DELETE"
        )
        urllib.request.urlopen(req).read()
        run_cycles(cycles_between)
        assert set(job.plan_ids) == set(ids) - {ids[1]}
    finally:
        svc.stop()
    return job, fed, cyc


def test_service_sustained_load_zero_drops_bounded_latency():
    job, fed, cyc = _sustained_streaming(
        n_queries=6, cycles_between=3, events_per_cycle=256
    )
    # ZERO dropped events across every mutation boundary
    assert job.processed_events == fed
    assert job.shed_events == 0 and job.late_dropped == 0
    # bounded added latency: admit cycles pay compile/fold work, but
    # steady cycles between mutations must stay far under a second
    steady = sorted(cyc)[: int(len(cyc) * 0.5)]
    assert max(steady) < 1.0, steady[-5:]
    st = job.control_status()
    assert st["counters"]["admitted"] == 6
    assert st["counters"]["retired"] == 1
    assert st["counters"]["stack_join"] >= 5


@pytest.mark.slow
def test_service_sustained_load_full_sweep():
    """The O(100s)-of-queries sweep (slow lane): 24 tenants across 3
    group hosts, heavier per-cycle load, same zero-drop contract."""
    job, fed, cyc = _sustained_streaming(
        n_queries=24, cycles_between=4, events_per_cycle=2048
    )
    assert job.processed_events == fed
    assert job.shed_events == 0 and job.late_dropped == 0
    st = job.control_status()
    assert st["counters"]["admitted"] == 24
    assert st["aot_cache"]["hits"] >= 1  # hosts 2..N from the cache


# -- resident mode: control at replay-epoch boundaries ----------------------


def _mk_batches(n, start):
    ids = (np.arange(n) % 4).astype(np.int64)
    ts = (start + np.arange(n) * 1000).astype(np.int64)
    return EventBatch(
        "S", SCHEMA,
        {"id": ids, "price": np.arange(n, dtype=np.float64),
         "timestamp": ts},
        ts,
    )


def _control_timeline():
    b = MetadataControlEvent.builder()
    b.add_execution_plan(chain_cql(1, 2), plan_id="qa")
    b2 = MetadataControlEvent.builder()
    b2.add_execution_plan(chain_cql(2, 3), plan_id="qb")
    drop = MetadataControlEvent.builder()
    drop.remove_execution_plan("qa")
    return [
        (0, b.build()),
        (9_500, b2.build()),
        (15_500, OperationControlEvent.disable_query("qb")),
        (20_500, OperationControlEvent.enable_query("qb")),
        (25_500, drop.build()),
    ]


def _run_mode(mode):
    batches = [_mk_batches(8, s) for s in (1000, 9000, 17000, 25000)]
    job = Job(
        [], [BatchSource("S", SCHEMA, iter(batches))], batch_size=8,
        time_mode="event",
        control_sources=[ControlListSource(_control_timeline())],
        plan_compiler=compiler,
    )
    if mode == "resident":
        ResidentReplay(job).execute()
    else:
        job.run()
    return job


def test_resident_epoch_control_parity_with_streaming():
    """Admit / stack-join / disable / enable / retire applied at
    replay-epoch boundaries produce row-for-row the SAME output a
    streaming run applies at micro-batch boundaries — the control-in-
    replay contract (docs/control_plane.md)."""
    a = _run_mode("streaming")
    b = _run_mode("resident")
    rows_a = sorted(a.results_with_ts("out"))
    rows_b = sorted(b.results_with_ts("out"))
    assert rows_a and rows_a == rows_b
    assert a.processed_events == b.processed_events
    # the replay really went through the control plane's counters too
    st = b.control_status()
    assert st["counters"]["admitted"] == 2
    assert st["counters"]["retired"] == 1


def test_resident_live_control_queue_drains_and_completes():
    """A live (service-fed) ControlQueueSource works in resident mode:
    events already pushed apply at their epoch boundary; an empty live
    queue never holds the data watermark (its documented contract), so
    the replay drains and completes."""
    src = BatchSource("S", SCHEMA, iter([_mk_batches(8, 1000)]))
    ctrl = ControlQueueSource()
    b = MetadataControlEvent.builder()
    b.add_execution_plan(chain_cql(1, 2), plan_id="qy")
    ctrl.push(b.build(), timestamp_ms=0)
    job = Job(
        [], [src], batch_size=8, time_mode="event",
        control_sources=[ctrl], plan_compiler=compiler,
    )
    ResidentReplay(job).execute()
    assert job.plan_ids == ["qy"]
    assert job.results("out") == [(2000, 3000), (6000, 7000)]


# -- checkpoint/restore: a pending control event survives exactly once ------


def test_checkpoint_mid_admit_applies_exactly_once():
    """Kill->restore with the admit still PENDING behind the event-time
    watermark: the restored job applies it exactly once — not lost
    (the query runs) and not doubled (one slot, one runtime)."""
    def build(events_batches, control):
        return Job(
            [],
            [BatchSource("S", SCHEMA, iter(events_batches))],
            batch_size=8, time_mode="event",
            control_sources=[ControlListSource(control)],
            plan_compiler=compiler,
        )

    b = MetadataControlEvent.builder()
    b.add_execution_plan(chain_cql(1, 2), plan_id="qx")
    # the admit sits at ts 9500; the source stays OPEN (CallbackSource
    # not closed), so the watermark holds below it: at snapshot time
    # the admit is still PENDING — the mid-admit kill point
    src1 = CallbackSource("S", SCHEMA)
    job1 = Job(
        [], [src1], batch_size=8, time_mode="event",
        control_sources=[ControlListSource([(9_500, b.build())])],
        plan_compiler=compiler,
    )
    for i in range(8):
        src1.emit(Rec(i % 4, float(i), 1000 + i * 1000), 1000 + i * 1000)
    job1.run_cycle()
    assert job1.plan_ids == []  # not applied yet
    snap = job1.snapshot()
    assert snap["control_pending"], "admit was not captured pending"

    # fresh process analog: second half of the stream only (the first
    # half's rows ride the snapshot's reorder buffer), control source
    # already consumed — the event lives in the snapshot now
    job2 = build([_mk_batches(8, 9000)], [])
    job2.restore(snap)
    job2.run()
    assert job2.plan_ids == ["qx"]
    assert len(job2._plans) == 1
    # applied exactly once: matches exist and are unique
    rows = job2.results_with_ts("out")
    assert rows == sorted(set(rows)) and rows

    # and the post-apply checkpoint does NOT double-apply on restore:
    snap2 = job2.snapshot()
    job3 = build([_mk_batches(8, 17000)], [])
    job3.restore(snap2)
    job3.run()
    assert job3.plan_ids == ["qx"]
    assert len(job3._plans) == 1
    rows3 = job3.results_with_ts("out")
    assert rows3 == sorted(set(rows3))


# -- control-event wire format: new fields ----------------------------------


def test_tenant_field_json_round_trip():
    b = MetadataControlEvent.builder()
    pid = b.add_execution_plan(chain_cql(1, 2), plan_id="fixed-id")
    ev = b.build()
    ev.tenant = "acme"
    ev2 = control_event_from_json(control_event_to_json(ev))
    assert ev2.tenant == "acme"
    assert pid == "fixed-id" and ev2.added_plans == ev.added_plans

    op = OperationControlEvent.disable_query("abc")
    op.tenant = "zorg"
    op2 = control_event_from_json(control_event_to_json(op))
    assert (op2.action, op2.plan_id, op2.tenant) == (
        "disable", "abc", "zorg",
    )
    # absent tenant stays None (backward compatible with old wires)
    op3 = control_event_from_json(
        json.dumps(
            {"type": "operation", "action": "enable", "plan_id": "p"}
        )
    )
    assert op3.tenant is None
