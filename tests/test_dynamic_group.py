"""Dynamic chain groups: runtime query add/remove as a DATA update.

VERDICT round-1 #8 / SURVEY.md §7 hard part 4: adding a structurally-
identical pattern query through the control plane must NOT stall the
stream on an XLA recompile — the group pre-pads query slots and an add
writes filter literals / within values into device state.

Reference analog: AbstractSiddhiOperator.onEventReceived add path
(:416-424), which pays a full SiddhiQL compile per add.
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control.events import (
    MetadataControlEvent,
    OperationControlEvent,
)
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.app.service import ControlQueueSource
from flink_siddhi_tpu.runtime.sources import CallbackSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)


class Rec:
    def __init__(self, id, price, timestamp):
        self.id, self.price, self.timestamp = id, price, timestamp


def make_job(src):
    return Job(
        [], [src], batch_size=64, time_mode="processing",
        plan_compiler=lambda cql, pid: compile_plan(
            cql, {"S": SCHEMA}, plan_id=pid
        ),
    )


def chain_cql(pid, a, b):
    return (
        f"from every s1 = S[id == {a}] -> s2 = S[id == {b}] "
        f"select s1.timestamp as t1, s2.timestamp as t2 "
        f"insert into out_{pid}"
    )


def test_second_add_is_a_data_update_no_retrace():
    src = CallbackSource("S", SCHEMA)
    job = make_job(src)
    job.add_plan(
        compile_plan(chain_cql("q1", 1, 2), {"S": SCHEMA}, plan_id="q1"),
        dynamic=True,
    )
    for i in range(8):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    (rt,) = job._plans.values()
    traces_after_first = rt.traces["n"]
    assert traces_after_first >= 1
    assert job.results("out_q1") == [(1001, 1002), (1005, 1006)]

    # second, structurally-identical add: folds into a spare slot
    job.add_plan(
        compile_plan(chain_cql("q2", 2, 3), {"S": SCHEMA}, plan_id="q2"),
        dynamic=True,
    )
    assert len(job._plans) == 1  # no new runtime
    assert set(job.plan_ids) == {"q1", "q2"}
    for i in range(8, 16):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    # THE criterion: stepping with both queries live retraced nothing
    assert rt.traces["n"] == traces_after_first
    assert job.results("out_q2") == [(1010, 1011), (1014, 1015)]
    assert len(job.results("out_q1")) == 4

    # disable / remove are slot updates on the same runtime
    n1 = len(job.results("out_q1"))
    job.set_plan_enabled("q1", False)
    for i in range(16, 24):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    assert len(job.results("out_q1")) == n1
    assert rt.traces["n"] == traces_after_first
    job.remove_plan("q2")
    assert job.plan_ids == ["q1"]
    job.remove_plan("q1")
    assert job.plan_ids == [] and not job._plans


def test_dynamic_adds_via_control_events():
    src = CallbackSource("S", SCHEMA)
    control = ControlQueueSource()
    job = Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[control],
        plan_compiler=lambda cql, pid: compile_plan(
            cql, {"S": SCHEMA}, plan_id=pid
        ),
    )
    b = MetadataControlEvent.builder()
    pid_a = b.add_execution_plan(chain_cql("a", 1, 2))
    control.push(b.build())
    for i in range(8):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    (rt,) = job._plans.values()
    t0 = rt.traces["n"]
    b2 = MetadataControlEvent.builder()
    b2.add_execution_plan(chain_cql("b", 3, 1))
    control.push(b2.build())
    for i in range(8, 16):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    assert rt.traces["n"] == t0
    assert len(job.results("out_b")) > 0
    # pause via OperationControlEvent routes to the slot
    control.push(OperationControlEvent.disable_query(pid_a))
    na = len(job.results("out_a"))
    for i in range(16, 24):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    job.run_cycle()
    assert len(job.results("out_a")) == na


def test_mixed_types_and_within_fold():
    # different within values and float literals are still DATA
    src = CallbackSource("S", SCHEMA)
    job = make_job(src)
    cql1 = (
        "from every s1 = S[price == 5.0] -> s2 = S[price == 7.0] "
        "within 5 sec "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into oa"
    )
    cql2 = (
        "from every s1 = S[price == 1.0] -> s2 = S[price == 2.0] "
        "within 1 sec "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into ob"
    )
    job.add_plan(
        compile_plan(cql1, {"S": SCHEMA}, plan_id="a"), dynamic=True
    )
    src.emit(Rec(0, 5.0, 1000), 1000)
    src.emit(Rec(0, 7.0, 2000), 2000)
    job.run_cycle()
    (rt,) = job._plans.values()
    t0 = rt.traces["n"]
    job.add_plan(
        compile_plan(cql2, {"S": SCHEMA}, plan_id="b"), dynamic=True
    )
    src.emit(Rec(0, 1.0, 3000), 3000)
    src.emit(Rec(0, 2.0, 5000), 5000)  # outside b's 1s within
    src.emit(Rec(0, 1.0, 6000), 6000)
    src.emit(Rec(0, 2.0, 6500), 6500)  # inside
    job.run_cycle()
    assert rt.traces["n"] == t0
    assert job.results("oa") == [(1000, 2000)]
    assert job.results("ob") == [(6000, 6500)]


def test_non_template_dynamic_add_still_works():
    # a window query can't fold; it gets its own runtime as before
    src = CallbackSource("S", SCHEMA)
    job = make_job(src)
    job.add_plan(
        compile_plan(chain_cql("q1", 1, 2), {"S": SCHEMA}, plan_id="q1"),
        dynamic=True,
    )
    job.add_plan(
        compile_plan(
            "from S select id, sum(price) as total group by id "
            "insert into totals",
            {"S": SCHEMA}, plan_id="w1",
        ),
        dynamic=True,
    )
    assert len(job._plans) == 2
    assert set(job.plan_ids) == {"q1", "w1"}
    for i in range(8):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    assert len(job.results("totals")) == 8


def test_checkpoint_restore_replays_dynamic_group(tmp_path):
    src = CallbackSource("S", SCHEMA)
    control = ControlQueueSource()
    job = Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[control],
        plan_compiler=lambda cql, pid: compile_plan(
            cql, {"S": SCHEMA}, plan_id=pid
        ),
    )
    b = MetadataControlEvent.builder()
    pid_a = b.add_execution_plan(chain_cql("a", 1, 2))
    pid_b = b.add_execution_plan(chain_cql("b", 2, 3))
    control.push(b.build())
    # a dangling s1 (id==1) partial carries across the checkpoint
    src.emit(Rec(1, 0.0, 1000), 1000)
    src.emit(Rec(9, 0.0, 1001), 1001)
    job.run_cycle()
    path = tmp_path / "ckpt.bin"
    job.save_checkpoint(str(path))

    src2 = CallbackSource("S", SCHEMA)
    job2 = make_job(src2)
    job2.restore(str(path))
    assert set(job2.plan_ids) == {pid_a, pid_b}
    # the carried partial completes after restore
    src2.emit(Rec(2, 0.0, 2000), 2000)
    job2.run_cycle()
    assert job2.results("out_a") == [(1000, 2000)]


def test_duplicate_dynamic_add_replaces_not_duplicates():
    # at-least-once control channels may redeliver an add: the re-add
    # replaces the query, never double-registers a second slot
    src = CallbackSource("S", SCHEMA)
    job = make_job(src)
    for _ in range(2):
        job.add_plan(
            compile_plan(
                chain_cql("q1", 1, 2), {"S": SCHEMA}, plan_id="q1"
            ),
            dynamic=True,
        )
    assert job.plan_ids == ["q1"]
    for i in range(8):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    # each match exactly once (a zombie slot would double-emit)
    assert job.results("out_q1") == [(1001, 1002), (1005, 1006)]


def test_non_integral_literal_on_int_column_not_folded():
    # `id == 5.5` on an int column can never match statically; folding
    # would truncate the param to 5 and match different events
    src = CallbackSource("S", SCHEMA)
    job = make_job(src)
    job.add_plan(
        compile_plan(chain_cql("q1", 1, 2), {"S": SCHEMA}, plan_id="q1"),
        dynamic=True,
    )
    cql = (
        "from every s1 = S[id == 5.5] -> s2 = S[id == 2] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into oz"
    )
    job.add_plan(
        compile_plan(cql, {"S": SCHEMA}, plan_id="qz"), dynamic=True
    )
    # not folded into the group: own runtime, exact static semantics
    assert "qz" in job._plans
    src.emit(Rec(5, 0.0, 1000), 1000)
    src.emit(Rec(2, 0.0, 1001), 1001)
    job.run_cycle()
    assert job.results("oz") == []


def test_direct_dynamic_add_checkpoint_needs_cql(tmp_path):
    src = CallbackSource("S", SCHEMA)
    job = make_job(src)
    cql = chain_cql("q1", 1, 2)
    job.add_plan(
        compile_plan(cql, {"S": SCHEMA}, plan_id="q1"), dynamic=True
    )
    src.emit(Rec(1, 0.0, 1000), 1000)
    job.run_cycle()
    # without a recorded CQL the snapshot would be unrestorable: refuse
    with pytest.raises(ValueError, match="no\\s+recorded CQL"):
        job.save_checkpoint(str(tmp_path / "x.bin"))
    # with cql= the add is checkpointable
    src2 = CallbackSource("S", SCHEMA)
    job2 = make_job(src2)
    job2.add_plan(
        compile_plan(cql, {"S": SCHEMA}, plan_id="q1"),
        dynamic=True, cql=cql,
    )
    src2.emit(Rec(1, 0.0, 1000), 1000)
    job2.run_cycle()
    p = tmp_path / "ok.bin"
    job2.save_checkpoint(str(p))
    src3 = CallbackSource("S", SCHEMA)
    job3 = make_job(src3)
    job3.restore(str(p))
    src3.emit(Rec(2, 0.0, 2000), 2000)
    job3.run_cycle()
    assert job3.results("out_q1") == [(1000, 2000)]


def test_replay_skips_member_with_missing_cql():
    # ADVICE round-2: a snapshot whose FIRST (lowest-slot) group member
    # has no recorded CQL must not abort the whole replay — the next
    # member becomes the group host and the rest still fold in
    src = CallbackSource("S", SCHEMA)
    control = ControlQueueSource()
    job = Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[control],
        plan_compiler=lambda cql, pid: compile_plan(
            cql, {"S": SCHEMA}, plan_id=pid
        ),
    )
    b = MetadataControlEvent.builder()
    pids = [
        b.add_execution_plan(chain_cql(n, a, a + 1))
        for n, a in (("a", 1), ("b", 2), ("c", 3))
    ]
    control.push(b.build())
    job.run_cycle()
    assert len(job._folded) == 3

    cqls = dict(job._dynamic_cql)
    first_pid = min(job._folded, key=lambda p: job._folded[p][1])
    del cqls[first_pid]
    src2 = CallbackSource("S", SCHEMA)
    job2 = make_job(src2)
    job2._replay_dynamic(
        cqls, dict(job._folded), {p: True for p in job._folded}
    )
    survivors = set(pids) - {first_pid}
    assert set(job2.plan_ids) == survivors
    # the surviving members still match end-to-end
    for ts, rec in [(1000, Rec(2, 0.0, 1000)), (2000, Rec(3, 0.0, 2000))]:
        src2.emit(rec, ts)
    job2.run_cycle()
    name_b = [n for n, p in zip("abc", pids) if p in survivors][0]
    assert job2.results(f"out_{name_b}") == [(1000, 2000)]


def test_range_predicates_fold_without_retrace():
    # VERDICT round-2 weak #8: the no-recompile family now spans
    # comparison and two-conjunct range predicates — the operator is
    # per-slot data, so `price > x`, `price <= y`, and a range all fold
    # into one group with `id == k` chains kept separate by key
    def cql(pid, f1, f2):
        return (
            f"from every s1 = S[{f1}] -> s2 = S[{f2}] "
            f"select s1.timestamp as t1, s2.timestamp as t2 "
            f"insert into out_{pid}"
        )

    src = CallbackSource("S", SCHEMA)
    job = make_job(src)
    job.add_plan(
        compile_plan(
            cql("a", "price > 10.0", "price < 3.0"),
            {"S": SCHEMA}, plan_id="a",
        ),
        dynamic=True,
    )
    for i in range(8):
        src.emit(Rec(i, float(i * 4), 1000 + i), 1000 + i)
    job.run_cycle()
    (rt,) = job._plans.values()
    traces0 = rt.traces["n"]
    # prices: 0,4,8,12,16,20,24,28 -> s1 first >10 at ts 1003 (12.0);
    # no later <3 -> no match yet for 'a'
    assert job.results("out_a") == []

    # different OPS over the same column: a pure data update
    job.add_plan(
        compile_plan(
            cql("b", "price >= 20.0", "price >= 24.0"),
            {"S": SCHEMA}, plan_id="b",
        ),
        dynamic=True,
    )
    # a two-conjunct RANGE also folds (same key, two conjuncts differ ->
    # different template; new runtime) — assert the single-conjunct ones
    # DID fold
    assert len(job._plans) == 1
    for i in range(8, 16):
        src.emit(Rec(i, float(i * 4), 1000 + i), 1000 + i)
    job.run_cycle()
    assert rt.traces["n"] == traces0  # no retrace for the data-only add
    # 'b' only sees events after its add (prices 32..60, all >=24):
    # first pair is the first two post-add events
    assert job.results("out_b")[0] == (1008, 1009)


def test_range_chain_matches_static_compile():
    # the parametric op-select path must agree with a statically
    # compiled plan of the same query
    cql = (
        "from every s1 = S[price > 5.0] -> s2 = S[price <= 2.0] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into o"
    )
    recs = [Rec(i, float([8, 1, 9, 2, 7, 0][i % 6]), 1000 + i)
            for i in range(24)]

    def run(dynamic):
        src = CallbackSource("S", SCHEMA)
        job = make_job(src)
        job.add_plan(
            compile_plan(cql, {"S": SCHEMA}, plan_id="q"),
            dynamic=dynamic,
        )
        for r in recs:
            src.emit(r, r.timestamp)
        job.run_cycle()
        job.flush()
        return job.results("o")

    static = run(False)
    assert run(True) == static
    assert len(static) > 0
