"""Event-time robustness under disorder (docs/event_time.md).

The engine's watermark gate used to *consume* watermarks — sources had
to hand perfect ones, late rows slid through the gate and merged out
of order, and one silent source pinned the min watermark forever.
These tests pin the robustness surface end to end:

* watermark GENERATION: ``BoundedDisorderWatermark`` /
  ``PunctuatedWatermark`` strategy units, the ``WatermarkedSource``
  wrapper replacing a source's native claim, per-partition generation
  in ``KafkaSource`` (source wm = min across producing partitions),
  and checkpoint round-trips of all strategy state;
* DISORDER ORACLE: a seeded ``DisorderSchedule`` (bounded-skew
  shuffle + bursty duplicates, runtime/faultinject.py) feeds the
  engine a shuffled stream while the oracle sees the SORTED stream —
  row-exact agreement in streaming, fused-segment, and resident modes
  over a five-query plan (filter, pattern chain, length-window
  group-by, timeBatch, unique), with ``baseline/interp.py`` (the
  measured per-event reference interpreter) as the sorted-stream
  ground truth on its supported surface (filter / chain /
  length-window group-by; the remaining zoo windows are pinned
  engine-sorted vs engine-shuffled — their per-case oracles live in
  tests/test_window_zoo.py);
* LATE POLICY: 'drop' (counted, exact vs the injected schedule),
  'side_output' (full rows on the '<stream>@late' channel, row and
  columnar consumers), 'allow' (in-order admission within
  allowed_lateness_ms);
* IDLE SOURCES: a silent source stops pinning the min watermark
  within its timeout, un-idles on the next event, stays visible in
  metrics, and keeps polling under the 'block' shed policy;
* SUPERVISED RECOVERY: watermark/gate state survives kill->restore
  with 0 duplicate / 0 lost rows against the unfaulted oracle.

Randomized multi-seed sweeps carry @pytest.mark.slow; tier-1 keeps a
fixed-seed deterministic subset (the ~870s budget, ROADMAP.md).
"""

import collections
import glob
import time

import numpy as np
import pytest

import bench  # noqa: F401  (sets the shared XLA compilation cache dir)
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import (
    MAX_WM,
    Job,
    late_stream,
)
from flink_siddhi_tpu.runtime.faultinject import (
    CrashPlan,
    DisorderSchedule,
    DisorderSource,
    wrap_job,
)
from flink_siddhi_tpu.runtime.replay import ResidentReplay
from flink_siddhi_tpu.runtime.sources import (
    BoundedDisorderWatermark,
    CallbackSource,
    ListSource,
    PunctuatedWatermark,
    WatermarkedSource,
    with_watermarks,
)
from flink_siddhi_tpu.runtime.supervisor import Supervisor
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType


def _schema():
    return StreamSchema(
        [
            ("id", AttributeType.INT),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )


def _stream(n=6000, seed=0, n_ids=5, step_ms=7):
    """Pristine sorted stream. Prices are integer-valued so window
    sums stay EXACT in f32 (no accumulation-order tolerance anywhere
    in these equality assertions)."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_ids, n)
    prices = rng.integers(0, 50, n).astype(np.float64)
    ts = 1_000 + np.arange(n, dtype=np.int64) * step_ms
    records = [
        (int(i), float(p), int(t))
        for i, p, t in zip(ids, prices, ts)
    ]
    return records, ts.tolist()


# one compile serves five query shapes: stateless filter, 2-step
# chain, sliding length-window group-by, tumbling timeBatch, and the
# unique (per-key latest) window
MULTI_CQL = (
    "from S[id == 2] select id, price insert into o_filter; "
    "from every s1 = S[id == 0] -> s2 = S[id == 1] within 2 sec "
    "select s1.timestamp as t1, s2.timestamp as t2 insert into o_pat; "
    "from S#window.length(50) select id, sum(price) as total, "
    "count() as cnt group by id insert into o_win; "
    "from S#window.timeBatch(3 sec) select sum(price) as total "
    "insert into o_tb; "
    "from S#window.unique(id) select id, sum(price) as total, "
    "count() as cnt insert into o_uni"
)
# the subset the per-event reference interpreter supports
INTERP_CQL = (
    "from S[id == 2] select id, price insert into o_filter; "
    "from every s1 = S[id == 0] -> s2 = S[id == 1] within 2 sec "
    "select s1.timestamp as t1, s2.timestamp as t2 insert into o_pat; "
    "from S#window.length(50) select id, sum(price) as total, "
    "count() as cnt group by id insert into o_win"
)

CHUNK = 300
SKEW_MS = 200


def _norm(ts, row):
    return (
        int(ts),
        tuple(
            np.float32(v).item() if isinstance(v, float) else v
            for v in row
        ),
    )


def _results(job):
    return {
        sid: sorted(_norm(t, r) for t, r in job.results_with_ts(sid))
        for sid in job.collected
    }


def _run_sorted(records, ts, cql=MULTI_CQL, **job_attrs):
    # skew 0 (claims max - 1): the sorted oracle stream may carry
    # duplicates whose ts equals the previous batch's max — the
    # ListSource's native max-ts claim would call those late
    schema = _schema()
    plan = compile_plan(cql, {"S": schema})
    job = Job(
        [plan],
        [with_watermarks(
            ListSource("S", schema, records, timestamps=ts,
                       chunk=CHUNK),
            skew_ms=0,
        )],
        batch_size=CHUNK, time_mode="event",
    )
    for k, v in job_attrs.items():
        setattr(job, k, v)
    job.run()
    assert job.late_events == 0  # the oracle run must be pristine
    return job


def _run_disordered(
    records, ts, schedule, mode="streaming", cql=MULTI_CQL,
    strategy_skew=SKEW_MS, **job_attrs,
):
    schema = _schema()
    plan = compile_plan(cql, {"S": schema})
    src = DisorderSource(
        ListSource("S", schema, records, timestamps=ts, chunk=CHUNK),
        schedule, chunk=CHUNK,
    )
    job = Job(
        [plan],
        [with_watermarks(src, skew_ms=strategy_skew)],
        batch_size=CHUNK, time_mode="event",
    )
    for k, v in job_attrs.items():
        setattr(job, k, v)
    if mode == "fused":
        job.fused_segment_len = 3
        job.run()
    elif mode == "resident":
        rep = ResidentReplay(job)
        rep.stage()
        rep.run()
        job.flush()
    else:
        job.run()
    return job, src


# -- watermark strategy units (no device work) ------------------------------

def test_bounded_disorder_strategy():
    s = BoundedDisorderWatermark(500)
    assert s.current() is None  # unknown until the first event
    s.observe(np.asarray([1_000, 3_000, 2_000]))
    # max - skew - 1: an event AT the bound is still admissible
    assert s.current() == 2_499
    s.observe(np.asarray([2_900]))  # max is sticky, never regresses
    assert s.current() == 2_499
    s.observe(np.asarray([10_000]))
    assert s.current() == 9_499
    clone = s.clone()
    assert clone.skew_ms == 500 and clone.current() is None
    # checkpoint round-trip
    d = s.state_dict()
    fresh = BoundedDisorderWatermark(500)
    fresh.load_state_dict(d)
    assert fresh.current() == 9_499
    with pytest.raises(ValueError):
        BoundedDisorderWatermark(-1)


def test_punctuated_strategy_passes_native_claims():
    s = PunctuatedWatermark()
    s.observe(np.asarray([99_999]))  # event times are ignored
    assert s.current() is None
    s.advance(4_000)
    s.advance(3_000)  # monotone
    assert s.current() == 4_000
    fresh = PunctuatedWatermark()
    fresh.load_state_dict(s.state_dict())
    assert fresh.current() == 4_000


def test_watermarked_source_replaces_native_claim():
    schema = _schema()
    records, ts = _stream(n=10, step_ms=100)
    src = WatermarkedSource(
        ListSource("S", schema, records, timestamps=ts, chunk=5),
        BoundedDisorderWatermark(250),
    )
    batch, wm, done = src.poll(5)
    # ListSource natively claims max(ts); the strategy holds back
    assert len(batch) == 5 and not done
    assert wm == int(batch.timestamps.max()) - 250 - 1
    # checkpoint carries inner position AND strategy state
    d = src.state_dict()
    src2 = WatermarkedSource(
        ListSource("S", schema, records, timestamps=ts, chunk=5),
        BoundedDisorderWatermark(250),
    )
    src2.load_state_dict(d)
    batch2, wm2, done2 = src2.poll(5)
    assert int(batch2.timestamps.min()) == ts[5]
    # the end-of-stream MAX sentinel passes through the strategy
    assert done2 and wm2 == MAX_WM


# -- disorder oracle: shuffled engine == sorted oracle, all modes -----------

_ORACLE_MEMO = {}


def _sorted_with_dups_oracle(records, ts, dup_log, dup_burst, key):
    """The sorted oracle stream carries the SAME duplicates, in sorted
    position. Memoized: the three mode params replay the identical
    schedule, so one oracle run serves all of them (tier-1 budget)."""
    if key not in _ORACLE_MEMO:
        dups = dup_log.tolist()
        allr = list(records) + [
            records[i] for i in dups for _ in range(dup_burst)
        ]
        allt = list(ts) + [
            ts[i] for i in dups for _ in range(dup_burst)
        ]
        order = np.argsort(np.asarray(allt), kind="stable")
        _ORACLE_MEMO[key] = _results(_run_sorted(
            [allr[i] for i in order], [allt[i] for i in order]
        ))
    return _ORACLE_MEMO[key]


@pytest.mark.parametrize("mode", ["streaming", "fused", "resident"])
def test_disorder_rowexact_vs_sorted_oracle(mode):
    """Bounded-skew shuffle + bursty duplicates: the engine fed the
    SHUFFLED stream (watermarking at the disorder bound) must emit
    row-identically to the same engine fed the SORTED stream, across
    all five query shapes, in every execution mode."""
    records, ts = _stream()
    sched = DisorderSchedule(
        seed=42, skew_ms=SKEW_MS, dup_rate=0.005, dup_burst=2
    )
    job, src = _run_disordered(records, ts, sched, mode=mode)
    assert job.late_events == 0  # strategy skew == disorder bound
    want = _sorted_with_dups_oracle(
        records, ts, src.dup_log, sched.dup_burst, "seed42"
    )
    got = _results(job)
    assert got.keys() == want.keys()
    for sid in want:
        assert got[sid] == want[sid], (mode, sid)
    if mode == "streaming":
        # gate telemetry recorded under disorder: watermark lag +
        # reorder-buffer residency histograms are live
        snap = job.telemetry.snapshot()["histograms"]
        assert snap["watermark.lag"]["count"] > 0
        assert snap["gate.residency"]["count"] > 0


def test_disorder_rowexact_vs_baseline_interpreter():
    """The sorted-stream ground truth per the reference interpreter
    (baseline/interp.py): the engine fed the SHUFFLED stream must
    match the per-event interpreter fed the SORTED stream, row-exact,
    on the interpreter's supported surface."""
    from flink_siddhi_tpu.baseline import BaselineEngine

    records, ts = _stream()
    sched = DisorderSchedule(
        seed=7, skew_ms=SKEW_MS, dup_rate=0.005, dup_burst=2
    )
    job, src = _run_disordered(records, ts, sched, cql=INTERP_CQL)
    eng = BaselineEngine(INTERP_CQL, ["id", "price", "timestamp"])
    rows = collections.defaultdict(list)
    eng._emit = lambda out, t, row: rows[out].append(_norm(t, row))
    dups = src.dup_log.tolist()
    allr = list(records) + [
        records[i] for i in dups for _ in range(sched.dup_burst)
    ]
    allt = list(ts) + [
        ts[i] for i in dups for _ in range(sched.dup_burst)
    ]
    order = np.argsort(np.asarray(allt), kind="stable")
    for i in order.tolist():
        rid, price, t = allr[i]
        eng.process(
            {"id": rid, "price": price, "timestamp": t}, allt[i]
        )
    got = _results(job)
    for sid in ("o_filter", "o_pat", "o_win"):
        assert got[sid] == sorted(rows[sid]), sid


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3, 4])
@pytest.mark.parametrize("skew_ms", [50, 500, 3_000])
def test_disorder_rowexact_randomized_sweep(seed, skew_ms):
    """Multi-seed randomized sweep (slow lane): shuffle + duplicates
    at several disorder bounds, streaming + fused, vs the sorted
    engine oracle."""
    records, ts = _stream(n=8000, seed=seed)
    sched = DisorderSchedule(
        seed=seed * 31, skew_ms=skew_ms, dup_rate=0.01, dup_burst=3
    )
    dups_oracle = None
    for mode in ("streaming", "fused"):
        job, src = _run_disordered(
            records, ts, sched, mode=mode, strategy_skew=skew_ms
        )
        assert job.late_events == 0
        if dups_oracle is None:
            dups = src.dup_log.tolist()
            allr = list(records) + [
                records[i] for i in dups for _ in range(3)
            ]
            allt = list(ts) + [ts[i] for i in dups for _ in range(3)]
            order = np.argsort(np.asarray(allt), kind="stable")
            dups_oracle = _results(_run_sorted(
                [allr[i] for i in order], [allt[i] for i in order]
            ))
        assert _results(job) == dups_oracle, (seed, skew_ms, mode)


# -- late-event policy ------------------------------------------------------

FILTER_CQL = "from S[id == 2] select id, price insert into o"


def _filter_oracle(records, ts, indices):
    """Python oracle for FILTER_CQL over the given pristine indices."""
    return sorted(
        (int(ts[i]), (records[i][0], np.float32(records[i][1]).item()))
        for i in indices
        if records[i][0] == 2
    )


def _late_schedule(seed=9):
    return DisorderSchedule(
        seed=seed, skew_ms=SKEW_MS, late_count=12,
        late_release_ms=2_000,
    )


def test_late_policy_drop_counts_exact():
    records, ts = _stream()
    sched = _late_schedule()
    job, src = _run_disordered(
        records, ts, sched, cql=FILTER_CQL, late_policy="drop"
    )
    assert src.injected["late"] == 12
    assert job.late_dropped == 12 == job.late_events
    counters = job.telemetry.snapshot()["counters"]
    assert counters["faults.late_dropped"] == 12
    keep = [i for i in range(len(records)) if i not in
            set(src.late_log.tolist())]
    assert sorted(
        _norm(t, r) for t, r in job.results_with_ts("o")
    ) == _filter_oracle(records, ts, keep)
    # the account is user-visible
    m = job.metrics()
    assert m["late_dropped"] == 12 and m["late_policy"] == "drop"


def test_late_policy_side_output_routes_full_rows():
    records, ts = _stream()
    sched = _late_schedule(seed=13)
    schema = _schema()
    plan = compile_plan(FILTER_CQL, {"S": schema})
    src = DisorderSource(
        ListSource("S", schema, records, timestamps=ts, chunk=CHUNK),
        sched, chunk=CHUNK,
    )
    job = Job(
        [plan], [with_watermarks(src, skew_ms=SKEW_MS)],
        batch_size=CHUNK, time_mode="event",
    )
    job.late_policy = "side_output"
    col_rows = []

    class _ColSink:
        def accept_columns(self, t, cols):
            for k in range(len(t)):
                col_rows.append(
                    (int(t[k]),
                     tuple(cols[n][k] for n in schema.field_names))
                )

    row_rows = []
    job.add_sink(late_stream("S"), _ColSink())
    job.add_sink(late_stream("S"), lambda t, row: row_rows.append(
        (int(t), row)
    ))
    job.run()
    want = sorted(
        (int(ts[i]), records[i]) for i in src.late_log.tolist()
    )
    # full input rows surface on the late channel — identically on
    # the columnar and the per-row sink, and in collected[]
    assert sorted(col_rows) == want
    assert sorted(row_rows) == want
    assert sorted(job.collected[late_stream("S")]) == want
    assert job.late_events == len(want) and job.late_dropped == 0
    counters = job.telemetry.snapshot()["counters"]
    assert counters["faults.late_side_output"] == len(want)
    # nothing late leaked into the query results
    keep = [i for i in range(len(records)) if i not in
            set(src.late_log.tolist())]
    assert sorted(
        _norm(t, r) for t, r in job.results_with_ts("o")
    ) == _filter_oracle(records, ts, keep)


def test_late_policy_allow_admits_within_allowance_in_order():
    """'allow': the gate holds its horizon back by the allowance, so
    stragglers within it still merge IN ORDER — output equals the
    pristine sorted stream's, nothing dropped."""
    records, ts = _stream()
    sched = _late_schedule(seed=17)
    # generous allowance: covers late_release_ms + placement slack
    # (two chunks) + the strategy skew
    job, src = _run_disordered(
        records, ts, sched, cql=FILTER_CQL,
        late_policy="allow", allowed_lateness_ms=15_000,
    )
    assert src.injected["late"] == 12
    assert job.late_dropped == 0 and job.late_events == 0
    assert sorted(
        _norm(t, r) for t, r in job.results_with_ts("o")
    ) == _filter_oracle(records, ts, range(len(records)))


def test_late_policy_allow_beyond_allowance_drops_loudly(caplog):
    """Beyond the allowance 'allow' DROPS, counted, with the
    documented re-fire rejection in the warning — never a silent
    wrong answer."""
    import logging

    records, ts = _stream()
    sched = _late_schedule(seed=21)
    with caplog.at_level(
        logging.WARNING, logger="flink_siddhi_tpu.runtime.executor"
    ):
        job, src = _run_disordered(
            records, ts, sched, cql=FILTER_CQL,
            late_policy="allow", allowed_lateness_ms=100,
        )
    assert job.late_dropped == src.injected["late"] == 12
    assert any("re-fire" in r.message.lower() for r in caplog.records)


# -- idle-source handling ---------------------------------------------------

def test_idle_source_stops_pinning_watermark_and_unidles():
    """One flowing source + one silent source: without idle handling
    the min watermark pins at the silent source and NOTHING releases;
    with idle_timeout_ms the silent source is marked idle within the
    timeout, the backlog releases, and the source un-idles on its
    next event (whose old rows meet the late policy, not the gate)."""
    schema = _schema()
    records, ts = _stream(n=900, step_ms=10)
    quiet = CallbackSource("S", schema)
    flowing = ListSource(
        "S", schema, records, timestamps=ts, chunk=CHUNK
    )
    plan = compile_plan(FILTER_CQL, {"S": schema})
    job = Job(
        [plan], [flowing, quiet], batch_size=CHUNK, time_mode="event"
    )
    job.idle_timeout_ms = 40.0
    deadline = time.monotonic() + 10.0
    while not job.collected.get("o") and time.monotonic() < deadline:
        job.run_cycle()
        job.drain_outputs()
    # the flowing source's rows released despite the silent source
    assert job.collected.get("o"), "idle source still pins the gate"
    assert job.idle_source_ids() == ["S"]
    m = job.metrics()
    assert [s for s in m["sources"] if s["idle"]], m["sources"]
    assert job.telemetry.snapshot()["counters"]["idle.marked"] >= 1
    # un-idle on the next event: its watermark claim rejoins the min
    quiet.emit((2, 1.0, 999_999), timestamp_ms=999_999)
    job.run_cycle()
    assert job.idle_source_ids() == []
    assert (
        job.telemetry.snapshot()["counters"]["idle.unidled"] == 1
    )


def test_idle_source_keeps_polling_under_block_shed_policy():
    """'block' + idle interaction: over the pending bound only
    watermark laggards keep polling — an idle (then un-idling) source
    must stay in that exempt set or the backlog deadlocks."""
    schema = _schema()
    records, ts = _stream(n=1200, step_ms=10)
    quiet = CallbackSource("S", schema)
    flowing = ListSource(
        "S", schema, records, timestamps=ts, chunk=CHUNK
    )
    plan = compile_plan(FILTER_CQL, {"S": schema})
    job = Job(
        [plan], [flowing, quiet], batch_size=CHUNK, time_mode="event"
    )
    job.idle_timeout_ms = 0.0  # first empty poll marks idle
    job.max_pending_events = 2 * CHUNK
    job.shed_policy = "block"
    deadline = time.monotonic() + 10.0
    while not job.collected.get("o") and time.monotonic() < deadline:
        job.run_cycle()
        job.drain_outputs()
    assert job.collected.get("o"), "block policy deadlocked the gate"
    # the silent source was still being polled while idle (that is
    # how it un-idles): feed it and finish the job
    quiet.advance_watermark(10**9)
    quiet.close()
    flowing_done = time.monotonic() + 10.0
    while not job.finished and time.monotonic() < flowing_done:
        job.run_cycle()
    assert job.finished
    expected = _filter_oracle(records, ts, range(len(records)))
    assert sorted(
        _norm(t, r) for t, r in job.results_with_ts("o")
    ) == expected


# -- multi-source join under disorder ---------------------------------------

JOIN_CQL = (
    "from T#window.length(4) as t join Q#window.length(3) as q "
    "on t.sym == q.sym select t.sym, t.price, q.bid insert into oj"
)


def _join_schemas():
    t = StreamSchema(
        [("sym", AttributeType.INT), ("price", AttributeType.DOUBLE)]
    )
    q = StreamSchema(
        [("sym", AttributeType.INT), ("bid", AttributeType.DOUBLE)]
    )
    return t, q


def _join_streams(n=1500, seed=3):
    """Interleaved skewed timestamps: trades on odd ms, quotes on
    even ms — two topics never arrive aligned."""
    rng = np.random.default_rng(seed)
    trades = [
        (int(s), float(p))
        for s, p in zip(rng.integers(0, 4, n),
                        rng.integers(1, 90, n))
    ]
    quotes = [
        (int(s), float(b))
        for s, b in zip(rng.integers(0, 4, n),
                        rng.integers(1, 90, n))
    ]
    t_ts = (1_001 + np.arange(n, dtype=np.int64) * 10).tolist()
    q_ts = (1_006 + np.arange(n, dtype=np.int64) * 10).tolist()
    return trades, t_ts, quotes, q_ts


def _run_join(t_src, q_src):
    ts_schema, qs_schema = _join_schemas()
    plan = compile_plan(JOIN_CQL, {"T": ts_schema, "Q": qs_schema})
    job = Job(
        [plan], [t_src, q_src], batch_size=CHUNK, time_mode="event"
    )
    job.run()
    return sorted(
        _norm(t, r) for t, r in job.results_with_ts("oj")
    )


def test_multi_source_join_under_disorder():
    """The 'honest multi-source joins' pin: two independently
    disordered sources through a windowed join, row-exact vs the same
    join fed both streams sorted."""
    ts_schema, qs_schema = _join_schemas()
    trades, t_ts, quotes, q_ts = _join_streams()
    want = _run_join(
        ListSource("T", ts_schema, trades, timestamps=t_ts,
                   chunk=CHUNK),
        ListSource("Q", qs_schema, quotes, timestamps=q_ts,
                   chunk=CHUNK),
    )
    assert want, "join oracle produced no rows"
    t_dis = DisorderSource(
        ListSource("T", ts_schema, trades, timestamps=t_ts,
                   chunk=CHUNK),
        DisorderSchedule(seed=51, skew_ms=SKEW_MS), chunk=CHUNK,
    )
    q_dis = DisorderSource(
        ListSource("Q", qs_schema, quotes, timestamps=q_ts,
                   chunk=CHUNK),
        DisorderSchedule(seed=52, skew_ms=SKEW_MS), chunk=CHUNK,
    )
    got = _run_join(
        with_watermarks(t_dis, skew_ms=SKEW_MS),
        with_watermarks(q_dis, skew_ms=SKEW_MS),
    )
    assert got == want


# -- kafka: per-partition watermark generation ------------------------------

def test_kafka_per_partition_watermark_min_across_partitions():
    import json

    from tests.fake_kafka import FakeBroker
    from flink_siddhi_tpu.runtime.kafka import KafkaSource

    broker = FakeBroker()
    try:
        broker.create_topic("t", partitions=2)

        def rec(i, t):
            return json.dumps(
                {"id": i, "price": 1.0, "timestamp": t}
            ).encode()

        # partition 0 far ahead of partition 1
        broker.append("t", 0, [rec(1, 10_000), rec(2, 20_000)])
        broker.append("t", 1, [rec(3, 5_000)])
        schema = _schema()
        src = KafkaSource(
            "S", schema, broker.bootstrap, "t",
            ts_field="timestamp",
            watermark=BoundedDisorderWatermark(1_000),
        )
        batch, wm, done = src.poll(64)
        assert len(batch) == 3 and not done
        # min across producing partitions: p0 at 19_999-1, p1 at
        # 5_000-1_000-1
        assert wm == 3_999
        # per-partition state rides the checkpoint
        d = src.state_dict()
        assert set(d["wm"]) == {"0", "1"}
        src2 = KafkaSource(
            "S", schema, broker.bootstrap, "t",
            ts_field="timestamp",
            watermark=BoundedDisorderWatermark(1_000),
        )
        src2.load_state_dict(d)
        assert src2._partition_watermark() == 3_999
        # the lagging partition catches up: the min advances
        broker.append("t", 1, [rec(4, 21_000)])
        batch, wm, done = src.poll(64)
        assert len(batch) == 1
        assert wm == 18_999  # now pinned by partition 0's 20_000
    finally:
        broker.close()


def test_kafka_empty_partition_does_not_pin_watermark():
    import json

    from tests.fake_kafka import FakeBroker
    from flink_siddhi_tpu.runtime.kafka import KafkaSource

    broker = FakeBroker()
    try:
        broker.create_topic("t", partitions=2)
        broker.append("t", 0, [json.dumps(
            {"id": 1, "price": 1.0, "timestamp": 50_000}
        ).encode()])
        # partition 1 never produces
        schema = _schema()
        src = KafkaSource(
            "S", schema, broker.bootstrap, "t",
            ts_field="timestamp",
            watermark=BoundedDisorderWatermark(1_000),
        )
        batch, wm, _ = src.poll(64)
        assert len(batch) == 1
        assert wm == 48_999  # the never-producing partition is absent
    finally:
        broker.close()


def test_kafka_partition_idleness_unpins_min_watermark():
    """One silent PARTITION must stop pinning the source's min claim
    after idle_timeout_ms (0 = the first poll it sits out), un-idle on
    its next record, and carry its idle flag through checkpoints — the
    PR 10 carried item (before this, only the job-level timeout could
    unpin, by silencing the whole SOURCE)."""
    import json

    from tests.fake_kafka import FakeBroker
    from flink_siddhi_tpu.runtime.kafka import KafkaSource
    from flink_siddhi_tpu.telemetry import MetricsRegistry

    broker = FakeBroker()
    try:
        broker.create_topic("t", partitions=2)

        def rec(i, t):
            return json.dumps(
                {"id": i, "price": 1.0, "timestamp": t}
            ).encode()

        broker.append("t", 0, [rec(1, 10_000)])
        broker.append("t", 1, [rec(2, 5_000)])
        schema = _schema()

        def make_src():
            return KafkaSource(
                "S", schema, broker.bootstrap, "t",
                ts_field="timestamp",
                watermark=BoundedDisorderWatermark(1_000),
                idle_timeout_ms=0,
            )

        src = make_src()
        reg = MetricsRegistry()
        src.bind_telemetry(reg)
        _b, wm, _d = src.poll(64)
        assert wm == 3_999  # both produced: plain min across partitions
        # partition 1 goes silent while 0 keeps producing: with the
        # 0ms timeout it idles on the first poll it sits out, and the
        # claim advances to partition 0's alone
        broker.append("t", 0, [rec(3, 30_000)])
        _b, wm, _d = src.poll(64)
        assert src._part_idle[1] and not src._part_idle[0]
        assert wm == 28_999
        assert reg.counter("idle.partition_marked").value == 1
        # the idle FLAG rides the checkpoint
        d = src.state_dict()
        assert d["part_idle"] == {"0": False, "1": True}
        src2 = make_src()
        src2.load_state_dict(d)
        assert src2._part_idle[1]
        assert src2._partition_watermark() == 28_999
        # an all-empty poll idles the remaining partition too (0ms =
        # first sit-out): ALL-idle means the claim HOLDS (None), not
        # jump-to-MAX — idle is "no information", Flink semantics
        _b, wm, done = src.poll(64)
        assert (wm, done) == (None, False)
        assert src._part_idle[0] and src._part_idle[1]
        # un-idles on its next record: the claim is that partition's
        # own again (the source claim may regress; the job's gate
        # watermark is monotone and classifies stragglers by policy)
        broker.append("t", 1, [rec(4, 6_000)])
        _b, wm, _d = src.poll(64)
        assert not src._part_idle[1] and src._part_idle[0]
        assert wm == 4_999
        assert reg.counter("idle.partition_unidled").value == 1
    finally:
        broker.close()


def test_kafka_partition_with_buffered_backlog_is_not_idle():
    """A partition whose records are fetched-but-unconsumed (a
    high-volume sibling can monopolize poll's max_events slice) is NOT
    silent: idling it would misclassify its still-queued rows as late
    once they drain."""
    import json

    from tests.fake_kafka import FakeBroker
    from flink_siddhi_tpu.runtime.kafka import KafkaSource

    broker = FakeBroker()
    try:
        broker.create_topic("t", partitions=2)

        def rec(i, t):
            return json.dumps(
                {"id": i, "price": 1.0, "timestamp": t}
            ).encode()

        broker.append(
            "t", 0, [rec(i, 10_000 + 1_000 * i) for i in range(4)]
        )
        broker.append("t", 1, [rec(9, 5_000)])
        schema = _schema()
        src = KafkaSource(
            "S", schema, broker.bootstrap, "t",
            ts_field="timestamp",
            watermark=BoundedDisorderWatermark(1_000),
            idle_timeout_ms=0,
        )
        # poll(2) consumes only partition 0's head; partition 1's
        # record waits in the fetch buffer — it must not idle even at
        # the 0ms timeout. (The claim is p0's alone for now: a
        # partition that never PRODUCED does not pin the min — the
        # pre-existing PR 10 semantics; idleness must not make that
        # permanent.)
        _b, wm, _d = src.poll(2)
        assert not src._part_idle[1]
        assert wm == 9_999
        # draining the backlog rejoins p1: the true min again (the
        # executor's per-source max keeps the gate monotone)
        _b, wm, _d = src.poll(64)
        assert not src._part_idle[1]
        assert wm == 3_999
    finally:
        broker.close()


# -- checkpoint / supervised recovery ---------------------------------------

def test_gate_watermark_state_survives_checkpoint_roundtrip(tmp_path):
    records, ts = _stream(n=1200)
    schema = _schema()

    def build():
        plan = compile_plan(FILTER_CQL, {"S": schema})
        src = DisorderSource(
            ListSource("S", schema, records, timestamps=ts,
                       chunk=CHUNK),
            DisorderSchedule(seed=2, skew_ms=SKEW_MS), chunk=CHUNK,
        )
        return Job(
            [plan], [with_watermarks(src, skew_ms=SKEW_MS)],
            batch_size=CHUNK, time_mode="event",
        )

    job = build()
    for _ in range(3):
        job.run_cycle()
    path = str(tmp_path / "ckpt")
    job.save_checkpoint(path)
    pre_rows = job.results_with_ts("o")  # emitted before the snapshot
    restored = build()
    restored.restore(path)
    assert restored._released_wm == job._released_wm
    assert restored._gate_wm == job._gate_wm
    assert restored._source_wm == job._source_wm
    assert restored._max_event_ts == job._max_event_ts
    # and the resumed run completes the stream: pre-checkpoint rows +
    # post-restore rows together equal an uninterrupted run's, with no
    # duplicate and no loss (the supervisor's commit protocol handles
    # the crash-suffix case; this pins plain save/restore)
    while not restored.finished:
        restored.run_cycle()
    restored.flush()
    uninterrupted = build()
    uninterrupted.run()
    assert sorted(pre_rows + restored.results_with_ts("o")) == sorted(
        uninterrupted.results_with_ts("o")
    )


def test_supervised_kill_restore_exactly_once_under_disorder(tmp_path):
    """The acceptance pin: watermark state survives supervised
    kill->restore (including a kill mid-checkpoint) with 0 duplicate
    and 0 lost rows vs the unfaulted oracle, under disorder + late
    drops (the late account stays exact across restarts too)."""
    records, ts = _stream(n=3000)
    schema = _schema()
    sched = DisorderSchedule(
        seed=29, skew_ms=SKEW_MS, dup_rate=0.005, dup_burst=2,
        late_count=8, late_release_ms=2_000,
    )
    crash = CrashPlan(at_pulls=(3, 9), at_checkpoints=(2,))

    def factory(armed=True):
        plan = compile_plan(FILTER_CQL, {"S": schema})
        src = DisorderSource(
            ListSource("S", schema, records, timestamps=ts,
                       chunk=CHUNK),
            sched, chunk=CHUNK,
        )
        job = Job(
            [plan], [with_watermarks(src, skew_ms=SKEW_MS)],
            batch_size=CHUNK, time_mode="event", retain_results=False,
        )
        job.late_policy = "drop"
        job._disorder_src = src
        return wrap_job(job, crash) if armed else job

    ckpt = str(tmp_path / "ckpt")
    sup = Supervisor(
        factory, ckpt, checkpoint_every_cycles=2, keep_checkpoints=3,
        max_restarts=10, restart_window_s=3600.0,
    )
    final_job = sup.run()
    assert crash.crashes == 3

    # unfaulted oracle: the same supervised wiring, no crashes
    oracle_job = factory(armed=False)
    rows = collections.defaultdict(list)
    for sid in ("o",):
        oracle_job.add_sink(
            sid, lambda t, row, _s=sid: rows[_s].append((t, row))
        )
    oracle_job.run()
    committed = collections.Counter(sup.results_with_ts("o"))
    oracle = collections.Counter(rows["o"])
    assert sum((committed - oracle).values()) == 0, "duplicate rows"
    assert sum((oracle - committed).values()) == 0, "lost rows"
    # the late account survived restore: exact vs the schedule
    assert final_job.late_dropped == sched.late_count
    assert glob.glob(f"{ckpt}.tmp.*") == []


# -- control backlog drain (the O(n^2) pop(0) fix) --------------------------

def test_control_backlog_applies_in_order_and_gates_on_watermark():
    schema = _schema()
    plan = compile_plan(FILTER_CQL, {"S": schema})
    job = Job(
        [plan],
        [ListSource("S", schema, [(2, 1.0, 1)], timestamps=[1])],
        batch_size=8, time_mode="event",
    )
    applied = []
    job._apply_control = applied.append
    # a long, unsorted backlog behind the watermark gate
    job._control_pending = [
        (t, f"ev{t}") for t in range(500, 0, -1)
    ]
    job._source_wm = [250]  # watermark admits only half
    job._apply_ready_control()
    assert applied == [f"ev{t}" for t in range(1, 251)]
    assert [t for t, _ in job._control_pending] == list(
        range(251, 501)
    )
    # the rest drains when the watermark passes
    job._source_wm = [10_000]
    job._apply_ready_control()
    assert len(applied) == 500
    assert job._control_pending == []
