"""``insert expired events into``: events emitted as they LEAVE a
window (round-3 verdict item: this used to parse and silently run with
current-event semantics — a silent wrong answer).

Reference semantics: any CQL accepted by siddhi-core's validateSiddhiApp
runs with its window's expired-event chunk
(core/.../operator/AbstractSiddhiOperator.java:301-313).
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
     ("timestamp", AttributeType.LONG)]
)


def run(cql, ids, prices=None, ts=None, batch=4):
    n = len(ids)
    prices = prices if prices is not None else [float(i) for i in range(n)]
    ts = ts if ts is not None else [1000 + i for i in range(n)]
    batches = [
        EventBatch(
            "S", SCHEMA,
            {
                "id": np.asarray(ids[s:s + batch], np.int32),
                "price": np.asarray(prices[s:s + batch], np.float64),
                "timestamp": np.asarray(ts[s:s + batch], np.int64),
            },
            np.asarray(ts[s:s + batch], np.int64),
        )
        for s in range(0, n, batch)
    ]
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job


def test_length_window_expired_oracle():
    # window.length(2): event i expires when event i+2 arrives
    cql = (
        "from S#window.length(2) select id, price "
        "insert expired events into ex"
    )
    job = run(cql, ids=list(range(6)))
    rows = job.results_with_ts("ex")
    # events 0..3 expire (displaced by 2..5); 4,5 still in the window
    assert [r for _, r in rows] == [
        (0, 0.0), (1, 1.0), (2, 2.0), (3, 3.0)
    ]
    # expiry ts = the displacing event's ts
    assert [t for t, _ in rows] == [1002, 1003, 1004, 1005]


def test_length_window_expired_across_batches():
    cql = (
        "from S#window.length(3) select id insert expired events into ex"
    )
    job = run(cql, ids=list(range(10)), batch=2)
    assert [r[0] for r in job.results("ex")] == list(range(7))


def test_length_window_expired_with_filter():
    # only matching events enter (and therefore leave) the window
    cql = (
        "from S[id % 2 == 0]#window.length(2) select id "
        "insert expired events into ex"
    )
    job = run(cql, ids=list(range(10)))
    assert [r[0] for r in job.results("ex")] == [0, 2, 4]


def test_time_window_expired_oracle():
    cql = (
        "from S#window.time(10 ms) select id "
        "insert expired events into ex"
    )
    ts = [1000, 1002, 1004, 1030, 1032]
    job = run(cql, ids=[0, 1, 2, 3, 4], ts=ts, batch=5)
    rows = job.results_with_ts("ex")
    # 0,1,2 expired when stream time reached 1030; 3,4 flush at stream
    # end (time advances past every deadline)
    assert [r[0] for _, r in rows] == [0, 1, 2, 3, 4]
    assert [t for t, _ in rows] == [1010, 1012, 1014, 1040, 1042]


def test_current_events_unchanged():
    cql = "from S#window.length(2) select id insert current events into c"
    job = run(cql, ids=[7, 8, 9])
    assert [r[0] for r in job.results("c")] == [7, 8, 9]


def test_expired_rejects_aggregates_loudly():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "from S#window.length(2) select sum(price) as s "
            "insert expired events into ex",
            {"S": SCHEMA},
        )


def test_expired_rejects_windowless_loudly():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "from S select id insert expired events into ex",
            {"S": SCHEMA},
        )


def test_all_events_emits_current_and_expired():
    """Round-5: 'insert all events into' = arriving AND leaving events
    interleaved into one stream (split into two queries by the
    compiler's rewrite, siddhi-core ALL_EVENTS junction behavior)."""
    cql = (
        "from S#window.length(2) select id, price "
        "insert all events into o"
    )
    job = run(cql, ids=list(range(6)))
    rows = sorted(job.results_with_ts("o"))
    # current: every arrival at its own ts; expired: events 0..3 at
    # their displacing event's ts (1002..1005)
    expect = sorted(
        [(1000 + i, (i, float(i))) for i in range(6)]
        + [(1002 + i, (i, float(i))) for i in range(4)]
    )
    assert rows == expect


def test_time_window_expired_cross_batch_straggler():
    # review finding: a straggler (older ts after newer ones, processing
    # time) must not desync the emit/retain split — it conservatively
    # expires late, and every event still expires exactly once
    cql = (
        "from S#window.time(10 ms) select id "
        "insert expired events into ex"
    )
    ts = [1004, 1012, 1003, 1025, 1040]
    job = run(cql, ids=[0, 1, 2, 3, 4], ts=ts, batch=2)
    rows = job.results_with_ts("ex")
    ids_out = sorted(r[0] for _, r in rows)
    assert ids_out == [0, 1, 2, 3, 4]  # exactly once each


def test_partitioned_expired_rejected_loudly():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "partition with (id of S) begin "
            "from S#window.length(2) select id "
            "insert expired events into ex end",
            {"S": SCHEMA},
        )
