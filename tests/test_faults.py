"""Supervised recovery under induced failures: exactly-once, row-exact.

The contract these tests pin (ISSUE 6 / PAPERS.md #1 Carbone et al.):
whatever the fault schedule does — broker connections dropped
mid-frame, transient broker error codes, corrupt batches on the wire,
the process killed between checkpoints and killed MID-checkpoint —
the supervised pipeline's committed output equals the unfaulted
oracle's output exactly once: no loss, no duplicates, same order
(sorted by time across shards). Every schedule is seeded: a failure
here replays bit-for-bit.
"""

import glob
import json
import pickle

import numpy as np
import pytest

from flink_siddhi_tpu.app.pipeline import PipelineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.connectors.kafka.retry import RetryPolicy
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.kafka import KafkaClient, KafkaSource
from flink_siddhi_tpu.runtime.sources import ListSource
from flink_siddhi_tpu.runtime.supervisor import (
    RestartBudgetExceeded,
    Supervisor,
)

from tests.fake_kafka import FakeBroker
from tests.faults import CrashPlan, FaultSchedule, InjectedCrash, wrap_job

FIELDS = [
    ("id", "int"),
    ("name", "string"),
    ("price", "double"),
    ("timestamp", "long"),
]

# stateful CQL: the window ring and running sums must survive every
# restore for the row-exact claim to hold
CQL = (
    "from S#window.length(6) select id, sum(price) as t, "
    "count() as c insert into out"
)


def _schema():
    return PipelineConfig(
        stream_id="S", fields=FIELDS, cql="", input_path="x",
        output_path="x",
    ).schema()


def _records(n, start=0):
    return [
        json.dumps(
            {
                "id": (start + i) % 4,
                "name": f"n{(start + i) % 3}",
                "price": float(start + i),
                "timestamp": 1000 + 10 * (start + i),
            }
        )
        for i in range(n)
    ]


def _record_tuples(n):
    return [
        ((i % 4), f"n{i % 3}", float(i), 1000 + 10 * i) for i in range(n)
    ]


def _test_retry(seed=0):
    # milliseconds-scale backoff: bounded, deterministic, fast tests
    return RetryPolicy(
        max_attempts=6, base_delay_ms=1.0, max_delay_ms=4.0, seed=seed
    )


def _oracle_rows(n, cql=CQL, batch_size=16):
    """The unfaulted ground truth: a plain single-run job over the
    same logical stream."""
    schema = _schema()
    src = ListSource(
        "S", schema, _record_tuples(n), ts_field="timestamp",
    )
    plan = compile_plan(cql, {"S": schema})
    job = Job([plan], [src], batch_size=batch_size)
    job.run()
    return job.results_with_ts("out")


# -- acceptance: broker flaps + crashes + kill-mid-checkpoint ---------------

@pytest.mark.parametrize("seed", [7, 23])
def test_supervised_kafka_exactly_once_under_fault_schedule(
    tmp_path, seed
):
    """The headline property: a seeded schedule of wire faults
    (drops, mid-frame closes, transient error codes, corrupt batches,
    delays) PLUS injected process deaths — including one mid-
    checkpoint — and the supervised pipeline still emits the oracle's
    rows exactly once, in order."""
    n = 96
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        for start in range(0, n, 16):  # several fetchable batches
            broker.append("t", 0, _records(16, start=start))
        schedule = FaultSchedule(seed, p_fault=0.25)
        broker.fault_hook = schedule
        crash = CrashPlan(at_pulls=(4, 9), at_checkpoints=(2,))
        schema = _schema()

        def factory():
            src = KafkaSource(
                "S", schema, broker.bootstrap, "t",
                ts_field="timestamp",
                client=KafkaClient(
                    broker.host, broker.port, retry=_test_retry(seed)
                ),
            )
            src.close()  # bounded run: drain the topic, then finish
            plan = compile_plan(CQL, {"S": schema})
            job = Job(
                [plan], [src], batch_size=16, retain_results=False
            )
            return wrap_job(job, crash)

        ckpt = str(tmp_path / "ckpt")
        sup = Supervisor(
            factory, ckpt,
            checkpoint_every_cycles=3, keep_checkpoints=3,
            max_restarts=10, restart_window_s=3600.0,
        )
        sup.run()

        assert crash.crashes == 3  # the schedule actually fired
        assert sup.restart_count == 3
        oracle = _oracle_rows(n)
        assert sup.results_with_ts("out") == oracle  # exactly once
        # the mid-checkpoint kill left debris; the next successful
        # save swept it
        assert glob.glob(f"{ckpt}.tmp.*") == []
        # recovery accounting is real, measured numbers
        tel = sup.telemetry.snapshot()
        assert tel["counters"]["faults.crashes"] == 3
        assert tel["counters"]["recovery.checkpoints"] >= 2
        assert tel["histograms"]["recovery.restore_ms"]["count"] >= 1
        assert sup.last_recovery_ms is not None
        h = sup.health()
        assert h["alive"] and h["finished"]
        assert h["restarts"] == 3
        assert h["last_checkpoint_age_s"] is not None
    finally:
        broker.close()


def test_supervised_fused_exactly_once_kill_mid_segment(tmp_path):
    """Fused-dispatch fault path (the scan-of-microbatches streaming
    step): segments of 4 micro-batches, process deaths scheduled at
    pulls that land MID-segment (between a tape being staged and its
    segment dispatching), plus one kill mid-checkpoint. Checkpoints
    land only at segment boundaries — save_checkpoint force-dispatches
    the staged partial segment before capturing state — so restore
    comes from the last segment-boundary checkpoint and the committed
    rows match the unfaulted oracle with 0 duplicate / 0 lost rows."""
    import collections

    n = 96
    schema = _schema()
    # checkpoint cadence (3) deliberately misaligned with the segment
    # length (4): every checkpoint interrupts a filling segment, and
    # pulls 3/7 kill with tapes staged but undispatched
    crash = CrashPlan(at_pulls=(3, 7), at_checkpoints=(2,))

    def factory():
        src = ListSource(
            "S", schema, _record_tuples(n), ts_field="timestamp",
        )
        plan = compile_plan(CQL, {"S": schema})
        job = Job([plan], [src], batch_size=16, retain_results=False)
        job.fused_segment_len = 4
        return wrap_job(job, crash)

    ckpt = str(tmp_path / "ckpt")
    sup = Supervisor(
        factory, ckpt,
        checkpoint_every_cycles=3, keep_checkpoints=3,
        max_restarts=10, restart_window_s=3600.0,
    )
    sup.run()

    assert crash.crashes == 3  # both pull kills + the checkpoint kill
    oracle = collections.Counter(_oracle_rows(n))
    committed = collections.Counter(sup.results_with_ts("out"))
    assert sum((committed - oracle).values()) == 0, "duplicate rows"
    assert sum((oracle - committed).values()) == 0, "lost rows"
    assert glob.glob(f"{ckpt}.tmp.*") == []


@pytest.mark.parametrize("seed", [1, 17])
def test_kafka_source_survives_wire_faults_unsupervised(seed):
    """Retry/backoff alone (no supervisor): a plain job over a flaky
    broker completes with row-exact oracle agreement, and the
    faults.kafka.* counters land in the job's telemetry registry."""
    n = 64
    broker = FakeBroker()
    try:
        broker.create_topic("t")
        for start in range(0, n, 16):
            broker.append("t", 0, _records(16, start=start))
        schedule = FaultSchedule(seed, p_fault=0.3)
        broker.fault_hook = schedule
        schema = _schema()
        src = KafkaSource(
            "S", schema, broker.bootstrap, "t", ts_field="timestamp",
            client=KafkaClient(
                broker.host, broker.port, retry=_test_retry(seed)
            ),
        )
        src.close()
        plan = compile_plan(CQL, {"S": schema})
        job = Job([plan], [src], batch_size=16)
        job.run()
        assert job.results_with_ts("out") == _oracle_rows(n)
        # "delay" serves normally (no retry); any other action forces
        # at least one counted retry (negotiation drops count under
        # faults.kafka.negotiation.retries)
        if any(a != "delay" for _, _, a in schedule.injected):
            counters = job.metrics()["telemetry"]["counters"]
            assert (
                sum(
                    v for k, v in counters.items()
                    if k.startswith("faults.kafka.")
                )
                >= 1
            )
    finally:
        broker.close()


def test_negotiated_dialect_survives_reconnect():
    """A connection drop AFTER successful negotiation must not pin
    anything stale: the reconnect re-runs ApiVersions and lands on
    the modern dialect again (the 'transient outage never pins v0'
    clause, this time for mid-lifetime faults)."""
    from flink_siddhi_tpu.connectors.kafka.protocol import API_FETCH

    broker = FakeBroker()
    try:
        broker.create_topic("t")
        broker.append("t", 0, [b'{"x": 1}'])
        drops = {"armed": False}

        def hook(api, seq):
            if drops["armed"]:
                drops["armed"] = False
                return "drop"
            return None

        broker.fault_hook = hook
        client = KafkaClient(
            broker.host, broker.port, retry=_test_retry()
        )
        assert client.api_versions()[API_FETCH] == 4
        drops["armed"] = True  # next request: connection slammed
        client.fetch("t", {0: 0})  # retried; renegotiates on reconnect
        assert client.negotiated[API_FETCH] == 4  # still modern
        assert client.fault_counts["faults.kafka.reconnects"] >= 1
        client.close()
    finally:
        broker.close()


def test_closed_connection_never_pins_dialect():
    """ANY teardown drops the negotiated versions: a v0 conclusion
    reached on one connection — legitimately (legacy broker) or
    wrongly (every ApiVersions attempt transiently slammed, which is
    indistinguishable) — must not survive onto the next connection.
    Pins the review finding that _close_locked left _versions set for
    clients whose on_retry hook never fired."""
    from flink_siddhi_tpu.connectors.kafka.protocol import API_FETCH

    broker = FakeBroker(legacy=True)
    try:
        broker.create_topic("t")
        broker.append("t", 0, [b'{"x": 1}'])
        client = KafkaClient(
            broker.host, broker.port, retry=_test_retry()
        )
        assert client.api_versions()[API_FETCH] == 0  # v0 concluded
        # the broker upgrades (or the slams were transient all along)
        broker.legacy = False
        client.close()  # teardown => the pinned dialect dies with it
        client.fetch("t", {0: 0})
        assert client.negotiated[API_FETCH] == 4  # renegotiated modern
        client.close()
    finally:
        broker.close()


# -- resident mode ----------------------------------------------------------

def test_supervised_resident_mode_exactly_once(tmp_path):
    """Resident replay under supervision: killed mid-stage and killed
    mid-(final-)checkpoint; the rerun's committed rows equal the
    oracle exactly once (uncommitted output of dead runs discarded)."""
    n = 60
    schema = _schema()
    crash = CrashPlan(at_pulls=(2,), at_checkpoints=(1,))

    def factory():
        src = ListSource(
            "S", schema, _record_tuples(n), ts_field="timestamp",
        )
        plan = compile_plan(CQL, {"S": schema})
        job = Job([plan], [src], batch_size=16, retain_results=False)
        return wrap_job(job, crash)

    sup = Supervisor(
        factory, str(tmp_path / "ckpt"), mode="resident",
        max_restarts=5, restart_window_s=3600.0,
    )
    sup.run()
    assert crash.crashes == 2
    assert sup.results_with_ts("out") == _oracle_rows(n)
    tel = sup.telemetry.snapshot()
    assert tel["counters"]["recovery.rows_discarded"] >= 1


# -- sharded mode -----------------------------------------------------------

def test_supervised_sharded_job_exactly_once(tmp_path):
    """A ShardedJob under supervision: crash -> restore across the
    whole mesh (stacked states, per-shard routers, sources); rows
    match the oracle exactly once (sorted by time: shard drains
    interleave). One kill here — the double-kill/double-restore
    round trip lives in tests/test_checkpoint.py
    (test_sharded_job_double_recovery_roundtrip); each extra mesh
    lifetime costs a full shard_map compile on the CPU lane."""
    from flink_siddhi_tpu.parallel import ShardedJob, make_cep_mesh

    n = 80
    cql = (
        "from S select id, sum(price) as total, count() as c "
        "group by id insert into out"
    )
    schema = _schema()
    # the crash point is deliberately MISALIGNED with the 2-cycle
    # checkpoint cadence: pull 4 dies one full cycle after the cycle-2
    # checkpoint, so cycle 3's events are genuinely replayed — a crash
    # landing exactly on a checkpoint boundary would replay nothing
    # and prove nothing
    crash = CrashPlan(at_pulls=(4,))

    def factory():
        src = ListSource(
            "S", schema, _record_tuples(n), ts_field="timestamp",
            chunk=16,  # events must still flow at the crash cycle
        )
        plan = compile_plan(cql, {"S": schema})
        job = ShardedJob(
            [plan], [src], mesh=make_cep_mesh(4), batch_size=16,
            retain_results=False,
        )
        return wrap_job(job, crash)

    sup = Supervisor(
        factory, str(tmp_path / "ckpt"),
        checkpoint_every_cycles=2, max_restarts=5,
        restart_window_s=3600.0,
    )
    sup.run()
    assert crash.crashes == 1
    oracle = sorted(_oracle_rows(n, cql=cql))
    assert sorted(sup.results_with_ts("out")) == oracle
    assert sup.telemetry.snapshot()["counters"]["faults.crashes"] == 1
    # the recovery restored from a mid-stream checkpoint, not a
    # from-scratch rebuild: events were genuinely replayed
    assert (
        sup.telemetry.snapshot()["counters"]["recovery.events_replayed"]
        > 0
    )


# -- restart budget ---------------------------------------------------------

def test_restart_budget_fails_loudly(tmp_path):
    """A deterministically-crashing job exhausts K restarts per window
    and raises instead of flapping forever; health flips to dead (the
    /api/v1/health 503)."""
    schema = _schema()
    crash = CrashPlan(at_pulls=tuple(range(1, 50)))  # always crash

    def factory():
        src = ListSource(
            "S", schema, _record_tuples(20), ts_field="timestamp",
        )
        plan = compile_plan(CQL, {"S": schema})
        job = Job([plan], [src], batch_size=16, retain_results=False)
        return wrap_job(job, crash)

    sup = Supervisor(
        factory, str(tmp_path / "ckpt"),
        max_restarts=2, restart_window_s=3600.0,
    )
    with pytest.raises(RestartBudgetExceeded) as ei:
        sup.run()
    assert isinstance(ei.value.__cause__, InjectedCrash)
    assert sup.health()["alive"] is False
    assert sup.results("out") == []  # nothing falsely committed


def test_all_generations_unreadable_refuses_loudly(tmp_path):
    """With rows already committed under a checkpoint, losing EVERY
    checkpoint generation must refuse loudly: a silent from-scratch
    rebuild would re-emit the committed rows (at-least-twice), and
    retrying cannot make the files readable — so the error must NOT
    burn the restart budget either."""
    from flink_siddhi_tpu.runtime.supervisor import (
        CheckpointsUnreadableError,
    )

    schema = _schema()
    ckpt = str(tmp_path / "ckpt")
    crash = CrashPlan(at_pulls=(3,))  # after the cycle-2 checkpoint
    builds = {"n": 0}

    def factory():
        builds["n"] += 1
        if builds["n"] == 2:
            # the rebuild after the crash finds every generation
            # destroyed (disk died harder than the process)
            for p in (ckpt, f"{ckpt}.1", f"{ckpt}.2"):
                if glob.glob(p):
                    with open(p, "wb") as f:
                        f.write(b"not a checkpoint")
        src = ListSource(
            "S", schema, _record_tuples(64), ts_field="timestamp",
            chunk=16,
        )
        plan = compile_plan(CQL, {"S": schema})
        job = Job([plan], [src], batch_size=16, retain_results=False)
        return wrap_job(job, crash)

    sup = Supervisor(
        factory, ckpt, checkpoint_every_cycles=2, keep_checkpoints=3,
        max_restarts=5, restart_window_s=3600.0,
    )
    with pytest.raises(CheckpointsUnreadableError, match="refusing"):
        sup.run()
    assert sup.health()["alive"] is False
    # committed rows stay exactly-once: the pre-crash committed prefix,
    # never a re-emitted duplicate
    committed = sup.results_with_ts("out")
    oracle = _oracle_rows(64)
    assert committed == oracle[: len(committed)]
    # the unreadable generations were counted, not silently skipped
    tel = sup.telemetry.snapshot()
    assert tel["counters"]["recovery.bad_checkpoints"] >= 1


def test_health_endpoint(tmp_path):
    """GET /api/v1/health: 200 + liveness fields while alive, 503
    once the restart budget is exhausted."""
    import urllib.error
    import urllib.request

    from flink_siddhi_tpu.app.service import (
        ControlQueueSource,
        QueryControlService,
    )

    schema = _schema()

    def factory():
        src = ListSource(
            "S", schema, _record_tuples(20), ts_field="timestamp",
        )
        plan = compile_plan(CQL, {"S": schema})
        return Job([plan], [src], batch_size=16, retain_results=False)

    sup = Supervisor(factory, str(tmp_path / "ckpt"))
    sup.run()
    control = ControlQueueSource()
    svc = QueryControlService(control, supervisor=sup).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/api/v1/health"
        ) as resp:
            assert resp.status == 200
            doc = json.loads(resp.read())
        assert doc["alive"] is True and doc["finished"] is True
        assert doc["restarts"] == 0
        assert doc["checkpoints"] >= 1
        assert doc["last_checkpoint_age_s"] is not None
        # simulate budget exhaustion: the route must turn 503
        sup._alive = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{svc.port}/api/v1/health"
            )
        assert ei.value.code == 503
    finally:
        svc.stop()
        control.close()


# -- graceful degradation: bounded pending backlog --------------------------

def test_shed_policy_drop_oldest_counts_loudly():
    """Over the pending bound with shed_policy='drop_oldest': the
    oldest batches are shed, the shed counter is loud, and the job
    keeps running instead of growing without bound."""
    schema = _schema()
    # two sources; the second's watermark lags far behind, so the
    # first's events pile up in the reorder buffer unreleasable
    fast = ListSource(
        "S", schema, _record_tuples(64), ts_field="timestamp",
        chunk=16,
    )
    plan = compile_plan(CQL, {"S": schema})
    job = Job([plan], [fast], batch_size=16)
    job.max_pending_events = 20
    job.shed_policy = "drop_oldest"
    # stuff the reorder buffer directly (the unit seam: _pull_sources
    # calls _shed_pending after pulls)
    job.run_cycle()
    from flink_siddhi_tpu.schema.batch import EventBatch

    big = EventBatch.from_records(
        "S", schema, _record_tuples(40),
        timestamps=[10_000 + i for i in range(40)],
    )
    job._pending.setdefault("S", []).append(big)
    assert job._pending_total() > job.max_pending_events
    job._shed_pending()
    assert job._pending_total() <= job.max_pending_events
    assert job.shed_events > 0
    counters = job.metrics()["telemetry"]["counters"]
    assert counters["faults.shed_events"] == job.shed_events


def test_block_policy_single_source_never_deadlocks():
    """'block' backpressure must not deadlock a single-source event-
    time job: the source pinning the min watermark keeps polling (the
    bound is soft for the laggard), so the run completes with oracle-
    exact rows."""
    n = 64
    schema = _schema()
    src = ListSource(
        "S", schema, _record_tuples(n), ts_field="timestamp", chunk=8,
    )
    plan = compile_plan(CQL, {"S": schema})
    job = Job([plan], [src], batch_size=16)
    job.max_pending_events = 4  # absurdly tight: every cycle is over
    job.shed_policy = "block"
    job.run(max_cycles=10_000)
    assert job.finished
    assert job.results_with_ts("out") == _oracle_rows(n)


def test_block_policy_blocks_the_ahead_source():
    """With one source far ahead of the watermark and one lagging
    (open, idle), 'block' stops pulling the ahead source (counted)
    while the laggard keeps polling for a watermark advance."""
    from flink_siddhi_tpu.runtime.sources import CallbackSource

    schema = _schema()
    ahead = ListSource(
        "S", schema, _record_tuples(64), ts_field="timestamp",
        chunk=32,
    )
    lag = CallbackSource("S2", _schema_s2())
    lag.advance_watermark(50)  # far below ahead's timestamps
    plan = compile_plan(CQL, {"S": schema})
    job = Job([plan], [ahead, lag], batch_size=32)
    job.max_pending_events = 8
    job.shed_policy = "block"
    job.run_cycle()  # both pull once; 'ahead' floods pending
    before = job._pending_total()
    assert before > job.max_pending_events  # watermark-held backlog
    job.run_cycle()
    counters = job.metrics()["telemetry"]["counters"]
    assert counters.get("faults.backpressure_blocks", 0) >= 1
    # the ahead source was not pulled while over the bound
    assert job._pending_total() == before
    lag.close()


def _schema_s2():
    cfg = PipelineConfig(
        stream_id="S2", fields=FIELDS, cql="", input_path="x",
        output_path="x",
    )
    return cfg.schema()


# -- degraded source-state markers ------------------------------------------

def test_source_state_degraded_marker_and_counter():
    """A byte source whose tell()/seek() fails must not checkpoint a
    silently-wrong position: the state dict carries degraded=True and
    faults.source_state counts (satellite: sources.py:333/349)."""
    import io

    from flink_siddhi_tpu.runtime.sources import JsonLinesSource
    from flink_siddhi_tpu.telemetry import MetricsRegistry

    class BrokenTell(io.BytesIO):
        def tell(self):
            raise OSError("tell refused")

        def seek(self, *a):
            raise OSError("seek refused")

    data = b'{"id": 1, "name": "a", "price": 2.0, "timestamp": 5}\n'
    src = JsonLinesSource("S", _schema(), BrokenTell(data))
    reg = MetricsRegistry()
    src.bind_telemetry(reg)
    d = src.state_dict()
    assert d["pos"] is None
    assert d["degraded"] is True
    assert reg.snapshot()["counters"]["faults.source_state"] == 1
    # restore through a failing seek: counted again, still degraded
    src2 = JsonLinesSource("S", _schema(), BrokenTell(data))
    src2.bind_telemetry(reg)
    src2.load_state_dict({"pos": 10, "arrival": 0, "done": False})
    assert reg.snapshot()["counters"]["faults.source_state"] == 2
    # capturing state through the still-broken tell counts AGAIN —
    # every failed capture is a fault occurrence, not a latched flag
    assert src2.state_dict()["degraded"] is True
    assert reg.snapshot()["counters"]["faults.source_state"] == 3
    # a healthy seekable source stays undegraded end to end
    src3 = JsonLinesSource("S", _schema(), io.BytesIO(data))
    assert "degraded" not in src3.state_dict()


# -- retry policy unit contracts --------------------------------------------

def test_retry_policy_deterministic_and_bounded():
    p = RetryPolicy(max_attempts=4, base_delay_ms=10.0, seed=42)
    seq = [next(iter([d])) for d, _ in zip(p.delays_ms(), range(6))]
    seq2 = [d for d, _ in zip(p.delays_ms(), range(6))]
    assert seq == seq2  # seeded jitter: identical replay
    calls = {"n": 0}
    slept = []

    class Boom(RuntimeError):
        retryable = True

    def fn():
        calls["n"] += 1
        raise Boom("x")

    with pytest.raises(Boom) as ei:
        p.call(fn, classify=lambda e: True, sleep=slept.append)
    assert calls["n"] == 4  # bounded attempts
    assert len(slept) == 3
    assert ei.value.retry_attempts == 4


def test_retry_policy_deadline_preempts_backoff():
    p = RetryPolicy(
        max_attempts=100, base_delay_ms=50.0, deadline_ms=100.0,
        jitter=0.0, seed=1,
    )
    clock = {"t": 0.0}

    def fake_sleep(s):
        clock["t"] += s

    calls = {"n": 0}

    class Boom(RuntimeError):
        pass

    def fn():
        calls["n"] += 1
        raise Boom("x")

    with pytest.raises(Boom):
        p.call(
            fn, classify=lambda e: True, sleep=fake_sleep,
            clock=lambda: clock["t"],
        )
    # 100ms budget, 50ms backoffs: ~3 attempts, never dozens
    assert calls["n"] <= 3


def test_retry_policy_fatal_is_immediate():
    p = RetryPolicy(max_attempts=5, base_delay_ms=1.0)
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise ValueError("fatal")

    with pytest.raises(ValueError):
        p.call(fn, classify=lambda e: False)
    assert calls["n"] == 1


def test_error_taxonomy():
    from flink_siddhi_tpu.connectors.kafka.errors import (
        BrokerClosedError,
        BrokerErrorResponse,
        BrokerIOError,
        is_connection_error,
        is_retryable,
    )
    from flink_siddhi_tpu.connectors.kafka.records import (
        CorruptBatchError,
    )
    from flink_siddhi_tpu.connectors.kafka.protocol import ProtocolError

    assert is_retryable(BrokerClosedError("x"))
    assert is_retryable(BrokerIOError("x"))
    assert is_retryable(CorruptBatchError("x"))
    assert is_retryable(BrokerErrorResponse("x", code=6))  # NOT_LEADER
    assert not is_retryable(BrokerErrorResponse("x", code=1))  # OOR
    assert not is_retryable(ProtocolError("x"))
    assert not is_retryable(ValueError("x"))
    assert is_connection_error(BrokerIOError("x"))
    assert not is_connection_error(BrokerErrorResponse("x", code=6))
    # KIP-98 transaction codes: fencing is FATAL (a newer incarnation
    # owns the id — retrying forever would mask a split-brain), a
    # coordinator mid-transition is retryable, sequence/state/mapping
    # violations are fatal correctness signals
    from flink_siddhi_tpu.connectors.kafka.errors import (
        ProducerFencedError,
        broker_error,
    )

    fenced = broker_error("x", 47, "produce")
    assert isinstance(fenced, ProducerFencedError)
    assert not is_retryable(fenced)
    assert is_retryable(broker_error("x", 51))  # CONCURRENT_TXNS
    assert not is_retryable(broker_error("x", 45))  # OUT_OF_ORDER_SEQ
    assert not is_retryable(broker_error("x", 48))  # INVALID_TXN_STATE
    assert not is_retryable(broker_error("x", 49))  # INVALID_PID_MAPPING


# -- checkpoint safelist (the loud-rejection satellite rides here too) ------

def test_checkpoint_load_rejects_arbitrary_classes(tmp_path):
    """A pickled arbitrary class must be rejected LOUDLY by the
    safelisting unpickler, never instantiated."""
    import io as _io

    from flink_siddhi_tpu.runtime import checkpoint as ckpt_mod

    class Evil:
        def __reduce__(self):
            return (print, ("pwned",))

    blob = pickle.dumps({"version": 1, "payload": Evil()})
    with pytest.raises(pickle.UnpicklingError, match="safelist"):
        ckpt_mod.safe_load_snapshot(_io.BytesIO(blob))
    # numpy + containers still load fine
    ok = pickle.dumps(
        {"a": np.arange(3), "b": np.float64(1.5), "c": [(1, "x")]}
    )
    out = ckpt_mod.safe_load_snapshot(_io.BytesIO(ok))
    assert out["b"] == 1.5 and list(out["a"]) == [0, 1, 2]


# -- kill zoo: transactional sink (kill-mid-transaction, zombies) -----------

# Two crash plans against the transactional KafkaSink; both include a
# kill-mid-CHECKPOINT (a doomed prepared transaction restore must
# abort) and a kill-mid-TRANSACTION (after the snapshot committed,
# before EndTxn — restore must RESUME the commit):
#   * plan A dies at commit 1 (second epoch already checkpointed on a
#     later run), so the resumed supervisor replays the resume-commit
#     path twice across restarts — the second resume hits the broker's
#     INVALID_TXN_STATE "already committed" answer and tolerates it;
#   * plan B dies at commit 2 AND at pull 3 before the first
#     checkpoint ever lands — the scratch rebuild re-runs
#     InitProducerId with no snapshot, fencing the dead run's epoch
#     and aborting its data-bearing orphan.
_TXN_PLANS = [
    ("resume-commit", dict(at_pulls=(4,), at_checkpoints=(2,), at_commits=(1,))),
    ("scratch-zombie", dict(at_pulls=(3,), at_checkpoints=(2,), at_commits=(2,))),
]


@pytest.mark.parametrize(
    "plan_kw", [kw for _, kw in _TXN_PLANS],
    ids=[name for name, _ in _TXN_PLANS],
)
def test_supervised_transactional_sink_exactly_once(tmp_path, plan_kw):
    """The tentpole acceptance: process deaths mid-checkpoint, mid-
    transaction (between the durable snapshot and EndTxn), and between
    checkpoints — and the EXTERNAL read-committed topic still equals
    the unfaulted oracle with zero duplicates and zero losses, while
    read_uncommitted sees the aborted debris the dead runs left."""
    from flink_siddhi_tpu.runtime.kafka import KafkaSink
    from tests.fake_kafka import read_topic

    n = 96
    broker = FakeBroker()
    try:
        broker.create_topic("out")
        schema = _schema()
        crash = CrashPlan(**plan_kw)

        def factory():
            src = ListSource(
                "S", schema, _record_tuples(n), ts_field="timestamp",
            )
            job = Job(
                [compile_plan(CQL, {"S": schema})], [src],
                batch_size=16, retain_results=False,
            )
            job.add_sink(
                "out",
                KafkaSink(
                    broker.bootstrap, "out", ["id", "t", "c"],
                    stream_id="out", transactional_id="tx",
                    flush_every=8,
                ),
            )
            return wrap_job(job, crash)

        sup = Supervisor(
            factory, str(tmp_path / "ckpt"),
            checkpoint_every_cycles=3, keep_checkpoints=3,
            max_restarts=10, restart_window_s=3600.0,
        )
        sup.run()
        # every scheduled death actually fired
        assert crash.crashes == sum(
            len(plan_kw[k]) for k in plan_kw
        )
        # internal account matches the oracle (the old contract) ...
        oracle = _oracle_rows(n)
        assert sup.results_with_ts("out") == oracle

        # ... and so does the EXTERNAL read-committed topic: the new
        # contract. Multisets of full rows — order within the topic is
        # append order, so compare content-exactly, not sequence.
        import collections

        expect = collections.Counter(
            (ts, row[0], row[1], row[2]) for ts, row in oracle
        )
        rc = [
            json.loads(v)
            for v in read_topic(broker.bootstrap, "out", committed=True)
        ]
        got = collections.Counter(
            (d["ts"], d["id"], d["t"], d["c"]) for d in rc
        )
        assert sum((got - expect).values()) == 0  # duplicates
        assert sum((expect - got).values()) == 0  # losses
        # the dead runs really wrote into transactions that were then
        # aborted: read_uncommitted must see strictly more rows
        ru = read_topic(broker.bootstrap, "out", committed=False)
        assert len(ru) > len(rc)
        # checkpoint debris swept (same invariant as the plain zoo)
        assert glob.glob(str(tmp_path / "ckpt" / "*.tmp.*")) == []
        # observability: health names the sink's transactional state,
        # the journal carries the txn lifecycle
        h = sup.health()
        (txs,) = h["transactional_sinks"]
        assert txs["stream"] == "out"
        assert txs["transactional_id"] == "tx"
        assert txs["commits"] >= 1 and txs["pending"] is False
        kinds = sup.job.flightrec.counts_by_kind()
        assert any(k.startswith("txn.") for k in kinds)
    finally:
        broker.close()


def test_zombie_producer_fenced_and_rows_invisible():
    """Split-brain: a paused incarnation keeps producing while a
    restarted one re-initialises the same transactional id. The
    broker's epoch fence turns the zombie's next produce into a FATAL
    ProducerFencedError, its open transaction is aborted, and none of
    its rows ever reach a read-committed consumer."""
    from flink_siddhi_tpu.connectors.kafka.errors import (
        ProducerFencedError,
    )
    from flink_siddhi_tpu.runtime.kafka import KafkaSink
    from tests.fake_kafka import read_topic

    broker = FakeBroker()
    try:
        broker.create_topic("out")
        old = KafkaSink(
            broker.bootstrap, "out", ["id"], stream_id="out",
            transactional_id="tx", flush_every=1,
        )
        old(1000, [1])  # opens epoch-0's transaction, row in flight
        # the "restart": a new incarnation adopts the checkpointed
        # state (here: the pristine one) and eagerly re-fences
        new = KafkaSink(
            broker.bootstrap, "out", ["id"], stream_id="out",
            transactional_id="tx", flush_every=1,
        )
        new.load_state_dict({"epoch_n": 0, "produced": 0})
        # the zombie's next emit dies on the fence, permanently
        with pytest.raises(ProducerFencedError):
            old(1010, [2])
        assert old.txn_stats()["fenced"] >= 1
        # the survivor commits its epoch; only ITS row is visible
        new(1020, [3])
        new.prepare_commit()
        new.commit_transaction()
        rc = [
            json.loads(v)
            for v in read_topic(broker.bootstrap, "out", committed=True)
        ]
        assert [(d["ts"], d["id"]) for d in rc] == [(1020, 3)]
        # the zombie's orphan really reached the log — aborted, not
        # lost in the client: read_uncommitted shows it
        ru = [
            json.loads(v)
            for v in read_topic(broker.bootstrap, "out", committed=False)
        ]
        assert (1000, 1) in [(d["ts"], d["id"]) for d in ru]
        new.close()
        old.close()
    finally:
        broker.close()
