"""End-to-end: stateless filter/projection queries on the device path.

Mirrors the reference's simple integration cases
(SiddhiCEPITCase.java:160-179 filter/select round-trips, :280-300 union,
:394-410 custom extension) against the compiled micro-batch engine.
"""

import dataclasses

import pytest

from flink_siddhi_tpu import SiddhiCEP, CEPEnvironment


@dataclasses.dataclass
class Event:
    id: int
    name: str
    price: float
    timestamp: int


def make_events(n, start_ts=1000):
    # deterministic timestamps like RandomEventSource.java:55-64
    return [
        Event(i % 4, f"name_{i % 3}", float(i), start_ts + 1000 * i)
        for i in range(n)
    ]


FIELDS = ["id", "name", "price", "timestamp"]


def test_select_projection():
    events = make_events(5)
    out = (
        SiddhiCEP.define("inputStream", events, FIELDS)
        .cql(
            "from inputStream select timestamp, id, name, price "
            "insert into  outputStream"
        )
        .returns("outputStream")
    )
    assert len(out) == 5
    assert out[0] == (1000, 0, "name_0", 0.0)
    assert out[3] == (4000, 3, "name_0", 3.0)


def test_filter_query():
    events = make_events(20)
    out = (
        SiddhiCEP.define("inputStream", events, FIELDS)
        .cql(
            "from inputStream[id == 2] select name, id insert into out"
        )
        .returns("out")
    )
    assert len(out) == 5  # ids cycle 0..3 over 20 events
    assert all(row[1] == 2 for row in out)
    assert out[0][0] == "name_2"


def test_compound_filter_arithmetic():
    events = make_events(20)
    out = (
        SiddhiCEP.define("inputStream", events, FIELDS)
        .cql(
            "from inputStream[id == 2 and price > 5.0] "
            "select price * 2.0 as doubled, name insert into out"
        )
        .returns("out")
    )
    expected = [
        (e.price * 2.0, e.name)
        for e in events
        if e.id == 2 and e.price > 5.0
    ]
    assert out == expected


def test_string_equality_filter():
    events = make_events(9)
    out = (
        SiddhiCEP.define("inputStream", events, FIELDS)
        .cql(
            "from inputStream[name == 'name_1'] select id insert into out"
        )
        .returns("out")
    )
    assert len(out) == 3


def test_select_star():
    events = make_events(4)
    out = (
        SiddhiCEP.define("inputStream", events, FIELDS)
        .cql("from inputStream insert into  outputStream")
        .returns("outputStream")
    )
    assert len(out) == 4
    assert out[0] == (0, "name_0", 0.0, 1000)  # schema field order


def test_union_multiple_streams():
    # SiddhiCEPITCase.java:280-300 — three streams into one output
    env = CEPEnvironment()
    s = (
        SiddhiCEP.define(
            "inputStream1", make_events(3), FIELDS, env=env
        )
        .union("inputStream2", make_events(4, start_ts=1500), FIELDS)
        .union("inputStream3", make_events(5, start_ts=1700), FIELDS)
    )
    out = s.cql(
        "from inputStream1 select timestamp, id, name, price insert into "
        "outputStream;"
        "from inputStream2 select timestamp, id, name, price insert into "
        "outputStream;"
        "from inputStream3 select timestamp, id, name, price insert into "
        "outputStream;"
    ).returns("outputStream")
    assert len(out) == 12


def test_return_as_map_and_row_and_pojo():
    events = make_events(3)
    es = SiddhiCEP.define("inputStream", events, FIELDS).cql(
        "from inputStream select id, name insert into out"
    )
    maps = es.return_as_map("out")
    assert maps[0] == {"id": 0, "name": "name_0"}
    rows = es.return_as_row("out")
    assert list(rows[1]) == [1, "name_1"]

    @dataclasses.dataclass
    class OutEvent:
        id: int
        name: str

    pojos = es.returns_pojo("out", OutEvent)
    assert pojos[2] == OutEvent(2, "name_2")


def test_custom_extension():
    # SiddhiCEPITCase.java:394-410 + CustomPlusFunctionExtension
    env = CEPEnvironment()
    env.register_extension("custom:plus", lambda a, b: a + b)
    out = (
        SiddhiCEP.define(
            "inputStream", make_events(4), FIELDS, env=env
        )
        .cql(
            "from inputStream select timestamp, id, name, "
            "custom:plus(price,price) as doubled_price insert into  "
            "outputStream"
        )
        .returns("outputStream")
    )
    assert [r[3] for r in out] == [0.0, 2.0, 4.0, 6.0]


def test_undefined_stream_fails():
    # SiddhiCEPITCase.java:441-463
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    with pytest.raises(SiddhiQLError):
        SiddhiCEP.define("inputStream", make_events(2), FIELDS).cql(
            "from unknownStream select id insert into out"
        )


def test_types_explicit_registration():
    env = CEPEnvironment()
    env.register_stream(
        "s",
        [(1, "a"), (2, "b")],
        fields=["id", "tag"],
        types=["int", "string"],
        ts_field=None,
    )
    from flink_siddhi_tpu.api.stream import SingleStream

    out = SingleStream(env, "s").cql(
        "from s[id == 2] select tag insert into o"
    ).returns("o")
    assert out == [("b",)]


def test_duplicate_stream_rejected():
    from flink_siddhi_tpu.api.cep import DuplicatedStreamError

    env = CEPEnvironment()
    env.register_stream("s", [(1,)], fields=["x"], types=["int"])
    with pytest.raises(DuplicatedStreamError):
        env.register_stream("s", [(2,)], fields=["x"], types=["int"])


def test_engine_config_caps_are_per_plan():
    """VERDICT round-1 #9: engine capacities are per-plan config, not
    module constants."""
    import numpy as np

    from flink_siddhi_tpu.compiler.config import EngineConfig
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    cfg = EngineConfig(pattern_pool=32, table_capacity=16)
    cql = """
define table T (id int);
from S select id insert into T;
from every s1 = S[id == 1] -> s2 = S[id == 2]
  select s1.timestamp as t1, s2.timestamp as t2 insert into o;
"""
    plan = compile_plan(cql, {"S": schema}, config=cfg)
    states = plan.init_state()
    # chain pool sized by config
    pat = [a for a in plan.artifacts if hasattr(a, "pool")][0]
    assert pat.pool == 32
    assert states[pat.name]["active"].shape == (32,)
    # table ring sized by config
    assert states["@tables"]["T"]["valid"].shape == (16,)

    ids = np.array([1, 2], np.int32)
    ts = np.array([1000, 1001], np.int64)
    job = Job(
        [plan],
        [BatchSource("S", schema, iter([EventBatch(
            "S", schema, {"id": ids, "timestamp": ts}, ts
        )]))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    assert job.results("o") == [(1000, 1001)]
