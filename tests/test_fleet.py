"""Serving fleet (flink_siddhi_tpu/fleet/, docs/fleet.md): the
persistent warm-start compile store, the commit-log exactly-once
account, the key-hash router, and the rolling-restart protocol.

The two headline properties pinned here:

* **cross-process zero-lowering warm start** — a store written by
  process A lets process B restore a 20-tenant fleet and serve rows
  with ``metrics()["compiles"]["total_lowerings"] == 0``, and the two
  processes agree byte-for-byte on every store key (the PR 11
  fresh-subprocess signature property extended to the disk tier);
* **rolling restart exactness** — replacing a replica under sustained
  load keeps every admitted tenant live and keeps the committed output
  row-exact against an unfaulted in-process oracle (0 duplicated,
  0 lost), with the handoff journaled.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from flink_siddhi_tpu.app.service import ControlQueueSource
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control import AdmissionGate, ControlPlane
from flink_siddhi_tpu.fleet.commitlog import (
    CommitLogSink,
    read_committed,
)
from flink_siddhi_tpu.fleet.router import (
    FleetRouter,
    hash_route,
    label_prometheus,
)
from flink_siddhi_tpu.fleet.warmstore import (
    WarmStartStore,
    aval_signature,
    store_key_dir,
    store_namespace,
)
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import CallbackSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = StreamSchema([
    ("id", AttributeType.INT),
    ("price", AttributeType.DOUBLE),
    ("timestamp", AttributeType.LONG),
])


def compiler(cql, pid):
    return compile_plan(cql, {"S": SCHEMA}, plan_id=pid)


def chain_cql(a, b):
    return (
        f"from every s1 = S[id == {a}] -> s2 = S[id == {b}] "
        "within 60 sec "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into out"
    )


class Rec:
    def __init__(self, id, price, timestamp):
        self.id, self.price, self.timestamp = id, price, timestamp


# -- the warm store: keys, signatures, fallback ------------------------------


def test_store_key_dir_is_deterministic_and_fs_safe():
    plan = compiler(chain_cql(0, 1), "q0")
    from flink_siddhi_tpu.control.aotcache import cache_key

    key = cache_key(plan)
    assert key is not None
    d1, d2 = store_key_dir(key), store_key_dir(key)
    assert d1 == d2
    assert "/" not in d1 and d1.startswith(key[0] + "-")
    ns = store_namespace()
    assert "/" not in ns and " " not in ns
    # the namespace pins platform + device population + jax version:
    # an executable serialized for another world must not be offered
    import jax

    assert str(jax.device_count()) in ns or f"n{jax.device_count()}" \
        in ns


def test_aval_signature_splits_on_shape_and_dtype():
    import numpy as np

    a = {"x": np.zeros((4, 2), np.float32)}
    b = {"x": np.zeros((4, 2), np.float32)}
    c = {"x": np.zeros((4, 3), np.float32)}
    d = {"x": np.zeros((4, 2), np.int32)}
    assert aval_signature((a,)) == aval_signature((b,))
    assert aval_signature((a,)) != aval_signature((c,))
    assert aval_signature((a,)) != aval_signature((d,))


def test_warm_slot_falls_back_to_wrapper_on_broken_executable(
    tmp_path,
):
    """A deserialized executable that rejects its inputs must degrade
    to the live jit wrapper (counted as a store error), never poison
    results."""
    from flink_siddhi_tpu.fleet.warmstore import WarmSlot

    store = WarmStartStore(str(tmp_path))
    calls = []

    def wrapper(x):
        calls.append(x)
        return x + 1

    class Broken:
        def __call__(self, *a):
            raise TypeError("wrong aval")

    slot = WarmSlot(wrapper, store, ("dyn", "sig"), "jitted")
    sig = aval_signature((3,))
    slot.adopt(sig, Broken())
    assert slot(3) == 4
    assert calls == [3]
    assert store.stats()["errors"] == 1


# -- store eviction: size-bounded LRU + corrupt-entry sweep ------------------


def _seed_store_entry(store, name, sig, nbytes, age_s):
    """Fabricate an on-disk store entry (valid pickled triple) whose
    newest-file mtime is ``age_s`` seconds in the past."""
    import pickle

    kd = os.path.join(store._dir, name)
    os.makedirs(kd, exist_ok=True)
    path = os.path.join(kd, f"jitted@{sig}.exe")
    with open(path, "wb") as f:
        pickle.dump((b"x" * nbytes, None, None), f)
    t = time.time() - age_s
    os.utime(path, (t, t))
    return path


def test_warm_store_gc_evicts_lru_under_byte_budget(tmp_path):
    store = WarmStartStore(str(tmp_path))
    _seed_store_entry(store, "k-old", "s1", 1000, 300)
    _seed_store_entry(store, "k-mid", "s1", 1000, 200)
    _seed_store_entry(store, "k-new", "s1", 1000, 100)
    out = store.gc(max_bytes=2500)
    assert out["evicted"] == 1 and out["corrupt_removed"] == 0
    assert sorted(os.listdir(store._dir)) == ["k-mid", "k-new"]
    assert out["bytes"] <= 2500
    assert store.stats()["evictions"] == 1
    # idempotent: already under budget → nothing further
    assert store.gc(max_bytes=2500)["evicted"] == 0


def test_warm_store_gc_entry_count_bound(tmp_path):
    store = WarmStartStore(str(tmp_path))
    for i, age in enumerate((400, 300, 200, 100)):
        _seed_store_entry(store, f"k-{i}", "s1", 10, age)
    out = store.gc(max_entries=2)
    assert out["evicted"] == 2 and out["kept"] == 2
    assert sorted(os.listdir(store._dir)) == ["k-2", "k-3"]


def test_warm_store_gc_sweeps_corrupt_and_torn_entries(tmp_path):
    """Unreadable ``.exe`` payloads and leftover ``.tmp-<pid>`` files
    are removed regardless of budget; an emptied key dir disappears;
    every removal is counted and journaled with a reason."""
    from flink_siddhi_tpu.telemetry.flightrec import FlightRecorder

    store = WarmStartStore(str(tmp_path))
    frec = FlightRecorder()
    store.bind_flightrec(frec)
    keep = _seed_store_entry(store, "k-good", "s1", 100, 100)
    bad = os.path.join(store._dir, "k-bad")
    os.makedirs(bad)
    with open(os.path.join(bad, "jitted@sX.exe"), "wb") as f:
        f.write(b"\x00not-a-pickle")
    with open(keep + ".tmp-99999", "wb") as f:
        f.write(b"torn write")
    out = store.gc()  # no budget: sweep only
    assert out["evicted"] == 0 and out["corrupt_removed"] == 2
    assert sorted(os.listdir(store._dir)) == ["k-good"]
    assert store.stats()["evictions"] == 2
    evs = [e for e in frec.events() if e["kind"] == "fleet.warm_evict"]
    assert len(evs) == 2
    assert {e["reason"] for e in evs} == {"corrupt"}


def test_warm_store_gc_lru_eviction_is_journaled(tmp_path):
    from flink_siddhi_tpu.telemetry.flightrec import FlightRecorder

    store = WarmStartStore(str(tmp_path))
    frec = FlightRecorder()
    store.bind_flightrec(frec)
    _seed_store_entry(store, "k-a", "s1", 500, 200)
    _seed_store_entry(store, "k-b", "s1", 500, 100)
    store.gc(max_entries=1)
    evs = [e for e in frec.events() if e["kind"] == "fleet.warm_evict"]
    assert len(evs) == 1
    assert evs[0]["reason"] == "lru" and evs[0]["entry"] == "k-a"
    assert evs[0]["bytes"] > 0


def test_warm_store_gc_evicted_key_recompiles_as_cold_miss(tmp_path):
    """The never-wrong contract: after eviction a lookup is an ordinary
    miss — the slot compiles live and re-persists, results unchanged."""
    import jax

    from flink_siddhi_tpu.fleet.warmstore import WarmSlot

    store = WarmStartStore(str(tmp_path))
    wrapper = jax.jit(lambda x: x + 1)
    slot = WarmSlot(wrapper, store, ("dyn", "sig-gc"), "jitted")
    assert slot(3) == 4  # cold miss, compiles via wrapper
    out = store.gc(max_entries=0)
    assert store.stats()["evictions"] == out["evicted"]
    slot2 = WarmSlot(wrapper, store, ("dyn", "sig-gc"), "jitted")
    assert slot2(3) == 4
    assert store.stats()["misses"] >= 2  # second cold miss, not a hit


# -- the commit log: two-phase exactness across handoffs ---------------------


def test_commitlog_two_phase_commit_and_read_back(tmp_path):
    path = str(tmp_path / "commit.log")
    sink = CommitLogSink(path, "out")
    sink(1000, (1, 2))
    sink(1001, (3, 4))
    assert sink.next_epoch() == 0
    sink.prepare_commit()
    assert sink.next_epoch() == 0  # pending epoch, not yet advanced
    sink.commit_transaction()
    assert sink.next_epoch() == 1
    sink(1002, (5, 6))
    sink.prepare_commit()
    sink.commit_transaction()
    rows = read_committed(path, "out")
    assert rows == [(1000, (1, 2)), (1001, (3, 4)), (1002, (5, 6))]
    st = sink.txn_stats()
    assert st["commits"] == 2 and st["committed_rows"] == 3


def test_commitlog_resume_is_exactly_once_both_crash_windows(
    tmp_path,
):
    """Crash between snapshot and append → the successor appends the
    promised epoch (zero lost). Crash after the append → the successor
    finds the epoch present and skips (zero duplicated). Either way
    the lineage row counter includes the epoch."""
    path = str(tmp_path / "commit.log")
    sink = CommitLogSink(path, "out")
    sink(1000, (1, 2))
    sink.prepare_commit()
    snap = sink.state_dict()  # the snapshot that rode the checkpoint
    # window 1: crash BEFORE the append — log is empty
    successor = CommitLogSink(path, "out")
    successor.load_state_dict(snap)
    assert read_committed(path, "out") == [(1000, (1, 2))]
    assert successor.committed_rows == 1
    assert successor.resumed == 1
    assert successor.next_epoch() == 1
    # window 2: crash AFTER the append — same snapshot, epoch now in
    # the log: the resume must NOT append again
    successor2 = CommitLogSink(path, "out")
    successor2.load_state_dict(snap)
    assert read_committed(path, "out") == [(1000, (1, 2))]
    assert successor2.committed_rows == 1
    assert successor2.next_epoch() == 1


def test_commitlog_abort_discards_uncommitted_only(tmp_path):
    path = str(tmp_path / "commit.log")
    sink = CommitLogSink(path, "out")
    sink(1000, (1, 2))
    sink.prepare_commit()
    sink.commit_transaction()
    sink(2000, (9, 9))
    sink.abort_transaction()
    assert read_committed(path, "out") == [(1000, (1, 2))]


def test_read_committed_skips_torn_tail_line(tmp_path):
    path = str(tmp_path / "commit.log")
    sink = CommitLogSink(path, "out")
    sink(1000, (1, 2))
    sink.prepare_commit()
    sink.commit_transaction()
    with open(path, "a") as f:
        f.write('{"epoch": 1, "streams": {"out": [[2, [')  # torn
    assert read_committed(path, "out") == [(1000, (1, 2))]


# -- the router: hashing, label injection ------------------------------------


def test_hash_route_is_deterministic_and_covers_slots():
    assert hash_route("k", 4) == hash_route("k", 4)
    assert hash_route(b"k", 4) == hash_route("k", 4)
    hits = {hash_route(str(i), 4) for i in range(64)}
    assert hits == {0, 1, 2, 3}
    assert all(0 <= hash_route(str(i), 3) < 3 for i in range(32))


def test_hash_route_matches_sha256_spec():
    import hashlib

    want = int.from_bytes(
        hashlib.sha256(b"42").digest()[:8], "big"
    ) % 5
    assert hash_route("42", 5) == want


def test_label_prometheus_injects_replica_label():
    text = (
        "# HELP fst_x c\n"
        "# TYPE fst_x counter\n"
        "fst_x_total 3\n"
        'fst_y{a="b"} 1 17\n'
        "other_metric 9\n"
    )
    out = label_prometheus(text, "r0")
    assert 'fst_x_total{replica="r0"} 3' in out
    assert 'fst_y{a="b",replica="r0"} 1 17' in out
    assert "other_metric 9" in out  # non-fst lines pass through
    assert "# HELP fst_x c" in out


# -- fleet status surfaces ---------------------------------------------------


def _make_job(src, ctrl, store=None):
    job = Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[ctrl], plan_compiler=compiler,
    )
    if store is not None:
        job.bind_warm_store(store)
    return job


def test_fleet_block_absent_outside_a_fleet():
    """Single-process jobs keep their payloads unchanged: no store, no
    replica identity → fleet is None everywhere it is surfaced."""
    src, ctrl = CallbackSource("S", SCHEMA), ControlQueueSource()
    job = _make_job(src, ctrl)
    assert job.fleet_status() is None
    assert job.metrics()["fleet"] is None
    assert "fst_fleet_" not in job.openmetrics()


def test_fleet_status_and_openmetrics_inside_a_fleet(tmp_path):
    src, ctrl = CallbackSource("S", SCHEMA), ControlQueueSource()
    store = WarmStartStore(str(tmp_path / "store"))
    job = _make_job(src, ctrl, store)
    job.set_replica_info("r7", boot={"warm_store": True})
    plane = ControlPlane(job, ctrl, gate=AdmissionGate(compiler))
    plane.admit(chain_cql(0, 1), plan_id="q0", tenant="t0")
    for i in range(6):
        src.emit(Rec(i % 2, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    job.persist_warm()
    st = job.fleet_status()
    assert st["replica"] == "r7" and st["role"] == "replica"
    assert st["warm_store"]["persists"] >= 1
    assert st["boot"]["warm_store"] is True
    text = job.openmetrics()
    assert 'fst_fleet_replica_info{replica="r7"' in text
    assert "fst_fleet_warm_store_persists_total" in text
    # the store events were journaled with plan scope
    kinds = {e["kind"] for e in job.flightrec.events()}
    assert "fleet.persist" in kinds
    assert "fleet.warm_miss" in kinds
    job.record_handoff(reason="test")
    assert any(
        e["kind"] == "fleet.handoff" for e in job.flightrec.events()
    )
    assert job.fleet_status()["last_handoff"]["reason"] == "test"


def test_fleet_epoch_and_handoff_ride_the_checkpoint(tmp_path):
    src, ctrl = CallbackSource("S", SCHEMA), ControlQueueSource()
    job = _make_job(src, ctrl)
    job.set_replica_info("r1")
    job._fleet_epoch = 7
    job.record_handoff(reason="drain")
    ckpt = str(tmp_path / "ckpt")
    job.save_checkpoint(ckpt)
    src2, ctrl2 = CallbackSource("S", SCHEMA), ControlQueueSource()
    job2 = _make_job(src2, ctrl2)
    job2.restore(ckpt)
    assert job2._fleet_epoch == 7
    assert job2._last_handoff["reason"] == "drain"


def test_standalone_dynamic_plan_restores_warm_from_store(tmp_path):
    """Regression: a NON-chain dynamic tenant (filter/select — no
    DynamicChainGroup wrap, so it replays through _replay_dynamic's
    standalone branch, not the group loop) must stay cacheable across
    restore: the original admit created it cacheable, and a replica
    bootstrap can only warm it from the persistent store if the replay
    does too. Before the fix the standalone branch replayed via plain
    add_plan (cacheable=False) and the warm store was silently skipped
    for every non-chain tenant."""
    store_dir = str(tmp_path / "store")
    src, ctrl = CallbackSource("S", SCHEMA), ControlQueueSource()
    job = _make_job(src, ctrl, WarmStartStore(store_dir))
    plane = ControlPlane(job, ctrl, gate=AdmissionGate(compiler))
    plane.admit(
        "from S[id == 0] select id, price insert into out",
        plan_id="flt0", tenant="t0",
    )
    for i in range(8):
        src.emit(Rec(i % 2, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    job.persist_warm()
    assert job.warm_store.stats()["persists"] >= 1
    ckpt = str(tmp_path / "ckpt")
    job.save_checkpoint(ckpt)

    src2, ctrl2 = CallbackSource("S", SCHEMA), ControlQueueSource()
    store2 = WarmStartStore(store_dir)
    job2 = _make_job(src2, ctrl2, store2)
    job2.restore(ckpt)
    rt = job2._plans["flt0"]
    assert rt.warm_key is not None  # replayed cacheable → store-wrapped
    # the preload walked the executables process A persisted
    assert store2.stats()["hits"] >= 1
    for i in range(8):
        src2.emit(Rec(i % 2, float(i), 2000 + i), 2000 + i)
    job2.run_cycle()
    job2.drain_outputs()
    assert store2.stats()["misses"] == 0
    assert len(job2.results("out")) > 0


# -- the headline: cross-process zero-lowering warm start --------------------


_AB_SCRIPT = r"""
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})

from flink_siddhi_tpu.app.service import ControlQueueSource
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control import AdmissionGate, ControlPlane
from flink_siddhi_tpu.control.aotcache import cache_key
from flink_siddhi_tpu.fleet.warmstore import (
    WarmStartStore, store_key_dir, store_namespace,
)
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import CallbackSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema([
    ("id", AttributeType.INT),
    ("price", AttributeType.DOUBLE),
    ("timestamp", AttributeType.LONG),
])

def compiler(cql, pid):
    return compile_plan(cql, {{"S": SCHEMA}}, plan_id=pid)

def chain_cql(a, b):
    return (
        f"from every s1 = S[id == {{a}}] -> s2 = S[id == {{b}}] "
        "within 60 sec select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into out"
    )

class Rec:
    def __init__(self, id, price, timestamp):
        self.id, self.price, self.timestamp = id, price, timestamp

store_dir, ckpt, mode = sys.argv[1], sys.argv[2], sys.argv[3]
src = CallbackSource("S", SCHEMA)
ctrl = ControlQueueSource()
job = Job(
    [], [src], batch_size=64, time_mode="processing",
    control_sources=[ctrl], plan_compiler=compiler,
)
job.bind_warm_store(WarmStartStore(store_dir))
job.set_replica_info("r-" + mode)

if mode == "cold":
    plane = ControlPlane(job, ctrl, gate=AdmissionGate(compiler))
    for t in range(20):
        plane.admit(chain_cql(t % 4, (t + 1) % 4), plan_id=f"q{{t}}",
                    tenant=f"t{{t}}")
    base = 1000
else:
    job.restore(ckpt)
    base = 2000
for i in range(16):
    src.emit(Rec(i % 4, float(i), base + i), base + i)
job.run_cycle()
job.run_cycle()
job.drain_outputs()
if mode == "cold":
    job.persist_warm()
    job.save_checkpoint(ckpt)
m = job.metrics()
keydirs = sorted({{
    store_key_dir(rt.warm_key)
    for rt in job._plans.values()
    if getattr(rt, "warm_key", None) is not None
}})
print(json.dumps({{
    "mode": mode,
    "rows": len(job.results("out")),
    "plans": len(job._plans) + len(job._folded),
    "namespace": store_namespace(),
    "keydirs": keydirs,
    "store": job.warm_store.stats(),
    "compiles": m["compiles"]["total_lowerings"],
    "fleet": m["fleet"],
}}))
"""


def _run_ab(tmp_path, mode):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", _AB_SCRIPT.format(repo=REPO),
         str(tmp_path / "store"), str(tmp_path / "ckpt"), mode],
        capture_output=True, text=True, cwd=REPO, timeout=240,
        env=env,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_warm_store_cross_process_zero_lowerings_20_tenants(
    tmp_path,
):
    """THE fleet acceptance pin: process A admits a 20-tenant fleet
    cold (populating the store + checkpoint), then an independent
    process B restores all 20 to live and serves fresh rows with ZERO
    new XLA lowerings — every executable deserialized from the store —
    and the two processes agree on every disk-tier cache key."""
    a = _run_ab(tmp_path, "cold")
    assert a["plans"] >= 20
    assert a["rows"] > 0
    assert a["store"]["persists"] >= 1
    assert a["store"]["errors"] == 0
    assert a["keydirs"], "cold process computed no store keys"

    b = _run_ab(tmp_path, "warm")
    assert b["plans"] == a["plans"]  # every tenant restored to live
    assert b["rows"] > 0  # ... and actually serving
    # the disk tier agreed on keys across independent processes
    assert b["namespace"] == a["namespace"]
    assert b["keydirs"] == a["keydirs"]
    # zero new lowerings, pinned via the attributed compile account
    assert b["compiles"] == 0, b
    assert b["store"]["hits"] >= 1
    assert b["store"]["misses"] == 0
    assert b["store"]["errors"] == 0
    assert b["fleet"]["replica"] == "r-warm"


# -- rolling restart: the dryrun-scale 2-replica tier-1 gate -----------------


def _spawn_replica(root, slot, rid):
    spec = {
        "replica_id": rid,
        "schema": [["id", "int"], ["price", "double"],
                   ["timestamp", "long"]],
        "checkpoint_path": os.path.join(root, f"slot{slot}", "ckpt"),
        "commit_log": os.path.join(root, f"slot{slot}", "commit.log"),
        "store_dir": os.path.join(root, "store"),
        "checkpoint_every_cycles": 1_000_000,
        "checkpoint_interval_s": 0.3,
        "batch_size": 64,
    }
    path = os.path.join(root, f"spec-{rid}.json")
    with open(path, "w") as f:
        json.dump(spec, f)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "flink_siddhi_tpu.fleet.replica",
         path],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        text=True, cwd=REPO,
    )
    line = proc.stdout.readline()
    try:
        ready = json.loads(line)
    except ValueError:
        proc.kill()
        raise AssertionError(
            f"replica {rid} did not boot: {line!r} "
            f"{proc.stderr.read()[-2000:]}"
        )
    return proc, ready


def _drain_and_exit(router, slot, proc):
    router.pause(slot)
    router.drain(slot)
    proc.wait(timeout=180)
    return json.loads(proc.stdout.readline() or "{}")


def _http_json(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=15
    ) as resp:
        return json.loads(resp.read())


def test_rolling_restart_two_replicas_row_exact_no_tenant_dropped(
    tmp_path,
):
    """Dryrun-scale 2-replica fleet under sustained feed: slot 0 is
    rolling-restarted mid-stream. Afterwards every admitted tenant is
    live on the successor, and each slot's committed output is
    row-exact (multiset) against an unfaulted single-process oracle
    fed the same partition — 0 duplicated, 0 lost."""
    root = str(tmp_path)
    tenants = 6
    pairs = [(t % 3, (t + 1) % 3) for t in range(tenants)]

    p0, r0 = _spawn_replica(root, 0, "r0")
    p1, r1 = _spawn_replica(root, 1, "r1")
    router = FleetRouter([r0, r1], key_field="id")
    try:
        for t, (a, b) in enumerate(pairs):
            ack = router.admit(
                chain_cql(a, b), plan_id=f"q{t}", tenant=f"t{t}"
            )
            assert ack["id"] == f"q{t}"
            assert set(ack["replicas"]) == {"r0", "r1"}

        def feed(rows):
            conn = socket.create_connection(
                ("127.0.0.1", router.ingest_port), timeout=10
            )
            try:
                conn.sendall(b"".join(
                    json.dumps(r).encode() + b"\n" for r in rows
                ))
            finally:
                conn.close()

        rows_a = [
            {"id": i % 3, "price": float(i), "timestamp": 1000 + i}
            for i in range(48)
        ]
        rows_b = [
            {"id": i % 3, "price": float(i), "timestamp": 2000 + i}
            for i in range(48, 96)
        ]
        feed(rows_a)
        time.sleep(1.5)  # sustained load in flight before the handoff

        # -- rolling restart of slot 0 mid-stream ----------------
        exit0 = _drain_and_exit(router, 0, p0)
        assert exit0["compiles"] >= 0  # clean exit account parsed
        p0b, r0b = _spawn_replica(root, 0, "r0b")
        router.set_replica(0, r0b)
        feed(rows_b)
        time.sleep(1.5)

        # every admitted tenant is live on the successor (poll: the
        # listing reads empty until the restore completes and the
        # supervisor publishes the restored job)
        want = {f"q{t}" for t in range(tenants)}
        deadline = time.monotonic() + 60
        live = {}
        while time.monotonic() < deadline:
            listing = _http_json(r0b["api_port"], "/api/v1/queries")
            live = {q["id"]: q for q in listing["queries"]}
            if want <= set(live):
                break
            time.sleep(0.2)
        assert want <= set(live), sorted(live)
        assert all(
            live[f"q{t}"].get("enabled", True)
            for t in range(tenants)
        )
        # the handoff is journaled on the successor
        health = _http_json(r0b["api_port"], "/api/v1/health")
        assert health["fleet"]["replica"] == "r0b"

        exit1 = _drain_and_exit(router, 1, p1)
        exit0b = _drain_and_exit(router, 0, p0b)
    finally:
        router.close()
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
        if "p0b" in dir() and p0b.poll() is None:
            p0b.kill()

    # -- row-exactness vs the unfaulted oracle, per partition --------
    all_rows = rows_a + rows_b
    for slot, final_exit in ((0, exit0b), (1, exit1)):
        part = [
            r for r in all_rows
            if hash_route(r["id"], 2) == slot
        ]
        oracle = _oracle_rows(pairs, part)
        log = read_committed(
            os.path.join(root, f"slot{slot}", "commit.log"), "out"
        )
        got = sorted(tuple(row) for _, row in log)
        assert got == sorted(oracle), (
            f"slot {slot}: committed log diverged from the unfaulted "
            f"oracle ({len(got)} vs {len(oracle)} rows)"
        )
        # the lineage counter (rides the checkpoint across the
        # handoff) must equal the log exactly: 0 lost
        lineage = sum(
            s.get("committed_rows", 0)
            for s in final_exit.get("commit", [])
        )
        assert lineage == len(got)


def _oracle_rows(pairs, partition_rows):
    """The unfaulted single-process oracle: one fresh Job fed the
    identical partition, same tenants — its output multiset is the
    ground truth for the commit log."""
    src, ctrl = CallbackSource("S", SCHEMA), ControlQueueSource()
    job = _make_job(src, ctrl)
    plane = ControlPlane(job, ctrl, gate=AdmissionGate(compiler))
    for t, (a, b) in enumerate(pairs):
        plane.admit(chain_cql(a, b), plan_id=f"q{t}", tenant=f"t{t}")
    for r in partition_rows:
        src.emit(
            Rec(r["id"], r["price"], r["timestamp"]), r["timestamp"]
        )
    job.run_cycle()
    job.run_cycle()
    job.drain_outputs()
    return [tuple(row) for row in job.results("out")]
