"""Flight recorder (telemetry/flightrec.py): journal exactness under
supervised faults, the filterable REST route, the /health last_restart
block, the restart-budget crash dump, rate collapse, and the measured
limiting-leg attribution surface (telemetry/attribution.py).

The headline property pinned here (ISSUE 15): the journal is part of
the checkpoint, so under supervised kill -> restore -> kill -> restore
every restart is recorded EXACTLY ONCE with monotone sequence numbers
and no duplicated pre-crash entries — the same rollback contract the
supervisor's uncommitted output already has.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from flink_siddhi_tpu.app.pipeline import PipelineConfig
from flink_siddhi_tpu.app.service import (
    ControlQueueSource,
    QueryControlService,
)
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control import ControlPlane
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import CallbackSource, ListSource
from flink_siddhi_tpu.runtime.supervisor import (
    RestartBudgetExceeded,
    Supervisor,
)
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType
from flink_siddhi_tpu.telemetry import FlightRecorder, MetricsRegistry
from flink_siddhi_tpu.telemetry import attribution

from tests.faults import CrashPlan, wrap_job

FIELDS = [
    ("id", "int"),
    ("name", "string"),
    ("price", "double"),
    ("timestamp", "long"),
]
CQL = (
    "from S#window.length(6) select id, sum(price) as t, "
    "count() as c insert into out"
)


def _schema():
    return PipelineConfig(
        stream_id="S", fields=FIELDS, cql="", input_path="x",
        output_path="x",
    ).schema()


def _record_tuples(n):
    return [
        ((i % 4), f"n{i % 3}", float(i), 1000 + 10 * i)
        for i in range(n)
    ]


# -- unit: ring, collapse, checkpoint state ---------------------------------


def test_rate_collapse_bounds_journal_under_burst():
    """A sustained shed/late burst folds into O(1) entries per window
    with the burst's counts accumulated — the journal stays bounded
    while the exact totals remain readable."""
    fr = FlightRecorder(capacity=64)
    for _ in range(500):
        fr.record("fault.shed", events=10)
    evs = fr.events(kind="fault.shed")
    assert len(evs) == 1
    assert evs[0]["events"] == 5000
    assert evs[0]["collapsed"] == 499
    assert evs[0]["t_last"] >= evs[0]["t_mono"]
    # discrete kinds never collapse
    fr.record("control.admit", plan="q1")
    fr.record("control.admit", plan="q1")
    assert len(fr.events(kind="control.admit")) == 2
    # by-kind summary counts the WHOLE burst
    assert fr.counts_by_kind()["fault.shed"] == 500
    # limit=0 is empty, not everything (evs[-0:] would be the lot)
    assert fr.events(limit=0) == []
    assert len(fr.events(limit=1)) == 1


def test_disabled_registry_silences_recorder():
    reg = MetricsRegistry(enabled=False)
    fr = FlightRecorder(registry=reg)
    assert fr.record("control.admit", plan="q") is None
    assert fr.events() == [] and fr.seq == 0
    reg.enabled = True
    assert fr.record("control.admit", plan="q") == 1


def test_state_roundtrip_continues_sequence():
    fr = FlightRecorder()
    for i in range(5):
        fr.record("checkpoint.save", path=f"p{i}")
    state = fr.state_dict()
    # post-snapshot entries must NOT survive a restore (rollback)
    fr.record("fault.crash")
    fr2 = FlightRecorder()
    fr2.record("noise.before.restore")  # replaced wholesale
    fr2.restore_state(state)
    assert [e["kind"] for e in fr2.events()] == ["checkpoint.save"] * 5
    assert fr2.record("supervisor.restart") == 6  # monotone continue
    # filters: since_seq is a strict cursor, kind matches by prefix
    assert [e["seq"] for e in fr2.events(since_seq=4)] == [5, 6]
    assert len(fr2.events(kind="supervisor")) == 1
    # limit semantics: newest-N tail view without a cursor, but
    # OLDEST-N with one — a cursor client pages FORWARD through a
    # backlog bigger than one page instead of silently skipping it
    assert [e["seq"] for e in fr2.events(limit=2)] == [5, 6]
    assert [
        e["seq"] for e in fr2.events(since_seq=1, limit=2)
    ] == [2, 3]


def test_attribution_cover_is_exhaustive_and_disjoint():
    """Every TOP_LEVEL_STAGES name maps to exactly one leg — a new
    stage cannot silently fall out of the limiting-leg verdict (the
    module asserts this on every call; here it runs in isolation so
    the failure is a named test, not a bench crash)."""
    from flink_siddhi_tpu.telemetry import TOP_LEVEL_STAGES

    mapped = [
        s
        for stages in attribution.LEG_STAGES.values()
        for s in stages
    ]
    assert sorted(mapped) == sorted(set(mapped))
    assert set(mapped) == set(TOP_LEVEL_STAGES)
    # smoke the verdict arithmetic: dispatch-dominated ledger
    att = attribution.limiting_leg(
        {
            "ingest": {"seconds": 1.0, "count": 1},
            "dispatch": {"seconds": 7.0, "count": 9},
            "drain": {"seconds": 1.5, "count": 4},
        },
        elapsed_s=10.0,
    )
    assert att["limiting_leg"] == "dispatch"
    assert att["coverage"] == pytest.approx(0.95, abs=0.01)
    assert att["legs"]["decode"]["overlapped"] is True
    # setup can dominate the cover without being named
    att = attribution.limiting_leg(
        {
            "plan_compile": {"seconds": 8.0, "count": 1},
            "ingest": {"seconds": 1.4, "count": 1},
            "dispatch": {"seconds": 0.6, "count": 9},
        },
        elapsed_s=10.0,
    )
    assert att["limiting_leg"] == "host_staging"
    assert att["legs"]["setup"]["share"] == pytest.approx(0.8)


# -- the headline: journal exactness under double kill/restore --------------


def test_journal_survives_double_kill_restore_exactly_once(tmp_path):
    """Supervised kill -> restore -> kill -> restore: the final
    journal records each restart EXACTLY once, each restore exactly
    once, sequence numbers strictly increase, and no pre-crash entry
    is duplicated. checkpoint_every_cycles=1 pins a commit between
    the two crashes, so restart #1's record is durable when crash #2
    rolls the journal back."""
    n = 60
    schema = _schema()
    # pulls 2 and 5: both crashes land with work (and a checkpoint)
    # between them
    crash = CrashPlan(at_pulls=(2, 5))

    def factory():
        src = ListSource(
            "S", schema, _record_tuples(n), ts_field="timestamp",
            chunk=16,
        )
        plan = compile_plan(CQL, {"S": schema})
        job = Job([plan], [src], batch_size=16, retain_results=False)
        return wrap_job(job, crash)

    sup = Supervisor(
        factory, str(tmp_path / "ckpt"),
        checkpoint_every_cycles=1, keep_checkpoints=3,
        max_restarts=5, restart_window_s=3600.0,
    )
    job = sup.run()
    assert crash.crashes == 2 and sup.restart_count == 2

    evs = job.flightrec.events()
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and len(seqs) == len(set(seqs)), (
        "journal sequence must be strictly monotone with no "
        "duplicated entries"
    )
    restarts = [e for e in evs if e["kind"] == "supervisor.restart"]
    assert len(restarts) == 2, (
        f"each restart exactly once, got {len(restarts)}"
    )
    assert [r["restart"] for r in restarts] == [1, 2]
    assert all(
        r["cause"] and "InjectedCrash" in r["cause"] for r in restarts
    )
    assert all(r["restore_ms"] > 0 for r in restarts)
    restores = [e for e in evs if e["kind"] == "checkpoint.restore"]
    assert len(restores) == 2
    # saves interleave restarts: every save entry is unique, and the
    # journal's order agrees with causality (restore N precedes
    # restart N precedes the next save)
    saves = [e for e in evs if e["kind"] == "checkpoint.save"]
    assert len(saves) >= 2
    assert len({e["seq"] for e in saves}) == len(saves)
    assert restarts[0]["seq"] < restarts[1]["seq"]
    assert restores[0]["seq"] < restarts[0]["seq"] < restores[1]["seq"]

    # the /health self-explanation: the LAST restart, fully described
    h = sup.health()
    lr = h["last_restart"]
    assert lr is not None
    assert "InjectedCrash" in lr["cause"]
    assert lr["restore_ms"] > 0
    assert lr["events_replayed"] >= 0
    assert lr["restart"] == 2
    assert lr["flightrec_seq"] == restarts[1]["seq"]
    assert h["crash_dump_path"] is None  # budget never exhausted


def test_crash_dump_written_on_restart_budget_exhaustion(tmp_path):
    """Budget exhaustion leaves a black-box file: the dead job's
    whole journal + a header naming the cause — written BEFORE the
    loud raise, and pointed to by /health."""
    schema = _schema()
    crash = CrashPlan(at_pulls=tuple(range(1, 50)))  # always crash

    def factory():
        src = ListSource(
            "S", schema, _record_tuples(20), ts_field="timestamp",
        )
        plan = compile_plan(CQL, {"S": schema})
        job = Job([plan], [src], batch_size=16, retain_results=False)
        return wrap_job(job, crash)

    ckpt = str(tmp_path / "ckpt")
    sup = Supervisor(
        factory, ckpt, max_restarts=2, restart_window_s=3600.0,
    )
    with pytest.raises(RestartBudgetExceeded):
        sup.run()
    dump_path = sup.crash_dump_path
    assert dump_path == ckpt + ".flightdump.json"
    assert os.path.exists(dump_path)
    with open(dump_path) as f:
        doc = json.load(f)
    assert doc["header"]["reason"] == "restart budget exhausted"
    assert "InjectedCrash" in doc["header"]["cause"]
    kinds = [e["kind"] for e in doc["events"]]
    assert "supervisor.budget_exhausted" in kinds
    assert sup.health()["crash_dump_path"] == dump_path


# -- the REST surface + live-job journal ------------------------------------

SCHEMA_S = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)


def _compiler(cql, pid):
    return compile_plan(cql, {"S": SCHEMA_S}, plan_id=pid)


def _chain(a, b):
    return (
        f"from every s1 = S[id == {a}] -> s2 = S[id == {b}] "
        "within 60 sec select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into out"
    )


class _Rec:
    def __init__(self, id, price, timestamp):
        self.id, self.price, self.timestamp = id, price, timestamp


def test_flightrecorder_route_filters_and_live_journal():
    """One control-plane session journaled end to end, read back over
    GET /api/v1/flightrecorder with kind/plan/since_seq filters; the
    metrics() surface carries the summary + the live attribution
    verdict."""
    src = CallbackSource("S", SCHEMA_S)
    ctrl = ControlQueueSource()
    job = Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[ctrl], plan_compiler=_compiler,
    )
    plane = ControlPlane(job, ctrl)
    plane.admit(_chain(1, 2), plan_id="q1", tenant="acme")
    for i in range(8):
        src.emit(_Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    plane.admit(_chain(2, 3), plan_id="q2")  # stack join
    job.run_cycle()
    plane.set_enabled("q2", False)
    job.run_cycle()
    plane.retire("q1")
    job.run_cycle()
    job.drain_outputs()

    evs = job.flightrec.events()
    kinds = [e["kind"] for e in evs]
    assert kinds.count("control.admit") == 2
    assert "control.disable" in kinds
    assert "control.retire" in kinds
    assert "aotcache.miss" in kinds
    admits = job.flightrec.events(kind="control.admit")
    assert admits[0]["plan"] == "q1" and admits[0]["tenant"] == "acme"
    assert admits[1]["stack_join"] is True

    m = job.metrics()
    assert m["flight_recorder"]["seq"] == evs[-1]["seq"]
    assert m["flight_recorder"]["by_kind"]["control.admit"] == 2
    att = m["attribution"]
    assert att["limiting_leg"] in attribution.CANDIDATE_LEGS
    assert att["coverage"] == pytest.approx(1.0)
    assert m["compiles"]["total_lowerings"] >= 1

    svc = QueryControlService(ctrl, job=job).start()
    try:
        base = f"http://127.0.0.1:{svc.port}/api/v1/flightrecorder"
        with urllib.request.urlopen(base) as resp:
            doc = json.loads(resp.read())
        assert doc["seq"] == evs[-1]["seq"]
        assert [e["seq"] for e in doc["events"]] == [
            e["seq"] for e in evs
        ]
        with urllib.request.urlopen(
            f"{base}?kind=control&plan=q1"
        ) as resp:
            q1 = json.loads(resp.read())["events"]
        assert q1 and all(
            e["kind"].startswith("control") and e["plan"] == "q1"
            for e in q1
        )
        cursor = evs[len(evs) // 2]["seq"]
        with urllib.request.urlopen(
            f"{base}?since_seq={cursor}&limit=3"
        ) as resp:
            tail = json.loads(resp.read())["events"]
        assert all(e["seq"] > cursor for e in tail)
        assert len(tail) <= 3
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}?since_seq=oops")
        assert ei.value.code == 400
    finally:
        svc.stop()
