"""fstlint: each rule fires on its known-bad fixture (incl. the
reconstructed PR 7 donation-aliasing and PR 8 falsy-zero bugs) and
stays quiet on the corrected twin; the baseline machinery enforces
reasons and staleness; and the repo itself lints clean — the same
contract scripts/run_static_analysis.py gates in the tier-1 lane."""

import os

import pytest

from flink_siddhi_tpu.analysis.baseline import (
    BaselineError,
    apply_baseline,
    parse_baseline,
)
from flink_siddhi_tpu.analysis.findings import RULES, Finding
from flink_siddhi_tpu.analysis.fstlint import REPO_ROOT, lint_paths, main
from flink_siddhi_tpu.analysis.rules import lint_module
from flink_siddhi_tpu.analysis.threads import analyze_sources

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def _lint_fixture(name):
    """BOTH passes — the per-module FST1xx rules and the fstrace
    FST2xx thread pass — over one fixture, so every bad fixture is
    checked quiet against EVERY other rule, not just its own
    family's."""
    path = os.path.join(FIXTURES, name)
    with open(path) as fh:
        src = fh.read()
    return sorted(
        set(lint_module(src, name) + analyze_sources({name: src}))
    )


# rule -> (bad fixture, expected finding count on it)
CASES = {
    "FST101": ("fst101_donation", 2),  # PR 7 reconstruction
    "FST102": ("fst102_hostsync", 4),
    "FST103": ("fst103_falsy_zero", 2),  # PR 8 reconstruction
    "FST104": ("fst104_tracer_leak", 2),
    "FST105": ("fst105_retrace", 2),
    "FST106": ("fst106_checkpoint", 2),  # PR 10 reconstruction
    # fstrace (analysis/threads.py): thread ownership & lock discipline
    "FST201": ("fst201_offthread", 2),  # PR 12 contract, enforced
    "FST202": ("fst202_shared", 2),
    "FST203": ("fst203_lock_sleep", 2),  # PR 7 backoff-under-lock
    "FST204": ("fst204_checkact", 1),
}


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_fires_on_bad_fixture(rule):
    stem, expected = CASES[rule]
    findings = _lint_fixture(f"{stem}_bad.py")
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) == expected, findings
    # and ONLY that rule fires: a bad fixture for one hazard must not
    # trip another rule's false positive
    assert {f.rule for f in findings} == {rule}, findings


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_quiet_on_corrected_twin(rule):
    stem, _ = CASES[rule]
    assert _lint_fixture(f"{stem}_good.py") == []


def test_pr7_donation_alias_is_the_alias_read():
    """The PR 7 shape specifically: the flagged read is the alias
    captured BEFORE the donating call, not the rebound binding."""
    findings = _lint_fixture("fst101_donation_bad.py")
    assert any("snap" in f.message for f in findings), findings


def test_pr8_reconstruction_names_the_config():
    findings = _lint_fixture("fst103_falsy_zero_bad.py")
    assert any("drain_interval_ms" in f.message for f in findings)


def test_every_rule_has_a_fixture_and_registry_entry():
    assert set(CASES) == set(RULES)


def test_fst101_same_line_read_after_donating_call():
    """`step(x) + x.sum()` reads x AFTER the donating call on one line
    (left-to-right evaluation) — must flag; the mirrored spelling
    evaluates x.sum() BEFORE the call and must not."""
    src = (
        "import jax\n"
        "step = jax.jit(lambda s: s, donate_argnums=(0,))\n"
        "def bad(x):\n"
        "    return step(x) + x.sum()\n"
        "def ok(x):\n"
        "    return x.sum() + step(x)\n"
    )
    findings = lint_module(src, "t.py")
    assert [(f.rule, f.line) for f in findings] == [("FST101", 4)]


def test_fst101_mutually_exclusive_branches_do_not_flag():
    """A donation in one if-branch must not flag a read in the OTHER
    branch (only one executes); a read AFTER the if still flags."""
    src = (
        "import jax\n"
        "step = jax.jit(lambda s: s, donate_argnums=(0,))\n"
        "def ok(x, cond):\n"
        "    if cond:\n"
        "        y = step(x)\n"
        "    else:\n"
        "        z = x.sum()\n"
        "def bad(x, cond):\n"
        "    if cond:\n"
        "        y = step(x)\n"
        "    return x.sum()\n"
    )
    findings = lint_module(src, "t.py")
    assert [(f.rule, f.line) for f in findings] == [("FST101", 11)]


def test_fst106_ephemeral_requires_reason():
    """A bare `# fst:ephemeral` is itself a finding — like baseline
    suppressions, the reason is mandatory."""
    src = (
        "class S:\n"
        "    def __init__(self):\n"
        "        # fst:ephemeral\n"
        "        self._clock = 0\n"
        "    def tick(self):\n"
        "        self._clock += 1\n"
        "    def state_dict(self):\n"
        "        return {}\n"
    )
    findings = lint_module(src, "t.py")
    # the bare mark is flagged AND the attr stays uncovered until the
    # reason lands — both surface
    assert [(f.rule, f.line) for f in findings] == [
        ("FST106", 4), ("FST106", 6),
    ]
    assert "without a reason" in findings[0].message


def test_fst106_uncovered_class_is_out_of_scope():
    """Classes with no checkpoint story (no state_dict, no
    fst:checkpointed mark) are not linted — the rule polices snapshot
    COMPLETENESS, not snapshot existence."""
    src = (
        "class Scratch:\n"
        "    def tick(self):\n"
        "        self._n = 1\n"
    )
    assert lint_module(src, "t.py") == []


def test_fst106_external_by_coverage_resolves_snapshot_job():
    """The `# fst:checkpointed by=` annotation pulls coverage from
    runtime/checkpoint.py: an attr snapshot_job reads is covered, a
    made-up one is flagged."""
    src = (
        "# fst:checkpointed by=flink_siddhi_tpu/runtime/checkpoint.py:snapshot_job\n"
        "class J:\n"
        "    def run(self):\n"
        "        self._epoch_ms = 5\n"      # snapshot_job reads job._epoch_ms
        "        self._never_saved = 1\n"
    )
    findings = lint_module(src, "t.py")
    assert [(f.rule, f.line) for f in findings] == [("FST106", 5)]
    assert "_never_saved" in findings[0].message


def test_rule_filter_cli(tmp_path):
    """`fstlint --rule` restricts output to one rule so it can be
    iterated without a full-repo sweep."""
    bad = tmp_path / "planted.py"
    bad.write_text(
        "def f(j):\n"
        "    return j.drain_interval_ms or 500\n"
    )
    # the planted file has an FST103 finding; filtered to FST106 it
    # reads clean, filtered to FST103 it fails
    assert main([str(bad), "--no-baseline", "--rule", "FST106"]) == 0
    assert main([str(bad), "--no-baseline", "--rule", "FST103"]) == 1
    with pytest.raises(SystemExit):
        main([str(bad), "--rule", "FST999"])
    # a baseline regenerated from a filtered sweep would drop other
    # rules' suppressions — the combination is refused
    with pytest.raises(SystemExit):
        main([
            str(bad), "--rule", "FST103",
            "--write-baseline", str(tmp_path / "gen.toml"),
        ])


def test_repo_lints_clean_with_checked_in_baseline():
    """The tier-1 contract: zero unsuppressed findings over the repo
    surface. If this fails, either fix the finding or baseline it WITH
    a reason (docs/static_analysis.md)."""
    assert main([]) == 0


def test_hotpath_allowlist_still_annotated():
    """The FST102 rule only sees functions carrying the fst:hotpath
    marker; a refactor that drops the annotations silently disables
    the rule. Pin the allowlist floor."""
    marked = {}
    for rel in (
        "flink_siddhi_tpu/runtime/executor.py",
        "flink_siddhi_tpu/runtime/replay.py",
        "flink_siddhi_tpu/compiler/plan.py",
        "flink_siddhi_tpu/compiler/nfa.py",
        "flink_siddhi_tpu/compiler/window.py",
        "flink_siddhi_tpu/compiler/scan_windows.py",
        "flink_siddhi_tpu/compiler/select.py",
        "flink_siddhi_tpu/compiler/join.py",
    ):
        with open(os.path.join(REPO_ROOT, rel)) as fh:
            marked[rel] = fh.read().count("fst:hotpath")
    assert marked["flink_siddhi_tpu/runtime/executor.py"] >= 3
    assert marked["flink_siddhi_tpu/runtime/replay.py"] >= 1
    assert marked["flink_siddhi_tpu/compiler/plan.py"] >= 4
    assert marked["flink_siddhi_tpu/compiler/nfa.py"] >= 5
    assert sum(marked.values()) >= 20


# -- baseline machinery ----------------------------------------------------


def test_baseline_requires_reason():
    with pytest.raises(BaselineError, match="reason"):
        parse_baseline(
            '[[suppress]]\nrule = "FST103"\npath = "a.py"\nline = 3\n'
        )
    with pytest.raises(BaselineError, match="reason"):
        parse_baseline(
            '[[suppress]]\nrule = "FST103"\npath = "a.py"\n'
            'reason = "  "\n'
        )


def test_baseline_rejects_unknown_syntax():
    with pytest.raises(BaselineError, match="unsupported"):
        parse_baseline("[suppress]\nrule = 'x'\n")


def test_baseline_reason_may_contain_hash():
    """Issue references are the most natural reasons; '#' inside a
    quoted string is content, not a comment."""
    sups = parse_baseline(
        '[[suppress]]  # trailing comment\nrule = "FST103"\n'
        'path = "a.py"\nreason = "tracked in #42"\n'
    )
    assert sups[0].reason == "tracked in #42"


def test_baseline_suppression_and_staleness():
    sups = parse_baseline(
        '[[suppress]]\nrule = "FST103"\npath = "a.py"\nline = 3\n'
        'reason = "explained"\n\n'
        '[[suppress]]\nrule = "FST101"\npath = "gone.py"\n'
        'reason = "also explained"\n'
    )
    f_hit = Finding("a.py", 3, "FST103", "x or 5")
    f_open = Finding("b.py", 9, "FST103", "y or 5")
    open_findings, stale = apply_baseline([f_hit, f_open], sups)
    assert open_findings == [f_open]
    assert [s.path for s in stale] == ["gone.py"]


def test_stale_and_reviewme_baseline_fail_the_run(tmp_path):
    bl = tmp_path / "baseline.toml"
    bl.write_text(
        '[[suppress]]\nrule = "FST103"\npath = "nowhere.py"\n'
        'reason = "stale on purpose"\n'
    )
    assert main(["--baseline", str(bl)]) == 2
    bl.write_text(
        '[[suppress]]\nrule = "FST103"\npath = "nowhere.py"\n'
        'reason = "REVIEWME: fill me in"\n'
    )
    assert main(["--baseline", str(bl)]) == 2


def test_write_baseline_roundtrip(tmp_path):
    out = tmp_path / "gen.toml"
    bad = os.path.join(FIXTURES, "fst103_falsy_zero_bad.py")
    assert main([bad, "--write-baseline", str(out)]) == 0
    sups = parse_baseline(out.read_text())
    assert len(sups) == 2
    findings = lint_paths([bad])
    open_findings, stale = apply_baseline(findings, sups)
    assert open_findings == [] and stale == []


def test_write_baseline_preserves_existing_reasons(tmp_path):
    """Regenerating a live baseline keeps human-written reasons for
    findings that still exist; only NEW findings get REVIEWME."""
    out = tmp_path / "gen.toml"
    bad = os.path.join(FIXTURES, "fst103_falsy_zero_bad.py")
    assert main([bad, "--write-baseline", str(out)]) == 0
    text = out.read_text().replace(
        "REVIEWME", "explained: tracked in #42", 1
    )
    out.write_text(text)
    assert main([bad, "--write-baseline", str(out)]) == 0
    sups = parse_baseline(out.read_text())
    reasons = sorted(s.reason for s in sups)
    assert any(r.startswith("explained: tracked in #42") for r in reasons)
    assert sum(r.startswith("REVIEWME") for r in reasons) == 1


def test_targeted_run_does_not_report_out_of_scope_stale(tmp_path):
    """`fstlint <one file>` with a baseline whose entries cover OTHER
    files must not call them stale (staleness is a full-sweep
    concept) — and suppressions for the targeted file still apply."""
    bl = tmp_path / "baseline.toml"
    bl.write_text(
        '[[suppress]]\nrule = "FST103"\npath = "bench.py"\n'
        'reason = "covers a file outside this targeted run"\n'
    )
    clean = os.path.join(FIXTURES, "fst103_falsy_zero_good.py")
    assert main([clean, "--baseline", str(bl)]) == 0
