"""Fused scan-of-microbatches streaming dispatch: row-exact equivalence.

The fused streaming step (``Job.fused_segment_len``,
runtime/executor.py ``_stage_fused``/``_dispatch_segment``) collapses
K per-micro-batch device dispatches into one lax.scan segment call —
the bounded replay's proven shape (runtime/replay.py), fed from live
tapes. These tests pin the contract:

* fused-scan streaming == per-batch streaming, ROW-EXACT, across the
  window zoo (length / timeBatch / unique / sort), pattern chains, and
  multiquery stacks, at segment lengths {1, 3, 16} — 10 micro-batches
  per run, so 3 ends on a partial trailing segment (3+3+3+1) and 16
  never fills a whole one (pure partial, padded with empty tapes);
* fused streaming == the per-event reference interpreter
  (``baseline/interp.py``) on its supported surface — row contents at
  f32 tolerance, the ``vs_baseline`` honesty check;
* drain staleness keeps recording under fused dispatch (drains fire
  between segments, not between batches) and its p99 stays bounded at
  segment_len=16;
* checkpoints land on segment boundaries: ``save_checkpoint`` force-
  dispatches the pending partial segment (the supervised-crash
  exactly-once case lives in tests/test_faults.py).

All tier-1, CPU lane; on this lane the Pallas kernels fall back to
their XLA forms (the kernel-vs-fallback equivalence runs under the
Pallas interpreter in tests/test_pallas_ops.py subprocesses).
"""

import numpy as np
import pytest

import bench
from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

N, BATCH = 40_000, 4096  # 10 micro-batches
SEGMENTS = (1, 3, 16)  # 3 -> partial trailing; 16 -> pure partial


def _schema():
    return StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )


CASES = {
    "filter": (
        "from inputStream[id == 2] select id, name, price "
        "insert into out",
        50,
    ),
    "pattern3_within": (
        "from every s1 = inputStream[id == 1] -> "
        "s2 = inputStream[id == 2] -> s3 = inputStream[id == 3] "
        "within 5 sec "
        "select s1.timestamp as t1, s3.timestamp as t3, "
        "s3.price as price insert into out",
        50,
    ),
    "window_groupby": (
        "from inputStream#window.length(100) "
        "select id, sum(price) as total, count() as cnt "
        "group by id insert into out",
        40,
    ),
    "timebatch": (
        "from inputStream#window.timeBatch(3 sec) "
        "select sum(price) as total insert into out",
        50,
    ),
    "unique_window": (
        "from inputStream#window.unique(id) "
        "select id, sum(price) as total, count() as cnt "
        "insert into out",
        20,
    ),
    "sort_window": (
        "from inputStream#window.sort(10, price) "
        "select id, min(price) as mn, max(price) as mx "
        "insert into out",
        20,
    ),
}


def _run(cql, n_ids, seg, n=N, batch=BATCH):
    schema = _schema()
    plan = compile_plan(
        cql, {"inputStream": schema},
        config=EngineConfig(lazy_projection=True, pred_pushdown=True),
    )
    job = Job(
        [plan],
        [BatchSource(
            "inputStream", schema,
            iter(bench.make_batches(n, batch, schema, "inputStream",
                                    n_ids)),
        )],
        batch_size=batch, time_mode="processing",
    )
    job.fused_segment_len = seg
    job.run()
    out = {
        sid: sorted(job.results_with_ts(sid)) for sid in job.collected
    }
    return out, job


@pytest.mark.parametrize("case", sorted(CASES))
def test_fused_matches_per_batch_rowexact(case):
    cql, n_ids = CASES[case]
    base, _ = _run(cql, n_ids, None)
    assert base and any(rows for rows in base.values()), case
    for seg in SEGMENTS:
        fused, job = _run(cql, n_ids, seg)
        assert fused.keys() == base.keys(), (case, seg)
        for sid in base:
            assert fused[sid] == base[sid], (
                case, seg, len(fused[sid]), len(base[sid])
            )
        counters = job.telemetry.snapshot()["counters"]
        if seg > 1:
            # the fused path actually ran AND collapsed dispatches
            assert counters.get("fusion.batches", 0) >= 10
            assert 0 < counters.get("fusion.dispatches", 0) < (
                counters["fusion.batches"]
            )


def test_fused_multiquery_stack_rowexact():
    """8 stacked chain queries over one stream: the stacked group
    artifact under the scanned segment dispatch."""
    parts = []
    for q in range(8):
        a, b = q % 5, (q * 3 + 1) % 5
        parts.append(
            f"from every s1 = inputStream[id == {a}] -> "
            f"s2 = inputStream[id == {b}] "
            f"select s1.timestamp as t1, s2.timestamp as t2 "
            f"insert into m{q}"
        )
    cql = "; ".join(parts)
    base, _ = _run(cql, 5, None, n=20_000)
    assert len(base) == 8
    for seg in SEGMENTS:
        fused, _ = _run(cql, 5, seg, n=20_000)
        assert fused.keys() == base.keys()
        for sid in base:
            assert fused[sid] == base[sid], (sid, seg)


def _norm_row(ts, row):
    return (
        int(ts),
        tuple(
            np.float32(v).item() if isinstance(v, float) else v
            for v in row
        ),
    )


@pytest.mark.parametrize("config", ["filter", "headline"])
def test_fused_matches_baseline_interpreter(config):
    """Fused streaming vs the measured-baseline per-event interpreter
    (flink_siddhi_tpu/baseline): identical stream, row contents at f32
    tolerance — the fused dispatch cannot drift from the reference
    semantics either."""
    from flink_siddhi_tpu.baseline import BaselineEngine

    n, batch = 40_000, 4096
    schema = _schema()
    cql = bench._config_cql(config)
    plan = compile_plan(
        cql, {"inputStream": schema},
        config=EngineConfig(lazy_projection=True, pred_pushdown=True),
    )
    job = Job(
        [plan],
        [BatchSource("inputStream", schema,
                     iter(bench.make_batches(n, batch, schema,
                                             "inputStream", 50)))],
        batch_size=batch, time_mode="processing", retain_results=False,
    )
    job.fused_segment_len = 3
    eng_rows = []
    for rt in job._plans.values():
        for out_stream in rt.plan.output_streams():
            job.add_sink(
                out_stream,
                lambda ts, row: eng_rows.append(_norm_row(ts, row)),
            )
    job.run()

    eng = BaselineEngine(cql, ["id", "name", "price", "timestamp"])
    base_rows = []
    eng._emit = lambda out, ts, row: base_rows.append(
        _norm_row(ts, row)
    )
    batches = bench.make_batches(n, batch, schema, "inputStream", 50)
    cols = {
        "id": np.concatenate([b.columns["id"] for b in batches]).tolist(),
        "name": ["test_event"] * n,
        "price": np.concatenate(
            [b.columns["price"] for b in batches]
        ).tolist(),
        "timestamp": np.concatenate(
            [b.timestamps for b in batches]
        ).tolist(),
    }
    eng.run_columns(cols, cols["timestamp"])
    assert sorted(eng_rows) == sorted(base_rows)


def test_drain_staleness_bounded_under_fused_dispatch():
    """Satellite: drains fire between segments, not between batches —
    the deadline scheduler's staleness leg must keep recording under
    fused dispatch, and its p99 must stay bounded (~interval + drain
    pipeline time, not the whole run) at segment_len=16."""
    cql, n_ids = CASES["window_groupby"]
    schema = _schema()
    plan = compile_plan(cql, {"inputStream": schema})
    job = Job(
        [plan],
        [BatchSource("inputStream", schema,
                     iter(bench.make_batches(40_000, 2048, schema,
                                             "inputStream", n_ids)))],
        batch_size=2048, time_mode="processing",
    )
    job.fused_segment_len = 16
    job.drain_interval_ms = 25.0
    job.run()
    h = job.telemetry.histogram("drain.staleness")
    assert h.count > 0, "staleness stopped recording under fused mode"
    # bounded: a broken scheduler would show staleness ~= the whole
    # run (tens of seconds when a segment never drains); the budget
    # here is interval + a generous drain+dispatch pipeline allowance
    assert h.percentile_ms(99) < 10_000.0, h.percentile_ms(99)
    counters = job.telemetry.snapshot()["counters"]
    assert counters.get("fusion.dispatches", 0) >= 1


# Named per-shape-bucket compile budget for the tier-1 gate shape
# (constant-cadence stream, one tape bucket, segment 8): the complete
# executable set is init_acc + full-segment scan + padded partial-
# trailing scan + backpressure ticket noop + drain count/pack shapes +
# flush + retrace headroom for jax-version drift. Measured 12 on this
# lane; the sticky-d0 widening regression class (every small-but-
# constant batch widening the wire kind and retracing the segment
# executable) lowers O(n_batches) extra modules and blows straight
# through this.
RETRACE_BUDGET_GATE_SHAPE = 16


def test_retrace_budget_gate_shape():
    """Satellite: count XLA executable builds over an end-to-end run
    of the gate shape via the PERMANENT compile-telemetry surface
    (telemetry/compile_events.py — the lowering event fires before
    the persistent compilation cache is consulted, so a warm
    .jax_cache cannot mask a retrace regression: cache hits skip
    backend_compile, not lowering) and pin them to the named budget.
    Previously this test registered a private jax.monitoring listener
    and tore down with clear_event_listeners(), which clobbered every
    other listener in the process."""
    from flink_siddhi_tpu.telemetry import compile_events

    with compile_events.watch() as w:
        cql, n_ids = CASES["window_groupby"]
        out, job = _run(cql, n_ids, seg=8)
    assert any(rows for rows in out.values())
    counters = job.telemetry.snapshot()["counters"]
    assert counters.get("fusion.dispatches", 0) >= 1
    n = w.count
    assert 0 < n <= RETRACE_BUDGET_GATE_SHAPE, (
        f"{n} executables lowered for ONE shape bucket (budget "
        f"{RETRACE_BUDGET_GATE_SHAPE}) — a retrace leak (sticky "
        "wire-kind widening, unstable jit signatures) is "
        "recompiling the hot loop"
    )
    # the same lowerings land, attributed, in the job's own compile
    # accounting: metrics()["compiles"] with finite durations (the
    # permanent surface the bench and REST readers see). The job sink
    # counts only job-attributed lowerings, so it is bounded by the
    # process-wide watcher count.
    comp = job.metrics()["compiles"]
    assert 0 < comp["total_lowerings"] <= n
    assert comp["total_duration_s"] > 0
    assert comp["by_signature"], "no per-signature attribution"
    assert comp["lowering_duration"]["count"] == comp["total_lowerings"]


def test_checkpoint_forces_segment_boundary(tmp_path):
    """Checkpoints land only at segment boundaries: save_checkpoint
    force-dispatches the staged partial segment, so the snapshot's
    device state covers every event the job has pulled (exactly-once
    depends on this — the supervised crash case is in
    tests/test_faults.py)."""
    cql, n_ids = CASES["window_groupby"]
    schema = _schema()
    plan = compile_plan(cql, {"inputStream": schema})
    job = Job(
        [plan],
        [BatchSource("inputStream", schema,
                     iter(bench.make_batches(N, BATCH, schema,
                                             "inputStream", n_ids)))],
        batch_size=BATCH, time_mode="processing",
    )
    job.fused_segment_len = 16
    for _ in range(3):
        job.run_cycle()
    rt = next(iter(job._plans.values()))
    assert rt.seg_pending, "expected a staged partial segment"
    job.save_checkpoint(str(tmp_path / "ck"))
    assert not rt.seg_pending, (
        "save_checkpoint left staged tapes undispatched — the "
        "checkpoint is not on a segment boundary"
    )
    # and the run completes normally afterwards
    job.run()
    assert job.results_with_ts("out")


def test_fused_h2d_overlap_counters(monkeypatch):
    """The double-buffering accounting: segment k+1's upload (one
    async device_put of the stacked tapes) counts as OVERLAPPED when
    it is issued while segment k's dispatch ticket is still in flight
    (fusion.h2d_overlapped; bench reports the fraction as
    h2d_overlap_frac, gated by schema v5). XLA:CPU retires these
    executions synchronously inside the dispatch call, so the busy
    window cannot be observed live on this lane — the device is
    forced to LOOK busy instead (tickets report in-flight), which
    pins the accounting deterministically; on an async accelerator
    the same counter measures the genuine overlap."""
    cql, n_ids = CASES["pattern3_within"]

    class _Busy:
        def __init__(self, real):
            self._real = real

        def is_ready(self):
            return False

        def block_until_ready(self):
            return self._real.block_until_ready()

    orig = Job._make_ticket
    monkeypatch.setattr(
        Job, "_make_ticket",
        classmethod(lambda cls, states: _Busy(orig(states))),
    )
    schema = _schema()
    plan = compile_plan(
        cql, {"inputStream": schema},
        config=EngineConfig(lazy_projection=True, pred_pushdown=True),
    )
    job = Job(
        [plan],
        [BatchSource("inputStream", schema,
                     iter(bench.make_batches(N, BATCH, schema,
                                             "inputStream", n_ids)))],
        batch_size=BATCH, time_mode="processing",
    )
    job.fused_segment_len = 3
    job.max_inflight_cycles = 99  # never hit the forced-block path
    job.run()
    counters = job.telemetry.snapshot()["counters"]
    # uploads count SEGMENTS (one device_put per stacked segment):
    # 10 batches at segment 3 -> 4 dispatches (3+3+3+1 partial)
    assert counters.get("fusion.h2d_uploads", 0) == 4
    # every upload after the first saw in-flight compute
    assert counters.get("fusion.h2d_overlapped", 0) == 3
