"""Windowed join end-to-end (SiddhiCEPITCase.java:306-327, 413-439 analog).

Oracle semantics: each arriving event joins the opposite side's window
contents as of its arrival; every ordered pair is emitted exactly once (by
the later event). Length windows = last n matching events; time windows =
events within t ms before the arrival.
"""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP


@dataclasses.dataclass
class Trade:
    sym: int
    price: float
    timestamp: int


@dataclasses.dataclass
class Quote:
    sym: int
    bid: float
    timestamp: int


TF = ["sym", "price", "timestamp"]
QF = ["sym", "bid", "timestamp"]


def join_oracle(trades, quotes, win_t, win_q, on, within=None):
    """Returns the multiset of (trade, quote) pairs a streaming windowed
    join emits. win_*: ('length', n) or ('time', ms)."""
    arrivals = sorted(
        [("t", e) for e in trades] + [("q", e) for e in quotes],
        key=lambda x: x[1].timestamp,
    )
    t_seen, q_seen = [], []
    pairs = []

    def window(seen, win, now_ts):
        kind, n = win
        if kind == "length":
            return seen[-n:]
        return [e for e in seen if e.timestamp > now_ts - n]

    for side, e in arrivals:
        if side == "t":
            for q in window(q_seen, win_q, e.timestamp):
                if on(e, q) and (
                    within is None or abs(e.timestamp - q.timestamp) <= within
                ):
                    pairs.append((e, q))
            t_seen.append(e)
        else:
            for t in window(t_seen, win_t, e.timestamp):
                if on(t, e) and (
                    within is None or abs(t.timestamp - e.timestamp) <= within
                ):
                    pairs.append((t, e))
            q_seen.append(e)
    return pairs


def run_join(trades, quotes, cql, batch_size=4096):
    env = CEPEnvironment(batch_size=batch_size)
    return (
        SiddhiCEP.define("Trades", trades, TF, env=env)
        .union("Quotes", quotes, QF)
        .cql(cql)
        .returns("out")
    )


def mk_trades(n, start=1000, step=1000, syms=3):
    return [Trade(i % syms, 100.0 + i, start + step * i) for i in range(n)]


def mk_quotes(n, start=1500, step=1000, syms=3):
    return [Quote(i % syms, 50.0 + i, start + step * i) for i in range(n)]


@pytest.mark.parametrize("batch_size", [4096, 6])
def test_length_window_join(batch_size):
    trades, quotes = mk_trades(12), mk_quotes(10)
    out = run_join(
        trades, quotes,
        "from Trades#window.length(4) as t "
        "join Quotes#window.length(3) as q on t.sym == q.sym "
        "select t.sym, t.price, q.bid insert into out",
        batch_size=batch_size,
    )
    expected = [
        (t.sym, t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("length", 4), ("length", 3),
            lambda t, q: t.sym == q.sym,
        )
    ]
    assert sorted(out) == sorted(expected)


@pytest.mark.parametrize("batch_size", [4096, 5])
def test_time_window_join(batch_size):
    trades, quotes = mk_trades(10), mk_quotes(10)
    out = run_join(
        trades, quotes,
        "from Trades#window.time(3 sec) as t "
        "join Quotes#window.time(2 sec) as q on t.sym == q.sym "
        "select t.price, q.bid insert into out",
        batch_size=batch_size,
    )
    expected = [
        (t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("time", 3000), ("time", 2000),
            lambda t, q: t.sym == q.sym,
        )
    ]
    assert sorted(out) == sorted(expected)


def test_join_compound_on_condition():
    trades, quotes = mk_trades(8), mk_quotes(8)
    out = run_join(
        trades, quotes,
        "from Trades#window.length(5) as t "
        "join Quotes#window.length(5) as q "
        "on t.sym == q.sym and t.price > q.bid + 52.0 "
        "select t.price, q.bid insert into out",
    )
    expected = [
        (t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("length", 5), ("length", 5),
            lambda t, q: t.sym == q.sym and t.price > q.bid + 52.0,
        )
    ]
    assert sorted(out) == sorted(expected)


def test_join_within():
    trades, quotes = mk_trades(8), mk_quotes(8)
    out = run_join(
        trades, quotes,
        "from Trades#window.length(8) as t "
        "join Quotes#window.length(8) as q on t.sym == q.sym "
        "within 1500 select t.price, q.bid insert into out",
    )
    expected = [
        (t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("length", 8), ("length", 8),
            lambda t, q: t.sym == q.sym, within=1500,
        )
    ]
    assert sorted(out) == sorted(expected)


def test_left_outer_join():
    trades = [Trade(0, 100.0, 1000), Trade(7, 101.0, 2000)]
    quotes = [Quote(0, 50.0, 500)]
    out = run_join(
        trades, quotes,
        "from Trades#window.length(4) as t "
        "left outer join Quotes#window.length(4) as q on t.sym == q.sym "
        "select t.sym, q.bid insert into out",
    )
    # sym 0 matches; sym 7 emits with a NULL quote side (Siddhi null)
    assert sorted(out, key=str) == [(0, 50.0), (7, None)]


def test_join_select_star():
    trades = [Trade(0, 100.0, 1000)]
    quotes = [Quote(0, 50.0, 1500)]
    out = run_join(
        trades, quotes,
        "from Trades#window.length(4) as t "
        "join Quotes#window.length(4) as q on t.sym == q.sym "
        "insert into out",
    )
    assert out == [(0, 100.0, 1000, 0, 50.0, 1500)]


def test_self_join_on_windowed_stream():
    # self-joins are supported with distinct aliases (round 2); each
    # qualifying ordered pair appears exactly once, no self-pairs
    trades = [Trade(0, 100.0, 1000), Trade(0, 101.0, 2000)]
    out = run_join(
        trades, mk_quotes(1),
        "from Trades#window.length(4) as a "
        "join Trades#window.length(4) as b on a.price < b.price "
        "select a.price as p1, b.price as p2 insert into out",
    )
    assert sorted(out) == [(100.0, 101.0)]


# --------------------------------------------------------------------------
# round 2: self-joins + null-masked outer joins (VERDICT #10)
# --------------------------------------------------------------------------

def test_self_join_with_aliases():
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    import numpy as np

    S = StreamSchema(
        [("x", AttributeType.DOUBLE), ("timestamp", AttributeType.LONG)]
    )
    plan = compile_plan(
        "from S as a join S as b on a.x < b.x "
        "select a.x as x1, b.x as x2 insert into o",
        {"S": S},
    )
    ts = np.array([1000, 1001, 1002], np.int64)
    b = EventBatch(
        "S", S, {"x": np.array([1.0, 3.0, 2.0]), "timestamp": ts}, ts
    )
    job = Job(
        [plan], [BatchSource("S", S, iter([b]))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    # every qualifying ordered pair exactly once; no self-pairs
    assert sorted(job.results("o")) == [(1.0, 2.0), (1.0, 3.0), (2.0, 3.0)]


def test_self_join_requires_distinct_aliases():
    import pytest
    from flink_siddhi_tpu.query.lexer import SiddhiQLError
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType
    from flink_siddhi_tpu.compiler.plan import compile_plan

    S = StreamSchema(
        [("x", AttributeType.DOUBLE), ("timestamp", AttributeType.LONG)]
    )
    with pytest.raises(SiddhiQLError, match="distinct aliases"):
        compile_plan(
            "from S join S on S.x < S.x select S.x as x insert into o",
            {"S": S},
        )


def test_outer_join_missing_side_is_null():
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    import numpy as np

    A = StreamSchema(
        [("id", AttributeType.INT), ("x", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    B = StreamSchema(
        [("id", AttributeType.INT), ("y", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    plan = compile_plan(
        "from A#window.length(5) left outer join B#window.length(5) "
        "on A.id == B.id "
        "select A.id as aid, B.y as by_ insert into o",
        {"A": A, "B": B},
    )
    ats = np.array([1000, 1002], np.int64)
    bts = np.array([999], np.int64)
    a = EventBatch(
        "A", A,
        {"id": np.array([1, 7], np.int32),
         "x": np.array([1.0, 9.0]), "timestamp": ats},
        ats,
    )
    b = EventBatch(
        "B", B,
        {"id": np.array([1], np.int32),
         "y": np.array([10.0]), "timestamp": bts},
        bts,
    )
    job = Job(
        [plan],
        [BatchSource("A", A, iter([a])), BatchSource("B", B, iter([b]))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    # unmatched A row carries None (Siddhi null), NOT a zero-filled value
    assert sorted(job.results("o"), key=str) == [(1, 10.0), (7, None)]


def test_self_join_with_asymmetric_filters():
    # per-side filters give the two sides different masks; self-pair
    # exclusion must track event identity, not per-side ordinals
    trades = [Trade(0, -1.0, 1000), Trade(0, 5.0, 2000)]
    out = run_join(
        trades, mk_quotes(1),
        "from Trades[price > 0.0] as a "
        "join Trades#window.length(4) as b on a.price > b.price "
        "select a.price as p1, b.price as p2 insert into out",
    )
    # the only legitimate pair: a = 5.0 (passes the filter), b = -1.0
    assert sorted(out) == [(5.0, -1.0)]


def test_self_join_equal_values_no_self_pair():
    trades = [Trade(0, 5.0, 1000), Trade(0, 5.0, 2000)]
    out = run_join(
        trades, mk_quotes(1),
        "from Trades as a join Trades as b on a.price == b.price "
        "select a.timestamp as t1, b.timestamp as t2 insert into out",
    )
    # the two equal-priced events pair with each other (once per role),
    # but never with themselves
    assert sorted(out) == [(1000, 2000), (2000, 1000)]
