"""Windowed join end-to-end (SiddhiCEPITCase.java:306-327, 413-439 analog).

Oracle semantics: each arriving event joins the opposite side's window
contents as of its arrival; every ordered pair is emitted exactly once (by
the later event). Length windows = last n matching events; time windows =
events within t ms before the arrival.
"""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP


@dataclasses.dataclass
class Trade:
    sym: int
    price: float
    timestamp: int


@dataclasses.dataclass
class Quote:
    sym: int
    bid: float
    timestamp: int


TF = ["sym", "price", "timestamp"]
QF = ["sym", "bid", "timestamp"]


def join_oracle(trades, quotes, win_t, win_q, on, within=None):
    """Returns the multiset of (trade, quote) pairs a streaming windowed
    join emits. win_*: ('length', n) or ('time', ms)."""
    arrivals = sorted(
        [("t", e) for e in trades] + [("q", e) for e in quotes],
        key=lambda x: x[1].timestamp,
    )
    t_seen, q_seen = [], []
    pairs = []

    def window(seen, win, now_ts):
        kind, n = win
        if kind == "length":
            return seen[-n:]
        return [e for e in seen if e.timestamp > now_ts - n]

    for side, e in arrivals:
        if side == "t":
            for q in window(q_seen, win_q, e.timestamp):
                if on(e, q) and (
                    within is None or abs(e.timestamp - q.timestamp) <= within
                ):
                    pairs.append((e, q))
            t_seen.append(e)
        else:
            for t in window(t_seen, win_t, e.timestamp):
                if on(t, e) and (
                    within is None or abs(t.timestamp - e.timestamp) <= within
                ):
                    pairs.append((t, e))
            q_seen.append(e)
    return pairs


def run_join(trades, quotes, cql, batch_size=4096):
    env = CEPEnvironment(batch_size=batch_size)
    return (
        SiddhiCEP.define("Trades", trades, TF, env=env)
        .union("Quotes", quotes, QF)
        .cql(cql)
        .returns("out")
    )


def mk_trades(n, start=1000, step=1000, syms=3):
    return [Trade(i % syms, 100.0 + i, start + step * i) for i in range(n)]


def mk_quotes(n, start=1500, step=1000, syms=3):
    return [Quote(i % syms, 50.0 + i, start + step * i) for i in range(n)]


@pytest.mark.parametrize("batch_size", [4096, 6])
def test_length_window_join(batch_size):
    trades, quotes = mk_trades(12), mk_quotes(10)
    out = run_join(
        trades, quotes,
        "from Trades#window.length(4) as t "
        "join Quotes#window.length(3) as q on t.sym == q.sym "
        "select t.sym, t.price, q.bid insert into out",
        batch_size=batch_size,
    )
    expected = [
        (t.sym, t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("length", 4), ("length", 3),
            lambda t, q: t.sym == q.sym,
        )
    ]
    assert sorted(out) == sorted(expected)


@pytest.mark.parametrize("batch_size", [4096, 5])
def test_time_window_join(batch_size):
    trades, quotes = mk_trades(10), mk_quotes(10)
    out = run_join(
        trades, quotes,
        "from Trades#window.time(3 sec) as t "
        "join Quotes#window.time(2 sec) as q on t.sym == q.sym "
        "select t.price, q.bid insert into out",
        batch_size=batch_size,
    )
    expected = [
        (t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("time", 3000), ("time", 2000),
            lambda t, q: t.sym == q.sym,
        )
    ]
    assert sorted(out) == sorted(expected)


def test_join_compound_on_condition():
    trades, quotes = mk_trades(8), mk_quotes(8)
    out = run_join(
        trades, quotes,
        "from Trades#window.length(5) as t "
        "join Quotes#window.length(5) as q "
        "on t.sym == q.sym and t.price > q.bid + 52.0 "
        "select t.price, q.bid insert into out",
    )
    expected = [
        (t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("length", 5), ("length", 5),
            lambda t, q: t.sym == q.sym and t.price > q.bid + 52.0,
        )
    ]
    assert sorted(out) == sorted(expected)


def test_join_within():
    trades, quotes = mk_trades(8), mk_quotes(8)
    out = run_join(
        trades, quotes,
        "from Trades#window.length(8) as t "
        "join Quotes#window.length(8) as q on t.sym == q.sym "
        "within 1500 select t.price, q.bid insert into out",
    )
    expected = [
        (t.price, q.bid)
        for t, q in join_oracle(
            trades, quotes, ("length", 8), ("length", 8),
            lambda t, q: t.sym == q.sym, within=1500,
        )
    ]
    assert sorted(out) == sorted(expected)


def test_left_outer_join():
    trades = [Trade(0, 100.0, 1000), Trade(7, 101.0, 2000)]
    quotes = [Quote(0, 50.0, 500)]
    out = run_join(
        trades, quotes,
        "from Trades#window.length(4) as t "
        "left outer join Quotes#window.length(4) as q on t.sym == q.sym "
        "select t.sym, q.bid insert into out",
    )
    # sym 0 matches; sym 7 emits with zero-filled quote side
    assert sorted(out) == [(0, 50.0), (7, 0.0)]


def test_join_select_star():
    trades = [Trade(0, 100.0, 1000)]
    quotes = [Quote(0, 50.0, 1500)]
    out = run_join(
        trades, quotes,
        "from Trades#window.length(4) as t "
        "join Quotes#window.length(4) as q on t.sym == q.sym "
        "insert into out",
    )
    assert out == [(0, 100.0, 1000, 0, 50.0, 1500)]


def test_self_join_rejected():
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    with pytest.raises(SiddhiQLError):
        run_join(
            mk_trades(2), mk_quotes(2),
            "from Trades#window.length(2) as a "
            "join Trades#window.length(2) as b on a.sym == b.sym "
            "select a.price insert into out",
        )
