"""Direct group by / having / aggregation ON a join query (round-3
missing item 5: the chaining form worked, the single-query spelling —
legal SiddhiQL — raised)."""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

S = StreamSchema(
    [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
     ("timestamp", AttributeType.LONG)]
)
T = StreamSchema(
    [("id", AttributeType.INT), ("qty", AttributeType.INT),
     ("timestamp", AttributeType.LONG)]
)


def run(cql, n=40, batch=24):
    rng = np.random.default_rng(13)
    ids_s = rng.integers(0, 3, n).astype(np.int32)
    prices = np.round(rng.random(n) * 10, 2)
    ts_s = (1000 + 2 * np.arange(n)).astype(np.int64)
    ids_t = rng.integers(0, 3, n).astype(np.int32)
    qty = rng.integers(1, 5, n).astype(np.int32)
    ts_t = (1001 + 2 * np.arange(n)).astype(np.int64)
    plan = compile_plan(cql, {"S": S, "T": T})
    # MULTIPLE micro-batches: donated-state bugs (e.g. cached device
    # arrays fed back into a donating jit) only surface past batch 1
    def src(sid, sch, cols, ts):
        return BatchSource(sid, sch, iter([
            EventBatch(
                sid, sch,
                {k: v[i:i + batch] for k, v in cols.items()},
                ts[i:i + batch],
            )
            for i in range(0, n, batch)
        ]))
    job = Job(
        [plan],
        [src("S", S, {"id": ids_s, "price": prices,
                      "timestamp": ts_s}, ts_s),
         src("T", T, {"id": ids_t, "qty": qty,
                      "timestamp": ts_t}, ts_t)],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job, (ids_s, prices, ts_s, ids_t, qty, ts_t)


def _join_rows(data, win=4):
    ids_s, prices, ts_s, ids_t, qty, ts_t = data
    events = sorted(
        [(int(t), "S", int(i), float(p))
         for t, i, p in zip(ts_s, ids_s, prices)]
        + [(int(t), "T", int(i), int(k))
           for t, i, k in zip(ts_t, ids_t, qty)]
    )
    ring = {"S": [], "T": []}
    rows = []
    for t, side, k, v in events:
        other = "T" if side == "S" else "S"
        for (ot, ok, ov) in ring[other][-win:]:
            if ok == k:
                if side == "S":
                    rows.append((t, k, v, ov))
                else:
                    rows.append((t, k, ov, v))
        ring[side].append((t, k, v))
    return rows  # (emit_ts, id, price, qty) in emission order


def test_join_direct_groupby_sum():
    cql = (
        "from S#window.length(4) join T#window.length(4) on S.id == T.id "
        "select S.id as k, sum(T.qty) as total "
        "group by S.id insert into o"
    )
    job, data = run(cql)
    rows = job.results("o")
    # oracle: per join emission, cumulative per-group sum of qty
    sums = {}
    exp = []
    for _, k, _p, q_ in _join_rows(data):
        sums[k] = sums.get(k, 0) + q_
        exp.append((k, sums[k]))
    assert len(rows) == len(exp) > 0
    assert rows == exp


def test_join_direct_having():
    cql = (
        "from S#window.length(4) join T#window.length(4) on S.id == T.id "
        "select S.id as k, count() as c group by S.id "
        "having c > 5 insert into o"
    )
    job, data = run(cql)
    rows = job.results("o")
    cnt = {}
    exp = []
    for _, k, _p, _q in _join_rows(data):
        cnt[k] = cnt.get(k, 0) + 1
        if cnt[k] > 5:
            exp.append((k, cnt[k]))
    assert rows == exp and len(rows) > 0


def test_join_direct_groupby_string_key():
    """Round-5: STRING group keys over the chained (join-rewrite) path —
    host batches and device columns both carry dictionary codes, so the
    numeric value->group mapping applies unchanged; output decodes the
    code back to the string."""
    S2 = StreamSchema(
        [("sym", AttributeType.STRING), ("price", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)],
        shared_strings=S.string_tables.get("sym"),
    )
    T2 = StreamSchema(
        [("sym", AttributeType.STRING), ("qty", AttributeType.INT),
         ("timestamp", AttributeType.LONG)],
    )
    # one shared dictionary across both streams (the CEPEnvironment
    # contract); T2 must intern through S2's table
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema as _SS
    shared = S2.string_tables["sym"]
    T2 = _SS(
        [("sym", AttributeType.STRING), ("qty", AttributeType.INT),
         ("timestamp", AttributeType.LONG)],
        shared_strings=shared,
    )
    syms = ["aaa", "bbb", "ccc"]
    n, batch = 40, 24
    rng = np.random.default_rng(13)
    cs = rng.integers(0, 3, n)
    codes_s = np.asarray(
        [shared.intern(syms[c]) for c in cs], np.int32
    )
    prices = np.round(rng.random(n) * 10, 2)
    ts_s = (1000 + 2 * np.arange(n)).astype(np.int64)
    ct = rng.integers(0, 3, n)
    codes_t = np.asarray(
        [shared.intern(syms[c]) for c in ct], np.int32
    )
    qty = rng.integers(1, 5, n).astype(np.int32)
    ts_t = (1001 + 2 * np.arange(n)).astype(np.int64)
    cql = (
        "from S#window.length(4) join T#window.length(4) "
        "on S.sym == T.sym "
        "select S.sym as k, sum(T.qty) as total "
        "group by S.sym insert into o"
    )
    plan = compile_plan(cql, {"S": S2, "T": T2})

    def src(sid, sch, cols, ts):
        return BatchSource(sid, sch, iter([
            EventBatch(
                sid, sch,
                {k: v[i:i + batch] for k, v in cols.items()},
                ts[i:i + batch],
            )
            for i in range(0, n, batch)
        ]))

    job = Job(
        [plan],
        [src("S", S2, {"sym": codes_s, "price": prices,
                       "timestamp": ts_s}, ts_s),
         src("T", T2, {"sym": codes_t, "qty": qty,
                       "timestamp": ts_t}, ts_t)],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    rows = job.results("o")
    # oracle over the join emissions (same ring logic as _join_rows,
    # keyed by symbol)
    data = (cs, prices, ts_s, ct, qty, ts_t)
    sums = {}
    exp = []
    for _, k, _p, q_ in _join_rows(data):
        sums[k] = sums.get(k, 0) + q_
        exp.append((syms[k], sums[k]))
    assert len(rows) == len(exp) > 0
    assert rows == exp
