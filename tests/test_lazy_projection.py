"""Late materialization (EngineConfig.lazy_projection): projection-only
columns never ship to the device — the chain matcher emits event
ordinals and decode resolves them from host-retained batches.

On a remote/tunneled accelerator the wire is the throughput ceiling
(README); this cuts the headline pattern's wire to the predicate column
+ timestamp deltas. Values decode at full host precision (float64),
strictly better than the device's float32 round-trip.
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("name", AttributeType.STRING),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)

CQL = (
    "from every s1 = S[id == 1] -> s2 = S[id == 2] -> s3 = S[id == 3] "
    "within 5 sec "
    "select s1.timestamp as t1, s3.timestamp as t3, s3.price as price, "
    "s3.name as n3 insert into matches"
)


def make_batches(n=2000, batch=64, seed=7):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 6, n).astype(np.int32)
    prices = np.round(rng.random(n) * 100, 3)
    names = rng.integers(0, 3, n)
    ts = (1000 + np.arange(n)).astype(np.int64)
    tbl = SCHEMA.string_tables["name"]
    codes = np.array([tbl.intern(f"nm{i}") for i in range(3)], np.int32)
    return [
        EventBatch(
            "S", SCHEMA,
            {
                "id": ids[s:s + batch],
                "name": codes[names[s:s + batch]],
                "price": prices[s:s + batch],
                "timestamp": ts[s:s + batch],
            },
            ts[s:s + batch],
        )
        for s in range(0, n, batch)
    ]


def run(cfg, batch=64):
    plan = compile_plan(CQL, {"S": SCHEMA}, config=cfg)
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(make_batches(batch=batch)))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return plan, sorted(job.results("matches"))


def test_lazy_matches_eager_results():
    plan_e, eager = run(EngineConfig())
    plan_l, lazy = run(EngineConfig(lazy_projection=True))
    # the predicate column is the only one left on the wire
    assert plan_l.spec.device_columns == ("S.id",)
    a = plan_l.artifacts[0]
    assert set(a.lazy_pairs) == {
        (0, "timestamp"), (2, "name"), (2, "price"), (2, "timestamp")
    }
    assert len(eager) == len(lazy) > 0
    for (t1e, t3e, pe, ne), (t1l, t3l, pl, nl) in zip(eager, lazy):
        assert (t1e, t3e, ne) == (t1l, t3l, nl)
        # lazy decodes the ORIGINAL float64; eager went through f32
        assert pl == pytest.approx(pe, rel=1e-6)


def test_lazy_partials_across_batch_boundaries():
    # a partial started in one batch completes several batches later:
    # its lazy ordinals resolve against older ring entries
    _, lazy = run(EngineConfig(lazy_projection=True), batch=16)
    _, eager = run(EngineConfig(), batch=16)
    assert len(lazy) == len(eager) > 0


def test_computed_projection_is_not_lazy():
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] "
        "select s1.timestamp as t1, s2.price * 2.0 as p2 insert into o"
    )
    plan = compile_plan(
        cql, {"S": SCHEMA}, config=EngineConfig(lazy_projection=True)
    )
    a = plan.artifacts[0]
    # price feeds a computed expression -> must stay on the device
    assert (1, "price") not in a.lazy_pairs
    assert "S.price" in (plan.spec.device_columns or ())


def test_ring_eviction_decodes_none():
    from flink_siddhi_tpu.runtime.executor import _LazyRing

    ring = _LazyRing(budget_bytes=64)
    ring.push(0, {"S.x": np.arange(8, dtype=np.float64)})  # 64 B
    ring.push(8, {"S.x": np.arange(8, dtype=np.float64) + 100})
    # first entry evicted (budget); its ordinals miss
    vals = ring.lookup("S.x", np.array([2, 9]))
    assert vals[0] is None
    assert vals[1] == 101.0
    assert ring.missed == 1


def test_lazy_survives_checkpoint_restore(tmp_path):
    # post-restore matches must decode real values: the host ring base
    # re-syncs from the restored device ordinal counter
    plan = compile_plan(
        CQL, {"S": SCHEMA}, config=EngineConfig(lazy_projection=True)
    )
    batches = make_batches(n=512, batch=64)
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches[:4]))],
        batch_size=64, time_mode="processing",
    )
    job.run(max_cycles=4)
    p = tmp_path / "c.bin"
    job.save_checkpoint(str(p))

    plan2 = compile_plan(
        CQL, {"S": SCHEMA}, config=EngineConfig(lazy_projection=True)
    )
    job2 = Job(
        [plan2], [BatchSource("S", SCHEMA, iter(batches[4:]))],
        batch_size=64, time_mode="processing",
    )
    job2.restore(str(p))
    job2.run()
    rows = job2.results("matches")
    post = [r for r in rows if r[2] is not None]
    # brand-new post-restore matches carry real values (only partials
    # carried ACROSS the restore may decode None)
    assert post, f"all post-restore matches decoded None: {rows[:5]}"


def test_lazy_plan_not_folded_dynamically():
    plan = compile_plan(
        CQL, {"S": SCHEMA}, config=EngineConfig(lazy_projection=True)
    )
    job = Job(
        [],
        [BatchSource("S", SCHEMA, iter(make_batches(n=256)))],
        batch_size=64, time_mode="processing",
    )
    job.add_plan(plan, dynamic=True)
    # lazy plans keep their own runtime (no parametric group wrap)
    assert list(job._plans) == [plan.plan_id]
    job.run()
    assert all(
        r[2] is not None for r in job.results("matches")
    )


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_sharded_job_auto_disables_lazy():
    # VERDICT round-2 item 8: a lazy-compiled plan must not make
    # ShardedJob refuse — it recompiles without lazy projection and
    # still matches the single-device results
    from flink_siddhi_tpu.parallel import ShardedJob

    plan = compile_plan(
        CQL, {"S": SCHEMA}, config=EngineConfig(lazy_projection=True)
    )
    assert any(getattr(a, "lazy_pairs", ()) for a in plan.artifacts)
    job = ShardedJob(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(n=512)))],
        n_shards=8, batch_size=64, time_mode="processing",
    )
    rt = next(iter(job._plans.values()))
    assert not any(
        getattr(a, "lazy_pairs", ()) for a in rt.plan.artifacts
    )
    job.run()
    single = Job(
        [compile_plan(CQL, {"S": SCHEMA})],
        [BatchSource("S", SCHEMA, iter(make_batches(n=512)))],
        batch_size=64, time_mode="processing",
    )
    single.run()
    assert sorted(job.results("matches")) == sorted(
        single.results("matches")
    )


# -- lazy stateless select/filter (round-4: the filter bench was wire-
# bound at 7 B/event because select plans always shipped every projected
# column; lazy select drops the wire to predicate column + ts deltas) --

SELECT_CQL = (
    "from S[id == 2] select id, name, price insert into out"
)


def run_select(cfg, cql=SELECT_CQL, batch=64, n=2000):
    plan = compile_plan(cql, {"S": SCHEMA}, config=cfg)
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(n=n, batch=batch)))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return plan, job.results("out")


def test_lazy_select_matches_eager():
    plan_e, eager = run_select(EngineConfig())
    plan_l, lazy = run_select(EngineConfig(lazy_projection=True))
    # only the predicate column ships; name/price resolve host-side
    assert plan_l.spec.device_columns == ("S.id",)
    a = plan_l.artifacts[0]
    assert set(a.lazy_pairs) == {"S.name", "S.price"}
    assert len(eager) == len(lazy) > 0
    for (ide, ne, pe), (idl, nl, pl) in zip(eager, lazy):
        assert (ide, ne) == (idl, nl)
        # lazy decodes the ORIGINAL float64; eager went through f32
        assert pl == pytest.approx(pe, rel=1e-6)


def test_lazy_select_no_filter_ships_nothing():
    # a projection-only query's wire is just the timestamp deltas
    cql = "from S select name, price insert into out"
    plan_l, lazy = run_select(EngineConfig(lazy_projection=True), cql=cql)
    assert plan_l.spec.device_columns == ()
    _, eager = run_select(EngineConfig(), cql=cql)
    assert len(lazy) == len(eager) == 2000
    for (ne, pe), (nl, pl) in zip(eager, lazy):
        assert ne == nl
        assert pl == pytest.approx(pe, rel=1e-6)


def test_lazy_select_computed_expr_stays_on_device():
    cql = "from S[id == 2] select price * 2.0 as p2, name insert into out"
    plan_l, lazy = run_select(EngineConfig(lazy_projection=True), cql=cql)
    a = plan_l.artifacts[0]
    assert a.lazy_pairs == ("S.name",)
    assert "S.price" in plan_l.spec.device_columns
    _, eager = run_select(EngineConfig(), cql=cql)
    assert lazy == eager and len(lazy) > 0


def test_lazy_select_survives_checkpoint_restore(tmp_path):
    plan = compile_plan(
        SELECT_CQL, {"S": SCHEMA},
        config=EngineConfig(lazy_projection=True),
    )
    batches = make_batches(n=512, batch=64)
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches[:4]))],
        batch_size=64, time_mode="processing",
    )
    job.run()
    path = str(tmp_path / "ck")
    job.save_checkpoint(path)
    plan2 = compile_plan(
        SELECT_CQL, {"S": SCHEMA},
        config=EngineConfig(lazy_projection=True),
    )
    job2 = Job(
        [plan2], [BatchSource("S", SCHEMA, iter(batches[4:]))],
        batch_size=64, time_mode="processing",
    )
    job2.restore(path)
    job2.run()
    for row in job2.results("out"):
        assert row[1] is not None and row[2] is not None


def test_ring_eviction_warns_at_drain(caplog):
    """Round-5 verdict item 9: horizon-evicted Nones in user rows must
    not be silent — the drain that surfaces them logs the miss count."""
    import logging

    plan = compile_plan(
        CQL, {"S": SCHEMA},
        config=EngineConfig(
            lazy_projection=True, lazy_ring_budget_bytes=2048
        ),
    )
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(batch=16)))],
        batch_size=16, time_mode="processing",
    )
    with caplog.at_level(
        logging.WARNING, logger="flink_siddhi_tpu.runtime.executor"
    ):
        job.run()
        rows = job.results("matches")
    rt = next(iter(job._plans.values()))
    assert rt.lazy.missed > 0, "tiny budget must evict live entries"
    assert any(None in r for r in rows)
    assert any(
        "evicted past the ring horizon" in rec.message
        for rec in caplog.records
    )
