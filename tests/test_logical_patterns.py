"""Logical pattern groups (`and`/`or`) and terminal timed absence
(`A -> not B for t`) — parity-pinned against per-event Python oracles.

Reference capability surface: siddhi-core pattern processing
(package-info.java:36-38, README.md:84); the reference's own tests only
exercise `->` chains, so these semantics are pinned by oracle instead.
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
)


def run(cql, ids, ts, batch=16):
    bs = []
    for s in range(0, len(ids), batch):
        e = min(s + batch, len(ids))
        bs.append(
            EventBatch(
                "S", SCHEMA,
                {
                    "id": np.array(ids[s:e], np.int32),
                    "timestamp": np.array(ts[s:e], np.int64),
                },
                np.array(ts[s:e], np.int64),
            )
        )
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(bs))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return sorted(job.results("o"))


# --------------------------------------------------------------------------
# and / or groups
# --------------------------------------------------------------------------

def oracle_or_chain(ids, ts, first, pair, last):
    """every s1=[first] -> (a=[pair0] or b=[pair1]) -> s4=[last]"""
    partials = []  # (t1, stage) stage: 1=want group, 2=want last
    out = []
    for eid, t in zip(ids, ts):
        nxt = []
        for t1, stage in partials:
            if stage == 1 and eid in pair:
                nxt.append((t1, 2))
            elif stage == 2 and eid == last:
                out.append((t1, t))
            else:
                nxt.append((t1, stage))
        partials = nxt
        if eid == first:
            partials.append((t, 1))
    return sorted(out)


def oracle_and_chain(ids, ts, first, pair):
    """every s1=[first] -> (a=[pair0] and b=[pair1]): any order."""
    partials = []  # (t1, {member: ts})
    out = []
    for eid, t in zip(ids, ts):
        nxt = []
        for t1, seen in partials:
            if eid in pair and eid not in seen:
                seen2 = dict(seen)
                seen2[eid] = t
                if len(seen2) == 2:
                    out.append((t1, seen2[pair[0]], seen2[pair[1]]))
                else:
                    nxt.append((t1, seen2))
            else:
                nxt.append((t1, seen))
        partials = nxt
        if eid == first:
            partials.append((t, {}))
    return sorted(out)


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("batch", [5, 64])
def test_or_group_vs_oracle(seed, batch):
    rng = np.random.default_rng(seed)
    n = 200
    ids = rng.integers(0, 6, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 5, n))).astype(int).tolist()
    cql = (
        "from every s1 = S[id == 1] -> "
        "(a = S[id == 2] or b = S[id == 3]) -> s4 = S[id == 4] "
        "select s1.timestamp as t1, s4.timestamp as t4 insert into o"
    )
    assert run(cql, ids, ts, batch) == oracle_or_chain(
        ids, ts, 1, (2, 3), 4
    )


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("batch", [5, 64])
def test_and_group_vs_oracle(seed, batch):
    rng = np.random.default_rng(100 + seed)
    n = 150
    ids = rng.integers(0, 5, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 5, n))).astype(int).tolist()
    cql = (
        "from every s1 = S[id == 1] -> "
        "(a = S[id == 2] and b = S[id == 3]) "
        "select s1.timestamp as t1, a.timestamp as ta, "
        "b.timestamp as tb insert into o"
    )
    assert run(cql, ids, ts, batch) == oracle_and_chain(ids, ts, 1, (2, 3))


def test_and_group_any_order_and_arming():
    cql = (
        "from every (a = S[id == 2] and b = S[id == 3]) -> s4 = S[id == 4] "
        "select a.timestamp as ta, b.timestamp as tb insert into o"
    )
    # b arrives first, then a, then the trailing element
    assert run(cql, [3, 2, 4], [1000, 1001, 1002]) == [(1001, 1000)]


def test_group_validation_errors():
    with pytest.raises(SiddhiQLError, match="mix 'and' and 'or'"):
        compile_plan(
            "from every (a = S[id == 1] and b = S[id == 2] or c = S[id == 3])"
            " -> d = S[id == 4] select a.timestamp as t insert into o",
            {"S": SCHEMA},
        )
    with pytest.raises(SiddhiQLError, match="cannot be quantified"):
        compile_plan(
            "from every (a = S[id == 1]+ and b = S[id == 2]) -> c = S[id==3]"
            " select b.timestamp as t insert into o",
            {"S": SCHEMA},
        )
    with pytest.raises(SiddhiQLError, match="ONE 'or' group"):
        compile_plan(
            "from every (a = S[id == 1] or b = S[id == 2]) "
            "select a.timestamp as t insert into o",
            {"S": SCHEMA},
        )
    with pytest.raises(SiddhiQLError, match="match in any order"):
        compile_plan(
            "from every s0 = S[id == 9] -> "
            "(a = S[id == 1] and b = S[id == 2 and b.timestamp > "
            "a.timestamp]) select a.timestamp as t insert into o",
            {"S": SCHEMA},
        )


# --------------------------------------------------------------------------
# timed terminal absence
# --------------------------------------------------------------------------

def oracle_timed_absence(ids, ts, first, guard, tfor):
    """every s1=[first] -> not [guard] for tfor. Emits (t1,) at deadline
    t1+tfor when no guard event lands in (t1, t1+tfor]. End of stream
    matures all pending windows."""
    out = []
    for i, (eid, t1) in enumerate(zip(ids, ts)):
        if eid != first:
            continue
        ok = True
        for eid2, t2 in zip(ids[i + 1:], ts[i + 1:]):
            if eid2 == guard and t1 < t2 <= t1 + tfor:
                ok = False
                break
        if ok:
            out.append((t1,))
    return sorted(out)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("batch", [4, 64])
def test_timed_absence_vs_oracle(seed, batch):
    rng = np.random.default_rng(seed)
    n = 120
    ids = rng.integers(0, 4, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(50, 800, n))).astype(int).tolist()
    cql = (
        "from every s1 = S[id == 1] -> not S[id == 2] for 2 sec "
        "select s1.timestamp as t1 insert into o"
    )
    assert run(cql, ids, ts, batch) == oracle_timed_absence(
        ids, ts, 1, 2, 2000
    )


def test_timed_absence_after_chain():
    # full chain then absence window: s1 -> s2 -> not s3 for 1 sec
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] "
        "-> not S[id == 3] for 1 sec "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into o"
    )
    # first chain killed by id3@2500 (inside 2000+1000); second survives
    ids = [1, 2, 3, 1, 2, 9]
    ts = [1000, 2000, 2500, 5000, 5100, 9000]
    assert run(cql, ids, ts) == [(5000, 5100)]


def test_timed_absence_emission_timestamp_is_deadline():
    cql = (
        "from every s1 = S[id == 1] -> not S[id == 2] for 2 sec "
        "select s1.timestamp as t1 insert into o"
    )
    plan = compile_plan(cql, {"S": SCHEMA})
    ids, ts = [1, 9], [1000, 8000]
    b = EventBatch(
        "S", SCHEMA,
        {
            "id": np.array(ids, np.int32),
            "timestamp": np.array(ts, np.int64),
        },
        np.array(ts, np.int64),
    )
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter([b]))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    rows = job.results_with_ts("o")
    assert rows == [(3000, (1000,))]  # visible at t1 + 2 sec


def test_absence_validation_errors():
    with pytest.raises(SiddhiQLError, match="needs a duration"):
        compile_plan(
            "from every s1 = S[id == 1] -> not S[id == 2] "
            "select s1.timestamp as t insert into o",
            {"S": SCHEMA},
        )
    with pytest.raises(SiddhiQLError, match="must be the last"):
        compile_plan(
            "from every s1 = S[id == 1] -> not S[id == 2] for 1 sec "
            "-> s3 = S[id == 3] select s1.timestamp as t insert into o",
            {"S": SCHEMA},
        )


def test_or_group_unfired_member_is_null():
    cql = (
        "from every s1 = S[id == 1] -> (a = S[id == 2] or b = S[id == 3]) "
        "select s1.timestamp as t1, a.timestamp as ta, b.timestamp as tb "
        "insert into o"
    )
    got = run(cql, [1, 3, 1, 2], [1000, 2000, 3000, 4000])
    # exactly one member fires per match; the other decodes None
    assert sorted(got, key=str) == sorted(
        [(1000, None, 2000), (3000, 4000, None)], key=str
    )


def test_non_every_timed_absence_single_match():
    cql = (
        "from s1 = S[id == 1] -> not S[id == 2] for 2 sec "
        "select s1.timestamp as t1 insert into o"
    )
    # two waiting partials at flush: only the earliest emits
    assert run(cql, [1, 1, 9], [1000, 1500, 1600]) == [(1000,)]
    # match matured in-stream: flush must not add a second
    assert run(cql, [1, 9, 1, 9], [1000, 4000, 4100, 4200]) == [(1000,)]


def test_same_timestamp_guard_does_not_kill():
    # window is (t1, t1 + t]: a guard AT t1 (later arrival, equal ts)
    # does not kill the absence window — matches the oracle's t1 < t2
    cql = (
        "from every s1 = S[id == 1] -> not S[id == 2] for 2 sec "
        "select s1.timestamp as t1 insert into o"
    )
    assert run(cql, [1, 2, 9], [1000, 1000, 5000]) == [(1000,)]


def test_same_ts_guard_does_not_mask_later_guard():
    # a same-timestamp guard must not hide a LATER guard inside the
    # window: id2@1000 is outside (t1, t1+t], but id2@2000 is inside
    cql = (
        "from every s1 = S[id == 1] -> not S[id == 2] for 2 sec "
        "select s1.timestamp as t1 insert into o"
    )
    assert run(cql, [1, 2, 2, 9], [1000, 1000, 2000, 9000]) == []
