"""Native C++ columnar decoder: correctness, interner-code consistency
with query compilation, fallback equivalence, and e2e ingest.

Reference analog: the schema/serializer bridge tests
(StreamSerializerTest.java:29-81) pin record->row conversion; here the
unit under test is bytes->columns with dictionary-interned strings.
"""

import io
import json

import numpy as np
import pytest

from flink_siddhi_tpu.native import (
    KIND_DOUBLE,
    KIND_INT,
    KIND_STRING,
    ColumnDecoder,
    available,
)
from flink_siddhi_tpu.schema.strings import StringTable


def make_decoder():
    table = StringTable()
    fields = [
        ("id", KIND_INT, None),
        ("name", KIND_STRING, table),
        ("price", KIND_DOUBLE, None),
    ]
    return ColumnDecoder(fields), table


def sample_lines(n=100):
    recs = [
        {"id": i, "name": f"n{i % 5}", "price": i * 0.5, "extra": [1, 2]}
        for i in range(n)
    ]
    return (
        "\n".join(json.dumps(r) for r in recs).encode() + b"\n",
        recs,
    )


def test_native_available():
    # the environment ships g++; the in-tree Makefile must build
    assert available(), "native decode library failed to build/load"


def test_json_decode_basic():
    dec, table = make_decoder()
    data, recs = sample_lines(100)
    cols, valid, n = dec.decode_json(data, 200)
    assert n == 100 and valid.all()
    assert cols[0].tolist() == [r["id"] for r in recs]
    assert [table.value(c) for c in cols[1]] == [r["name"] for r in recs]
    np.testing.assert_allclose(
        cols[2], [r["price"] for r in recs]
    )


def test_json_escapes_and_unicode():
    dec, table = make_decoder()
    line = (
        b'{"id": 1, "name": "a\\"b\\\\c\\nd\\u00e9\\ud83d\\ude00", '
        b'"price": -2.5e2}\n'
    )
    cols, valid, n = dec.decode_json(line, 10)
    assert n == 1 and valid[0]
    assert table.value(cols[1][0]) == 'a"b\\c\ndé\U0001F600'
    assert cols[2][0] == -250.0


def test_json_missing_fields_and_null():
    dec, table = make_decoder()
    data = (
        b'{"id": 7}\n'
        b'{"name": null, "price": 1.5, "id": 8}\n'
    )
    cols, valid, n = dec.decode_json(data, 10)
    assert n == 2 and valid.all()
    assert cols[0].tolist() == [7, 8]
    assert table.value(cols[1][0]) == "" and table.value(cols[1][1]) == ""
    assert cols[2].tolist() == [0.0, 1.5]


def test_json_malformed_rows_flagged():
    dec, _ = make_decoder()
    data = b'{"id": 1}\nnot json\n{"id": 3}\n{"id": oops}\n'
    cols, valid, n = dec.decode_json(data, 10)
    assert n == 4
    assert valid.tolist() == [1, 0, 1, 0]
    assert cols[0][0] == 1 and cols[0][2] == 3


def test_interner_codes_match_precompiled_constants():
    # query compilation interns constants FIRST; native decode must reuse
    # those codes, and newly discovered strings must round-trip back
    dec, table = make_decoder()
    pre = table.intern("n3")  # as a query predicate constant would
    data, recs = sample_lines(20)
    cols, valid, n = dec.decode_json(data, 30)
    codes = {table.value(c): int(c) for c in cols[1]}
    assert codes["n3"] == pre
    # every python-side lookup agrees with the decoded codes
    for name, code in codes.items():
        assert table.lookup(name) == code


def test_python_fallback_equivalence():
    data, recs = sample_lines(50)
    native_dec, t1 = make_decoder()
    if not native_dec.native:
        pytest.skip("no native library in this environment")
    py_dec, t2 = make_decoder()
    py_dec._lib = None  # force fallback
    py_dec._mirrors = []
    a_cols, a_valid, a_n = native_dec.decode_json(data, 100)
    b_cols, b_valid, b_n = py_dec.decode_json(data, 100)
    assert a_n == b_n and a_valid.tolist() == b_valid.tolist()
    assert a_cols[0].tolist() == b_cols[0].tolist()
    np.testing.assert_allclose(a_cols[2], b_cols[2])
    assert [t1.value(c) for c in a_cols[1]] == [
        t2.value(c) for c in b_cols[1]
    ]


def test_csv_decode():
    dec, table = make_decoder()
    data = b'1,alpha,0.5\n2,"beta,x",1.5\n3,alpha,2.5\nbad,row,zz\n'
    cols, valid, n = dec.decode_csv(data, 10)
    assert n == 4
    assert valid.tolist() == [1, 1, 1, 0]
    assert cols[0][:3].tolist() == [1, 2, 3]
    assert table.value(cols[1][1]) == "beta,x"
    assert cols[2][:3].tolist() == [0.5, 1.5, 2.5]


def test_json_lines_source_e2e(tmp_path):
    # file -> native decode -> CEP filter query -> typed results
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import JsonLinesSource
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        for i in range(200):
            f.write(
                json.dumps(
                    {
                        "id": i % 4,
                        "name": f"n{i % 3}",
                        "price": float(i),
                        "timestamp": 1000 + i,
                    }
                )
                + "\n"
            )
    schema = StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )
    src = JsonLinesSource(
        "S", schema, str(path), ts_field="timestamp", chunk_bytes=512
    )
    plan = compile_plan(
        "from S[id == 2] select name, price insert into out",
        {"S": schema},
    )
    job = Job([plan], [src], batch_size=64)
    job.run()
    rows = job.results("out")
    assert len(rows) == 50
    assert rows[0] == ("n2", 2.0)


def test_csv_source_e2e(tmp_path):
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import CsvSource
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    path = tmp_path / "events.csv"
    with open(path, "w") as f:
        f.write("id,name,price,timestamp\n")
        for i in range(100):
            f.write(f"{i % 4},n{i % 3},{float(i)},{1000 + i}\n")
    schema = StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )
    src = CsvSource(
        "S", schema, str(path), header=True, ts_field="timestamp"
    )
    plan = compile_plan(
        "from S[price > 90.0] select id, price insert into big",
        {"S": schema},
    )
    job = Job([plan], [src], batch_size=64)
    job.run()
    assert len(job.results("big")) == 9


def test_csv_bool_literals_both_decoders():
    # bool cells accept case-insensitive true/false (and 0/1), matching
    # the JSON path; previously only strtoll parsed and 'true' cells
    # silently invalidated the row
    from flink_siddhi_tpu.native import KIND_BOOL

    def make_bool_decoder():
        table = StringTable()
        fields = [("id", KIND_INT, None), ("flag", KIND_BOOL, None)]
        return ColumnDecoder(fields)

    data = (
        b"1,true\n2,False\n3,TRUE\n4,0\n5,1\n6,maybe\n"
        b"+7,true \n 8 , FALSE\n"  # signs/whitespace: int()/float() parity
    )
    native_dec = make_bool_decoder()
    py_dec = make_bool_decoder()
    py_dec._lib = None  # force fallback
    py_dec._mirrors = []
    for dec in (native_dec, py_dec):
        cols, valid, n = dec.decode_csv(data, 10)
        assert n == 8
        assert valid.tolist() == [1, 1, 1, 1, 1, 0, 1, 1], dec.native
        assert cols[0][6:8].tolist() == [7, 8], dec.native
        assert (
            cols[1][:5].tolist() + cols[1][6:8].tolist()
        ) == [1, 0, 1, 0, 1, 1, 0], dec.native


def test_source_allowed_lateness(tmp_path):
    # bounded-disorder input: with allowed_lateness_ms the watermark holds
    # back, so a later chunk carrying older timestamps still reorders
    # correctly through the executor's reorder buffer
    from flink_siddhi_tpu.runtime.sources import JsonLinesSource
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    lines = [
        {"id": 0, "timestamp": 1000},
        {"id": 1, "timestamp": 1200},  # chunk 1 max ts = 1200
        {"id": 2, "timestamp": 1100},  # older than chunk 1's max
        {"id": 3, "timestamp": 1300},
    ]
    raw = "\n".join(json.dumps(r) for r in lines).encode() + b"\n"
    src = JsonLinesSource(
        "S", schema, io.BytesIO(raw), ts_field="timestamp",
        chunk_bytes=40, allowed_lateness_ms=200,
    )
    batch, wm, done = src.poll(10)
    assert wm == int(batch.timestamps.max()) - 200


def test_sink_streams_skip_retention_when_disabled():
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    ids = np.arange(100, dtype=np.int64) % 4
    ts = 1000 + np.arange(100, dtype=np.int64)
    batch = EventBatch("S", schema, {"id": ids, "timestamp": ts}, ts)
    plan = compile_plan(
        "from S[id == 2] select id, timestamp insert into out",
        {"S": schema},
    )
    got = []
    job = Job(
        [plan],
        [BatchSource("S", schema, iter([batch]))],
        batch_size=64,
        retain_results=False,
    )
    job.add_sink("out", lambda ts, row: got.append(row))
    job.run()
    assert len(got) == 25
    # sink consumed every row; host retention skipped, counter still live
    assert job.results("out") == []
    assert job.emitted_counts["out"] == 25
