"""Randomized equivalence: device pattern engines vs a trivial Python
NFA oracle.

The role SiddhiSyntaxTest plays in the reference (pinning raw engine
behavior, SiddhiCEPITCase.java:333-382 semantics) — here the oracle is
an obviously-correct per-event interpreter for `every A -> B [-> C]
[within t]` chains, and the engine must produce identical match sets
for random streams regardless of micro-batch boundaries.
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType


def oracle_chain(ids, ts, steps, within=None, every=True):
    """Pure-python chain NFA: each event matching steps[0] opens a
    partial; each partial advances through steps in order, taking the
    FIRST later event that matches its next step; `within` bounds
    last-first timestamps. Returns sorted match tuples of event ids'
    timestamps."""
    partials = []  # list of (start_idx, next_step, captured ts list)
    matches = []
    done = False
    for i, (eid, t) in enumerate(zip(ids, ts)):
        new_partials = []
        for start, step, caps in partials:
            if eid == steps[step]:
                caps2 = caps + [t]
                if within is not None and caps2[-1] - caps2[0] > within:
                    continue  # expired
                if step + 1 == len(steps):
                    if every or not done:
                        matches.append(tuple(caps2))
                        done = True
                else:
                    new_partials.append((start, step + 1, caps2))
            else:
                new_partials.append((start, step, caps))
        partials = new_partials
        if eid == steps[0] and (every or not done):
            if len(steps) == 1:
                matches.append((t,))
                done = True
            else:
                partials.append((i, 1, [t]))
    return sorted(matches)


ID_TS_SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
)


def make_batches(schema, cols, ts, batch):
    """Split columns into EventBatches of ``batch`` events."""
    n = len(ts)
    out = []
    for s in range(0, n, batch):
        e = min(s + batch, n)
        out.append(
            EventBatch(
                "S", schema,
                {
                    k: np.asarray(v[s:e], dt)
                    for k, (v, dt) in cols.items()
                },
                np.asarray(ts[s:e], np.int64),
            )
        )
    return out


def run_engine(ids, ts, steps, within, batch, every=True):
    schema = ID_TS_SCHEMA
    batches = make_batches(
        schema,
        {"id": (ids, np.int32), "timestamp": (ts, np.int64)},
        ts, batch,
    )
    pat = " -> ".join(
        f"s{k} = S[id == {v}]" for k, v in enumerate(steps)
    )
    sel = ", ".join(
        f"s{k}.timestamp as t{k}" for k in range(len(steps))
    )
    w = f" within {within // 1000} sec" if within is not None else ""
    ev = "every " if every else ""
    cql = f"from {ev}{pat}{w} select {sel} insert into o"
    plan = compile_plan(cql, {"S": schema})
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return sorted(job.results("o"))


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("batch", [7, 64, 997])
def test_chain_vs_oracle_random(seed, batch):
    rng = np.random.default_rng(seed)
    n = 400
    ids = rng.integers(0, 6, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 50, n))).tolist()
    steps = [1, 2, 3]
    expected = oracle_chain(ids, ts, steps)
    got = run_engine(ids, ts, steps, None, batch)
    assert got == expected


@pytest.mark.parametrize("within_s", [1, 5])
def test_chain_within_vs_oracle(within_s):
    rng = np.random.default_rng(42)
    n = 500
    ids = rng.integers(0, 5, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 900, n))).tolist()
    steps = [1, 2]
    within = within_s * 1000
    expected = oracle_chain(ids, ts, steps, within=within)
    got = run_engine(ids, ts, steps, within, batch=61)
    assert got == expected


def test_non_every_vs_oracle():
    rng = np.random.default_rng(9)
    n = 300
    ids = rng.integers(0, 4, n).tolist()
    ts = (1000 + np.arange(n) * 10).tolist()
    steps = [1, 2]
    expected = oracle_chain(ids, ts, steps, every=False)
    got = run_engine(ids, ts, steps, None, batch=37, every=False)
    assert got == expected


def test_time_window_groupby_vs_oracle():
    """Sliding #window.time group-by sum/count (prefix/expiry path)
    against a per-event python oracle, across batch splits."""
    rng = np.random.default_rng(11)
    n = 600
    ids = rng.integers(0, 5, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 400, n))).tolist()
    vals = [float(v) for v in rng.integers(1, 100, n)]
    span = 2000

    def oracle():
        out = []
        hist = []  # (ts, id, val)
        for t, g, v in zip(ts, ids, vals):
            hist.append((t, g, v))
            window = [h for h in hist if h[0] > t - span]
            mine = [h for h in window if h[1] == g]
            out.append((g, sum(h[2] for h in mine), len(mine)))
        return out

    schema = StreamSchema(
        [
            ("id", AttributeType.INT),
            ("v", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )
    for batch in (41, 512):
        batches = make_batches(
            schema,
            {
                "id": (ids, np.int32),
                "v": (vals, np.float64),
                "timestamp": (ts, np.int64),
            },
            ts, batch,
        )
        plan = compile_plan(
            "from S#window.time(2 sec) select id, sum(v) as t, "
            "count() as c group by id insert into o",
            {"S": schema},
        )
        job = Job(
            [plan], [BatchSource("S", schema, iter(batches))],
            batch_size=batch,
        )
        job.run()
        got = job.results("o")
        expected = oracle()
        assert len(got) == len(expected)
        for (gg, gt, gc), (eg, et, ec) in zip(got, expected):
            assert gg == eg and gc == ec
            assert abs(gt - et) < 1e-3 * max(1.0, abs(et))


def oracle_absence(ids, ts, a, b, c):
    """`every A -> not B -> C`: partial opened per A; first later B or C
    resolves it (B kills, C completes)."""
    partials = []
    matches = []
    for eid, t in zip(ids, ts):
        resolved = []
        for i, (ta,) in enumerate(partials):
            if eid == b:
                resolved.append(i)  # killed
            elif eid == c:
                matches.append((ta, t))
                resolved.append(i)
        for i in reversed(resolved):
            partials.pop(i)
        if eid == a:
            partials.append((t,))
    return sorted(matches)


@pytest.mark.parametrize("batch", [11, 128])
def test_midchain_absence_vs_oracle(batch):
    rng = np.random.default_rng(5)
    n = 500
    ids = rng.integers(0, 6, n).tolist()
    ts = (1000 + np.arange(n) * 7).tolist()
    expected = oracle_absence(ids, ts, 1, 2, 3)
    schema = ID_TS_SCHEMA
    batches = make_batches(
        schema,
        {"id": (ids, np.int32), "timestamp": (ts, np.int64)},
        ts, batch,
    )
    plan = compile_plan(
        "from every s1 = S[id == 1] -> not S[id == 2] -> "
        "s3 = S[id == 3] select s1.timestamp as t1, "
        "s3.timestamp as t3 insert into o",
        {"S": schema},
    )
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    assert sorted(job.results("o")) == expected


def oracle_sequence(ids, ts, steps, every=True):
    """Per-event interpreter for `[every] e0, e1, ... (sequence)` where
    each step is (match_id, min_count, max_count; max -1 = unbounded),
    following the engine's documented rules (nfa.py module docstring):
    strict continuity, greedy absorb-before-advance, optional-skip,
    break kills (emitting if every remaining element is optional), and
    `every` spawning an independent partial per first-element match.

    Returns sorted tuples of (first_ts of step0, last_ts of step0,
    ts of final matched step).
    """
    matches = []

    def min_sum(a, b):  # sum of min_counts for steps in (a, b)
        return sum(steps[i][1] for i in range(a + 1, b))

    partials = []  # (step_idx, count, caps)
    armed_done = False

    def close(caps):
        nonlocal armed_done
        matches.append(_seq_result(caps))
        armed_done = True

    for eid, t in zip(ids, ts):
        survivors = []
        for step, count, caps in partials:
            sid, mn, mx = steps[step]
            if eid == sid and (mx < 0 or count < mx):
                caps[step][1] = t
                if caps[step][0] is None:
                    caps[step][0] = t
                if step == len(steps) - 1 and count + 1 == mx:
                    close(caps)
                else:
                    survivors.append((step, count + 1, caps))
                continue
            advanced = False
            if count >= mn:
                for tgt in range(step + 1, len(steps)):
                    if min_sum(step, tgt) == 0 and eid == steps[tgt][0]:
                        caps[tgt][0] = caps[tgt][1] = t
                        if (
                            tgt == len(steps) - 1
                            and steps[tgt][2] == 1
                        ):
                            close(caps)
                        else:
                            survivors.append((tgt, 1, caps))
                        advanced = True
                        break
            if advanced:
                continue
            # break: emit if all remaining elements are optional
            if count >= mn and min_sum(step, len(steps)) == 0:
                close(caps)
        partials = survivors
        can_arm = every or (not armed_done and not partials)
        if eid == steps[0][0] and can_arm:
            caps = [[None, None] for _ in steps]
            caps[0][0] = caps[0][1] = t
            if len(steps) == 1 and steps[0][2] == 1:
                close(caps)
            else:
                partials.append((0, 1, caps))
    return sorted(matches)


def _seq_result(caps):
    last_step = max(i for i, c in enumerate(caps) if c[0] is not None)
    return (caps[0][0], caps[0][1], caps[last_step][1])


@pytest.mark.parametrize("seed", [3, 4])
@pytest.mark.parametrize("batch", [13, 256])
def test_sequence_plus_vs_oracle(seed, batch):
    """`every s1 = A[id==1]+ , s2 = A[id==2]` vs the oracle."""
    rng = np.random.default_rng(seed)
    n = 400
    ids = rng.integers(0, 4, n).tolist()
    ts = (1000 + np.arange(n) * 3).tolist()
    expected = oracle_sequence(
        ids, ts, [(1, 1, -1), (2, 1, 1)]
    )
    schema = ID_TS_SCHEMA
    batches = make_batches(
        schema,
        {"id": (ids, np.int32), "timestamp": (ts, np.int64)},
        ts, batch,
    )
    plan = compile_plan(
        "from every s1 = S[id == 1]+ , s2 = S[id == 2] "
        "select s1[0].timestamp as a, s1[last].timestamp as b, "
        "s2.timestamp as c insert into o",
        {"S": schema},
    )
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    got = sorted(job.results("o"))
    assert got == expected


# --------------------------------------------------------------------------
# Cross-element filter references (s2 = S[price > s1.price])
# --------------------------------------------------------------------------

PRICE_SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)


def oracle_cross(ids, prices, ts, kind="pattern", within=None):
    """Per-event interpreter for
    ``every s1 = S[id==1] (->|,) s2 = S[id==2 and price > s1.price]``.
    Pattern: non-matching events are skipped; sequence: the immediately
    next event must match or the partial dies (emitting nothing)."""
    partials = []  # list of s1 price/ts
    matches = []
    for eid, p, t in zip(ids, prices, ts):
        nxt = []
        for (p1, t1) in partials:
            if within is not None and t - t1 > within:
                continue
            if eid == 2 and p > p1:
                matches.append((p1, p))
            elif kind == "pattern":
                nxt.append((p1, t1))
            # sequence: any non-advancing event kills the partial
        partials = nxt
        if eid == 1:
            partials.append((p, t))
    return sorted(matches)


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("batch", [5, 64])
@pytest.mark.parametrize("kind", ["pattern", "sequence"])
def test_cross_element_filter_vs_oracle(seed, batch, kind):
    rng = np.random.default_rng(seed)
    n = 300
    ids = rng.integers(0, 4, n).tolist()
    prices = np.round(rng.random(n) * 10, 1).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 5, n))).tolist()
    sep = " -> " if kind == "pattern" else ", "
    cql = (
        f"from every s1 = S[id == 1]{sep}"
        "s2 = S[id == 2 and price > s1.price] "
        "select s1.price as p1, s2.price as p2 insert into o"
    )
    plan = compile_plan(cql, {"S": PRICE_SCHEMA})
    batches = make_batches(
        PRICE_SCHEMA,
        {
            "id": (ids, np.int32),
            "price": (prices, np.float64),
            "timestamp": (ts, np.int64),
        },
        ts, batch,
    )
    job = Job(
        [plan], [BatchSource("S", PRICE_SCHEMA, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    got = sorted(
        (round(p1, 1), round(p2, 1)) for p1, p2 in job.results("o")
    )
    assert got == oracle_cross(ids, prices, ts, kind)


def test_cross_element_quantified_last_ref():
    # s2 must exceed the LAST event absorbed by the quantified s1+
    ids = [1, 1, 1, 2, 2]
    prices = [3.0, 6.0, 4.0, 5.0, 7.0]
    ts = [1000 + i for i in range(5)]
    cql = (
        "from every s1 = S[id == 1]+, s2 = S[price > s1[last].price] "
        "select s1[0].price as first1, s1[last].price as last1, "
        "s2.price as p2 insert into o"
    )
    plan = compile_plan(cql, {"S": PRICE_SCHEMA})
    batches = make_batches(
        PRICE_SCHEMA,
        {
            "id": (ids, np.int32),
            "price": (prices, np.float64),
            "timestamp": (ts, np.int64),
        },
        ts, 8,
    )
    job = Job(
        [plan], [BatchSource("S", PRICE_SCHEMA, iter(batches))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    # greedy s1+ absorbs 3,6,4 (others die on non-absorbing events);
    # s2 needs price > 4 -> the id==2@5.0 event completes it
    assert (3.0, 4.0, 5.0) in job.results("o")


def test_cross_element_forward_reference_rejected():
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    cql = (
        "from every s1 = S[price > s2.price] -> s2 = S[id == 2] "
        "select s1.price as p insert into o"
    )
    with pytest.raises(SiddhiQLError, match="EARLIER"):
        compile_plan(cql, {"S": PRICE_SCHEMA})


def test_cross_ref_to_skipped_optional_never_matches():
    # s2 is optional and absent from the input; s3's filter references
    # s2 -> the comparison is against nothing (Siddhi: null), so no match
    ids = [1, 3]
    prices = [9.0, 5.0]
    ts = [1000, 1001]
    cql = (
        "from every s1 = S[id == 1], s2 = S[id == 2]?, "
        "s3 = S[id == 3 and price > s2.price] "
        "select s1.price as p1, s3.price as p3 insert into o"
    )
    plan = compile_plan(cql, {"S": PRICE_SCHEMA})
    batches = make_batches(
        PRICE_SCHEMA,
        {
            "id": (ids, np.int32),
            "price": (prices, np.float64),
            "timestamp": (ts, np.int64),
        },
        ts, 8,
    )
    job = Job(
        [plan], [BatchSource("S", PRICE_SCHEMA, iter(batches))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    assert job.results("o") == []
    # and WITH the optional present, the filter applies to its capture
    ids2, prices2, ts2 = [1, 2, 3], [9.0, 4.0, 5.0], [1000, 1001, 1002]
    plan2 = compile_plan(cql, {"S": PRICE_SCHEMA})
    job2 = Job(
        [plan2],
        [BatchSource("S", PRICE_SCHEMA, iter(make_batches(
            PRICE_SCHEMA,
            {
                "id": (ids2, np.int32),
                "price": (prices2, np.float64),
                "timestamp": (ts2, np.int64),
            },
            ts2, 8,
        )))],
        batch_size=8, time_mode="processing",
    )
    job2.run()
    assert job2.results("o") == [(9.0, 5.0)]


# --------------------------------------------------------------------------
# Sequence absence before a QUANTIFIED element (`A, not B, C+` /
# `A, not B, C<m:n>`): the count-conditional entry guard vs the
# measured-baseline per-event interpreter (baseline/interp.py
# _Sequence) — the ROADMAP carried item's done-condition.
# --------------------------------------------------------------------------

def _run_vs_baseline_interp(cql, ids, prices, batch):
    """Engine rows vs BaselineEngine rows on the identical stream."""
    from flink_siddhi_tpu.baseline import BaselineEngine

    n = len(ids)
    ts = (1000 + np.arange(n) * 3).tolist()
    schema = PRICE_SCHEMA
    batches = make_batches(
        schema,
        {
            "id": (ids, np.int32),
            "price": (prices, np.float64),
            "timestamp": (ts, np.int64),
        },
        ts, batch,
    )
    plan = compile_plan(cql, {"S": schema})
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    base = BaselineEngine(cql, ["id", "price", "timestamp"])
    base_rows = []
    base._emit = lambda out, t, row: base_rows.append(row)
    base.run_columns(
        {"id": ids, "price": prices, "timestamp": ts}, ts
    )
    assert sorted(job.results("m")) == sorted(
        base_rows
    )


@pytest.mark.parametrize("seed", [11, 12])
def test_sequence_absence_plus_vs_baseline_interp(seed):
    """`A, not B, C+, D`: the guard vetoes only C's ENTRY event; later
    absorbed C's may match B freely (count-conditional placement)."""
    rng = np.random.default_rng(seed)
    n = 500
    ids = rng.integers(0, 5, n).tolist()
    prices = rng.uniform(0.0, 100.0, n).round(1).tolist()
    cql = (
        "from every s1 = S[id == 1], not S[price > 50.0], "
        "s3 = S[id == 3]+ , s4 = S[id == 4] "
        "select s1.timestamp as t1, s3.timestamp as t3, "
        "s4.timestamp as t4 insert into m"
    )
    _run_vs_baseline_interp(cql, ids, prices, batch=64)


def test_sequence_absence_bounded_vs_baseline_interp():
    """`A, not B, C<2:4>`: entry guard + bounded greedy absorb, with
    completion on both the count-4 absorb and the break paths."""
    rng = np.random.default_rng(13)
    n = 500
    # denser C's so <2:4> runs of every length actually occur
    ids = rng.choice([0, 1, 3, 3], size=n).tolist()
    prices = rng.uniform(0.0, 100.0, n).round(1).tolist()
    cql = (
        "from every s1 = S[id == 1], not S[price > 50.0], "
        "s3 = S[id == 3]<2:4> "
        "select s1.timestamp as t1, s3.timestamp as t3 insert into m"
    )
    _run_vs_baseline_interp(cql, ids, prices, batch=64)
