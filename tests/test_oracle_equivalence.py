"""Randomized equivalence: device pattern engines vs a trivial Python
NFA oracle.

The role SiddhiSyntaxTest plays in the reference (pinning raw engine
behavior, SiddhiCEPITCase.java:333-382 semantics) — here the oracle is
an obviously-correct per-event interpreter for `every A -> B [-> C]
[within t]` chains, and the engine must produce identical match sets
for random streams regardless of micro-batch boundaries.
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType


def oracle_chain(ids, ts, steps, within=None, every=True):
    """Pure-python chain NFA: each event matching steps[0] opens a
    partial; each partial advances through steps in order, taking the
    FIRST later event that matches its next step; `within` bounds
    last-first timestamps. Returns sorted match tuples of event ids'
    timestamps."""
    partials = []  # list of (start_idx, next_step, captured ts list)
    matches = []
    done = False
    for i, (eid, t) in enumerate(zip(ids, ts)):
        new_partials = []
        for start, step, caps in partials:
            if eid == steps[step]:
                caps2 = caps + [t]
                if within is not None and caps2[-1] - caps2[0] > within:
                    continue  # expired
                if step + 1 == len(steps):
                    if every or not done:
                        matches.append(tuple(caps2))
                        done = True
                else:
                    new_partials.append((start, step + 1, caps2))
            else:
                new_partials.append((start, step, caps))
        partials = new_partials
        if eid == steps[0] and (every or not done):
            if len(steps) == 1:
                matches.append((t,))
                done = True
            else:
                partials.append((i, 1, [t]))
    return sorted(matches)


def run_engine(ids, ts, steps, within, batch, every=True):
    schema = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    n = len(ids)
    batches = []
    for s in range(0, n, batch):
        e = min(s + batch, n)
        batches.append(
            EventBatch(
                "S", schema,
                {
                    "id": np.asarray(ids[s:e], np.int32),
                    "timestamp": np.asarray(ts[s:e], np.int64),
                },
                np.asarray(ts[s:e], np.int64),
            )
        )
    pat = " -> ".join(
        f"s{k} = S[id == {v}]" for k, v in enumerate(steps)
    )
    sel = ", ".join(
        f"s{k}.timestamp as t{k}" for k in range(len(steps))
    )
    w = f" within {within // 1000} sec" if within is not None else ""
    ev = "every " if every else ""
    cql = f"from {ev}{pat}{w} select {sel} insert into o"
    plan = compile_plan(cql, {"S": schema})
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return sorted(job.results("o"))


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("batch", [7, 64, 997])
def test_chain_vs_oracle_random(seed, batch):
    rng = np.random.default_rng(seed)
    n = 400
    ids = rng.integers(0, 6, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 50, n))).tolist()
    steps = [1, 2, 3]
    expected = oracle_chain(ids, ts, steps)
    got = run_engine(ids, ts, steps, None, batch)
    assert got == expected


@pytest.mark.parametrize("within_s", [1, 5])
def test_chain_within_vs_oracle(within_s):
    rng = np.random.default_rng(42)
    n = 500
    ids = rng.integers(0, 5, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 900, n))).tolist()
    steps = [1, 2]
    within = within_s * 1000
    expected = oracle_chain(ids, ts, steps, within=within)
    got = run_engine(ids, ts, steps, within, batch=61)
    assert got == expected


def test_non_every_vs_oracle():
    rng = np.random.default_rng(9)
    n = 300
    ids = rng.integers(0, 4, n).tolist()
    ts = (1000 + np.arange(n) * 10).tolist()
    steps = [1, 2]
    expected = oracle_chain(ids, ts, steps, every=False)
    got = run_engine(ids, ts, steps, None, batch=37, every=False)
    assert got == expected


def test_time_window_groupby_vs_oracle():
    """Sliding #window.time group-by sum/count (prefix/expiry path)
    against a per-event python oracle, across batch splits."""
    rng = np.random.default_rng(11)
    n = 600
    ids = rng.integers(0, 5, n).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 400, n))).tolist()
    vals = [float(v) for v in rng.integers(1, 100, n)]
    span = 2000

    def oracle():
        out = []
        hist = []  # (ts, id, val)
        for t, g, v in zip(ts, ids, vals):
            hist.append((t, g, v))
            window = [h for h in hist if h[0] > t - span]
            mine = [h for h in window if h[1] == g]
            out.append((g, sum(h[2] for h in mine), len(mine)))
        return out

    schema = StreamSchema(
        [
            ("id", AttributeType.INT),
            ("v", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ]
    )
    for batch in (41, 512):
        batches = []
        for s in range(0, n, batch):
            e = min(s + batch, n)
            batches.append(
                EventBatch(
                    "S", schema,
                    {
                        "id": np.asarray(ids[s:e], np.int32),
                        "v": np.asarray(vals[s:e], np.float64),
                        "timestamp": np.asarray(ts[s:e], np.int64),
                    },
                    np.asarray(ts[s:e], np.int64),
                )
            )
        plan = compile_plan(
            "from S#window.time(2 sec) select id, sum(v) as t, "
            "count() as c group by id insert into o",
            {"S": schema},
        )
        job = Job(
            [plan], [BatchSource("S", schema, iter(batches))],
            batch_size=batch,
        )
        job.run()
        got = job.results("o")
        expected = oracle()
        assert len(got) == len(expected)
        for (gg, gt, gc), (eg, et, ec) in zip(got, expected):
            assert gg == eg and gc == ec
            assert abs(gt - et) < 1e-3 * max(1.0, abs(et))


def oracle_absence(ids, ts, a, b, c):
    """`every A -> not B -> C`: partial opened per A; first later B or C
    resolves it (B kills, C completes)."""
    partials = []
    matches = []
    for eid, t in zip(ids, ts):
        resolved = []
        for i, (ta,) in enumerate(partials):
            if eid == b:
                resolved.append(i)  # killed
            elif eid == c:
                matches.append((ta, t))
                resolved.append(i)
        for i in reversed(resolved):
            partials.pop(i)
        if eid == a:
            partials.append((t,))
    return sorted(matches)


@pytest.mark.parametrize("batch", [11, 128])
def test_midchain_absence_vs_oracle(batch):
    rng = np.random.default_rng(5)
    n = 500
    ids = rng.integers(0, 6, n).tolist()
    ts = (1000 + np.arange(n) * 7).tolist()
    expected = oracle_absence(ids, ts, 1, 2, 3)
    schema = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    batches = []
    for s in range(0, n, batch):
        e = min(s + batch, n)
        batches.append(
            EventBatch(
                "S", schema,
                {
                    "id": np.asarray(ids[s:e], np.int32),
                    "timestamp": np.asarray(ts[s:e], np.int64),
                },
                np.asarray(ts[s:e], np.int64),
            )
        )
    plan = compile_plan(
        "from every s1 = S[id == 1] -> not S[id == 2] -> "
        "s3 = S[id == 3] select s1.timestamp as t1, "
        "s3.timestamp as t3 insert into o",
        {"S": schema},
    )
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    assert sorted(job.results("o")) == expected
