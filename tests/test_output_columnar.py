"""Columnar sink fast lane: equivalence with the per-row path, the
emission_order side-channel contract, rate-limiter batch accounting,
and the tail-aware drain scheduler's staleness leg.

The per-row ``decode_buffered``/``decode_packed_block`` path is the
compatibility ORACLE (ISSUE 5): every columnar product must carry
identical values, order, and counts. The parametrized job-level test
covers all three device emission layouts (aligned select, buffered
pattern, packed lazy-chain ordinals) plus a rate-limited stream.
"""

import numpy as np
import pytest

from flink_siddhi_tpu import (
    AttributeType,
    ColumnarSink,
    EventBatch,
    StreamSchema,
)
from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.output import (
    ColumnBatch,
    OutputField,
    OutputSchema,
    emission_order,
)
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job, _OutputRateLimiter
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.strings import StringTable


# -- emission_order: the side-channel desync contract ----------------------


def test_emission_order_is_stable_by_timestamp():
    """THE permutation (compiler/output.py:120-125 contract): stable
    sort by timestamp — equal timestamps keep slot order, so artifacts
    reordering side-channel rows with the same helper stay aligned."""
    rng = np.random.default_rng(11)
    for trial in range(200):
        n = int(rng.integers(1, 64))
        # heavy duplication on purpose: stability only matters for ties
        ts = rng.integers(0, 8, size=n).astype(np.int64)
        order = emission_order(ts, n)
        # brute-force oracle: sort (ts, original index) pairs
        expect = sorted(range(n), key=lambda i: (ts[i], i))
        assert order.tolist() == expect, (trial, ts.tolist())


def test_emission_order_keeps_side_channel_rows_paired():
    """Fuzz the slot-NFA-mbits / join-missing-side pattern: a packed
    block whose extra row (past the schema columns) is reordered by the
    SAME emission_order call must stay paired with its data row."""
    schema = OutputSchema(
        "s",
        (
            OutputField("a", AttributeType.INT),
            OutputField("b", AttributeType.DOUBLE),
        ),
    )
    rng = np.random.default_rng(7)
    for trial in range(100):
        n = int(rng.integers(1, 48))
        ts = rng.integers(0, 6, size=n).astype(np.int32)
        a = np.arange(n, dtype=np.int32)  # unique: identifies the row
        b = rng.random(n).astype(np.float32)
        side = a * 3 + 1  # the side-channel marker, keyed to its row
        block = np.stack(
            [ts, a, b.view(np.int32), side.astype(np.int32)]
        )
        rows = schema.decode_packed_block(n, block[:3])
        markers = np.asarray(block[3, :n])[emission_order(block[0], n)]
        assert len(rows) == n
        for (row_ts, row), m in zip(rows, markers.tolist()):
            # the marker must still belong to ITS data row
            assert m == row[0] * 3 + 1, (trial, rows, markers)
        # and the columnar twin applies the identical permutation
        cb = schema.decode_packed_columns(n, block[:3])
        assert cb.ts.tolist() == [t for t, _ in rows]
        assert cb.cols["a"].tolist() == [r[0] for _, r in rows]


def test_side_channel_desync_without_the_helper():
    """Negative control: a permutation that breaks ties differently
    (sort by timestamp, LATEST slot first) is NOT emission_order — the
    desync bug class the contract pins."""
    ts = np.array([3, 1, 1, 0], dtype=np.int64)
    n = 4
    good = emission_order(ts, n)
    reversed_ties = np.array(
        sorted(range(n), key=lambda i: (ts[i], -i)), dtype=np.int64
    )
    assert not np.array_equal(good, reversed_ties)


# -- whole-column decode equivalence ---------------------------------------


def _schema_with_strings():
    table = StringTable()
    for v in ("alpha", "beta", "gamma"):
        table.intern(v)
    return (
        OutputSchema(
            "s",
            (
                OutputField("i", AttributeType.INT),
                OutputField("f", AttributeType.DOUBLE),
                OutputField("s", AttributeType.STRING, table=table),
                OutputField("b", AttributeType.BOOL),
            ),
        ),
        table,
    )


def test_decode_columns_matches_decode_buffered():
    schema, table = _schema_with_strings()
    rng = np.random.default_rng(3)
    for trial in range(50):
        n = int(rng.integers(0, 40))
        cap = n + int(rng.integers(0, 8))
        ts = rng.integers(0, 10, size=cap).astype(np.int32)
        cols = [
            rng.integers(-5, 5, size=cap).astype(np.int32),
            rng.random(cap).astype(np.float32),
            rng.integers(-1, len(table) + 1, size=cap).astype(np.int32),
            rng.integers(0, 2, size=cap).astype(np.int32),
        ]
        rows = schema.decode_buffered(n, ts, cols)
        cb = schema.decode_columns(n, ts, cols)
        assert len(cb) == len(rows) == n
        assert cb.rows() == rows  # values, order, AND types-on-tolist


def test_decode_aligned_columns_matches_decode_aligned():
    schema, table = _schema_with_strings()
    rng = np.random.default_rng(5)
    for _ in range(30):
        cap = int(rng.integers(1, 40))
        mask = rng.integers(0, 2, size=cap).astype(bool)
        ts = rng.integers(0, 9, size=cap).astype(np.int32)
        cols = [
            rng.integers(0, 9, size=cap).astype(np.int32),
            rng.random(cap).astype(np.float32),
            rng.integers(0, len(table), size=cap).astype(np.int32),
            rng.integers(0, 2, size=cap).astype(np.int32),
        ]
        rows = schema.decode_aligned(mask, ts, cols)
        cb = schema.decode_aligned_columns(mask, ts, cols)
        assert cb.rows() == rows


def test_decode_column_np_out_of_range_codes_decode_none():
    schema, table = _schema_with_strings()
    f = schema.fields[2]
    arr = np.array([0, 99, -1, 2], dtype=np.int32)
    assert f.decode_column_np(arr).tolist() == [
        "alpha", None, None, "gamma",
    ]
    assert f.decode_column_np(arr).tolist() == f.decode_column(arr)


# -- rate limiter: batch accounting parity ---------------------------------


def _cb_of(ts_vals):
    ts = np.asarray(ts_vals, dtype=np.int64)
    return ColumnBatch(ts, {"v": ts * 10})


class _Rate:
    def __init__(self, mode, which, n_events=1, ms=0.0):
        self.mode, self.which = mode, which
        self.n_events, self.ms = n_events, ms


@pytest.mark.parametrize("which", ["all", "first", "last"])
def test_feed_columns_matches_feed_events_mode(which):
    rng = np.random.default_rng(13)
    for chunk in (1, 3, 5):
        lim_r = _OutputRateLimiter(_Rate("events", which, chunk))
        lim_c = _OutputRateLimiter(_Rate("events", which, chunk))
        t = 0
        out_r, out_c = [], []
        for _ in range(20):
            m = int(rng.integers(0, 7))
            ts = list(range(t, t + m))
            t += m
            rows = [(x, (x * 10,)) for x in ts]
            out_r.extend(lim_r.feed(rows))
            for part in lim_c.feed_columns(_cb_of(ts)):
                out_c.extend(
                    (int(a), (int(v),))
                    for a, v in zip(
                        part.ts.tolist(), part.cols["v"].tolist()
                    )
                )
        # end-of-stream flush parity too
        out_r.extend(lim_r.flush())
        for part in lim_c.flush():
            out_c.extend(
                (int(a), (int(v),))
                for a, v in zip(
                    part.ts.tolist(), part.cols["v"].tolist()
                )
            )
        assert out_c == out_r, (which, chunk)


@pytest.mark.parametrize("which", ["all", "first", "last"])
def test_feed_columns_matches_feed_time_mode(which):
    """Deterministic time-mode check: a far deadline (nothing flushes
    mid-run), then flush() — row and columnar lanes release identical
    output."""
    lim_r = _OutputRateLimiter(_Rate("time", which, ms=60_000.0))
    lim_c = _OutputRateLimiter(_Rate("time", which, ms=60_000.0))
    out_r, out_c = [], []
    t = 0
    for m in (2, 0, 4, 1):
        ts = list(range(t, t + m))
        t += m
        out_r.extend(lim_r.feed([(x, (x * 10,)) for x in ts]))
        for part in lim_c.feed_columns(_cb_of(ts)):
            out_c.extend(part.rows())
    out_r.extend(lim_r.flush())
    for part in lim_c.flush():
        out_c.extend(
            (int(a), (int(v),))
            for a, v in zip(part.ts.tolist(), part.cols["v"].tolist())
        )
    out_r2 = [(int(a), (int(v),)) for a, (v,) in out_r]
    out_c2 = [(int(a), (int(v),)) for a, (v,) in out_c]
    assert out_c2 == out_r2


# -- job-level equivalence: ColumnarSink vs row sink on the same job -------


def _make_batches(schema, n=4000, chunk=1000, n_ids=5, seed=0):
    name_code = schema.string_tables["name"].intern("ev")
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_ids, n).astype(np.int32)
    prices = rng.random(n)
    ts = np.arange(n, dtype=np.int64) + 1_000
    out = []
    for i in range(0, n, chunk):
        out.append(
            EventBatch(
                "s",
                schema,
                {
                    "id": ids[i:i + chunk],
                    "name": np.full(
                        len(ids[i:i + chunk]), name_code, np.int32
                    ),
                    "price": prices[i:i + chunk],
                },
                ts[i:i + chunk],
            )
        )
    return out


class _Recorder(ColumnarSink):
    """Records whatever lane delivers, normalized to (ts, row) pairs."""

    def __init__(self, names):
        self.names = names
        self.rows = []
        self.batches = 0

    def accept_columns(self, ts, cols):
        self.batches += 1
        lists = [cols[n].tolist() for n in self.names]
        for t, *vals in zip(ts.tolist(), *lists):
            self.rows.append((int(t), tuple(vals)))


CASES = {
    # aligned layout (stateless select), string decode included
    "aligned_select": (
        "from s[id == 2] select id, name, price insert into out",
        EngineConfig(),
    ),
    # buffered layout (pattern match buffer)
    "buffered_pattern": (
        "from every e1 = s[id == 1] -> e2 = s[id == 2] "
        "select e1.price as p1, e2.price as p2 insert into out",
        EngineConfig(),
    ),
    # packed lazy-ordinal layout: projection-only columns resolve
    # through the host ring (lookup_np on the columnar lane)
    "packed_lazy": (
        "from s[id == 2] select id, name, price insert into out",
        EngineConfig(lazy_projection=True, pred_pushdown=True),
    ),
    # rate-limited stream: the limiter accounts column batches
    "rate_limited": (
        "from s[id == 2] select id, price "
        "output all every 7 events insert into out",
        EngineConfig(),
    ),
}


def _schema():
    return StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
        ]
    )


@pytest.mark.parametrize("case", sorted(CASES))
def test_columnar_and_row_sinks_observe_identical_data(case):
    cql, cfg = CASES[case]

    def run(columnar_only):
        schema = _schema()
        plan = compile_plan(cql, {"s": schema}, config=cfg)
        job = Job(
            [plan],
            [BatchSource("s", schema, iter(_make_batches(schema)))],
            batch_size=1000,
            retain_results=False,
        )
        names = plan.output_streams()["out"][0].field_names
        col_sink = _Recorder(names)
        row_rows = []
        job.add_sink("out", col_sink)
        if not columnar_only:
            job.add_sink(
                "out", lambda ts, row: row_rows.append((ts, tuple(row)))
            )
        job.run()
        return col_sink, row_rows

    # fast lane: columnar-only consumers -> zero row tuples in engine
    col_fast, _ = run(columnar_only=True)
    # mixed consumers: the stream decodes row-wise; the columnar sink
    # gets the converted batches, the row sink the tuples
    col_mixed, row_rows = run(columnar_only=False)

    assert col_fast.rows, case  # the query actually emitted
    assert col_fast.rows == col_mixed.rows == row_rows, case


def test_columnar_lane_requires_all_columnar_consumers():
    """A stream with any row sink decodes row-wise (the fallback), and
    retained-results jobs never go columnar — _columnar_streams gate."""
    schema = _schema()
    plan = compile_plan(
        "from s[id == 2] select id, price insert into out",
        {"s": schema},
    )
    job = Job(
        [plan],
        [BatchSource("s", schema, iter(_make_batches(schema)))],
        batch_size=1000,
        retain_results=True,  # retention on: rows must exist
    )
    sink = _Recorder(["id", "price"])
    job.add_sink("out", sink)
    rt = next(iter(job._plans.values()))
    assert job._columnar_streams(rt) == frozenset()
    job.run()
    # the columnar sink still observed every row via the fallback
    assert sink.rows == [
        (ts, row) for ts, row in job.collected["out"]
    ]


def test_tail_scheduler_records_staleness_and_deadline_drains():
    """The deadline drain scheduler: a consumer job records the
    drain.staleness leg (age of the oldest undrained match at
    completion), and it is bounded by interval + drain time at this
    scale (CPU lane: generous 10x headroom against scheduler jitter)."""
    schema = _schema()
    plan = compile_plan(
        "from s[id == 2] select id, price insert into out",
        {"s": schema},
    )
    job = Job(
        [plan],
        [BatchSource("s", schema, iter(_make_batches(schema)))],
        batch_size=1000,
        retain_results=False,
    )
    job.drain_interval_ms = 20.0
    sink = _Recorder(["id", "price"])
    job.add_sink("out", sink)
    import time as _time

    while not job.finished:
        job.run_cycle()
        _time.sleep(0.005)  # give deadlines a chance to arrive
    job.flush()
    h = job.telemetry.histogram("drain.staleness")
    assert h.count > 0
    assert h.percentile_ms(99) < 10 * (20.0 + 1000.0)
    assert sink.rows


@pytest.mark.parametrize("which", ["all", "last"])
def test_limiter_survives_lane_switch_mid_chunk(which):
    """A stream can change lanes mid-flight (add_sink of a row sink
    drops it off the columnar lane; the gate re-resolves per drain).
    Buffered fragments from the other lane are normalized, so chunk
    accounting continues exactly — oracle: one limiter fed all rows."""

    def norm(parts):
        out = []
        for p in parts:
            if isinstance(p, ColumnBatch):
                out.extend(
                    (int(a), (int(v),))
                    for a, v in zip(
                        p.ts.tolist(), p.cols["v"].tolist()
                    )
                )
            else:
                a, (v,) = p
                out.append((int(a), (int(v),)))
        return out

    for chunk in (3, 7):
        # columnar -> row: feed_columns leaves a partial chunk buffered,
        # then the row path takes over
        lim = _OutputRateLimiter(_Rate("events", which, chunk))
        got = norm(lim.feed_columns(_cb_of(list(range(10)))))
        got += norm(lim.feed([(x, (x * 10,)) for x in range(10, 20)]))
        got += norm(lim.flush())
        # row -> columnar: the buffered row tuples get lifted
        lim2 = _OutputRateLimiter(_Rate("events", which, chunk))
        got2 = norm(lim2.feed([(x, (x * 10,)) for x in range(10)]))
        got2 += norm(lim2.feed_columns(_cb_of(list(range(10, 20)))))
        got2 += norm(lim2.flush())
        # oracle: all 20 rows through the row path alone
        ora = _OutputRateLimiter(_Rate("events", which, chunk))
        want = norm(ora.feed([(x, (x * 10,)) for x in range(20)]))
        want += norm(ora.flush())
        assert got == want, (which, chunk, "columnar->row")
        assert got2 == want, (which, chunk, "row->columnar")
