"""``output [all|last|first] every N events | <duration>``: output rate
limiting at the emission layer (siddhi-core output rate limiters; this
was a reserved keyword that never parsed before round 4)."""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
)


def run(cql, n=10, batch=4):
    ids = list(range(n))
    ts = [1000 + i for i in range(n)]
    batches = [
        EventBatch(
            "S", SCHEMA,
            {"id": np.asarray(ids[s:s + batch], np.int32),
             "timestamp": np.asarray(ts[s:s + batch], np.int64)},
            np.asarray(ts[s:s + batch], np.int64),
        )
        for s in range(0, n, batch)
    ]
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job


def test_output_last_every_n_events():
    job = run(
        "from S select id output last every 3 events insert into o"
    )
    # chunks [0,1,2][3,4,5][6,7,8][9]: last of each complete chunk,
    # plus the pending last at stream end
    assert [r[0] for r in job.results("o")] == [2, 5, 8, 9]


def test_output_first_every_n_events():
    job = run(
        "from S select id output first every 4 events insert into o"
    )
    assert [r[0] for r in job.results("o")] == [0, 4, 8]


def test_output_all_every_n_events_batches():
    job = run(
        "from S select id output all every 5 events insert into o"
    )
    # all rows arrive, released in 5-chunks (+ tail at stream end)
    assert [r[0] for r in job.results("o")] == list(range(10))


def test_output_time_mode_flushes_at_stream_end():
    job = run(
        "from S select id output last every 1 sec insert into o"
    )
    # the run finishes well inside 1s: only the end-of-stream flush
    # emits, carrying the LAST row
    assert [r[0] for r in job.results("o")] == [9]


def test_output_snapshot_plain_select_rejects_loudly():
    # window-CONTENTS snapshots (no aggregation) stay a loud rejection
    with pytest.raises(SiddhiQLError, match="snapshot"):
        compile_plan(
            "from S select id output snapshot every 1 sec insert into o",
            {"S": SCHEMA},
        )


def test_output_snapshot_periodic_aggregate_per_group():
    """Round-5: 'output snapshot every T' over an aggregation emits the
    CURRENT aggregate per group every interval (and the final state at
    stream end), not the row-per-event stream."""
    import time as _time

    from flink_siddhi_tpu.runtime.sources import CallbackSource

    src = CallbackSource("S", SCHEMA)
    plan = compile_plan(
        "from S select id, count() as c group by id "
        "output snapshot every 40 insert into o",
        {"S": SCHEMA},
    )
    assert plan.snapshot_keys["o"] == (0,)
    job = Job([plan], [src], batch_size=8, time_mode="processing")
    job.drain_interval_ms = 10.0
    for i in range(6):  # ids 0,1,0,1,0,1
        src.emit({"id": i % 2, "timestamp": 1000 + i}, 1000 + i)
    t0 = _time.monotonic()
    while (
        len(job.results("o")) < 2 and _time.monotonic() - t0 < 5.0
    ):
        job.run_cycle()
        _time.sleep(0.005)
    # first interval's snapshot: ONE row per group with current counts
    first = sorted(job.results("o")[:2])
    assert first == [(0, 3), (1, 3)]
    src.emit({"id": 0, "timestamp": 2000}, 2000)
    src.close()
    job.run()
    final = sorted(job.results("o")[-2:])
    assert final == [(0, 4), (1, 3)]


def test_time_mode_limiter_emits_without_new_rows():
    """ADVICE r4: buffered time-mode output must surface when the
    interval elapses even if no new row arrives for that stream —
    polled from the run loop's interval-drain cadence."""
    import time as _time

    from flink_siddhi_tpu.runtime.sources import CallbackSource

    src = CallbackSource("S", SCHEMA)
    plan = compile_plan(
        "from S select id output all every 50 insert into o",
        {"S": SCHEMA},
    )
    job = Job(
        [plan], [src], batch_size=8, time_mode="processing",
    )
    job.drain_interval_ms = 10.0
    src.emit({"id": 7, "timestamp": 1000}, 1000)
    t0 = _time.monotonic()
    # run idle cycles ONLY (no further rows): the buffered row must
    # appear once the 50ms interval elapses, well before stream end
    while not job.results("o") and _time.monotonic() - t0 < 5.0:
        job.run_cycle()
        _time.sleep(0.005)
    assert [r[0] for r in job.results("o")] == [7]
    src.close()
    job.run()


def test_limiter_phase_survives_checkpoint(tmp_path):
    """ADVICE r4: events-mode chunk position + buffered rows restore,
    so a resumed job emits at the same chunk boundaries."""
    ids = list(range(10))
    ts = [1000 + i for i in ids]

    def batches(lo, hi, step=2):
        return [
            EventBatch(
                "S", SCHEMA,
                {"id": np.asarray(ids[s:s + step], np.int32),
                 "timestamp": np.asarray(ts[s:s + step], np.int64)},
                np.asarray(ts[s:s + step], np.int64),
            )
            for s in range(lo, hi, step)
        ]

    cql = "from S select id output last every 3 events insert into o"

    def build(bs):
        return Job(
            [compile_plan(cql, {"S": SCHEMA})],
            [BatchSource("S", SCHEMA, iter(bs))],
            batch_size=2, time_mode="processing",
        )

    # uninterrupted run: boundaries at ids 2, 5, 8, then pending 9
    solo = build(batches(0, 10))
    solo.run()
    expect = [r[0] for r in solo.results("o")]

    # stop mid-stream (4 of 10 events, mid-chunk): no end-of-stream
    # limiter flush may run before the snapshot
    job1 = build(batches(0, 10))
    job1.run(max_cycles=2)
    assert not job1.finished
    ck = str(tmp_path / "ck")
    job1.save_checkpoint(ck)
    job2 = build(batches(4, 10))
    job2.restore(ck)
    job2.run()
    got = [r[0] for r in job1.results("o")] + [
        r[0] for r in job2.results("o")
    ]
    assert got == expect
