"""``output [all|last|first] every N events | <duration>``: output rate
limiting at the emission layer (siddhi-core output rate limiters; this
was a reserved keyword that never parsed before round 4)."""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
)


def run(cql, n=10, batch=4):
    ids = list(range(n))
    ts = [1000 + i for i in range(n)]
    batches = [
        EventBatch(
            "S", SCHEMA,
            {"id": np.asarray(ids[s:s + batch], np.int32),
             "timestamp": np.asarray(ts[s:s + batch], np.int64)},
            np.asarray(ts[s:s + batch], np.int64),
        )
        for s in range(0, n, batch)
    ]
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job


def test_output_last_every_n_events():
    job = run(
        "from S select id output last every 3 events insert into o"
    )
    # chunks [0,1,2][3,4,5][6,7,8][9]: last of each complete chunk,
    # plus the pending last at stream end
    assert [r[0] for r in job.results("o")] == [2, 5, 8, 9]


def test_output_first_every_n_events():
    job = run(
        "from S select id output first every 4 events insert into o"
    )
    assert [r[0] for r in job.results("o")] == [0, 4, 8]


def test_output_all_every_n_events_batches():
    job = run(
        "from S select id output all every 5 events insert into o"
    )
    # all rows arrive, released in 5-chunks (+ tail at stream end)
    assert [r[0] for r in job.results("o")] == list(range(10))


def test_output_time_mode_flushes_at_stream_end():
    job = run(
        "from S select id output last every 1 sec insert into o"
    )
    # the run finishes well inside 1s: only the end-of-stream flush
    # emits, carrying the LAST row
    assert [r[0] for r in job.results("o")] == [9]


def test_output_snapshot_rejects_loudly():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "from S select id output snapshot every 1 sec insert into o",
            {"S": SCHEMA},
        )
