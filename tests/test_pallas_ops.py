"""Pallas reverse-cummin kernel: equivalence with lax.cummin.

The kernel logic (blocked right-to-left grid, in-block shift-min sweep,
revisited-output carry) is exercised on CPU via the pallas interpreter.
That import path registers TPU lowering rules, which conflicts with this
suite's conftest (it deletes non-CPU backend factories to keep the
remote-accelerator tunnel out of tests), so the interpreter run happens
in a clean subprocess.
"""

import os
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_INTERPRET_SNIPPET = """
import numpy as np, jax.numpy as jnp
from flink_siddhi_tpu.compiler import pallas_ops
assert pallas_ops.available()
assert pallas_ops.warmup(), "kernel failed to build/probe"
E = 4096
rng = np.random.default_rng(7)
rows = [jnp.asarray(rng.integers(0, 2 ** 29, E).astype(np.int32))
        for _ in range(3)]
out = pallas_ops.multi_reverse_cummin(rows)
assert not pallas_ops._FAILED, "kernel fell back in interpret mode"
for o, r in zip(out, rows):
    ref = np.minimum.accumulate(np.asarray(r)[::-1])[::-1]
    assert np.array_equal(np.asarray(o), ref)
print("OK")
"""


def test_multi_reverse_cummin_interpret():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        FST_PALLAS_INTERPRET="1",
        PYTHONPATH=_REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-c", _INTERPRET_SNIPPET],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert r.returncode == 0 and "OK" in r.stdout, (
        r.stdout + "\n" + r.stderr
    )


def test_fallback_matches():
    # available() reads the env dynamically; no module reload needed
    os.environ["FST_NO_PALLAS"] = "1"
    try:
        import jax.numpy as jnp

        from flink_siddhi_tpu.compiler import pallas_ops

        rows = [jnp.asarray(np.array([5, 3, 7, 1], np.int32))]
        out = pallas_ops.multi_reverse_cummin(rows)
        assert np.asarray(out[0]).tolist() == [1, 1, 1, 1]
    finally:
        os.environ.pop("FST_NO_PALLAS", None)
