"""Pallas reverse-cummin kernel: equivalence with lax.cummin.

The kernel logic (blocked right-to-left grid, in-block shift-min sweep,
revisited-output carry) is exercised on CPU via the pallas interpreter.
That import path registers TPU lowering rules, which conflicts with this
suite's conftest (it deletes non-CPU backend factories to keep the
remote-accelerator tunnel out of tests), so the interpreter run happens
in a clean subprocess.
"""

import os
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_INTERPRET_SNIPPET = """
import numpy as np, jax.numpy as jnp
from flink_siddhi_tpu.compiler import pallas_ops
assert pallas_ops.available()
assert pallas_ops.warmup(), "kernel failed to build/probe"
E = 4096
rng = np.random.default_rng(7)
rows = [jnp.asarray(rng.integers(0, 2 ** 29, E).astype(np.int32))
        for _ in range(3)]
out = pallas_ops.multi_reverse_cummin(rows)
assert not pallas_ops._FAILED, "kernel fell back in interpret mode"
for o, r in zip(out, rows):
    ref = np.minimum.accumulate(np.asarray(r)[::-1])[::-1]
    assert np.array_equal(np.asarray(o), ref)
print("OK")
"""


def test_multi_reverse_cummin_interpret():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        FST_PALLAS_INTERPRET="1",
        PYTHONPATH=_REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-c", _INTERPRET_SNIPPET],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert r.returncode == 0 and "OK" in r.stdout, (
        r.stdout + "\n" + r.stderr
    )


def test_fallback_matches():
    # available() reads the env dynamically; no module reload needed
    os.environ["FST_NO_PALLAS"] = "1"
    try:
        import jax.numpy as jnp

        from flink_siddhi_tpu.compiler import pallas_ops

        rows = [jnp.asarray(np.array([5, 3, 7, 1], np.int32))]
        out = pallas_ops.multi_reverse_cummin(rows)
        assert np.asarray(out[0]).tolist() == [1, 1, 1, 1]
    finally:
        os.environ.pop("FST_NO_PALLAS", None)


# -- chain-advance + unique-fold kernels (fused-dispatch round) ------------
# warmup() probes BOTH against numpy oracles (a probe mismatch disables
# the kernel and the asserts below fail loudly — never skip); the e2e
# snippet then runs real queries twice in ONE process, kernels on
# (interpreter) vs forced fallback (FST_NO_PALLAS reread dynamically),
# and pins row-identical output.

_KERNELS_SNIPPET = """
import os
import numpy as np
from flink_siddhi_tpu.compiler import pallas_ops
assert pallas_ops.available()
pallas_ops.warmup()
assert pallas_ops.chain_kernel_active(), "chain-advance probe failed"
assert pallas_ops.fold_kernel_active(), "unique-fold probe failed"

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

schema = StreamSchema([
    ("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
    ("timestamp", AttributeType.LONG),
])
rng = np.random.default_rng(11)
n, batch = 6000, 512
ids = rng.integers(0, 5, n).astype(np.int32)
prices = np.round(rng.random(n) * 50, 2)
ts = (1000 + 3 * np.arange(n)).astype(np.int64)

def batches():
    return iter([
        EventBatch("S", schema,
                   {"id": ids[s:s + batch], "price": prices[s:s + batch],
                    "timestamp": ts[s:s + batch]}, ts[s:s + batch])
        for s in range(0, n, batch)
    ])

CQLS = {
    "chain": "from every s1 = S[id == 1] -> s2 = S[id == 2] -> "
             "s3 = S[id == 3] within 5 sec select s1.timestamp as t1, "
             "s3.timestamp as t3, s3.price as p insert into o",
    "guard": "from every s1 = S[id == 1] -> not S[id == 4] -> "
             "s2 = S[id == 2] select s1.timestamp as t1, "
             "s2.timestamp as t2 insert into o",
    "unique": "from S#window.unique(id) select id, sum(price) as t, "
              "count() as c, min(price) as mn, max(price) as mx "
              "insert into o",
}

def run_all():
    out = {}
    for name, cql in CQLS.items():
        plan = compile_plan(cql, {"S": schema})
        job = Job([plan], [BatchSource("S", schema, batches())],
                  batch_size=batch, time_mode="processing")
        job.run()
        out[name] = job.results_with_ts("o")
    return out

with_kernels = run_all()
os.environ["FST_NO_PALLAS"] = "1"  # read dynamically: forces fallback
without = run_all()
for name in CQLS:
    a, b = with_kernels[name], without[name]
    assert len(a) == len(b) and a, (name, len(a), len(b))
    assert a == b, f"{name}: kernel rows != fallback rows"
print("OK", {k: len(v) for k, v in with_kernels.items()})
"""


def test_chain_and_fold_kernels_interpret_equivalence():
    """The kernel-vs-fallback contract for the fused-dispatch round's
    two new kernels, end to end: warmup oracle probes must PASS (not
    fall back) under the interpreter, and full queries produce
    row-identical output with kernels on vs forced off. Runs in a
    clean subprocess (the pallas import path registers TPU lowering
    rules this suite's conftest strips)."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        FST_PALLAS_INTERPRET="1",
        PYTHONPATH=_REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    env.pop("XLA_FLAGS", None)
    env.pop("FST_NO_PALLAS", None)
    r = subprocess.run(
        [sys.executable, "-c", _KERNELS_SNIPPET],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0 and "OK" in r.stdout, (
        r.stdout + "\n" + r.stderr
    )
