"""Sharded execution over a virtual 8-device mesh.

The analog of the reference's MiniCluster integration tests
(SiddhiCEPITCase.java:63 — real multi-subtask pipelines in one process):
every test runs the same plan on a 1-device path (plain Job) and on an
8-shard ShardedJob over the CPU mesh from conftest, asserting result
equivalence. Routing exactness contract: group-by streams are key-routed
(exact), pattern/join streams are owner-pinned (exact), stateless filters
are shuffle-routed (exact up to order).
"""

import dataclasses

import jax
import pytest

from flink_siddhi_tpu import CEPEnvironment
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.parallel import Router, ShardedJob, make_cep_mesh
from flink_siddhi_tpu.query.planner import StreamPartition
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.schema.batch import EventBatch


@dataclasses.dataclass
class Event:
    id: int
    name: str
    price: float
    timestamp: int


FIELDS = ["id", "name", "price", "timestamp"]


def make_events(n, start_ts=1000, id_mod=7, step=100):
    return [
        Event(i % id_mod, f"name_{i % 5}", float(i), start_ts + step * i)
        for i in range(n)
    ]


def build_job(cql, streams, sharded, batch_size=512):
    """streams: {stream_id: events}. Returns a fresh Job/ShardedJob."""
    env = CEPEnvironment(batch_size=batch_size)
    for sid, events in streams.items():
        env.register_stream(sid, events, FIELDS)
    plan = compile_plan(
        cql,
        {sid: env.schemas[sid] for sid in streams},
        extensions=env.extensions,
    )
    sources = [env.sources[sid] for sid in plan.input_stream_ids]
    if sharded:
        return ShardedJob(
            [plan], sources, mesh=make_cep_mesh(8), batch_size=batch_size
        )
    return Job([plan], sources, batch_size=batch_size)


def run_both(cql, streams, batch_size=512):
    single = build_job(cql, streams, sharded=False, batch_size=batch_size)
    single.run()
    sharded = build_job(cql, streams, sharded=True, batch_size=batch_size)
    sharded.run()
    out_stream = next(iter(single.collected), None)
    if out_stream is None:
        out_stream = next(iter(sharded.collected), "out")
    return (
        single.results_with_ts(out_stream),
        sharded.results_with_ts(out_stream),
    )


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    assert make_cep_mesh(8).devices.size == 8


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_filter_sharded_equivalence():
    # stateless filter: shuffle routing, union of shards == global
    events = make_events(500)
    cql = (
        "from inputStream[id == 2] select id, name, price "
        "insert into out"
    )
    single, sharded = run_both(cql, {"inputStream": events})
    assert sorted(single) == sorted(sharded)
    assert len(single) == len([e for e in events if e.id == 2])


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_groupby_cumulative_sharded_equivalence():
    # keyed aggregation state lives on exactly one shard per group -> exact
    events = make_events(600, id_mod=13)
    cql = (
        "from inputStream select id, sum(price) as total, count() as cnt "
        "group by id insert into out"
    )
    single, sharded = run_both(cql, {"inputStream": events})
    assert sorted(single) == sorted(sharded)


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_groupby_time_window_sharded_equivalence():
    # time-window eviction boundaries are key-independent -> per-group rows
    # identical under key routing
    events = make_events(400, id_mod=9)
    cql = (
        "from inputStream#window.time(2 sec) "
        "select id, sum(price) as total group by id insert into out"
    )
    single, sharded = run_both(cql, {"inputStream": events})
    assert sorted(single) == sorted(sharded)


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_pattern_sharded_equivalence():
    # pattern streams are owner-pinned: the NFA sees the full stream once
    s1 = [Event(i % 50, "a", 0.0, 1000 + 1000 * i) for i in range(50)]
    s2 = [Event(i % 50, "b", 0.0, 1500 + 1000 * i) for i in range(50)]
    cql = (
        "from every s1 = inputStream1[id == 2] -> s2 = inputStream2[id == 3]"
        " select s1.id as id_1, s2.id as id_2 insert into out"
    )
    streams = {"inputStream1": s1, "inputStream2": s2}
    single, sharded = run_both(cql, streams)
    assert single == sharded
    assert len(sharded) == 1


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_join_sharded_equivalence():
    # equi-join: both sides key-routed on the join key -> exact. Time
    # windows are used because their eviction boundary is key-independent;
    # length windows are shard-local by design (reference parity: Flink
    # subtask-local window state).
    s1 = [Event(i % 10, "l", float(i), 1000 + 100 * i) for i in range(200)]
    s2 = [Event(i % 10, "r", float(i), 1000 + 100 * i) for i in range(200)]
    cql = (
        "from inputStream1#window.time(1 sec) as a "
        "join inputStream2#window.time(1 sec) as b on a.id == b.id "
        "select a.id as id, a.price as lp, b.price as rp insert into out"
    )
    streams = {"inputStream1": s1, "inputStream2": s2}
    single, sharded = run_both(cql, streams)
    assert sorted(single) == sorted(sharded)


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_multi_query_plan_sharded():
    # one plan, several queries with different partition needs
    events = make_events(300, id_mod=6)
    cql = (
        "from inputStream[price > 100.0] select id, price insert into big; "
        "from inputStream select id, count() as cnt group by id "
        "insert into counts"
    )
    single = build_job(cql, {"inputStream": events}, sharded=False)
    single.run()
    sharded = build_job(cql, {"inputStream": events}, sharded=True)
    sharded.run()
    for out in ("big", "counts"):
        assert sorted(single.results_with_ts(out)) == sorted(
            sharded.results_with_ts(out)
        )


# -------------------------------------------------------------------------
# router unit behavior
# -------------------------------------------------------------------------

def _batch(events):
    env = CEPEnvironment()
    env.register_stream("s", events, FIELDS)
    src = env.sources["s"]
    batch, _, _ = src.poll(10_000)
    return batch


def test_router_groupby_consistency():
    events = make_events(200, id_mod=11)
    batch = _batch(events)
    r = Router(8, {"s": StreamPartition("groupby", ("id",))})
    pieces = r.route(batch)
    total = sum(len(p) for p in pieces if p is not None)
    assert total == len(events)
    # same key always lands on the same shard
    key_shard = {}
    for s, p in enumerate(pieces):
        if p is None:
            continue
        for v in p.columns["id"]:
            assert key_shard.setdefault(int(v), s) == s


def test_router_shuffle_balance_and_broadcast_pin():
    events = make_events(160)
    batch = _batch(events)
    r = Router(8, {})
    pieces = r.route(batch)
    assert [len(p) for p in pieces] == [20] * 8
    rb = Router(8, {"s": StreamPartition("broadcast")})
    pieces = rb.route(batch)
    assert len(pieces[0]) == len(events)
    assert all(p is None for p in pieces[1:])


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_sharded_stacked_chain_group():
    """A plan whose chain queries auto-stack must run under ShardedJob
    (regression: the stacked packed output is a 3-tuple)."""
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.parallel import ShardedJob, make_cep_mesh
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    n = 256
    ids = (np.arange(n) % 6).astype(np.int32)
    ts = 1000 + np.arange(n, dtype=np.int64)
    batch = EventBatch("S", schema, {"id": ids, "timestamp": ts}, ts)
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into o1; "
        "from every s1 = S[id == 3] -> s2 = S[id == 4] "
        "select s1.timestamp as a, s2.timestamp as b insert into o2"
    )
    plan = compile_plan(cql, {"S": schema}, plan_id="p")
    assert len(plan.artifacts) == 1  # stacked
    mesh = make_cep_mesh(4)
    job = ShardedJob(
        [plan], [BatchSource("S", schema, iter([batch]))],
        mesh=mesh, batch_size=128,
    )
    job.run()
    assert len(job.results("o1")) > 0
    assert len(job.results("o2")) > 0


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_nonequi_time_join_replicated_scales():
    # VERDICT round-2 item 7: a non-equi TIME-window join must use more
    # than one shard (replicate-one-side routing) and still match the
    # single-device results exactly
    evs_l = make_events(64, id_mod=7)
    evs_r = [
        Event(i % 5, f"name_{i}", 1000.0 + i, 1050 + 100 * i)
        for i in range(64)
    ]
    cql = (
        # 300ms windows keep the pair count under the per-batch join
        # output cap (out_factor * E) so BOTH paths are lossless
        "from L#window.time(300 millisec) as a "
        "join R#window.time(300 millisec) as b "
        "on a.price < b.price "
        "select a.id, b.id as rid, a.price, b.price as rprice "
        "insert into out"
    )
    single, sharded = run_both(cql, {"L": evs_l, "R": evs_r})
    assert sorted(single) == sorted(sharded)
    # and the left side genuinely spreads: the router sends L rows to
    # more than one shard while R replicates everywhere
    from flink_siddhi_tpu.query.planner import infer_stream_partitions
    from flink_siddhi_tpu.query.parser import parse_plan

    parts = infer_stream_partitions(parse_plan(cql).queries)
    assert parts["L"].kind == "shuffle"
    assert parts["R"].kind == "replicate"


def test_nonequi_length_join_stays_pinned():
    # length windows are global last-n state: spreading a side would
    # change membership, so the planner keeps the owner-pinned instance
    from flink_siddhi_tpu.query.planner import infer_stream_partitions
    from flink_siddhi_tpu.query.parser import parse_plan

    cql = (
        "from L#window.length(4) as a join R#window.length(4) as b "
        "on a.price < b.price select a.id insert into out"
    )
    parts = infer_stream_partitions(parse_plan(cql).queries)
    assert parts["L"].kind == "broadcast"
    assert parts["R"].kind == "broadcast"


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_unkeyed_pattern_segment_parallel():
    # VERDICT round-2 item 7: an unkeyed 3-step every-chain must use
    # more than one shard (time-segment routing + partial-match handoff)
    # and still match single-device results exactly
    evs = [
        Event(i % 9, f"n{i}", float(i), 1000 + 37 * i) for i in range(300)
    ]
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] -> s3 = S[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2, s3.timestamp as t3 "
        "insert into out"
    )
    from flink_siddhi_tpu.query.parser import parse_plan
    from flink_siddhi_tpu.query.planner import infer_stream_partitions

    parts = infer_stream_partitions(parse_plan(cql).queries)
    assert parts["S"].kind == "segment"
    single, sharded = run_both(cql, {"S": evs}, batch_size=128)
    assert sorted(single) == sorted(sharded)
    assert len(single) > 0


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_unkeyed_pattern_segment_within():
    # within-deadline must hold across segment boundaries (the global
    # batch max gates expiry, partial handoff preserves start ts)
    evs = [
        Event(i % 11, f"n{i}", float(i), 1000 + 311 * i) for i in range(200)
    ]
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] within 2 sec "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into out"
    )
    single, sharded = run_both(cql, {"S": evs}, batch_size=64)
    assert sorted(single) == sorted(sharded)
    assert len(single) > 0


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_unkeyed_pattern_segment_midchain_absence():
    # mid-chain absence guards must kill partials wherever the guard
    # event lands — including a different segment than the partial
    evs = [
        Event(i % 13, f"n{i}", float(i), 1000 + 53 * i) for i in range(260)
    ]
    cql = (
        "from every s1 = S[id == 1] -> not S[id == 7] -> s2 = S[id == 2] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into out"
    )
    single, sharded = run_both(cql, {"S": evs}, batch_size=128)
    assert sorted(single) == sorted(sharded)


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_replicate_does_not_duplicate_coconsumer_output():
    # review regression: a plain query reading the replicated side of a
    # non-equi join must emit each row ONCE (the mixed requirement
    # degrades to owner-pinning)
    evs_l = make_events(32)
    evs_r = [
        Event(i % 5, f"n{i}", 1000.0 + i, 1050 + 100 * i)
        for i in range(32)
    ]
    cql = (
        "from R select id, price insert into rcopy; "
        "from L#window.time(300 millisec) as a "
        "join R#window.time(300 millisec) as b on a.price < b.price "
        "select a.id, b.id as rid insert into out"
    )
    single = build_job(cql, {"L": evs_l, "R": evs_r}, sharded=False)
    single.run()
    sharded = build_job(cql, {"L": evs_l, "R": evs_r}, sharded=True)
    sharded.run()
    assert sorted(single.results_with_ts("rcopy")) == sorted(
        sharded.results_with_ts("rcopy")
    )
    assert sorted(single.results_with_ts("out")) == sorted(
        sharded.results_with_ts("out")
    )


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_segment_plus_nonsegmentable_pattern_compiles():
    # review regression: a segmentable chain and a quantified chain on
    # the same stream must still compile (requirements merge to
    # broadcast instead of raising)
    evs = [Event(i % 5, "x", float(i), 1000 + 100 * i) for i in range(60)]
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] "
        "select s1.timestamp as t1 insert into o1; "
        "from every a1 = S[id == 1]<2:3> -> a2 = S[id == 2] "
        "select a1[0].timestamp as t1 insert into o2"
    )
    single = build_job(cql, {"S": evs}, sharded=False)
    single.run()
    sharded = build_job(cql, {"S": evs}, sharded=True)
    sharded.run()
    for out in ("o1", "o2"):
        assert sorted(single.results_with_ts(out)) == sorted(
            sharded.results_with_ts(out)
        )
