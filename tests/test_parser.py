"""SiddhiQL parser tests — queries drawn from the reference's integration
suite (SiddhiCEPITCase.java) so the accepted surface provably covers it."""

import pytest

from flink_siddhi_tpu.query import ast, parse_plan, parse_query, SiddhiQLError
from flink_siddhi_tpu.query.planner import infer_stream_partitions
from flink_siddhi_tpu.schema.types import AttributeType


def test_simple_select():
    q = parse_query(
        "from inputStream select timestamp, id, name, price "
        "insert into  outputStream"
    )
    assert isinstance(q.input, ast.StreamInput)
    assert q.input.stream_id == "inputStream"
    assert [i.output_name() for i in q.selector.items] == [
        "timestamp", "id", "name", "price",
    ]
    assert q.output_stream == "outputStream"


def test_select_star_passthrough():
    q = parse_query("from inputStream insert into outputStream")
    assert q.selector.is_star


def test_filter_and_aliases():
    q = parse_query(
        "from inputStream[id == 2 and price > 5.0] "
        "select name, id as renamed insert into out"
    )
    (filt,) = q.input.filters
    assert isinstance(filt, ast.Binary) and filt.op == "and"
    assert q.selector.items[1].alias == "renamed"


def test_define_stream_plan():
    plan = parse_plan(
        "define stream inputStream (id int, name string, price double, "
        "timestamp long);"
        "from inputStream[id == 2] select name insert into out;"
    )
    (sd,) = plan.stream_defs
    assert sd.stream_id == "inputStream"
    assert sd.fields[1] == ("name", AttributeType.STRING)
    assert len(plan.queries) == 1


def test_window_join():  # SiddhiCEPITCase.java:314-320
    q = parse_query(
        "from inputStream1#window.length(5) as s1 "
        "join inputStream2#window.time(500) as s2 "
        "on s1.id == s2.id "
        "select s1.timestamp as t, s1.name as n, s1.price as p1, "
        "s2.price as p2 insert into JoinStream"
    )
    j = q.input
    assert isinstance(j, ast.JoinInput)
    assert j.left.windows[0] == ast.Window(
        "length", (ast.Literal(5, AttributeType.INT),)
    )
    assert j.right.windows[0].name == "time"
    assert isinstance(j.on, ast.Binary) and j.on.op == "=="


def test_pattern():  # SiddhiCEPITCase.java:343-348
    q = parse_query(
        "from every s1 = inputStream1[id == 2] "
        " -> s2 = inputStream2[id == 3] "
        "select s1.id as id_1, s1.name as name_1, s2.id as id_2, "
        "s2.name as name_2 insert into outputStream"
    )
    p = q.input
    assert isinstance(p, ast.PatternInput)
    assert p.kind == "pattern" and p.every_
    assert [e.alias for e in p.elements] == ["s1", "s2"]
    assert p.elements[0].stream_id == "inputStream1"
    assert q.input_stream_ids() == ("inputStream1", "inputStream2")


def test_sequence_with_quantifiers_and_within():
    # SiddhiCEPITCase.java:369-374
    q = parse_query(
        "from every s1 = inputStream1[id == 2]+ , "
        "s2 = inputStream2[id == 3]? "
        "within 1000 second "
        "select s1[0].name as n1, s2.name as n2 "
        "insert into outputStream"
    )
    p = q.input
    assert p.kind == "sequence"
    assert p.within == 1_000_000
    e1, e2 = p.elements
    assert (e1.min_count, e1.max_count) == (1, -1)
    assert (e2.min_count, e2.max_count) == (0, 1)
    ref = q.selector.items[0].expr
    assert ref == ast.Attr("name", qualifier="s1", index=0)


def test_group_by_having_aggregation():
    q = parse_query(
        "from inputStream#window.length(5) "
        "select name, sum(price) as total, count() as cnt "
        "group by name having total > 10.0 insert into agg"
    )
    assert q.selector.group_by == ("name",)
    assert ast.is_aggregate_call(q.selector.items[1].expr)
    assert q.selector.having is not None


def test_extension_call():  # SiddhiCEPITCase.java:403
    q = parse_query(
        "from inputStream select timestamp, id, name, "
        "custom:plus(price,price) as doubled_price insert into  outputStream"
    )
    call = q.selector.items[3].expr
    assert call == ast.Call(
        "plus",
        (ast.Attr("price"), ast.Attr("price")),
        namespace="custom",
    )


def test_multi_query_plan():  # SiddhiCEPITCase.java:289-292
    plan = parse_plan(
        "from inputStream1 select timestamp, id, name, price insert into "
        "outputStream;"
        "from inputStream2 select timestamp, id, name, price insert into "
        "outputStream;"
        "from inputStream3 select timestamp, id, name, price insert into "
        "outputStream;"
    )
    assert len(plan.queries) == 3
    assert {q.output_stream for q in plan.queries} == {"outputStream"}


def test_annotation_info_name():
    q = parse_plan(
        "@info(name = 'q7') from s select a insert into o"
    ).queries[0]
    assert q.name == "q7"


def test_mixed_connectors_rejected():
    with pytest.raises(SiddhiQLError):
        parse_query(
            "from every a = S1[x == 1] -> b = S2[x == 2], c = S3[x == 3] "
            "select a.x insert into o"
        )


def test_time_literals():
    q = parse_query(
        "from every a = S1[x == 1] -> b = S2[x == 2] within 1 min 30 sec "
        "select a.x insert into o"
    )
    assert q.input.within == 90_000


def test_partition_inference_groupby_vs_shuffle():
    plan = parse_plan(
        "from s1#window.length(5) select name, sum(price) as p group by "
        "name insert into o1;"
        "from s2[id == 1] select id insert into o2;"
    )
    parts = infer_stream_partitions(plan.queries)
    assert parts["s1"].kind == "groupby" and parts["s1"].keys == ("name",)
    assert parts["s2"].kind == "shuffle"


def test_partition_inference_conflict():
    plan = parse_plan(
        "from s1#window.length(5) select name, sum(price) as p group by "
        "name insert into o1;"
        "from s1#window.length(5) select id, sum(price) as p group by id "
        "insert into o2;"
    )
    with pytest.raises(SiddhiQLError):
        infer_stream_partitions(plan.queries)


def test_join_partition_by_equikey():
    plan = parse_plan(
        "from a#window.length(5) as s1 join b#window.time(500) as s2 "
        "on s1.id == s2.id select s1.id insert into o;"
    )
    parts = infer_stream_partitions(plan.queries)
    assert parts["a"] == parts["b"]
    assert parts["a"].kind == "groupby"
