"""`partition with (key of S)` — per-key pattern/aggregation isolation
and key-hash scaling across the shard mesh.

Reference analog: keyed-stream passthrough (SiddhiStream.java:88-97) +
group-key routing (AddRouteOperator.java:79-92); Siddhi's `partition
with` gives each key its own NFA instance, which is what makes pattern
queries scale across shards with exact results (VERDICT round-1 #4).
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.parallel import ShardedJob
from flink_siddhi_tpu.parallel.router import Router
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("user", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)

PAT_CQL = """
partition with (user of S)
begin
  from every s1 = S[id == 1] -> s2 = S[id == 2] -> s3 = S[id == 3]
  select s1.timestamp as t1, s3.timestamp as t3, s1.user as u
  insert into o;
end
"""


def make_data(seed=3, n=600, n_users=16):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 5, n).astype(np.int32)
    users = rng.integers(0, n_users, n).astype(np.int32)
    prices = rng.random(n)
    ts = (1000 + np.arange(n)).astype(np.int64)
    return ids, users, prices, ts


def make_batches(ids, users, prices, ts, batch=64):
    return [
        EventBatch(
            "S", SCHEMA,
            {
                "id": ids[s:s + batch],
                "user": users[s:s + batch],
                "price": prices[s:s + batch],
                "timestamp": ts[s:s + batch],
            },
            ts[s:s + batch],
        )
        for s in range(0, len(ts), batch)
    ]


def oracle_per_key_chain(ids, users, ts):
    out = []
    per_user = {}
    for eid, u, t in zip(ids.tolist(), users.tolist(), ts.tolist()):
        lst = per_user.setdefault(u, [])
        nxt = []
        for (t1, step) in lst:
            if eid == step + 1:
                if step + 1 == 3:
                    out.append((t1, t, u))
                else:
                    nxt.append((t1, step + 1))
            else:
                nxt.append((t1, step))
        per_user[u] = nxt
        if eid == 1:
            per_user[u].append((t, 1))
    return sorted(out)


def test_partitioned_pattern_matches_per_key_oracle():
    ids, users, prices, ts = make_data()
    plan = compile_plan(PAT_CQL, {"S": SCHEMA})
    assert plan.partitions["S"].kind == "groupby"
    assert plan.partitions["S"].keys == ("user",)
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(ids, users, prices, ts)))],
        batch_size=64, time_mode="processing",
    )
    job.run()
    assert sorted(job.results("o")) == oracle_per_key_chain(ids, users, ts)


def test_partitioned_pattern_scales_across_shards():
    # VERDICT #4 'done' criterion: an 8-shard mesh where a keyed 3-step
    # pattern uses >1 shard and matches the single-device result
    ids, users, prices, ts = make_data()
    plan = compile_plan(PAT_CQL, {"S": SCHEMA})
    router = Router(8, plan.partitions)
    shards = router.route_all(make_batches(ids, users, prices, ts)[:1])
    assert sum(1 for sh in shards if sh) > 1, "pattern pinned to one shard"
    sj = ShardedJob(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(ids, users, prices, ts)))],
        n_shards=8, batch_size=64, time_mode="processing",
    )
    sj.run()
    assert sorted(sj.results("o")) == oracle_per_key_chain(ids, users, ts)


def test_partitioned_aggregation_is_per_key():
    ids, users, prices, ts = make_data(n=200)
    cql = """
partition with (user of S)
begin
  from S select user, sum(price) as total insert into totals;
end
"""
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(ids, users, prices, ts)))],
        batch_size=64, time_mode="processing",
    )
    job.run()
    rows = job.results("totals")
    # cumulative per-key running sum: the last row per user equals the
    # user's total
    last = {}
    for u, total in rows:
        last[u] = total
    expect = {}
    for u, p in zip(users.tolist(), prices.tolist()):
        expect[u] = expect.get(u, 0.0) + p
    assert set(last) == set(expect)
    for u in expect:
        np.testing.assert_allclose(last[u], expect[u], rtol=1e-5)


def test_partition_validation_errors():
    with pytest.raises(SiddhiQLError, match="no partition key"):
        compile_plan(
            """
partition with (user of Other)
begin
  from every s1 = S[id == 1] -> s2 = S[id == 2]
  select s1.timestamp as t insert into o;
end
""",
            {
                "S": SCHEMA,
                "Other": SCHEMA,
            },
        )
    with pytest.raises(SiddhiQLError, match="not supported yet"):
        compile_plan(
            """
partition with (user of S)
begin
  from every s1 = S[id == 1], s2 = S[id == 2]
  select s1.timestamp as t insert into o;
end
""",
            {"S": SCHEMA},
        )
    with pytest.raises(SiddhiQLError, match="windows inside"):
        compile_plan(
            """
partition with (user of S)
begin
  from S#window.length(10) select user, sum(price) as t insert into o;
end
""",
            {"S": SCHEMA},
        )


def test_partitioned_non_every_rejected():
    with pytest.raises(SiddhiQLError, match="per partition key"):
        compile_plan(
            """
partition with (user of S)
begin
  from s1 = S[id == 1] -> s2 = S[id == 2]
  select s1.user as u insert into o;
end
""",
            {"S": SCHEMA},
        )
