"""`partition with (key of S)` — per-key pattern/aggregation isolation
and key-hash scaling across the shard mesh.

Reference analog: keyed-stream passthrough (SiddhiStream.java:88-97) +
group-key routing (AddRouteOperator.java:79-92); Siddhi's `partition
with` gives each key its own NFA instance, which is what makes pattern
queries scale across shards with exact results (VERDICT round-1 #4).
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.parallel import ShardedJob
from flink_siddhi_tpu.parallel.router import Router
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("user", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)

PAT_CQL = """
partition with (user of S)
begin
  from every s1 = S[id == 1] -> s2 = S[id == 2] -> s3 = S[id == 3]
  select s1.timestamp as t1, s3.timestamp as t3, s1.user as u
  insert into o;
end
"""


def make_data(seed=3, n=600, n_users=16):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 5, n).astype(np.int32)
    users = rng.integers(0, n_users, n).astype(np.int32)
    prices = rng.random(n)
    ts = (1000 + np.arange(n)).astype(np.int64)
    return ids, users, prices, ts


def make_batches(ids, users, prices, ts, batch=64):
    return [
        EventBatch(
            "S", SCHEMA,
            {
                "id": ids[s:s + batch],
                "user": users[s:s + batch],
                "price": prices[s:s + batch],
                "timestamp": ts[s:s + batch],
            },
            ts[s:s + batch],
        )
        for s in range(0, len(ts), batch)
    ]


def oracle_per_key_chain(ids, users, ts):
    out = []
    per_user = {}
    for eid, u, t in zip(ids.tolist(), users.tolist(), ts.tolist()):
        lst = per_user.setdefault(u, [])
        nxt = []
        for (t1, step) in lst:
            if eid == step + 1:
                if step + 1 == 3:
                    out.append((t1, t, u))
                else:
                    nxt.append((t1, step + 1))
            else:
                nxt.append((t1, step))
        per_user[u] = nxt
        if eid == 1:
            per_user[u].append((t, 1))
    return sorted(out)


def test_partitioned_pattern_matches_per_key_oracle():
    ids, users, prices, ts = make_data()
    plan = compile_plan(PAT_CQL, {"S": SCHEMA})
    assert plan.partitions["S"].kind == "groupby"
    assert plan.partitions["S"].keys == ("user",)
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(ids, users, prices, ts)))],
        batch_size=64, time_mode="processing",
    )
    job.run()
    assert sorted(job.results("o")) == oracle_per_key_chain(ids, users, ts)


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_partitioned_pattern_scales_across_shards():
    # VERDICT #4 'done' criterion: an 8-shard mesh where a keyed 3-step
    # pattern uses >1 shard and matches the single-device result
    ids, users, prices, ts = make_data()
    plan = compile_plan(PAT_CQL, {"S": SCHEMA})
    router = Router(8, plan.partitions)
    shards = router.route_all(make_batches(ids, users, prices, ts)[:1])
    assert sum(1 for sh in shards if sh) > 1, "pattern pinned to one shard"
    sj = ShardedJob(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(ids, users, prices, ts)))],
        n_shards=8, batch_size=64, time_mode="processing",
    )
    sj.run()
    assert sorted(sj.results("o")) == oracle_per_key_chain(ids, users, ts)


def test_partitioned_aggregation_is_per_key():
    ids, users, prices, ts = make_data(n=200)
    cql = """
partition with (user of S)
begin
  from S select user, sum(price) as total insert into totals;
end
"""
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(ids, users, prices, ts)))],
        batch_size=64, time_mode="processing",
    )
    job.run()
    rows = job.results("totals")
    # cumulative per-key running sum: the last row per user equals the
    # user's total
    last = {}
    for u, total in rows:
        last[u] = total
    expect = {}
    for u, p in zip(users.tolist(), prices.tolist()):
        expect[u] = expect.get(u, 0.0) + p
    assert set(last) == set(expect)
    for u in expect:
        np.testing.assert_allclose(last[u], expect[u], rtol=1e-5)


def test_partition_validation_errors():
    with pytest.raises(SiddhiQLError, match="no partition key"):
        compile_plan(
            """
partition with (user of Other)
begin
  from every s1 = S[id == 1] -> s2 = S[id == 2]
  select s1.timestamp as t insert into o;
end
""",
            {
                "S": SCHEMA,
                "Other": SCHEMA,
            },
        )
    with pytest.raises(SiddhiQLError, match="not supported yet"):
        compile_plan(
            """
partition with (user of S)
begin
  from every s1 = S[id == 1], s2 = S[id == 2]
  select s1.timestamp as t insert into o;
end
""",
            {"S": SCHEMA},
        )
    # round-5: length, time, sort, unique, and session windows compile
    # inside 'partition with'; timeBatch (per-partition t0) still
    # rejects loudly
    with pytest.raises(SiddhiQLError, match="partition"):
        compile_plan(
            """
partition with (user of S)
begin
  from S#window.timeBatch(10 ms)
  select user, sum(price) as t insert into o;
end
""",
            {"S": SCHEMA},
        )
    for w in (
        "#window.length(10)", "#window.time(10 ms)",
        "#window.unique(id)",
    ):
        compile_plan(
            f"""
partition with (user of S)
begin
  from S{w} select user, sum(price) as t insert into o;
end
""",
            {"S": SCHEMA},
        )


def test_partitioned_non_every_rejected():
    with pytest.raises(SiddhiQLError, match="per partition key"):
        compile_plan(
            """
partition with (user of S)
begin
  from s1 = S[id == 1] -> s2 = S[id == 2]
  select s1.user as u insert into o;
end
""",
            {"S": SCHEMA},
        )


def test_partitioned_length_window_per_key_oracle():
    """Round-4 verdict item 7: a per-partition length window holds each
    KEY'S last C events (not a group-by over one shared window)."""
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    cql = (
        "partition with (k of S) begin "
        "from S#window.length(3) select k, sum(v) as s, count() as c "
        "insert into o end"
    )
    rng = np.random.default_rng(21)
    n = 500
    ks = rng.integers(0, 7, n)
    vs = np.round(rng.random(n) * 10, 2)
    ts = 1000 + np.arange(n, dtype=np.int64)
    batches = [
        EventBatch(
            "S", schema,
            {"k": ks[s:s + 64].astype(np.int32),
             "v": vs[s:s + 64], "timestamp": ts[s:s + 64]},
            ts[s:s + 64],
        )
        for s in range(0, n, 64)
    ]
    plan = compile_plan(cql, {"S": schema})
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=64, time_mode="processing",
    )
    job.run()
    rows = job.results("o")
    # oracle: per-key deque of that key's last 3 events
    from collections import defaultdict, deque

    wins = defaultdict(lambda: deque(maxlen=3))
    exp = []
    for k, v in zip(ks.tolist(), vs.tolist()):
        wins[k].append(v)
        exp.append((k, sum(wins[k]), len(wins[k])))
    assert len(rows) == len(exp)
    for (k, s_, c), (ek, es, ec) in zip(rows, exp):
        assert (k, c) == (ek, ec)
        assert s_ == pytest.approx(es, rel=1e-4)


def test_partitioned_window_differs_from_shared_window():
    # the same query WITHOUT partition: one shared 3-event window
    # grouped by k — different numbers (this is the semantic the
    # round-3 carve-out protected against silently conflating)
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    ks = [0, 1, 0, 1, 0, 1]
    vs = [1.0, 10.0, 2.0, 20.0, 4.0, 40.0]
    ts = np.arange(1000, 1006, dtype=np.int64)
    def run(cql):
        batches = [EventBatch(
            "S", schema,
            {"k": np.asarray(ks, np.int32), "v": np.asarray(vs),
             "timestamp": ts}, ts,
        )]
        plan = compile_plan(cql, {"S": schema})
        job = Job([plan], [BatchSource("S", schema, iter(batches))],
                  batch_size=8, time_mode="processing")
        job.run()
        return job.results("o")

    part = run(
        "partition with (k of S) begin from S#window.length(2) "
        "select k, sum(v) as s insert into o end"
    )
    shared = run(
        "from S#window.length(2) select k, sum(v) as s group by k "
        "insert into o"
    )
    # per-key: key 0's window at event 4 holds [2.0, 4.0] -> 6.0
    assert part[4][1] == pytest.approx(6.0)
    # shared: the global last-2 window at event 4 holds [20.0, 4.0];
    # key 0's share is just [4.0]
    assert shared[4][1] == pytest.approx(4.0)


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_partitioned_window_sharded_equivalence():
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.parallel import ShardedJob
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    cql = (
        "partition with (k of S) begin "
        "from S#window.length(4) select k, sum(v) as s, count() as c "
        "insert into o end"
    )
    rng = np.random.default_rng(33)
    n = 256
    ks = rng.integers(0, 5, n).astype(np.int32)
    vs = np.round(rng.random(n) * 10, 2)
    ts = 1000 + np.arange(n, dtype=np.int64)

    def batches():
        return iter([
            EventBatch(
                "S", schema,
                {"k": ks[s:s + 32], "v": vs[s:s + 32],
                 "timestamp": ts[s:s + 32]},
                ts[s:s + 32],
            )
            for s in range(0, n, 32)
        ])

    single = Job(
        [compile_plan(cql, {"S": schema})],
        [BatchSource("S", schema, batches())],
        batch_size=32, time_mode="processing",
    )
    single.run()
    sharded = ShardedJob(
        [compile_plan(cql, {"S": schema})],
        [BatchSource("S", schema, batches())],
        n_shards=8, batch_size=32, time_mode="processing",
    )
    sharded.run()
    a = sorted(single.results("o"))
    b = sorted(sharded.results("o"))
    assert len(a) == len(b) > 0
    for (k1, s1, c1), (k2, s2, c2) in zip(a, b):
        assert (k1, c1) == (k2, c2)
        assert s1 == pytest.approx(s2, rel=1e-4)


# -- round-5: partitioned time / sort / unique / session windows ---------

def _run_part(cql, schema, batches, batch=64):
    plan = compile_plan(cql, {"S": schema})
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job


def _kvt_schema():
    return StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )


def _kvt_batches(schema, ks, vs, ts, batch=64):
    return [
        EventBatch(
            "S", schema,
            {"k": ks[s:s + batch].astype(np.int32),
             "v": vs[s:s + batch], "timestamp": ts[s:s + batch]},
            ts[s:s + batch],
        )
        for s in range(0, len(ks), batch)
    ]


def test_partitioned_time_window_oracle():
    """Per-key time window == per-key member set of a shared time
    window (wall-clock expiry is key-independent): each emission
    aggregates the key's own last-T events."""
    schema = _kvt_schema()
    rng = np.random.default_rng(5)
    n = 400
    ks = rng.integers(0, 5, n)
    vs = np.round(rng.random(n) * 10, 2)
    # irregular spacing so windows cut mid-stream
    ts = 1000 + np.cumsum(rng.integers(1, 9, n)).astype(np.int64)
    cql = (
        "partition with (k of S) begin "
        "from S#window.time(20 ms) select k, sum(v) as s, count() as c "
        "insert into o end"
    )
    job = _run_part(cql, schema, _kvt_batches(schema, ks, vs, ts))
    rows = job.results("o")
    assert len(rows) == n
    for i, (k, s, c) in enumerate(rows):
        member = [
            j for j in range(i + 1)
            if ks[j] == ks[i] and ts[j] > ts[i] - 20
        ]
        assert k == ks[i]
        assert c == len(member)
        assert s == pytest.approx(sum(vs[j] for j in member), rel=1e-4)


def test_partitioned_unique_window_oracle():
    """Per-partition unique(id): each key's window holds the latest
    event per id WITHIN that partition."""
    schema = StreamSchema(
        [("k", AttributeType.INT), ("id", AttributeType.INT),
         ("v", AttributeType.DOUBLE), ("timestamp", AttributeType.LONG)]
    )
    rng = np.random.default_rng(11)
    n = 300
    ks = rng.integers(0, 4, n)
    ids = rng.integers(0, 6, n)
    vs = np.round(rng.random(n) * 10, 2)
    ts = 1000 + np.arange(n, dtype=np.int64)
    batches = [
        EventBatch(
            "S", schema,
            {"k": ks[s:s + 64].astype(np.int32),
             "id": ids[s:s + 64].astype(np.int32),
             "v": vs[s:s + 64], "timestamp": ts[s:s + 64]},
            ts[s:s + 64],
        )
        for s in range(0, n, 64)
    ]
    cql = (
        "partition with (k of S) begin "
        "from S#window.unique(id) select k, sum(v) as s, count() as c "
        "insert into o end"
    )
    job = _run_part(cql, schema, batches)
    rows = job.results("o")
    assert len(rows) == n
    for i, (k, s, c) in enumerate(rows):
        latest = {}
        for j in range(i + 1):
            if ks[j] == ks[i]:
                latest[ids[j]] = vs[j]
        assert k == ks[i]
        assert c == len(latest)
        assert s == pytest.approx(sum(latest.values()), rel=1e-4)


def test_partitioned_sort_window_oracle():
    """Per-partition sort(N, v): each key keeps its own N smallest."""
    schema = _kvt_schema()
    rng = np.random.default_rng(13)
    n = 240
    ks = rng.integers(0, 3, n)
    vs = np.round(rng.random(n) * 100, 2)
    ts = 1000 + np.arange(n, dtype=np.int64)
    cql = (
        "partition with (k of S) begin "
        "from S#window.sort(4, v) select k, min(v) as mn, count() as c "
        "insert into o end"
    )
    job = _run_part(cql, schema, _kvt_batches(schema, ks, vs, ts))
    rows = job.results("o")
    assert len(rows) == n
    kept = {k: [] for k in range(3)}
    for i, (k, mn, c) in enumerate(rows):
        b = kept[ks[i]]
        b.append(vs[i])
        b.sort()
        del b[4:]
        assert k == ks[i]
        assert c == len(b)
        assert mn == pytest.approx(min(b), rel=1e-4)


def test_partitioned_session_window_oracle():
    """partition with + #window.session(gap) == keyed sessions on the
    partition attribute."""
    schema = _kvt_schema()
    ks = np.array([0, 1, 0, 0, 1, 0, 1, 1], dtype=np.int64)
    ts = np.array(
        [1000, 1002, 1005, 1040, 1041, 1100, 1101, 1150],
        dtype=np.int64,
    )
    vs = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0])
    cql = (
        "partition with (k of S) begin "
        "from S#window.session(10 ms) "
        "select k, sum(v) as s, count() as c insert into o end"
    )
    job = _run_part(cql, schema, _kvt_batches(schema, ks, vs, ts, 4))
    rows = sorted(job.results("o"))
    # oracle: per-key sessions split at >10ms gaps
    # k=0: [1000,1005] sum 4 c2; [1040] sum 4 c1; [1100] sum 6 c1
    # k=1: [1002] sum 2 c1; [1041] sum 5 c1; [1101] sum 7 c1; [1150] 8 c1
    expect = sorted([
        (0, 4.0, 2), (0, 4.0, 1), (0, 6.0, 1),
        (1, 2.0, 1), (1, 5.0, 1), (1, 7.0, 1), (1, 8.0, 1),
    ])
    assert len(rows) == len(expect)
    for (k, s, c), (ek, es, ec) in zip(rows, expect):
        assert (k, c) == (ek, ec)
        assert s == pytest.approx(es, rel=1e-4)
