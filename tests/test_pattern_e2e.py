"""Pattern / sequence matching end-to-end.

Pins the match semantics of the reference's pattern and sequence integration
tests (SiddhiCEPITCase.java:333-357 simple pattern, :363-382 sequence with
quantifiers + within) against both compiled engines: the vectorized chain
matcher and the slot NFA.
"""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP


@dataclasses.dataclass
class Event:
    id: int
    name: str
    price: float
    timestamp: int


FIELDS = ["id", "name", "price", "timestamp"]


def ev(id, ts, name="test_event", price=0.0):
    return Event(id, name, price, ts)


def run_pattern(cql, stream1, stream2=None, batch_size=4096):
    env = CEPEnvironment(batch_size=batch_size)
    s = SiddhiCEP.define(
        "inputStream1", stream1, FIELDS, env=env
    )
    if stream2 is not None:
        s = s.union("inputStream2", stream2, FIELDS)
    return s.cql(cql).return_as_map("outputStream")


TWO_STEP = (
    "from every s1 = inputStream1[id == 2] -> s2 = inputStream2[id == 3] "
    "select s1.id as id_1, s1.name as name_1, s2.id as id_2, s2.name as "
    "name_2 insert into outputStream"
)


def test_simple_pattern_match():
    # SiddhiCEPITCase.java:333-357: ids 0..49 on both streams -> one match
    s1 = [ev(i % 50, 1000 + 1000 * i) for i in range(50)]
    s2 = [ev(i % 50, 1000 + 1000 * i) for i in range(50)]
    out = run_pattern(TWO_STEP, s1, s2)
    assert out == [
        {"id_1": 2, "name_1": "test_event", "id_2": 3, "name_2": "test_event"}
    ]


def test_every_multiplicity():
    # A@2 A@2 B@3: every start pairs with the next completion -> 2 matches
    s1 = [ev(2, 1000), ev(2, 2000)]
    s2 = [ev(3, 3000)]
    out = run_pattern(TWO_STEP, s1, s2)
    assert len(out) == 2
    assert {m["id_1"] for m in out} == {2}


def test_every_exact_pairs():
    # A B A B -> two matches, each A pairing its following B
    env = CEPEnvironment()
    s1 = [ev(2, 1000), ev(2, 3000)]
    s2 = [ev(3, 2000), ev(3, 4000)]
    s = SiddhiCEP.define(
        "inputStream1", s1, FIELDS, env=env
    ).union("inputStream2", s2, FIELDS)
    out = s.cql(
        "from every s1 = inputStream1[id == 2] -> s2 = inputStream2[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream"
    ).returns("outputStream")
    assert out == [(1000, 2000), (3000, 4000)]


def test_no_every_matches_once():
    s1 = [ev(2, 1000), ev(2, 3000)]
    s2 = [ev(3, 2000), ev(3, 4000)]
    out = run_pattern(
        "from s1 = inputStream1[id == 2] -> s2 = inputStream2[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        s1,
        s2,
    )
    assert out == [{"t1": 1000, "t2": 2000}]


def test_three_step_pattern():
    # the north-star shape: every s1 -> s2 -> s3
    s1 = [ev(1, 1000), ev(1, 5000), ev(1, 9000)]
    s2 = [ev(2, 2000), ev(2, 6000), ev(2, 10000)]
    # reuse inputStream1 for step 3 via a third id
    env = CEPEnvironment()
    s3 = [ev(3, 3000), ev(3, 7000), ev(3, 11000)]
    s = (
        SiddhiCEP.define(
            "inputStream1", s1, FIELDS, env=env
        )
        .union("inputStream2", s2, FIELDS)
        .union("inputStream3", s3, FIELDS)
    )
    out = s.cql(
        "from every s1 = inputStream1[id == 1] -> s2 = inputStream2[id == 2]"
        " -> s3 = inputStream3[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2, s3.timestamp as t3 "
        "insert into outputStream"
    ).returns("outputStream")
    assert out == [
        (1000, 2000, 3000),
        (5000, 6000, 7000),
        (9000, 10000, 11000),
    ]


def test_pattern_within_expires():
    s1 = [ev(2, 1000)]
    s2 = [ev(3, 500000)]  # arrives too late for `within 100 sec`
    out = run_pattern(
        "from every s1 = inputStream1[id == 2] -> s2 = inputStream2[id == 3]"
        " within 100 sec "
        "select s1.id as a, s2.id as b insert into outputStream",
        s1,
        s2,
    )
    assert out == []


def test_pattern_within_allows():
    s1 = [ev(2, 1000)]
    s2 = [ev(3, 50000)]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2] -> s2 = inputStream2[id == 3]"
        " within 100 sec "
        "select s1.id as a, s2.id as b insert into outputStream",
        s1,
        s2,
    )
    assert len(out) == 1


def test_pattern_cross_batch_carry():
    # force the partial to straddle micro-batches (batch_size=2 -> the
    # start and completion land in different device steps)
    s1 = [ev(2, 1000), ev(0, 2000), ev(0, 3000), ev(0, 4000)]
    s2 = [ev(0, 1500), ev(0, 2500), ev(0, 3500), ev(3, 5000)]
    out = run_pattern(TWO_STEP, s1, s2, batch_size=2)
    assert len(out) == 1
    assert out[0]["id_1"] == 2 and out[0]["id_2"] == 3


def test_pattern_interleaved_ignores_unrelated():
    # '->' skips unrelated events between steps
    s1 = [ev(2, 1000), ev(7, 1500), ev(9, 1800)]
    s2 = [ev(1, 2000), ev(3, 3000)]
    out = run_pattern(TWO_STEP, s1, s2)
    assert len(out) == 1


def test_sequence_reference_shape():
    # SiddhiCEPITCase.java:363-382: every s1 = A[id==2]+ , s2 = B[id==3]?
    # within 1000 sec over ids 0..4 duplicated on both streams -> 1 match
    evs = [ev(i, 1000 + 1000 * i) for i in range(5)]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2]+ , "
        "s2 = inputStream2[id == 3]? within 1000 second "
        "select s1[0].name as n1, s2.name as n2 insert into outputStream",
        evs,
        list(evs),
    )
    assert len(out) == 1
    assert out[0]["n1"] == "test_event"


def test_sequence_strict_continuity_breaks():
    # sequence s1 = A[id==2], s2 = A[id==3]: an intervening non-matching
    # event kills the partial
    evs = [ev(2, 1000), ev(7, 2000), ev(3, 3000)]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2] , s2 = inputStream1[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        evs,
    )
    assert out == []


def test_sequence_adjacent_matches():
    evs = [ev(2, 1000), ev(3, 2000), ev(2, 3000), ev(3, 4000)]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2] , s2 = inputStream1[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        evs,
    )
    assert [(m["t1"], m["t2"]) for m in out] == [(1000, 2000), (3000, 4000)]


def test_sequence_plus_quantifier_first_and_last():
    # s1 = A[id==2]+ , s2 = A[id==3]: greedy absorb of consecutive id==2
    evs = [
        ev(2, 1000, price=1.0),
        ev(2, 2000, price=2.0),
        ev(2, 3000, price=3.0),
        ev(3, 4000, price=9.0),
    ]
    out = run_pattern(
        "from s1 = inputStream1[id == 2]+ , s2 = inputStream1[id == 3] "
        "select s1[0].price as first_p, s1[last].price as last_p, "
        "s2.price as close_p insert into outputStream",
        evs,
    )
    assert len(out) == 1
    assert out[0] == {"first_p": 1.0, "last_p": 3.0, "close_p": 9.0}


def test_pattern_with_quantified_middle():
    # pattern kind with a bounded quantifier runs on the slot NFA
    evs = [ev(2, 1000), ev(5, 1500), ev(2, 2000), ev(3, 3000)]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2]<2:2> -> "
        "s2 = inputStream1[id == 3] "
        "select s1[0].timestamp as t1, s1[last].timestamp as t2, "
        "s2.timestamp as t3 insert into outputStream",
        evs,
    )
    assert len(out) == 1
    assert out[0] == {"t1": 1000, "t2": 2000, "t3": 3000}


def test_sequence_rearms_after_break():
    # non-every sequence: a broken partial must not disarm matching forever
    # (Siddhi still emits the later (2,3) match)
    events = [ev(2, 1000), ev(9, 2000), ev(2, 3000), ev(3, 4000)]
    out = run_pattern(
        "from s1 = inputStream1[id == 2] , s2 = inputStream1[id == 3] "
        "select s1.id as a, s2.id as b insert into outputStream",
        events,
    )
    assert out == [{"a": 2, "b": 3}]


def test_single_element_every_pattern_timestamps():
    # K == 1 chain: each match emits at its own event's timestamp
    env = CEPEnvironment()
    es = SiddhiCEP.define(
        "inputStream1", [ev(2, 5000), ev(1, 7000), ev(2, 9000)], FIELDS,
        env=env,
    ).cql(
        "from every s1 = inputStream1[id == 2] select s1.id as a "
        "insert into outputStream"
    )
    rows = es.execute().results_with_ts("outputStream")
    assert rows == [(5000, (2,)), (9000, (2,))]


def test_quantified_pattern_compaction_equivalence():
    """Large-batch slot-NFA runs the relevance-compacted scan; its matches
    must equal the uncompacted small-batch run over the same events."""
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    rng = np.random.default_rng(3)
    n = 8192
    ids = rng.integers(0, 40, n).astype(np.int32)
    ts = 1000 + np.arange(n, dtype=np.int64)
    prices = np.round(rng.random(n) * 100, 3)

    def make_job(batch):
        batches = []
        for s in range(0, n, batch):
            e = min(s + batch, n)
            batches.append(EventBatch(
                "S", schema,
                {"id": ids[s:e], "price": prices[s:e],
                 "timestamp": ts[s:e]}, ts[s:e],
            ))
        cql = (
            "from every s1 = S[id == 1]+ -> s2 = S[id == 2] "
            "select s1[0].price as p0, s1[last].price as pl, "
            "s2.price as p2 insert into o"
        )
        plan = compile_plan(cql, {"S": schema})
        job = Job([plan], [BatchSource("S", schema, iter(batches))],
                  batch_size=batch, time_mode="processing")
        job.run()
        return job.results("o")

    big = make_job(8192)   # compacted scan path (E >= 4096)
    small = make_job(512)  # full scan path
    assert len(big) > 0
    assert big == small


def test_midchain_absence():
    """`A -> not B -> C`: C completes the match only when no B arrived
    in between (mid-chain absence)."""
    from flink_siddhi_tpu import SiddhiCEP

    @dataclasses.dataclass
    class E:
        id: int
        timestamp: int

    # stream: A(1) C(3)      -> match
    #         A(1) B(2) C(3) -> no match (B intervenes)
    ev = [E(1, 1000), E(3, 1100), E(1, 2000), E(2, 2100), E(3, 2200),
          E(1, 3000), E(9, 3100), E(3, 3200)]
    rows = (
        SiddhiCEP.define("S", ev, ["id", "timestamp"])
        .cql(
            "from every s1 = S[id == 1] -> not S[id == 2] -> "
            "s3 = S[id == 3] select s1.timestamp as t1, "
            "s3.timestamp as t3 insert into o"
        )
        .returns("o")
    )
    assert rows == [(1000, 1100), (3000, 3200)]


def test_midchain_absence_across_batches():
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType
    import numpy as np

    schema = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    # A | (batch boundary) B C  -> killed by B in the later batch
    # A | C                     -> match across the boundary
    ids = [1, 7, 2, 3, 1, 7, 3]
    ts = [1000, 1500, 2000, 2500, 3000, 3500, 4000]
    batches = [
        EventBatch("S", schema,
                   {"id": np.asarray(ids[:2], np.int32),
                    "timestamp": np.asarray(ts[:2], np.int64)},
                   np.asarray(ts[:2], np.int64)),
        EventBatch("S", schema,
                   {"id": np.asarray(ids[2:], np.int32),
                    "timestamp": np.asarray(ts[2:], np.int64)},
                   np.asarray(ts[2:], np.int64)),
    ]
    plan = compile_plan(
        "from every s1 = S[id == 1] -> not S[id == 2] -> "
        "s3 = S[id == 3] select s1.timestamp as t1, s3.timestamp as t3 "
        "insert into o",
        {"S": schema},
    )
    job = Job([plan], [BatchSource("S", schema, iter(batches))],
              batch_size=8, time_mode="processing")
    job.run()
    # first A killed by B at 2000; second A matches C at 4000
    assert job.results("o") == [(3000, 4000)]


def test_absence_validation_errors():
    import pytest

    from flink_siddhi_tpu import SiddhiCEP
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    @dataclasses.dataclass
    class E:
        id: int
        timestamp: int

    ev = [E(1, 1000)]
    base = SiddhiCEP.define("S", ev, ["id", "timestamp"])
    for bad in (
        # terminal absence needs a duration (unsupported)
        "from every s1 = S[id == 1] -> not S[id == 2] "
        "select s1.id as a insert into o",
        # absence cannot lead
        "from not S[id == 2] -> s1 = S[id == 1] "
        "select s1.id as a insert into o",
        # absent elements cannot be quantified
        "from every s1 = S[id == 1] -> not S[id == 2]+ -> "
        "s3 = S[id == 3] select s1.id as a insert into o",
    ):
        with pytest.raises(SiddhiQLError):
            base.cql(bad).returns("o")


def test_indexed_capture_returns_nth_event():
    # VERDICT round-2 repro: s1[1].price over prices 10/20/30 must be the
    # SECOND absorbed event (20.0), not the last (siddhi-core array-indexed
    # refs, SiddhiCEPITCase.java:373)
    evs = [
        ev(2, 1000, price=10.0),
        ev(2, 2000, price=20.0),
        ev(2, 3000, price=30.0),
        ev(3, 4000, price=99.0),
    ]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2]<3:3> -> "
        "s2 = inputStream1[id == 3] "
        "select s1[0].price as p0, s1[1].price as p1, s1[2].price as p2, "
        "s1[last].price as pl insert into outputStream",
        evs,
    )
    assert out == [{"p0": 10.0, "p1": 20.0, "p2": 30.0, "pl": 30.0}]


def test_indexed_capture_decodes_none_when_absent():
    # s1 absorbed a single event: s1[1] does not exist -> null (None),
    # never a stale/zero value
    evs = [ev(2, 1000, price=10.0), ev(3, 2000, price=99.0)]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2]<1:3> -> "
        "s2 = inputStream1[id == 3] "
        "select s1[0].price as p0, s1[1].price as p1 "
        "insert into outputStream",
        evs,
    )
    assert out == [{"p0": 10.0, "p1": None}]


def test_indexed_capture_in_cross_element_filter():
    # foreign indexed ref inside a later element's filter: only holds once
    # the referenced element actually absorbed > k events. `every` starts
    # an instance at EVERY id==2 event, so three instances are in flight
    # by ts 6000: {10,20}, {20,10}, {10,20} (one per start event that
    # collected two absorbs); the 1-event instance {20} can never pass.
    evs = [
        ev(2, 1000, price=10.0),
        ev(2, 2000, price=20.0),
        ev(3, 3000, price=15.0),   # 15 > s1[1].price (20)? no
        ev(2, 4000, price=10.0),
        ev(2, 5000, price=20.0),
        ev(3, 6000, price=25.0),   # 25 > s1[1] -> match for full slots
    ]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2]<2:2> -> "
        "s2 = inputStream1[id == 3 and price > s1[1].price] "
        "select s1[1].price as p1, s2.price as pc "
        "insert into outputStream",
        evs,
    )
    assert [m["pc"] for m in out] == [25.0, 25.0, 25.0]
    assert sorted(m["p1"] for m in out) == [10.0, 20.0, 20.0]


def test_indexed_capture_per_instance_isolation():
    # overlapping every-instances: each slot's s1[1] is its own. Starts at
    # 1000/2000/4000/5000 collect {1,2}, {2,7} (a '->' pattern skips the
    # irrelevant id==3 event), {7,8}, {8...incomplete}; each completed
    # instance reports ITS second absorbed price, not a shared last value.
    evs = [
        ev(2, 1000, price=1.0),
        ev(2, 2000, price=2.0),
        ev(3, 3000, price=0.0),
        ev(2, 4000, price=7.0),
        ev(2, 5000, price=8.0),
        ev(3, 6000, price=0.0),
    ]
    out = run_pattern(
        "from every s1 = inputStream1[id == 2]<2:2> -> "
        "s2 = inputStream1[id == 3] "
        "select s1[1].price as p1 insert into outputStream",
        evs,
    )
    assert sorted(m["p1"] for m in out) == [2.0, 7.0, 8.0]


def test_grouped_every_restarts_after_complete_match():
    # Siddhi: `every (A -> B)` keeps ONE instance in flight and restarts
    # only after a complete occurrence — input A A B yields (A1, B) only,
    # while ungrouped `every A -> B` yields (A1, B) and (A2, B)
    s1 = [ev(2, 1000), ev(2, 2000)]
    s2 = [ev(3, 3000)]
    grouped = run_pattern(
        "from every (s1 = inputStream1[id == 2] -> "
        "s2 = inputStream2[id == 3]) "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        s1, s2,
    )
    assert [(m["t1"], m["t2"]) for m in grouped] == [(1000, 3000)]
    ungrouped = run_pattern(
        "from every s1 = inputStream1[id == 2] -> "
        "s2 = inputStream2[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        s1, s2,
    )
    assert sorted((m["t1"], m["t2"]) for m in ungrouped) == [
        (1000, 3000), (2000, 3000),
    ]


def test_grouped_every_rearms_for_next_occurrence():
    # after the first complete (A, B) the group re-arms: A@4000 B@5000
    # forms a second, disjoint occurrence
    s1 = [ev(2, 1000), ev(2, 2000), ev(2, 4000)]
    s2 = [ev(3, 3000), ev(3, 5000)]
    out = run_pattern(
        "from every (s1 = inputStream1[id == 2] -> "
        "s2 = inputStream2[id == 3]) "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        s1, s2,
    )
    assert [(m["t1"], m["t2"]) for m in out] == [(1000, 3000), (4000, 5000)]


def test_grouped_every_completing_event_does_not_rearm():
    # overlapping filters: every event matches both elements. Grouped
    # every must consume the completing event — it cannot double as the
    # next occurrence's first element — so 3 events yield ONE match,
    # while ungrouped every yields two
    evs = [
        ev(1, 1000, price=2.0),
        ev(1, 2000, price=2.0),
        ev(1, 3000, price=2.0),
    ]
    grouped = run_pattern(
        "from every (s1 = inputStream1[price > 0] -> "
        "s2 = inputStream1[price > 1]) "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        evs,
    )
    assert [(m["t1"], m["t2"]) for m in grouped] == [(1000, 2000)]


def test_midchain_every_last_element():
    # `A -> every B`: one A (non-every leading), then EVERY later B
    # completes a match — the matched prefix is never consumed
    # (siddhi-core mid-chain every, package-info.java:36-38)
    evs = [ev(1, 1000), ev(2, 2000), ev(2, 3000), ev(5, 3500), ev(2, 4000)]
    out = run_pattern(
        "from s1 = inputStream1[id == 1] -> every s2 = inputStream1[id == 2] "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        evs,
    )
    assert [(m["t1"], m["t2"]) for m in out] == [
        (1000, 2000), (1000, 3000), (1000, 4000),
    ]


def test_midchain_every_middle_element():
    # `A -> every B -> C`: every B forks a pending instance; C completes
    # ALL pending forks
    evs = [
        ev(1, 1000), ev(2, 2000), ev(2, 3000), ev(3, 4000), ev(2, 5000),
        ev(3, 6000),
    ]
    out = run_pattern(
        "from s1 = inputStream1[id == 1] -> every s2 = inputStream1[id == 2] "
        "-> s3 = inputStream1[id == 3] "
        "select s2.timestamp as t2, s3.timestamp as t3 "
        "insert into outputStream",
        evs,
    )
    assert sorted((m["t2"], m["t3"]) for m in out) == [
        (2000, 4000), (3000, 4000), (5000, 6000),
    ]


def test_midchain_every_with_leading_every():
    # `every A -> every B`: every A starts an instance AND each instance
    # pairs with every later B
    evs = [ev(1, 1000), ev(1, 2000), ev(2, 3000), ev(2, 4000)]
    out = run_pattern(
        "from every s1 = inputStream1[id == 1] -> "
        "every s2 = inputStream1[id == 2] "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        evs,
    )
    assert sorted((m["t1"], m["t2"]) for m in out) == [
        (1000, 3000), (1000, 4000), (2000, 3000), (2000, 4000),
    ]


def test_midchain_every_within_expiry():
    # the prefix and its forks share the pattern's start time: within
    # kills both once the deadline passes
    evs = [ev(1, 1000), ev(2, 2000), ev(2, 50000)]
    out = run_pattern(
        "from s1 = inputStream1[id == 1] -> every s2 = inputStream1[id == 2] "
        "within 10 sec "
        "select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into outputStream",
        evs,
    )
    assert [(m["t1"], m["t2"]) for m in out] == [(1000, 2000)]


def test_midchain_every_parse_errors():
    import pytest as _pytest

    from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    env = CEPEnvironment()
    base = SiddhiCEP.define("inputStream1", [ev(1, 1000)], FIELDS, env=env)
    for bad in (
        # sequences cannot re-arm mid-chain
        "from s1 = inputStream1[id == 1] , every s2 = inputStream1[id == 2] "
        "select s1.id as a insert into o",
        # quantified every-marked element
        "from s1 = inputStream1[id == 1] -> every s2 = inputStream1[id == 2]+ "
        "select s1.id as a insert into o",
    ):
        with _pytest.raises(SiddhiQLError):
            base.cql(bad).returns("o")
