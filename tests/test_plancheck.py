"""plancheck: the full query zoo verifies clean (trace tier for every
entry, deep inert-tape tier for the padded/stacked shapes), and
deliberately miscompiled plans — dtype drift, malformed NFA tables,
donation-signature breaks, non-inert padding — are rejected with
rule-ID'd errors."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from flink_siddhi_tpu.analysis.plancheck import (
    PlanCheckError,
    _check_one_nfa,
    verify_plan,
)
from flink_siddhi_tpu.analysis.zoo import PLAN_ZOO, compile_zoo, zoo_schemas
from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan

_ZOO = dict(compile_zoo())

# the entries whose padding/free rows the deep tier exists for; the
# full-zoo deep pass lives in scripts/run_static_analysis.py (CI) —
# tier-1 keeps the expensive eager executions to the shapes that carry
# padded stacks or slot pools
DEEP = (
    "multiquery_stack6",
    "slot_nfa_quantified",
    "pattern_absence",
    "chained_composition",
)


@pytest.mark.parametrize("name", sorted(PLAN_ZOO))
def test_zoo_entry_verifies_trace_tier(name):
    assert verify_plan(_ZOO[name], trace=True) == []


@pytest.mark.parametrize("name", DEEP)
def test_zoo_entry_verifies_deep(name):
    assert verify_plan(_ZOO[name], trace=True, deep=True) == []


def _fresh(name):
    return compile_plan(
        PLAN_ZOO[name], zoo_schemas(), plan_id=f"mis:{name}"
    )


def test_verify_plans_config_flag_runs_at_compile(monkeypatch):
    monkeypatch.delenv("FST_VERIFY_PLANS", raising=False)
    compile_plan(
        PLAN_ZOO["filter_select"],
        zoo_schemas(),
        config=EngineConfig(verify_plans=True),
    )
    # and the escape hatch force-disables even explicit True
    monkeypatch.setenv("FST_VERIFY_PLANS", "0")
    compile_plan(
        PLAN_ZOO["filter_select"],
        zoo_schemas(),
        config=EngineConfig(verify_plans=True),
    )


# -- deliberate miscompiles ------------------------------------------------


def _rules_of(plan, **kw):
    return {
        i.rule
        for i in verify_plan(plan, raise_on_error=False, trace=True, **kw)
    }


def test_dtype_mismatch_rejected():
    """Declared DOUBLE column silently emitting int32 — the class of
    miscompile where decode bitcasts garbage — must be PLC105."""
    plan = _fresh("filter_select")
    art = plan.artifacts[0]
    sch = art.output_schema
    price_i = next(
        i for i, f in enumerate(sch.fields) if f.name == "price"
    )
    from flink_siddhi_tpu.schema.types import AttributeType

    bad_fields = list(sch.fields)
    bad_fields[price_i] = dataclasses.replace(
        bad_fields[price_i], atype=AttributeType.INT
    )
    art.output_schema = dataclasses.replace(
        sch, fields=tuple(bad_fields)
    )
    assert "PLC105" in _rules_of(plan)


def test_malformed_nfa_tables_rejected():
    """Corrupt the slot engine's REAL derived tables (the ones the
    scan body indexes by): a non-monotone min-count prefix and a group
    table that lost an element."""
    plan = _fresh("slot_nfa_quantified")
    art = plan.artifacts[0]
    art._min_prefix = np.asarray(
        art._min_prefix[::-1].copy(), dtype=np.int32
    )
    art._groups = art._groups[:-1]
    rules = _rules_of(plan)
    assert "PLC207" in rules and "PLC208" in rules


def test_guard_on_undeclared_element_rejected():
    """PLC203 unit: an absence guard pointing at a non-'not' element
    (or out of its inter-positive window) is a miscompiled table."""
    base = dict(
        name="q",
        n_elements=3,
        positive=(0, 2),
        guards=((), (1,)),
        t_guard=None,
        negated=(False, False, False),  # 1 is NOT declared absent
        quantifiers=((1, 1), (1, 1), (1, 1)),
    )
    issues = []
    _check_one_nfa("p", base, issues)
    assert any(i.rule == "PLC203" for i in issues)
    issues = []
    _check_one_nfa(
        "p",
        {**base, "negated": (False, True, False), "guards": ((1,), ())},
        issues,
    )
    assert any(i.rule == "PLC203" for i in issues)


def test_entry_guard_placement_pinned():
    """PLC203 extension: a first-occurrence entry guard (sequence
    absence folded before a QUANTIFIED element, `A, not B, C+`) may sit
    only on a quantified, non-negated, non-first element with a
    mandatory first occurrence (min >= 1)."""
    base = dict(
        name="q",
        n_elements=2,
        positive=(0, 1),
        guards=((), ()),
        t_guard=None,
        negated=(False, False),
        quantifiers=((1, 1), (1, -1)),
        entry_guards=(1,),
    )
    issues = []
    _check_one_nfa("p", base, issues)
    assert issues == []  # the compiled `A, not B, C+` shape
    for patch in (
        {"entry_guards": (0,)},  # nothing precedes element 0
        {"entry_guards": (5,)},  # out of range
        {"quantifiers": ((1, 1), (1, 1))},  # unquantified: wrong fold
        {"quantifiers": ((1, 1), (0, -1))},  # min-0: skip bypasses it
    ):
        issues = []
        _check_one_nfa("p", {**base, **patch}, issues)
        assert any(i.rule == "PLC203" for i in issues), patch


def test_sequence_entry_guard_compiles_and_verifies():
    """The real compiled `A, not B, C+` plan carries its entry guard in
    check info (on the quantified element) and verifies clean."""
    plan = compile_plan(
        "from every s1 = S[id == 1], not S[price > 50.0], "
        "s3 = S[id == 3]+ , s4 = S[id == 4] "
        "select s1.timestamp as t1, s4.timestamp as t4 insert into m",
        zoo_schemas(),
        plan_id="seq-entry-guard",
    )
    (info,) = plan.artifacts[0].nfa_check_info()
    # rewrite drops the 'not' element: A, C+(guarded), D
    assert tuple(info["entry_guards"]) == (1,)
    assert tuple(info["quantifiers"])[1] == (1, -1)
    assert verify_plan(plan, trace=True) == []


def test_unreachable_element_rejected():
    issues = []
    _check_one_nfa(
        "p",
        dict(
            name="q",
            n_elements=3,
            positive=(0, 1),  # element 2 is neither step nor guard
            guards=((), ()),
            t_guard=None,
            negated=(False, False, False),
            quantifiers=((1, 1), (1, 1), (1, 1)),
        ),
        issues,
    )
    assert any(i.rule == "PLC205" for i in issues)


def test_donation_signature_break_rejected():
    """A state leaf consumed but not reproduced (the scan carry cannot
    type, donation frees a live buffer) must be PLC401."""
    plan = _fresh("length_window_agg")
    art = plan.artifacts[0]
    orig_init = art.init_state
    art.init_state = lambda: {
        **orig_init(),
        "@bogus": jnp.zeros(4, jnp.int32),
    }
    assert "PLC401" in _rules_of(plan)


def test_non_inert_padding_rejected():
    """An artifact emitting a phantom row for an ALL-INVALID tape (a
    stale pad row reaching the accumulator) must be PLC311 in deep
    mode."""
    plan = _fresh("filter_select")
    art = plan.artifacts[0]
    orig_step = art.step

    def leaky_step(state, tape):
        new_state, (n, ts, cols) = orig_step(state, tape)
        return new_state, (n + 1, ts, cols)

    art.step = leaky_step
    assert "PLC311" in _rules_of(plan, deep=True)


def test_plancheck_error_renders_rule_ids():
    plan = _fresh("slot_nfa_quantified")
    plan.artifacts[0]._groups = plan.artifacts[0]._groups[:-1]
    with pytest.raises(PlanCheckError, match="PLC208"):
        verify_plan(plan, trace=False)
