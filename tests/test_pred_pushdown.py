"""Wire predicate pushdown (EngineConfig.pred_pushdown): host-evaluable
predicates are computed on the ingest host with numpy and ship as ONE
packed BIT per event; their raw columns drop off the device tape.

Also covers the wire kinds the bench relies on: 'b1' (bit-packed bools)
and 'd0' (constant-cadence timestamps, zero wire bytes).
"""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.runtime.tape import build_wire_tape
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("name", AttributeType.STRING),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)


def make_batches(n=2000, batch=64, seed=11, step_ms=1):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 6, n).astype(np.int32)
    prices = np.round(rng.random(n) * 100, 3)
    names = rng.integers(0, 3, n)
    ts = (1000 + step_ms * np.arange(n)).astype(np.int64)
    tbl = SCHEMA.string_tables["name"]
    codes = np.array([tbl.intern(f"nm{i}") for i in range(3)], np.int32)
    return [
        EventBatch(
            "S", SCHEMA,
            {
                "id": ids[s:s + batch],
                "name": codes[names[s:s + batch]],
                "price": prices[s:s + batch],
                "timestamp": ts[s:s + batch],
            },
            ts[s:s + batch],
        )
        for s in range(0, n, batch)
    ]


def run(cql, cfg, batch=64, n=2000):
    plan = compile_plan(cql, {"S": SCHEMA}, config=cfg)
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(make_batches(n=n, batch=batch)))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return plan, job


EAGER = EngineConfig()
PUSH = EngineConfig(pred_pushdown=True)
PUSH_LAZY = EngineConfig(pred_pushdown=True, lazy_projection=True)


def test_select_pushdown_matches_eager():
    cql = "from S[id == 2] select name, price insert into out"
    plan_e, job_e = run(cql, EAGER)
    plan_p, job_p = run(cql, PUSH)
    # the predicate column drops off the wire; the mask ships instead
    assert plan_p.spec.host_preds and plan_p.spec.host_preds[0].out_key == "@p:0"
    assert "S.id" not in plan_p.spec.device_columns
    eager, push = job_e.results("out"), job_p.results("out")
    assert len(eager) == len(push) > 0
    for (ne, pe), (np_, pp) in zip(eager, push):
        assert ne == np_
        assert pp == pytest.approx(pe, rel=1e-6)


def test_select_pushdown_skipped_when_nothing_freed():
    # id is also projected (non-lazy): pushing would free nothing, so
    # the predicate stays on the device and no mask ships
    cql = "from S[id == 2] select id, name, price insert into out"
    plan_p, _ = run(cql, PUSH, n=200)
    assert plan_p.spec.host_preds == ()
    assert plan_p.spec.device_columns is None


def test_select_pushdown_plus_lazy_ships_only_bits():
    cql = "from S[id == 2] select id, name, price insert into out"
    plan, job = run(cql, PUSH_LAZY)
    # with lazy projection the pred column becomes ordinal-decodable,
    # so pushdown fires and NOTHING but the mask ships
    assert plan.spec.device_columns == ()
    assert [h.out_key for h in plan.spec.host_preds] == ["@p:0"]
    _, job_e = run(cql, EAGER)
    eager, push = job_e.results("out"), job.results("out")
    assert len(eager) == len(push) > 0
    for (ie, ne, pe), (ip, np_, pp) in zip(eager, push):
        assert (ie, ne) == (ip, np_)
        assert pp == pytest.approx(pe, rel=1e-6)


def test_chain_pushdown_matches_eager():
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] -> s3 = S[id == 3] "
        "within 5 sec "
        "select s1.timestamp as t1, s3.timestamp as t3, s3.price as p "
        "insert into m"
    )
    plan_e, job_e = run(cql, EAGER)
    plan_p, job_p = run(cql, PUSH_LAZY)
    a = plan_p.artifacts[0]
    assert a.pushed_preds == (0, 1, 2)
    assert plan_p.spec.device_columns == ()
    assert len(plan_p.spec.host_preds) == 3
    eager, push = sorted(job_e.results("m")), sorted(job_p.results("m"))
    assert len(eager) == len(push) > 0
    for (t1e, t3e, pe), (t1p, t3p, pp) in zip(eager, push):
        assert (t1e, t3e) == (t1p, t3p)
        assert pp == pytest.approx(pe, rel=1e-6)


def test_chain_pushdown_string_and_float_preds():
    cql = (
        "from every s1 = S[name == 'nm1'] -> s2 = S[price > 50.0] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into m"
    )
    _, job_e = run(cql, EAGER)
    plan_p, job_p = run(cql, PUSH_LAZY)
    assert plan_p.artifacts[0].pushed_preds == (0, 1)
    # host predicates see f64: results must still agree with the oracle
    # (the bench literals are f32-exact; here > keeps them comparable)
    assert sorted(job_e.results("m")) == sorted(job_p.results("m"))
    assert len(job_p.results("m")) > 0


def test_cross_element_filters_not_pushed():
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[price > s1.price] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into m"
    )
    plan_p, job_p = run(cql, PUSH_LAZY)
    # the cross filter must never be host-pushed (it reads captures);
    # this pattern compiles to the slot engine, which skips pushdown
    # entirely — either way no host pred may read a capture-dependent
    # filter, and results must match the eager oracle
    assert getattr(plan_p.artifacts[0], "pushed_preds", ()) == ()
    assert plan_p.spec.host_preds == ()
    _, job_e = run(cql, EAGER)
    assert sorted(job_p.results("m")) == sorted(job_e.results("m"))
    assert len(job_p.results("m")) > 0


def test_pushdown_dynamic_add_keeps_own_runtime():
    # a pushed plan cannot fold into a parametric dynamic group (its
    # tape lacks the raw columns); it must keep its own runtime
    plan = compile_plan(
        "from every s1 = S[id == 1] -> s2 = S[id == 2] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into m",
        {"S": SCHEMA}, config=PUSH_LAZY,
    )
    job = Job(
        [], [BatchSource("S", SCHEMA, iter(make_batches(n=256)))],
        batch_size=64, time_mode="processing",
    )
    job.add_plan(plan, dynamic=True)
    assert list(job._plans) == [plan.plan_id]
    job.run()
    assert len(job.results("m")) > 0


# -- wire kind unit coverage ------------------------------------------------


def _wire_for(batch_events, cfg=PUSH_LAZY, cql=None, step_ms=1):
    cql = cql or "from S[id == 2] select name, price insert into out"
    plan = compile_plan(cql, {"S": SCHEMA}, config=cfg)
    batches = make_batches(n=batch_events, batch=batch_events,
                           step_ms=step_ms)
    return plan, build_wire_tape(
        plan.spec, batches[:1], 1000, {}, capacity=None
    )[0]


def test_b1_bitpack_roundtrip():
    import jax

    plan, wire = _wire_for(8192)
    assert dict(wire.kinds)["@p:0"] == "b1"
    packed = wire.cols["@p:0"]
    assert packed.dtype == np.uint8 and packed.nbytes == 8192 // 8
    tape = jax.jit(lambda w: w.expand().cols["@p:0"])(wire)
    ids = np.concatenate(
        [b.columns["id"] for b in make_batches(n=8192, batch=8192)]
    )
    np.testing.assert_array_equal(np.asarray(tape)[:8192], ids == 2)


def test_d0_constant_cadence_ships_zero_ts_bytes():
    import jax

    plan, wire = _wire_for(8192, step_ms=7)
    assert wire.ts_kind == "d0"
    assert wire.ts.size == 0
    assert wire.capacity == 8192
    ts = np.asarray(jax.jit(lambda w: w.expand().ts)(wire))
    assert ts[0] == 0 and ts[1] == 7  # rebased to epoch, step 7
    assert ts[8191] == 7 * 8191


def test_d0_degrades_to_deltas_on_irregular_batch():
    plan = compile_plan(
        "from S[id == 2] select name, price insert into out",
        {"S": SCHEMA}, config=PUSH_LAZY,
    )
    sticky = {}
    regular = make_batches(n=8192, batch=8192)
    build_wire_tape(plan.spec, regular[:1], 1000, sticky, capacity=8192)
    assert sticky["__ts__"] == "d0"
    # irregular cadence: widen, never narrow back
    irr = make_batches(n=8192, batch=8192)
    irr[0].columns["timestamp"][5] += 3
    irr[0].timestamps[5] += 3
    build_wire_tape(plan.spec, irr[:1], 1000, sticky, capacity=8192)
    assert sticky["__ts__"] in ("d8", "d16")
    build_wire_tape(plan.spec, regular[:1], 1000, sticky, capacity=8192)
    assert sticky["__ts__"] in ("d8", "d16")


def test_small_batches_never_pick_d0():
    plan = compile_plan(
        "from S[id == 2] select name, price insert into out",
        {"S": SCHEMA}, config=PUSH_LAZY,
    )
    sticky = {}
    build_wire_tape(
        plan.spec, make_batches(n=64, batch=64)[:1], 1000, sticky,
        capacity=64,
    )
    assert sticky["__ts__"] != "d0"
