"""Out-of-process side-channel RTT prober (telemetry/prober.py): the
independent witness for every latency claim. The contract under test:

* the prober runs in a SEPARATE OS process (asserted by pid — the
  acceptance criterion of the falsifiable-latency round);
* sentinel events round-trip through a REAL socket-source job (TCP
  ingest -> decode -> dispatch -> drain -> sink -> ack) and every probe
  is accounted for (received or explicitly lost);
* the prober's externally-clocked p99 agrees with the per-event traced
  p99 from the job's own TraceSampler within a stated tolerance
  (CPU lane: |prober - trace| <= max(3x, 250 ms) — generous because the
  two measure deliberately different spans: the prober adds the socket
  hop in and the ack hop out, and the 2-core CI box schedules threads
  coarsely; the point is catching ORDER-OF-MAGNITUDE lies, e.g. an
  internal p99 of 5 ms when users see 500).
"""

import os
import socket
import time

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import SocketLineSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType
from flink_siddhi_tpu.telemetry.prober import (
    ProbeReport,
    SideChannelProber,
)

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)
MAGIC = 1_000_000.0


def _probe_job():
    src = SocketLineSource("S", SCHEMA, port=0, ts_field="timestamp")
    plan = compile_plan(
        "from S[id == 2] select id, price insert into o",
        {"S": SCHEMA},
    )
    job = Job([plan], [src], batch_size=256, time_mode="processing")
    job.drain_interval_ms = 20.0
    job.tracer.sample_every = 1  # trace EVERY event: exact comparison
    return job, src


def _nonce_of(row):
    p = float(row[1])
    return int(p - MAGIC) if p >= MAGIC / 2 else None


def _payloads(n):
    return [
        '{"id": 2, "price": %.1f, "timestamp": %d}\n'
        % (MAGIC + i, 1_000_000_000 + i * 8)
        for i in range(n)
    ]


def _drive(job, prober, deadline_s=60.0):
    """Pump the run loop (the engine under test) until the child's
    report lands."""
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        job.run_cycle()
        if prober.poll_result() is not None:
            return prober.result(5.0)
        time.sleep(0.001)
    return prober.result(5.0)


def test_prober_round_trips_through_real_socket_job():
    job, src = _probe_job()
    n = 25
    prober = SideChannelProber(
        src.host, src.port, _payloads(n), period_s=0.03, timeout_s=15.0
    )
    job.add_sink("o", prober.make_sink(_nonce_of))
    # warm the compile path off the probe clock: the first cycle pays
    # jit compiles that would otherwise land entirely in probe 0's RTT
    with socket.create_connection((src.host, src.port)) as c:
        c.sendall(b'{"id": 2, "price": 1.0, "timestamp": 1000}\n')
    for _ in range(40):
        job.run_cycle()
    prober.start()
    report = _drive(job, prober)
    try:
        assert report is not None, "prober child produced no report"
        # --- the separate-OS-process criterion, by pid ---
        assert isinstance(report.pid, int)
        assert report.pid != os.getpid()
        assert prober.child_pid == report.pid
        # child-clocked samples: every probe accounted for
        assert report.n_sent == n
        assert report.n_received + len(report.lost) == n
        # the engine at idle must deliver essentially all probes
        assert report.n_received >= n - 2, (
            report.n_received, report.lost,
        )
        assert report.clock == "child-monotonic"
        p99_probe = report.percentile_ms(99)
        p50_probe = report.percentile_ms(50)
        assert p99_probe is not None and p99_probe > 0
        assert p50_probe <= p99_probe
        # --- reconcile against the per-event traced p99 ---
        trace = job.tracer.snapshot()
        assert trace["e2e"]["count"] >= report.n_received
        p99_trace = trace["e2e"]["p99_ms"]
        # stated CPU-lane tolerance: within 3x + 250 ms slack, either
        # direction (the prober span strictly contains the traced span,
        # but thread scheduling on the 2-core box adds noise both ways)
        assert p99_probe <= 3.0 * p99_trace + 250.0, (
            p99_probe, p99_trace,
        )
        assert p99_trace <= 3.0 * p99_probe + 250.0, (
            p99_probe, p99_trace,
        )
    finally:
        prober.close()
        src.close()
        job.run()  # drain and finish cleanly


def test_prober_reports_losses_not_hangs():
    """Probes that never match (id != 2) must come back as LOST after
    the child's timeout — a broken data path cannot produce a
    plausible-looking latency number."""
    job, src = _probe_job()
    payloads = [
        '{"id": 7, "price": %.1f, "timestamp": %d}\n'
        % (MAGIC + i, 1_000_000_000 + i * 8)
        for i in range(5)
    ]
    prober = SideChannelProber(
        src.host, src.port, payloads, period_s=0.01, timeout_s=2.0
    )
    job.add_sink("o", prober.make_sink(_nonce_of))
    prober.start()
    report = _drive(job, prober, deadline_s=30.0)
    try:
        assert report is not None
        assert report.n_received == 0
        assert len(report.lost) == 5
        assert report.percentile_ms(99) is None
    finally:
        prober.close()
        src.close()
        job.run()


def test_probe_report_percentiles_nearest_rank():
    rep = ProbeReport(
        pid=1, n_sent=4,
        rtt_ms={0: 10.0, 1: 20.0, 2: 30.0, 3: 40.0},
    )
    assert rep.percentile_ms(50) == 20.0
    assert rep.percentile_ms(99) == 40.0
    assert rep.n_received == 4
