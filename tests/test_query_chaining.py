"""Query chaining: one query's `insert into` feeding a later query's
input within the same plan (the reference's multi-query composition
style, package-info.java:19-51). Unlocks aggregation over join output —
siddhi-core supports aggregating joined streams (README.md:84-88), which
round 2 rejected outright (VERDICT item 6)."""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP
from flink_siddhi_tpu.query.lexer import SiddhiQLError


@dataclasses.dataclass
class Trade:
    sym: int
    price: float
    timestamp: int


@dataclasses.dataclass
class Quote:
    sym: int
    bid: float
    timestamp: int


TF = ["sym", "price", "timestamp"]
QF = ["sym", "bid", "timestamp"]


def mk_trades(n, start=1000, step=1000, syms=3):
    return [Trade(i % syms, 100.0 + i, start + step * i) for i in range(n)]


def mk_quotes(n, start=1500, step=1000, syms=3):
    return [Quote(i % syms, 50.0 + i, start + step * i) for i in range(n)]


def join_pairs(trades, quotes, nt, nq):
    """Oracle: equi-join pairs of a streaming length-window join."""
    arrivals = sorted(
        [("t", e) for e in trades] + [("q", e) for e in quotes],
        key=lambda x: x[1].timestamp,
    )
    t_seen, q_seen, pairs = [], [], []
    for side, e in arrivals:
        if side == "t":
            pairs += [
                (e, q) for q in q_seen[-nq:] if q.sym == e.sym
            ]
            t_seen.append(e)
        else:
            pairs += [
                (t, e) for t in t_seen[-nt:] if t.sym == e.sym
            ]
            q_seen.append(e)
    return pairs


@pytest.mark.parametrize("batch_size", [4096, 7])
def test_aggregate_over_windowed_join(batch_size):
    # the VERDICT's exact ask: sum() over a windowed join, via chaining
    trades, quotes = mk_trades(12), mk_quotes(10)
    env = CEPEnvironment(batch_size=batch_size)
    out = (
        SiddhiCEP.define("Trades", trades, TF, env=env)
        .union("Quotes", quotes, QF)
        .cql(
            "from Trades#window.length(4) as t "
            "join Quotes#window.length(3) as q on t.sym == q.sym "
            "select t.sym as sym, t.price + q.bid as v insert into mid; "
            "from mid select sum(v) as total, count() as cnt "
            "insert into out"
        )
        .return_as_map("out")
    )
    pairs = join_pairs(trades, quotes, 4, 3)
    # unbounded running aggregate: the join emits within-batch pairs in
    # segment (not ts) order, so the final totals are at the max-count
    # row — and must equal the oracle over ALL pairs
    assert out, "no aggregate rows emitted"
    final = max(out, key=lambda m: m["cnt"])
    assert final["cnt"] == len(pairs)
    assert abs(
        final["total"] - sum(t.price + q.bid for t, q in pairs)
    ) < 1e-6


def test_filter_chain_pipe():
    # simple pipe: filter -> intermediate -> second filter
    evs = [Trade(i % 5, float(i), 1000 + i) for i in range(50)]
    env = CEPEnvironment()
    out = (
        SiddhiCEP.define("S", evs, TF, env=env)
        .cql(
            "from S[sym == 2] select sym, price insert into mid; "
            "from mid[price > 20.0] select price insert into out"
        )
        .returns("out")
    )
    expect = [
        (e.price,) for e in evs if e.sym == 2 and e.price > 20.0
    ]
    assert out == expect


def test_pattern_into_windowed_aggregate():
    # chain pattern -> intermediate -> length-window aggregation
    evs = [Trade(i % 4, float(i), 1000 + 1000 * i) for i in range(40)]
    env = CEPEnvironment()
    out = (
        SiddhiCEP.define("S", evs, TF, env=env)
        .cql(
            "from every s1 = S[sym == 1] -> s2 = S[sym == 2] "
            "select s2.price as p insert into mid; "
            "from mid#window.lengthBatch(4) select sum(p) as total "
            "insert into out"
        )
        .return_as_map("out")
    )
    # oracle: every sym==1 pairs with the NEXT sym==2; p = that price
    ps = []
    pending = 0
    for e in evs:
        if e.sym == 1:
            pending += 1
        elif e.sym == 2 and pending:
            ps += [e.price] * pending
            pending = 0
    batches = [ps[i:i + 4] for i in range(0, len(ps) - len(ps) % 4, 4)]
    assert [m["total"] for m in out] == [sum(b) for b in batches]


def test_chained_errors():
    evs = [Trade(0, 1.0, 1000)]
    env = CEPEnvironment()
    base = SiddhiCEP.define("S", evs, TF, env=env)
    # forward reference: consumer before producer
    with pytest.raises(SiddhiQLError):
        base.cql(
            "from mid select price insert into out; "
            "from S select sym, price insert into mid"
        ).returns("out")
    # pattern over an intermediate stream is rejected clearly
    with pytest.raises(SiddhiQLError):
        base.cql(
            "from S select sym, price, timestamp insert into mid; "
            "from every a = mid[sym == 1] -> b = mid[sym == 2] "
            "select a.price as p insert into out"
        ).returns("out")


def test_chained_group_by_clear_error():
    evs = [Trade(0, 1.0, 1000)]
    env = CEPEnvironment()
    with pytest.raises(SiddhiQLError, match="chained stream"):
        SiddhiCEP.define("S", evs, TF, env=env).cql(
            "from S select sym, price insert into mid; "
            "from mid select sym, sum(price) as t group by sym "
            "insert into out"
        ).returns("out")
