"""Resident (bounded-replay) mode vs streaming mode: bit-identical rows.

The resident replay (runtime/replay.py) changes only the DISPATCH
granularity — its scan body is the streaming step — so the two modes
must agree on every emitted row and timestamp across plan shapes:
stateless filters, pattern chains, windowed group-by (incl. the
end-of-stream timeBatch flush), multi-stream patterns, and wide
multi-query stacks that exercise the tape-capacity chunking.
"""

import numpy as np
import pytest

import bench
from flink_siddhi_tpu.compiler.config import EngineConfig
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.replay import ResidentReplay
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType


def _schema(shared=None):
    return StreamSchema(
        [
            ("id", AttributeType.INT),
            ("name", AttributeType.STRING),
            ("price", AttributeType.DOUBLE),
            ("timestamp", AttributeType.LONG),
        ],
        shared_strings=shared,
    )


def _run(cql, batches_fn, mode, batch, config=None, time_mode="processing"):
    schema = _schema()
    plan = compile_plan(
        cql, {"inputStream": schema},
        config=config or EngineConfig(),
    )
    job = Job(
        [plan],
        [BatchSource("inputStream", schema, iter(batches_fn(schema)))],
        batch_size=batch, time_mode=time_mode,
    )
    if mode == "resident":
        ResidentReplay(job).execute()
    else:
        job.run()
    out = {}
    for sid in job.collected:
        out[sid] = sorted(job.results_with_ts(sid))
    return out


CASES = {
    "filter": (
        "from inputStream[id == 2] select id, name, price "
        "insert into out",
        50,
    ),
    "pattern3": (
        "from every s1 = inputStream[id == 1] -> "
        "s2 = inputStream[id == 2] -> s3 = inputStream[id == 3] "
        "within 5 sec "
        "select s1.timestamp as t1, s3.timestamp as t3, "
        "s3.price as price insert into out",
        50,
    ),
    "window_groupby": (
        "from inputStream#window.length(100) "
        "select id, sum(price) as total, count() as cnt "
        "group by id insert into out",
        40,
    ),
    "timebatch": (
        "from inputStream#window.timeBatch(3 sec) "
        "select sum(price) as total insert into out",
        50,
    ),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_resident_matches_streaming(case):
    cql, n_ids = CASES[case]
    n, batch = 40_000, 4096

    def batches(schema):
        return bench.make_batches(n, batch, schema, "inputStream", n_ids)

    cfg = EngineConfig(lazy_projection=True, pred_pushdown=True)
    a = _run(cql, batches, "streaming", batch, config=cfg)
    b = _run(cql, batches, "resident", batch, config=cfg)
    assert a.keys() == b.keys() and a, (case, a.keys(), b.keys())
    for sid in a:
        assert a[sid] == b[sid], (case, sid, len(a[sid]), len(b[sid]))


def test_resident_matches_streaming_multiquery():
    # 8 stacked chain queries over one stream: exercises the stacked
    # group artifact and (with a small tape cap) the chunked windows
    parts = []
    for q in range(8):
        a, b = q % 5, (q * 3 + 1) % 5
        parts.append(
            f"from every s1 = inputStream[id == {a}] -> "
            f"s2 = inputStream[id == {b}] "
            f"select s1.timestamp as t1, s2.timestamp as t2 "
            f"insert into m{q}"
        )
    cql = "; ".join(parts)
    n, batch = 20_000, 4096

    def batches(schema):
        return bench.make_batches(n, batch, schema, "inputStream", 5)

    a = _run(cql, batches, "streaming", batch)
    b = _run(cql, batches, "resident", batch)
    assert a.keys() == b.keys() and len(a) == 8
    for sid in a:
        assert a[sid] == b[sid], (sid, len(a[sid]), len(b[sid]))


def test_resident_multi_stream_event_time():
    # two physical sources, event-time watermark gating: the replay
    # stager must reproduce the streaming reorder-release exactly
    s1 = _schema()
    s2 = _schema()
    rng = np.random.default_rng(3)

    def mk(schema, sid, n, seed_off):
        r = np.random.default_rng(10 + seed_off)
        out = []
        for start in range(0, n, 512):
            m = min(512, n - start)
            ts = 1000 + 7 * (start + np.arange(m, dtype=np.int64))
            cols = {
                "id": r.integers(0, 4, size=m).astype(np.int32),
                "name": np.zeros(m, dtype=np.int32),
                "price": r.random(m) * 10.0,
                "timestamp": ts,
            }
            out.append(EventBatch(sid, schema, cols, ts))
        return out

    cql = (
        "from every a = in1[id == 1] -> b = in2[id == 2] "
        "select a.timestamp as t1, b.timestamp as t2 insert into out"
    )

    def build(mode):
        plan = compile_plan(cql, {"in1": s1, "in2": s2})
        job = Job(
            [plan],
            [
                BatchSource("in1", s1, iter(mk(s1, "in1", 4000, 0))),
                BatchSource("in2", s2, iter(mk(s2, "in2", 4000, 1))),
            ],
            batch_size=1024, time_mode="event",
        )
        if mode == "resident":
            ResidentReplay(job).execute()
        else:
            job.run()
        return sorted(job.results_with_ts("out"))

    a, b = build("streaming"), build("resident")
    assert a and a == b


def test_resident_control_streams_contract():
    """ResidentReplay ACCEPTS control sources (epoch-boundary apply —
    the control/ plane; behavior pinned in tests/test_control_plane.py)
    while the sharded variant still refuses, naming the contract and
    the working alternatives — no stale pointers."""
    from flink_siddhi_tpu.runtime.replay import ShardedResidentReplay
    from flink_siddhi_tpu.runtime.sources import ControlListSource

    schema = _schema()
    plan = compile_plan(
        "from inputStream[id == 1] select id insert into out",
        {"inputStream": schema},
    )
    job = Job(
        [plan],
        [BatchSource("inputStream", schema, iter([]))],
        control_sources=[ControlListSource([])],
    )
    rep = ResidentReplay(job)  # accepted: epoch-boundary control
    rep.execute()
    assert job.finished
    job2 = Job(
        [plan],
        [BatchSource("inputStream", schema, iter([]))],
        control_sources=[ControlListSource([])],
    )
    with pytest.raises(ValueError, match="epoch") as ei:
        ShardedResidentReplay(job2)
    msg = str(ei.value)
    assert "streaming" in msg and "control_plane" in msg


def test_rerun_is_deterministic_counts_only():
    """rerun() resets state and replays the staged tapes: emitted
    counts double exactly (same matches found twice), and it refuses
    jobs with consumers."""
    schema = _schema()
    n, batch = 20_000, 4096
    cql = CASES["pattern3"][0]

    def batches():
        return bench.make_batches(n, batch, schema, "inputStream", 50)

    plan = compile_plan(
        cql, {"inputStream": schema},
        config=EngineConfig(lazy_projection=True, pred_pushdown=True),
    )
    job = Job(
        [plan],
        [BatchSource("inputStream", schema, iter(batches()))],
        batch_size=batch, time_mode="processing", retain_results=False,
    )
    rep = ResidentReplay(job)
    rep.stage()
    rep.run()
    job.flush()
    first = dict(job.emitted_counts)
    assert sum(first.values()) > 0
    dt = rep.rerun()
    assert dt > 0
    assert {k: 2 * v for k, v in first.items()} == dict(
        job.emitted_counts
    )

    # with a consumer attached, rerun refuses
    job2 = Job(
        [compile_plan(cql, {"inputStream": schema})],
        [BatchSource("inputStream", schema, iter(batches()))],
        batch_size=batch, time_mode="processing",
    )
    rep2 = ResidentReplay(job2)
    rep2.stage()
    rep2.run()
    job2.flush()
    with pytest.raises(ValueError, match="counts-only"):
        rep2.rerun()


@pytest.mark.slow  # full-mesh-8 shard_map: minutes of XLA CPU compile on the 2-core tier-1 lane (mesh-4 sharded coverage stays tier-1)
def test_sharded_resident_matches_sharded_streaming():
    """Bounded replay over a ShardedJob mesh: the [cycles, shards, ...]
    scan whose body is the shard_map'd step must reproduce the sharded
    streaming run row-for-row (8-device virtual CPU mesh)."""
    from flink_siddhi_tpu.parallel import ShardedJob
    from flink_siddhi_tpu.runtime.replay import ShardedResidentReplay

    schema = StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    rng = np.random.default_rng(17)
    n, batch = 6000, 512
    ks = rng.integers(0, 11, n).astype(np.int32)
    vs = np.round(rng.random(n) * 10, 2)
    ts = (1000 + np.arange(n)).astype(np.int64)

    def batches():
        return iter([
            EventBatch(
                "S", schema,
                {"k": ks[s:s + batch], "v": vs[s:s + batch],
                 "timestamp": ts[s:s + batch]},
                ts[s:s + batch],
            )
            for s in range(0, n, batch)
        ])

    cql = (
        "from S select k, sum(v) as s group by k insert into o; "
        "partition with (k of S) begin "
        "from every a = S[v > 5] -> b = S[v <= 5] "
        "select a.timestamp as t1, b.timestamp as t2, a.k as kk "
        "insert into p end"
    )

    def build():
        return ShardedJob(
            [compile_plan(cql, {"S": schema})],
            [BatchSource("S", schema, iter(batches()))],
            n_shards=8, batch_size=batch, time_mode="processing",
        )

    sj1 = build()
    sj1.run()
    sj2 = build()
    rep = ShardedResidentReplay(sj2)
    rep.stage()
    rep.run()
    sj2.flush()
    for sid in ("o", "p"):
        a = sorted(sj1.results_with_ts(sid))
        b = sorted(sj2.results_with_ts(sid))
        assert a and len(a) == len(b), (sid, len(a), len(b))
        for (t1, r1), (t2, r2) in zip(a, b):
            assert t1 == t2
            for x, y in zip(r1, r2):
                if isinstance(x, float):
                    assert x == pytest.approx(y, rel=1e-5)
                else:
                    assert x == y
