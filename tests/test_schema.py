"""Schema/type-bridge unit tests.

Model: reference schema tests (schema/StreamSchemaTest.java:33-97,
schema/StreamSerializerTest.java:29-81, utils/SiddhiTypeFactoryTest.java,
schema/SiddhiExecutionPlanSchemaTest.java:47-48 DDL golden test).
"""

import dataclasses
from collections import namedtuple

import numpy as np
import pytest

from flink_siddhi_tpu.schema import (
    AttributeType,
    EventBatch,
    StreamSchema,
    StringTable,
)


@dataclasses.dataclass
class Event:  # the reference's test POJO (source/Event.java)
    id: int
    name: str
    price: float
    timestamp: int


SCHEMA_FIELDS = [
    ("id", "int"),
    ("name", "string"),
    ("price", "double"),
    ("timestamp", "long"),
]


def test_field_resolution_pojo():
    s = StreamSchema(SCHEMA_FIELDS)
    assert s.arity == 4
    assert s.field_index("price") == 2
    assert s.field_type("name") == AttributeType.STRING
    row = s.get_row(Event(1, "a", 2.5, 100))
    assert row == (1, "a", 2.5, 100)


def test_field_resolution_tuple_dict_namedtuple_atomic():
    s = StreamSchema(SCHEMA_FIELDS)
    assert s.get_row((1, "a", 2.5, 100)) == (1, "a", 2.5, 100)
    assert (
        s.get_row({"id": 1, "name": "a", "price": 2.5, "timestamp": 100})
        == (1, "a", 2.5, 100)
    )
    NT = namedtuple("NT", ["id", "name", "price", "timestamp"])
    assert s.get_row(NT(1, "a", 2.5, 100)) == (1, "a", 2.5, 100)
    atomic = StreamSchema([("words", "string")])
    assert atomic.get_row("hello") == ("hello",)


def test_unknown_field_raises():
    s = StreamSchema(SCHEMA_FIELDS)
    with pytest.raises(KeyError):
        s.field_index("unknown")


def test_duplicate_field_raises():
    with pytest.raises(ValueError):
        StreamSchema([("a", "int"), ("a", "int")])


def test_ddl_golden():
    s = StreamSchema(SCHEMA_FIELDS)
    assert (
        s.ddl("inputStream")
        == "define stream inputStream (id int, name string, price double, "
        "timestamp long);"
    )


def test_string_table_roundtrip():
    t = StringTable()
    codes = t.intern_many(["a", "b", "a", "c"])
    assert codes.tolist() == [0, 1, 0, 2]
    assert t.decode(np.array([2, 0])) == ["c", "a"]
    assert t.lookup("missing") == -1


def test_event_batch_encode_decode():
    s = StreamSchema(SCHEMA_FIELDS)
    events = [Event(i, f"n{i % 2}", 1.5 * i, 1000 + i) for i in range(5)]
    b = EventBatch.from_records(
        "inputStream", s, events, timestamps=[1000 + i for i in range(5)]
    )
    assert len(b) == 5
    assert b.columns["id"].dtype == np.int32
    assert b.columns["name"].dtype == np.int32  # dictionary codes
    assert b.columns["price"].dtype == np.float32
    rec = b.record(3)
    assert rec == {"id": 3, "name": "n1", "price": 4.5, "timestamp": 1003}


def test_event_batch_concat_sort():
    s = StreamSchema([("x", "int")])
    b1 = EventBatch.from_records("s", s, [(1,), (3,)], timestamps=[10, 30])
    b2 = EventBatch.from_records("s", s, [(2,)], timestamps=[20])
    merged = EventBatch.concat([b1, b2]).sort_by_time()
    assert merged.timestamps.tolist() == [10, 20, 30]
    assert merged.columns["x"].tolist() == [1, 2, 3]
