"""Per-tenant observability (PR: scoped metric attribution, the
admitted-vs-measured footprint meter, OpenMetrics exposition).

Contracts pinned here (docs/observability.md):

* **Conservation** — across a full admit / stack-join / disable /
  enable / retire control timeline, per-plan ``rows_emitted`` scopes
  sum EXACTLY to the job-level emitted total, in streaming, fused, and
  resident modes, and the per-plan split agrees across all three modes
  row-for-row.
* **Footprint meter** — for every legit zoo plan the measured device
  footprint stays within the admission-time ADM101/102 prediction; a
  deliberately under-admitted plan trips the loud
  ``footprint.overruns`` counter; the meter is metadata-only (runs
  clean under ``HOTLOOP_TRANSFER_GUARD`` inside the guarded hot loop).
* **OpenMetrics** — ``Job.openmetrics()`` / the
  ``GET /api/v1/metrics/prometheus`` route parse with a STANDALONE
  text-format checker (no client library) and carry ``plan`` and
  ``tenant`` labels on the scoped series.
* **Tenant rollup** — ``metrics()["tenants"]`` merges plan scopes per
  tenant (counters summed, histograms bucket-merged), and AOT-cache /
  stack-join traffic is attributable per tenant.
"""

import json
import math
import re
import urllib.request

import numpy as np
import pytest

from flink_siddhi_tpu.analysis.admit import analyze_plan
from flink_siddhi_tpu.app.service import (
    ControlQueueSource,
    QueryControlService,
)
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control import (
    ControlPlane,
    MetadataControlEvent,
    OperationControlEvent,
)
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.replay import ResidentReplay
from flink_siddhi_tpu.runtime.sources import (
    BatchSource,
    CallbackSource,
    ControlListSource,
)
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)


class Rec:
    def __init__(self, id, price, timestamp):
        self.id, self.price, self.timestamp = id, price, timestamp


def compiler(cql, pid):
    return compile_plan(cql, {"S": SCHEMA}, plan_id=pid)


def chain_cql(a, b, out="out"):
    return (
        f"from every s1 = S[id == {a}] -> s2 = S[id == {b}] "
        "within 60 sec "
        f"select s1.timestamp as t1, s2.timestamp as t2 "
        f"insert into {out}"
    )


def _mk_batches(n, start):
    ids = (np.arange(n) % 4).astype(np.int64)
    ts = (start + np.arange(n) * 1000).astype(np.int64)
    return EventBatch(
        "S", SCHEMA,
        {"id": ids, "price": np.arange(n, dtype=np.float64),
         "timestamp": ts},
        ts,
    )


def _control_timeline():
    """The PR 12 parity timeline (tests/test_control_plane.py), with
    tenants on the adds: admit qa (acme) -> stack-join qb (bobcorp) ->
    disable/enable qb -> retire qa."""
    b = MetadataControlEvent.builder()
    b.add_execution_plan(chain_cql(1, 2), plan_id="qa")
    ev_a = b.build()
    ev_a.tenant = "acme"
    b2 = MetadataControlEvent.builder()
    b2.add_execution_plan(chain_cql(2, 3), plan_id="qb")
    ev_b = b2.build()
    ev_b.tenant = "bobcorp"
    drop = MetadataControlEvent.builder()
    drop.remove_execution_plan("qa")
    return [
        (0, ev_a),
        (9_500, ev_b),
        (15_500, OperationControlEvent.disable_query("qb")),
        (20_500, OperationControlEvent.enable_query("qb")),
        (25_500, drop.build()),
    ]


def _run_mode(mode):
    batches = [_mk_batches(8, s) for s in (1000, 9000, 17000, 25000)]
    job = Job(
        [], [BatchSource("S", SCHEMA, iter(batches))], batch_size=8,
        time_mode="event",
        control_sources=[ControlListSource(_control_timeline())],
        plan_compiler=compiler,
    )
    if mode == "fused":
        job.fused_segment_len = 2
    if mode == "resident":
        ResidentReplay(job).execute()
    else:
        job.run()
    return job


# one timeline run per mode, shared by the conservation / rollup /
# exposition tests below (the engine work is identical to the PR 12
# parity tests, so the XLA executables are persistent-cache-warm)
_JOBS = {}


def _job_for(mode):
    if mode not in _JOBS:
        _JOBS[mode] = _run_mode(mode)
    return _JOBS[mode]


def _per_plan_rows(job):
    return {
        pid: reg.counter_value("rows_emitted")
        for pid, reg in job.telemetry.scope_map("plan").items()
        if not pid.startswith("@dyn:")
    }


def _job_total(job):
    return sum(
        n
        for sid, n in job.emitted_counts.items()
        if not sid.endswith("@late")
    )


# -- conservation across the control timeline, all three modes --------------


@pytest.mark.parametrize("mode", ["streaming", "fused", "resident"])
def test_rows_emitted_conserve_across_control_timeline(mode):
    """Per-plan emitted-row scopes sum EXACTLY to job-level emitted
    rows across admit/stack-join/disable/enable/retire — including the
    retired plan, whose scope persists. The two members share ONE
    output stream and one dynamic-group host, so this pins the
    per-slot decode attribution, not just per-stream bookkeeping."""
    job = _job_for(mode)
    per_plan = _per_plan_rows(job)
    total = _job_total(job)
    assert total > 0
    assert sum(per_plan.values()) == total, (per_plan, total)
    # both tenants' queries really contributed (qa retired mid-stream)
    assert per_plan.get("qa", 0) > 0
    assert per_plan.get("qb", 0) > 0
    # matches (pre-rate-limit) agree with rows here: no limiter thins
    scopes = job.telemetry.scope_map("plan")
    for pid, n in per_plan.items():
        assert scopes[pid].counter_value("matches") == n


@pytest.mark.parametrize("mode", ["fused", "resident"])
def test_per_plan_attribution_parity_with_streaming(mode):
    """The per-plan split itself (not only the sum) is identical in
    all three modes — the control-in-replay / fused-boundary row
    parity of PR 12, now holding per ATTRIBUTED plan."""
    assert _per_plan_rows(_job_for(mode)) == _per_plan_rows(
        _job_for("streaming")
    )


def test_tenant_rollup_merges_plan_scopes():
    job = _job_for("streaming")
    m = job.metrics()
    tenants = m["tenants"]
    assert tenants["acme"]["plans"] == ["qa"]
    assert tenants["bobcorp"]["plans"] == ["qb"]
    per_plan = _per_plan_rows(job)
    assert tenants["acme"]["rows_emitted"] == per_plan["qa"]
    assert tenants["bobcorp"]["rows_emitted"] == per_plan["qb"]
    # rollup conservation: tenant sums cover the whole job total
    assert (
        sum(t["rows_emitted"] for t in tenants.values())
        == _job_total(job)
    )
    # drain histograms merged bucket-exactly: counts add up
    assert tenants["acme"]["drain"]["count"] >= 1
    # plans carry their tenant in the live listing too
    assert m["plans"]["qb"]["tenant"] == "bobcorp"


def test_tenant_cache_and_stack_attribution():
    """A tenant's AOT-cache traffic and stack-joins land in ITS scope:
    acme's first admit is the compile (cache_miss), bobcorp's
    constants-only variant is a pure data update (stack_join, no cache
    traffic)."""
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[ctrl], plan_compiler=compiler,
    )
    plane = ControlPlane(job, ctrl)
    plane.admit(chain_cql(1, 2), plan_id="c1", tenant="acme")
    plane.admit(chain_cql(2, 3), plan_id="c2", tenant="bobcorp")
    for i in range(8):
        src.emit(Rec(i % 4, float(i), 1000 + i), 1000 + i)
    job.run_cycle()
    job.run_cycle()
    t = job.metrics()["tenants"]
    assert t["acme"]["cache_misses"] == 1
    assert t["acme"]["stack_joins"] == 0
    assert t["bobcorp"]["stack_joins"] == 1
    assert t["bobcorp"]["cache_misses"] == 0
    # the scoped counters also surface in the registry snapshot
    scopes = job.telemetry.snapshot()["scopes"]["tenant"]
    assert scopes["acme"]["counters"]["control.cache_miss"] == 1
    assert scopes["bobcorp"]["counters"]["control.stack_join"] == 1


def test_query_listing_one_poll_shows_fleet():
    job = _job_for("streaming")
    listing = {q["id"]: q for q in job.query_listing()}
    # qa was retired: only qb remains live, with tenant + fold info
    assert "qa" not in listing
    qb = listing["qb"]
    assert qb["tenant"] == "bobcorp"
    assert qb["enabled"] is True
    assert qb["folded"]["host"].startswith("@dyn:")
    assert isinstance(qb["folded"]["slot"], int)


# -- conservation UNDER SUBPLAN SHARING (PR 20 satellite) --------------------
#
# Two structurally-distinct tenants ride ONE shared @shr: prefix host
# across an admit / retire / re-admit timeline. The PR 14 gate must
# hold EXACTLY: the host is measured-only bookkeeping, every emitted
# row is attributed to a member tenant, in all three modes.

_SHR_A = "from S[price > 2.0][id == 1] select id, price insert into oa"
_SHR_B = ("from S[price > 2.0]#window.lengthBatch(2) "
          "select sum(price) as tot insert into ob")


def _share_timeline():
    def add(pid, cql, t, tenant):
        b = MetadataControlEvent.builder()
        b.add_execution_plan(cql, plan_id=pid)
        ev = b.build()
        ev.tenant = tenant
        return (t, ev)

    def drop(pid, t):
        b = MetadataControlEvent.builder()
        b.remove_execution_plan(pid)
        return (t, b.build())

    # sa+sb share a host; sa retires (host survives on sb), then a
    # re-admit sa2 rejoins the still-live host — the slot-reclaim path
    return [
        add("sa", _SHR_A, 0, "acme"),
        add("sb", _SHR_B, 100, "bobcorp"),
        drop("sa", 9_500),
        add("sa2", _SHR_A, 17_500, "acme"),
    ]


def _run_share_mode(mode):
    batches = [_mk_batches(8, s) for s in (1000, 9000, 17000, 25000)]
    job = Job(
        [], [BatchSource("S", SCHEMA, iter(batches))], batch_size=8,
        time_mode="event",
        control_sources=[ControlListSource(_share_timeline())],
        plan_compiler=compiler,
    )
    job.share_subplans = True
    if mode == "fused":
        job.fused_segment_len = 2
    if mode == "resident":
        from flink_siddhi_tpu.runtime.replay import ResidentReplay

        ResidentReplay(job).execute()
    else:
        job.run()
    return job


_SHARE_JOBS = {}


def _share_job_for(mode):
    if mode not in _SHARE_JOBS:
        _SHARE_JOBS[mode] = _run_share_mode(mode)
    return _SHARE_JOBS[mode]


def _per_plan_rows_shared(job):
    """Per-plan scopes excluding BOTH host kinds (@dyn: groups and
    @shr: prefix hosts) — only tenant-attributed scopes may count."""
    return {
        pid: reg.counter_value("rows_emitted")
        for pid, reg in job.telemetry.scope_map("plan").items()
        if not pid.startswith(("@dyn:", "@shr:"))
    }


@pytest.mark.parametrize("mode", ["streaming", "fused", "resident"])
def test_rows_conserve_under_subplan_sharing(mode):
    job = _share_job_for(mode)
    # the share really formed, and survived sa's retire on refcount
    assert job.control_status()["counters"]["subplan_share"] == 3
    per_plan = _per_plan_rows_shared(job)
    total = _job_total(job)
    assert total > 0
    assert sum(per_plan.values()) == total, (per_plan, total)
    # every phase of the timeline really contributed rows
    assert per_plan.get("sa", 0) > 0      # pre-retire
    assert per_plan.get("sb", 0) > 0      # rides the host throughout
    assert per_plan.get("sa2", 0) > 0     # post-readmit
    # and no @shr: scope leaked rows_emitted attribution
    assert all(
        reg.counter_value("rows_emitted") == 0
        for pid, reg in job.telemetry.scope_map("plan").items()
        if pid.startswith("@shr:")
    )


@pytest.mark.parametrize("mode", ["fused", "resident"])
def test_shared_attribution_parity_with_streaming(mode):
    assert _per_plan_rows_shared(
        _share_job_for(mode)
    ) == _per_plan_rows_shared(_share_job_for("streaming"))


def test_shared_tenant_rollup_conserves():
    """The tenant rollup covers the whole job total with the @shr host
    mapped onto its members (tenant 'shared' never owns rows)."""
    job = _share_job_for("streaming")
    tenants = job.metrics()["tenants"]
    assert (
        sum(t["rows_emitted"] for t in tenants.values())
        == _job_total(job)
    )
    assert sorted(tenants["acme"]["plans"]) == ["sa", "sa2"]
    assert tenants["bobcorp"]["plans"] == ["sb"]
    assert tenants.get("shared", {}).get("rows_emitted", 0) == 0


# -- the admitted-vs-measured footprint meter --------------------------------


def _meter_job(plan, admitted=None):
    job = Job([plan], [], batch_size=64)
    if admitted is not None:
        job.set_admitted_footprint(plan.plan_id, admitted)
    job.drain_outputs()  # the meter polls at drain boundaries
    return job


def test_footprint_measured_within_admitted_for_legit_zoo():
    """Every legit zoo plan's LIVE device bytes stay within the
    admission analyzer's worst-case prediction (the soundness
    direction ADM101 promises), and none trips the overrun counter."""
    from flink_siddhi_tpu.analysis.zoo import compile_zoo

    for name, plan in compile_zoo():
        report = analyze_plan(plan, deep=True)
        assert report.state_bytes is not None, name
        admitted = int(report.state_bytes + report.acc_bytes)
        job = _meter_job(plan, admitted)
        fp = job.footprint_status()[plan.plan_id]
        assert 0 < fp["measured_bytes"] <= admitted, (name, fp)
        assert fp["utilization"] <= 1.0 + 1e-9, (name, fp)
        assert (
            job.telemetry.counter_value("footprint.overruns") == 0
        ), name


def test_under_admitted_plan_trips_overrun_counter():
    plan = compiler(chain_cql(1, 2), "tiny")
    job = _meter_job(plan, admitted=1024)  # deliberately under-admitted
    fp = job.footprint_status()["tiny"]
    assert fp["utilization"] > 1.0
    assert job.telemetry.counter_value("footprint.overruns") >= 1
    sc = job.telemetry.scope_map("plan")["tiny"]
    assert sc.counter_value("footprint.overruns") >= 1


def test_footprint_meter_clean_under_transfer_guard(monkeypatch):
    """The meter reads leaf nbytes (aval metadata) only: polling it at
    drain boundaries inside the guarded hot loop must raise no
    transfer-guard violation and no overrun for a correctly-admitted
    plan."""
    from flink_siddhi_tpu.runtime import executor as _executor

    plan = compiler(chain_cql(1, 2), "guarded")
    report = analyze_plan(plan, deep=True)
    src = BatchSource(
        "S", SCHEMA,
        iter([_mk_batches(8, 1000), _mk_batches(8, 17000)]),
    )
    job = Job([plan], [src], batch_size=8, time_mode="event")
    job.set_admitted_footprint(
        "guarded", int(report.state_bytes + report.acc_bytes)
    )
    job.drain_interval_ms = 0.0  # meter polls on every cycle's drain
    monkeypatch.setattr(_executor, "HOTLOOP_TRANSFER_GUARD", True)
    job.run()
    fp = job.footprint_status()["guarded"]
    assert fp["measured_bytes"] > 0
    assert job.telemetry.counter_value("footprint.overruns") == 0
    assert len(job.results("out")) > 0  # the run really computed


# -- OpenMetrics exposition ---------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{[^{}]*\})?"
    r" (-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_VALID_TYPES = ("counter", "gauge", "summary", "histogram", "untyped")


def check_prometheus_text(text):
    """Standalone Prometheus text-format (0.0.4) checker — no client
    dependency. Every line must be blank, a comment, or a parsable
    ``name{labels} value`` sample; every sample's family must have
    exactly one TYPE declared before its samples; counter values
    non-negative; all values finite. Returns (n_samples, types)."""
    types = {}
    n_samples = 0
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            assert len(parts) == 4, f"line {ln}: malformed TYPE"
            name, mtype = parts[2], parts[3]
            assert mtype in _VALID_TYPES, f"line {ln}: {mtype!r}"
            assert name not in types, (
                f"line {ln}: duplicate TYPE for {name}"
            )
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"line {ln}: unparsable sample {line!r}"
        name, labels, value = m.group(1), m.group(2), m.group(3)
        family = name
        for suffix in ("_count", "_sum"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and base in types:
                family = base
        assert family in types, (
            f"line {ln}: sample {name} has no TYPE declaration"
        )
        v = float(value)
        assert math.isfinite(v), f"line {ln}: non-finite {value}"
        if types[family] == "counter":
            assert v >= 0, f"line {ln}: negative counter"
        if labels:
            body = labels[1:-1]
            pairs = _LABEL_RE.findall(body)
            rebuilt = ",".join(f'{k}="{v2}"' for k, v2 in pairs)
            assert rebuilt == body, (
                f"line {ln}: malformed labels {labels!r}"
            )
        n_samples += 1
    return n_samples, types


def test_openmetrics_renders_and_parses_with_scoped_labels():
    job = _job_for("streaming")
    text = job.openmetrics()
    n_samples, types = check_prometheus_text(text)
    assert n_samples > 20
    # scoped series carry plan AND tenant labels
    assert re.search(
        r'fst_rows_emitted_total\{plan="qa",tenant="acme"\} \d+', text
    ), text[:2000]
    assert re.search(
        r'fst_rows_emitted_total\{plan="qb",tenant="bobcorp"\} \d+',
        text,
    )
    # histogram summaries render in seconds with quantile labels
    assert 'quantile="0.99"' in text
    assert types.get("fst_drain_total_seconds") == "summary"
    # the pre-merged tenant rollup series are present
    assert 'fst_tenant_rows_emitted_total{tenant="acme"}' in text
    # scoped sample values agree with the scoped counters they render
    per_plan = _per_plan_rows(job)
    m = re.search(
        r'fst_rows_emitted_total\{plan="qb",tenant="bobcorp"\} (\d+)',
        text,
    )
    assert int(m.group(1)) == per_plan["qb"]


def test_prometheus_route_serves_text_format():
    job = _job_for("streaming")
    svc = QueryControlService(ControlQueueSource(), job=job).start()
    try:
        base = f"http://127.0.0.1:{svc.port}/api/v1"
        with urllib.request.urlopen(
            f"{base}/metrics/prometheus"
        ) as resp:
            assert resp.status == 200
            ctype = resp.headers.get("Content-Type", "")
            assert ctype.startswith("text/plain")
            text = resp.read().decode("utf-8")
        n_samples, _ = check_prometheus_text(text)
        assert n_samples > 0
        assert 'plan="qb"' in text and 'tenant="bobcorp"' in text
        # the richer per-query status rides the same service: live
        # scoped metrics + tenant in one GET
        with urllib.request.urlopen(f"{base}/queries/qb") as resp:
            q = json.loads(resp.read())
        assert q["tenant"] == "bobcorp"
        assert q["metrics"]["counters"]["rows_emitted"] > 0
        assert "host_footprint" in q["metrics"]
        # and the fleet listing is one poll
        with urllib.request.urlopen(f"{base}/queries") as resp:
            listing = json.loads(resp.read())["queries"]
        assert listing and all(
            {"id", "tenant", "enabled", "folded"} <= set(q2)
            for q2 in listing
        )
    finally:
        svc.stop()


def test_build_info_gauge_present_and_parses():
    """Satellite (ISSUE 15): the exposition carries the standard
    *_info gauge — package version, jax version, backend, bench schema
    version as labels, value 1 — and the whole document still parses
    under the standalone text-format checker."""
    import jax

    import flink_siddhi_tpu as pkg

    job = _job_for("streaming")
    text = job.openmetrics()
    n_samples, types = check_prometheus_text(text)
    assert n_samples > 0
    assert types.get("fst_build_info") == "gauge"
    m = re.search(r"^fst_build_info\{([^}]*)\} 1$", text, re.M)
    assert m, "fst_build_info sample missing"
    labels = dict(_LABEL_RE.findall(m.group(1)))
    assert labels["package_version"] == pkg.__version__
    assert labels["jax_version"] == jax.__version__
    assert labels["backend"] == "cpu"
    assert labels["bench_schema_version"] == str(
        pkg.BENCH_SCHEMA_VERSION
    )


def test_checker_rejects_malformed_text():
    """The checker itself must actually check (a checker that accepts
    anything proves nothing)."""
    with pytest.raises(AssertionError):
        check_prometheus_text("fst_x_total 1\n")  # sample w/o TYPE
    with pytest.raises(AssertionError):
        check_prometheus_text(
            "# TYPE fst_x_total counter\nfst_x_total oops\n"
        )
    with pytest.raises(AssertionError):
        check_prometheus_text(
            "# TYPE fst_x gauge\nfst_x{bad-label=\"v\"} 1\n"
        )
