"""Round-4: 'and'/'or' groups and absence inside SEQUENCES (strict
contiguity). Reference: siddhi-core sequence processing
(README.md:77-96); round-3 verdict item 10."""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
     ("timestamp", AttributeType.LONG)]
)


def run(cql, ids, batch=8):
    n = len(ids)
    prices = [float(i) for i in range(n)]
    ts = [1000 + i for i in range(n)]
    batches = [
        EventBatch(
            "S", SCHEMA,
            {
                "id": np.asarray(ids[s:s + batch], np.int32),
                "price": np.asarray(prices[s:s + batch], np.float64),
                "timestamp": np.asarray(ts[s:s + batch], np.int64),
            },
            np.asarray(ts[s:s + batch], np.int64),
        )
        for s in range(0, n, batch)
    ]
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job


def test_sequence_and_group_oracle():
    # s1 = A and s2 = B (any order, two consecutive events), then C
    cql = (
        "from every s1 = S[id == 1] and s2 = S[id == 2], s3 = S[id == 3] "
        "select s1.timestamp as t1, s2.timestamp as t2, "
        "s3.timestamp as t3 insert into m"
    )
    #      0  1  2  3  4  5  6  7  8  9 10 11
    ids = [1, 2, 3, 2, 1, 3, 1, 2, 9, 3, 1, 3]
    job = run(cql, ids)
    rows = job.results("m")
    # matches: (1@0, 2@1, 3@2) both orders ok: (2@3, 1@4, 3@5);
    # (1@6, 2@7) broken by 9@8 -> no match
    assert sorted(rows) == [
        (1000, 1001, 1002), (1004, 1003, 1005),
    ]


def test_sequence_or_group_oracle():
    cql = (
        "from every s1 = S[id == 1] or s2 = S[id == 2], s3 = S[id == 3] "
        "select s3.timestamp as t3 insert into m"
    )
    ids = [1, 3, 9, 2, 3, 1, 9, 3]
    job = run(cql, ids)
    # 1@0,3@1 match; 2@3,3@4 match; 1@5 broken by 9@6
    assert sorted(r[0] for r in job.results("m")) == [1001, 1004]


def test_sequence_absence_same_stream_oracle():
    # A, not B, C over one stream: the event right after A must be C
    # and must NOT match B's filter
    cql = (
        "from every s1 = S[id == 1], not S[price > 50.0], "
        "s3 = S[id == 3] "
        "select s1.timestamp as t1, s3.timestamp as t3 insert into m"
    )
    # prices are 0,1,2,... so price > 50 from index 51 on
    ids = [0] * 100
    for i, v in [(10, 1), (11, 3), (60, 1), (61, 3), (80, 1), (81, 9)]:
        ids[i] = v
    job = run(cql, ids)
    rows = job.results("m")
    # (1@10, 3@11): price@11 = 11 <= 50 -> match
    # (1@60, 3@61): price@61 = 61 > 50 -> guard kills it
    # (1@80, 9@81): contiguity broken
    assert rows == [(1010, 1011)]


def test_sequence_absence_different_stream_is_vacuous():
    # a different-stream 'not' between strict steps can never fire:
    # any T event in between would break the sequence by itself
    t_schema = StreamSchema(
        [("k", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    cql = (
        "from every s1 = S[id == 1], not T[k == 7], s3 = S[id == 3] "
        "select s1.timestamp as t1, s3.timestamp as t3 insert into m"
    )
    plan = compile_plan(cql, {"S": SCHEMA, "T": t_schema})
    n = 6
    ids = [1, 3, 1, 9, 1, 3]
    batches = [
        EventBatch(
            "S", SCHEMA,
            {
                "id": np.asarray(ids, np.int32),
                "price": np.zeros(n, np.float64),
                "timestamp": 1000 + np.arange(n, dtype=np.int64),
            },
            1000 + np.arange(n, dtype=np.int64),
        )
    ]
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    assert sorted(job.results("m")) == [(1000, 1001), (1004, 1005)]


def test_sequence_absence_terminal_rejected():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "from every s1 = S[id == 1], not S[id == 2] "
            "select s1.timestamp as t1 insert into m",
            {"S": SCHEMA},
        )


def test_sequence_unfiltered_same_stream_absence_rejected():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "from every s1 = S[id == 1], not S, s3 = S[id == 3] "
            "select s1.timestamp as t1 insert into m",
            {"S": SCHEMA},
        )


def test_sequence_chained_absences_guard_all():
    # review finding: 'A, not B1, not B2, C' must apply BOTH guards to
    # the next concrete element (folding one absent filter into another
    # absent element would negate it twice)
    cql = (
        "from every s1 = S[id == 1], not S[price > 50.0], "
        "not S[price < 10.0], s3 = S[id == 3] "
        "select s1.timestamp as t1, s3.timestamp as t3 insert into m"
    )
    # price = index; id pattern: 1 at i, 3 at i+1
    ids = [0] * 100
    for i, v in [(20, 1), (21, 3),   # price 21: 10<=21<=50 -> match
                 (60, 1), (61, 3),   # price 61 > 50 -> killed by guard 1
                 (5, 1), (6, 3)]:    # price 6 < 10 -> killed by guard 2
        ids[i] = v
    job = run(cql, ids)
    assert job.results("m") == [(1020, 1021)]
