"""Per-tenant SLO watchdog (telemetry/slo.py) + the serving
observability surface around it: violation/recovery state transitions
with exact journal parity, multi-window burn rates, the measurement
layer's missing-data honesty, the ``/api/v1/slo`` route and compact
``/health`` slo block, the flight recorder's ``?tenant=`` filter, the
prometheus exposition's consistency under mid-scrape churn, and the
carried-verdict preclear path on the control apply (the run loop skips
the redundant deep re-analysis the service gate already ran —
observable as ``control.preclear``).

``bench.py --serve`` drives all of this end to end off the REST plane;
these are the deterministic unit/route versions of the same contracts.
"""

import json
import re
import time
import urllib.request

import pytest

from flink_siddhi_tpu.analysis.admit import STRICT_BUDGETS
from flink_siddhi_tpu.app.service import (
    ControlQueueSource,
    QueryControlService,
)
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control import ControlPlane, MetadataControlEvent
from flink_siddhi_tpu.control.plane import AdmissionGate
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import CallbackSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType
from flink_siddhi_tpu.telemetry import FlightRecorder, MetricsRegistry
from flink_siddhi_tpu.telemetry.slo import SLOPolicy, SLOWatchdog

SCHEMA = StreamSchema(
    [
        ("id", AttributeType.INT),
        ("price", AttributeType.DOUBLE),
        ("timestamp", AttributeType.LONG),
    ]
)


def compiler(cql, pid):
    return compile_plan(cql, {"S": SCHEMA}, plan_id=pid)


def filter_cql(v, out="out"):
    return f"from S[id == {v}] select id, price insert into {out}"


def chain_cql(a, b):
    return (
        f"from every s1 = S[id == {a}] -> s2 = S[id == {b}] "
        "within 60 sec select s1.timestamp as t1, s2.timestamp as t2 "
        "insert into out"
    )


class Rec:
    def __init__(self, id, price, timestamp):
        self.id, self.price, self.timestamp = id, price, timestamp


def make_job(src, ctrl, **kw):
    return Job(
        [], [src], batch_size=64, time_mode="processing",
        control_sources=[ctrl], plan_compiler=compiler, **kw,
    )


# -- unit: watchdog against a stub job --------------------------------------


class _StubJob:
    """The exact surface SLOWatchdog._measure reads, no runtime."""

    def __init__(self):
        self.telemetry = MetricsRegistry()
        self.flightrec = FlightRecorder(registry=self.telemetry)
        self._plan_tenant = {}
        self._max_event_ts = None
        self._gate_wm = -(2 ** 62)
        self.late_dropped = 0
        self.shed_events = 0
        self.processed_events = 0

    def tenant_of(self, pid):
        return self._plan_tenant.get(pid, "default")


def _record_drain_ms(job, pid, ms, n=50):
    # LatencyHistogram's native unit is microseconds
    h = job.telemetry.scope("plan", pid).histogram("drain.total")
    for _ in range(n):
        h.record(int(ms * 1e3))


def test_violation_recovery_transitions_and_journal_parity():
    """Sustained breach -> one rate-collapsed journal entry whose full
    count matches the watchdog's tally; the transition back journals
    ONE discrete recovery; snapshot()['reconciled'] asserts the two
    accounts agree."""
    job = _StubJob()
    job._plan_tenant["q1"] = "t0"
    wd = SLOWatchdog(job, min_interval_s=0.0)
    wd.set_policy(SLOPolicy(tenant="t0", p99_ms=10.0, budget=0.5,
                            windows_s=(100.0,)))
    _record_drain_ms(job, "q1", ms=50.0)

    t_base = time.monotonic()
    for i in range(3):
        wd.evaluate(now=t_base + i)
    snap = wd.snapshot()
    t0 = snap["tenants"]["t0"]
    assert t0["compliant"] is False
    assert t0["breaches"] == ["p99_ms"]
    assert t0["measured"]["p99_ms"] > 10.0
    assert t0["violations"] == snap["violations_total"] == 3
    # the sustained breach occupies O(1) journal slots but counts in
    # full — and the watchdog's tally matches the journal replay
    evs = job.flightrec.events(kind="slo.violation")
    assert len(evs) == 1 and evs[0]["collapsed"] == 2
    assert snap["journal"]["violations"] == 3
    assert snap["reconciled"] is True
    assert snap["active_violations"] == 1
    assert snap["worst_burning_tenant"] == "t0"
    # violating 100% of evaluations against a 0.5 budget: burn rate 2
    assert t0["burn_rates"]["100s"] == pytest.approx(2.0)

    # raising the objective heals the tenant: one discrete recovery
    wd.set_policy(SLOPolicy(tenant="t0", p99_ms=10_000.0))
    wd.evaluate(now=t_base + 10.0)
    snap = wd.snapshot()
    assert snap["tenants"]["t0"]["compliant"] is True
    assert snap["recoveries_total"] == 1
    assert len(job.flightrec.events(kind="slo.recovered")) == 1
    assert snap["journal"]["recoveries"] == 1
    assert snap["reconciled"] is True
    assert snap["active_violations"] == 0


def test_missing_data_is_not_a_breach():
    """Objectives nothing has measured yet are OMITTED, not breached:
    no drain samples, a pre-first-event watermark, and a zero-served
    loss account all stay silent."""
    job = _StubJob()
    wd = SLOWatchdog(job, min_interval_s=0.0)
    wd.set_policy(SLOPolicy(
        tenant="t9", p99_ms=1.0, freshness_s=0.001, loss_ratio=1e-9,
    ))
    wd.evaluate(now=0.0)
    snap = wd.snapshot()
    t9 = snap["tenants"]["t9"]
    assert t9["compliant"] is True
    assert t9["measured"] == {}
    assert snap["violations_total"] == 0


def test_loss_and_freshness_objectives_measure_the_gate():
    job = _StubJob()
    job.late_dropped, job.shed_events = 5, 5
    job.processed_events = 990
    job._max_event_ts = 10_000
    job._gate_wm = 7_500
    wd = SLOWatchdog(job, min_interval_s=0.0)
    wd.set_policy(SLOPolicy(
        tenant="t0", loss_ratio=0.005, freshness_s=3.0,
    ))
    wd.evaluate(now=0.0)
    t0 = wd.snapshot()["tenants"]["t0"]
    # loss 10/1000 = 0.01 breaches the 0.005 budget; the 2.5 s
    # watermark lag stays inside the 3 s freshness objective
    assert t0["breaches"] == ["loss_ratio"]
    assert t0["measured"]["loss_ratio"] == pytest.approx(0.01)
    assert t0["measured"]["freshness_s"] == pytest.approx(2.5)


def test_burn_rates_are_per_window_fractions_over_budget():
    # 4 evaluations in the short window (2 violating), 8 in the long
    # (2 violating): short window burns 0.5/0.1 = 5x budget, long 2.5x
    history = [(float(t), t >= 6) for t in range(8)]
    rates = SLOWatchdog._burn_rates(
        history, windows_s=(3.0, 10.0), budget=0.1, now=7.0,
    )
    assert rates["3s"] == pytest.approx(5.0)
    assert rates["10s"] == pytest.approx(2.5)


def test_evaluate_rate_limit_and_policy_less_noop():
    job = _StubJob()
    wd = SLOWatchdog(job, min_interval_s=1.0)
    wd.evaluate(now=0.0)  # no policies: nothing counted
    assert wd.snapshot()["evaluations"] == 0
    wd.set_policy(SLOPolicy(tenant="t0", p99_ms=1.0))
    wd.evaluate(now=2.0)
    wd.evaluate(now=2.5)  # inside min_interval_s: dropped
    wd.evaluate(now=3.5)
    assert wd.snapshot()["evaluations"] == 2


# -- the REST surface: /api/v1/slo, /health, ?tenant= filter ----------------


def _get(base, path):
    with urllib.request.urlopen(f"{base}{path}") as resp:
        body = resp.read()
    try:
        return json.loads(body)
    except ValueError:
        return body.decode()


def test_slo_route_health_block_and_tenant_filter():
    """A live job with a breaching tenant: GET /api/v1/slo serves the
    reconciled snapshot, /health carries the compact alertable block,
    and GET /api/v1/flightrecorder?tenant= narrows the journal to one
    tenant's story."""
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    plane = ControlPlane(job, ctrl)
    plane.admit(filter_cql(1), plan_id="q1", tenant="t0")
    plane.admit(filter_cql(2), plan_id="q2", tenant="t1")
    job.slo.min_interval_s = 0.0
    job.slo.set_policy(SLOPolicy(tenant="t0", p99_ms=1e-4))  # breaches
    job.slo.set_policy(SLOPolicy(tenant="t1", p99_ms=1e9))  # never
    for cycle in range(3):
        for i in range(8):
            src.emit(Rec(1 + (i % 2), float(i), 1000 + i), 1000 + i)
        job.run_cycle()
    job.drain_outputs()
    # drain.total records at drain time: one more epoch boundary so
    # the watchdog evaluates against the recorded samples
    job.run_cycle()

    svc = QueryControlService(ctrl, job=job).start()
    try:
        base = f"http://127.0.0.1:{svc.port}/api/v1"
        slo = _get(base, "/slo")
        assert slo["policies"] == 2
        assert slo["reconciled"] is True
        assert slo["tenants"]["t0"]["compliant"] is False
        assert slo["tenants"]["t0"]["breaches"] == ["p99_ms"]
        assert slo["tenants"]["t1"]["compliant"] is True
        assert slo["violations_total"] == slo["journal"]["violations"]
        assert slo["worst_burning_tenant"] == "t0"
        # the violation entry is cross-linked into the journal
        seq = slo["tenants"]["t0"]["last_violation_seq"]
        assert isinstance(seq, int) and seq >= 1

        health = _get(base, "/health")
        blk = health["slo"]
        assert blk["policies"] == 2
        assert blk["active_violations"] == 1
        assert blk["worst_burning_tenant"] == "t0"
        assert blk["violations_total"] >= 1
        # compact means compact: no per-tenant detail rides /health
        assert "tenants" not in blk

        # ?tenant= narrows to one tenant's journal (admit + breaches);
        # entries without the label never match a set filter
        t0_evs = _get(base, "/flightrecorder?tenant=t0")["events"]
        assert t0_evs and all(e["tenant"] == "t0" for e in t0_evs)
        kinds = {e["kind"] for e in t0_evs}
        assert "control.admit" in kinds and "slo.violation" in kinds
        t1_evs = _get(base, "/flightrecorder?tenant=t1")["events"]
        assert all(e["tenant"] == "t1" for e in t1_evs)
        assert not any(e["kind"] == "slo.violation" for e in t1_evs)
        # composed with a kind filter
        both = _get(
            base, "/flightrecorder?tenant=t0&kind=slo",
        )["events"]
        assert both and all(
            e["kind"].startswith("slo") and e["tenant"] == "t0"
            for e in both
        )
    finally:
        svc.stop()


# -- prometheus exposition stays consistent mid-churn -----------------------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)'
)
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def _prom_parse(text):
    samples = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparsable exposition line: {line!r}"
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        samples.append((m.group("name"), labels, float(m.group("value"))))
    return samples


def test_prometheus_exposition_consistent_under_churn():
    """Scrapes interleaved with admit/disable/enable/retire mutations:
    every exposition parses, carries no duplicate (name, labelset)
    sample, keeps the job-wide processed counter monotone, and the
    tenant families follow the churn — the serving benchmark's scrape
    loop relies on exactly this."""
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    plane = ControlPlane(job, ctrl)
    plane.admit(filter_cql(1), plan_id="q1", tenant="t0")

    svc = QueryControlService(ctrl, job=job).start()
    try:
        base = f"http://127.0.0.1:{svc.port}/api/v1"

        def feed(n=8):
            for i in range(n):
                src.emit(
                    Rec(1 + (i % 3), float(i), 1000 + i), 1000 + i
                )
            job.run_cycle()
            job.drain_outputs()

        def scrape():
            samples = _prom_parse(_get(base, "/metrics/prometheus"))
            keys = [
                (n, tuple(sorted(l.items()))) for n, l, _ in samples
            ]
            assert len(keys) == len(set(keys)), (
                "duplicate sample in one exposition"
            )
            processed = [
                v for n, l, v in samples
                if n == "fst_processed_events_total"
                and "plan" not in l and "tenant" not in l
            ]
            assert len(processed) == 1
            tenants = {
                l["tenant"] for n, l, _ in samples if "tenant" in l
            }
            return processed[0], tenants

        feed()
        p0, tenants = scrape()
        assert "t0" in tenants

        # churn: admit a second tenant mid-stream, scrape between
        # every mutation
        plane.admit(filter_cql(2), plan_id="q2", tenant="t1")
        feed()
        p1, tenants = scrape()
        assert p1 >= p0 and {"t0", "t1"} <= tenants

        plane.set_enabled("q2", False)
        feed()
        p2, tenants = scrape()
        assert p2 >= p1 and "t1" in tenants  # history survives pause

        plane.set_enabled("q2", True)
        feed()
        plane.retire("q2")
        feed()
        p3, tenants = scrape()
        # a retired tenant's cumulative account must NOT vanish from
        # the exposition (counters are forever), and the job total
        # never moves backwards across any mutation
        assert p3 >= p2 and {"t0", "t1"} <= tenants
    finally:
        svc.stop()


# -- the carried-verdict preclear on the control apply ----------------------


def test_carried_verdict_preclears_deep_reanalysis():
    """An add whose event carries the service gate's PASSING verdict
    (with footprint bytes) skips the run-loop's deep eval_shape pass —
    counted as ``control.preclear`` and journaled — while a raw event
    with no carried verdict keeps the full defense-in-depth path. Both
    adds end up admitted with a footprint denominator."""
    src = CallbackSource("S", SCHEMA)
    ctrl = ControlQueueSource()
    job = make_job(src, ctrl)
    job.admission_budgets = STRICT_BUDGETS  # arms the deep tier
    gate = AdmissionGate(compiler, budgets=STRICT_BUDGETS)
    plane = ControlPlane(job, ctrl, gate=gate)

    plane.admit(chain_cql(1, 2), plan_id="q1", tenant="t0")
    job.run_cycle()
    assert job.telemetry.counter_value("control.preclear") == 1
    evs = job.flightrec.events(kind="control.preclear")
    assert len(evs) == 1 and evs[0]["plan"] == "q1"
    assert evs[0]["tenant"] == "t0"
    # the footprint meter's denominator comes from the carried bytes
    assert job._plan_admitted_bytes["q1"] > 0
    assert "q1" in job.plan_ids

    # a raw control event (no gate, no carried verdict) still runs
    # the deep tier: no preclear counted, fresh prediction stamped
    b = MetadataControlEvent.builder()
    b.add_execution_plan(chain_cql(2, 3), plan_id="q2")
    ctrl.push(b.build())
    job.run_cycle()
    assert job.telemetry.counter_value("control.preclear") == 1
    assert len(job.flightrec.events(kind="control.preclear")) == 1
    assert job._plan_admitted_bytes["q2"] > 0
    assert "q2" in job.plan_ids

    # a REJECTING carried verdict is never precleared past apply time:
    # the hostile add is refused at the gate already (ControlRejected
    # surfaces before any event is pushed), so push the event shape an
    # attacker would: verdict admitted=False carried on a raw event
    b = MetadataControlEvent.builder()
    b.add_execution_plan(
        chain_cql(3, 4).replace(" within 60 sec", ""),
        admission={"admitted": False,
                   "findings": [{"rule": "ADM110", "message": "x"}]},
        plan_id="q3",
    )
    ctrl.push(b.build())
    job.run_cycle()
    assert "q3" not in job.plan_ids
    assert job.control_rejections["q3"]["source"] == "carried-verdict"
    assert job.telemetry.counter_value("control.preclear") == 1
