"""SocketLineSource: TCP newline-delimited ingest (the deployable-story
analog of the reference's experimental Kafka pipeline,
CEPPipeline.scala:33-78, with no external broker)."""

import socket
import time

import numpy as np

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import SocketLineSource
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
     ("timestamp", AttributeType.LONG)]
)


def _send(port, payload: bytes):
    with socket.create_connection(("127.0.0.1", port)) as c:
        c.sendall(payload)


def test_socket_json_lines_end_to_end():
    src = SocketLineSource("S", SCHEMA, port=0, ts_field="timestamp")
    plan = compile_plan(
        "from S[id == 2] select id, price insert into o", {"S": SCHEMA}
    )
    job = Job([plan], [src], batch_size=64, time_mode="processing")
    lines = b"".join(
        b'{"id": %d, "price": %d.5, "timestamp": %d}\n'
        % (i % 3, i, 1000 + i)
        for i in range(30)
    )
    _send(src.port, lines)
    deadline = time.time() + 10
    while time.time() < deadline:
        job.run_cycle()
        if sum(job.emitted_counts.values()) or job.results("o"):
            if len(job.results("o")) == 10:
                break
        time.sleep(0.01)
    src.close()
    job.run()  # drains + finishes after close
    rows = job.results("o")
    assert [r[0] for r in rows] == [2] * 10
    assert rows[0][1] == 2.5


def test_socket_csv_partial_lines_and_close():
    src = SocketLineSource("S", SCHEMA, port=0, fmt="csv",
                           ts_field="timestamp")
    plan = compile_plan(
        "from S select id insert into o", {"S": SCHEMA}
    )
    job = Job([plan], [src], batch_size=64, time_mode="processing")
    # split one line across two sends; leave the final line UNTERMINATED
    # (the reader flushes it on disconnect)
    with socket.create_connection(("127.0.0.1", src.port)) as c:
        c.sendall(b"1,0.5,10")
        time.sleep(0.05)
        c.sendall(b"00\n2,1.5,1001\n3,2.5,1002")
    time.sleep(0.2)
    src.close()
    job.run()
    assert [r[0] for r in job.results("o")] == [1, 2, 3]
