"""scripts/run_static_analysis.py in the tier-1 lane (the analog of
test_bench_schema.py running check_bench_schema.py): the combined
lint + plancheck gate must exit 0 on the repo as committed. ``--fast``
skips only the deep inert-tape zoo executions (run in full by CI /
direct invocation; tests/test_plancheck.py keeps deep coverage on the
padded-stack shapes in tier-1)."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "run_static_analysis",
        os.path.join(REPO, "scripts", "run_static_analysis.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_static_analysis_gate_passes():
    assert _load().main(["--fast"]) == 0


def test_gate_fails_on_unsuppressed_finding(tmp_path, monkeypatch):
    """The gate actually gates: a planted finding flips fstlint's
    exit, and run_static_analysis propagates a lint failure to its
    own exit code (the tier-1 lane reads only the latter)."""
    mod = _load()
    bad = tmp_path / "planted.py"
    bad.write_text("def f(j):\n    return j.drain_interval_ms or 500\n")
    from flink_siddhi_tpu.analysis import fstlint

    assert fstlint.main([str(bad), "--no-baseline"]) == 1
    assert mod.main(["--skip-plancheck"]) == 0  # repo itself is clean
    # combined-runner propagation: a failing lint half must flip the
    # runner's exit even when plancheck is skipped
    monkeypatch.setattr(fstlint, "main", lambda argv: 1)
    assert mod.main(["--skip-plancheck"]) == 1
