"""Cross-tenant common-subplan sharing (analysis/share.py + the
executor's ``@shr:`` prefix hosts — docs/control_plane.md).

Contracts pinned here:

* **Split + key semantics** — ``split_shared_prefix`` lifts exactly the
  leading filter bracket (stream queries) / the conjuncts common to
  EVERY pattern element (pattern queries); the execution share key
  includes constants (sharing a running filter is only sound for
  semantically identical predicates) and is renderer-stable: rendering
  the prefix back to CQL and re-splitting reproduces the key.
* **Row exactness** — a fleet of structurally-distinct tenants riding
  one shared prefix produces byte-identical sorted rows versus the
  unshared run, in streaming, fused, and resident modes.
* **Refcounted retire** — members retire individually; the host
  outlives all but the last member, drops with it (``subplan_unshare``),
  and re-forms for a later re-admit through the AOT cache.
* **Checkpoint** — the share table rides the snapshot: a restored job
  re-forms hosts + loopback before replaying member suffixes, and the
  continued run is row-exact against a continuous oracle.
"""

import numpy as np
import pytest

from flink_siddhi_tpu.analysis.share import (
    MID_STREAM_PREFIX,
    SHARE_HOST_PREFIX,
    prefix_cql,
    render_expr,
    split_shared_prefix,
    suffix_cql,
)
from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.control import MetadataControlEvent
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.replay import ResidentReplay
from flink_siddhi_tpu.runtime.sources import (
    BatchSource,
    ControlListSource,
)
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema([
    ("id", AttributeType.INT),
    ("price", AttributeType.DOUBLE),
    ("timestamp", AttributeType.LONG),
])

# three STRUCTURALLY distinct tenants behind one exact leading-bracket
# predicate: plain filter chain, windowed aggregate, pattern whose
# every element carries the shared conjunct
T1 = "from S[price > 2.0][id == 1] select id, price insert into o1"
T2 = ("from S[price > 2.0]#window.lengthBatch(2) "
      "select sum(price) as tot insert into o2")
T3 = ("from every s1 = S[price > 2.0 and id == 1] -> "
      "s2 = S[price > 2.0 and id == 2] within 60 sec "
      "select s1.timestamp as t1, s2.timestamp as t2 insert into o3")


def compiler(cql, pid):
    return compile_plan(cql, {"S": SCHEMA}, plan_id=pid)


def _query_of(cql):
    return compiler(cql, "probe").source_ast.queries[0]


def _mk(n, start):
    ids = (np.arange(n) % 4).astype(np.int64)
    ts = (start + np.arange(n) * 1000).astype(np.int64)
    return EventBatch(
        "S", SCHEMA,
        {"id": ids, "price": np.arange(n, dtype=np.float64),
         "timestamp": ts},
        ts,
    )


def _add(pid, cql, t, tenant=None):
    b = MetadataControlEvent.builder()
    b.add_execution_plan(cql, plan_id=pid)
    ev = b.build()
    ev.tenant = tenant or pid
    return (t, ev)


def _drop(pid, t):
    b = MetadataControlEvent.builder()
    b.remove_execution_plan(pid)
    return (t, b.build())


def _job(batches, timeline, share=True, fused=False):
    job = Job(
        [], [BatchSource("S", SCHEMA, iter(batches))], batch_size=8,
        time_mode="event",
        control_sources=[ControlListSource(timeline)],
        plan_compiler=compiler,
    )
    job.share_subplans = share
    if fused:
        job.fused_segment_len = 2
    return job


def _rows(job):
    return {
        sid: sorted(rows) for sid, rows in job.collected.items() if rows
    }


# -- split + key semantics ----------------------------------------------------


def test_split_lifts_leading_bracket_and_pattern_common_conjuncts():
    s1 = split_shared_prefix(_query_of(T1))
    s2 = split_shared_prefix(_query_of(T2))
    s3 = split_shared_prefix(_query_of(T3))
    assert s1 and s2 and s3
    # all three land on the SAME running prefix: S[price > 2.0]
    assert s1.key() == s2.key() == s3.key()
    assert render_expr(s1.predicate) == render_expr(s3.predicate)


def test_share_key_includes_constants():
    """Unlike the AOT shape key, the EXECUTION key must split on
    constants: S[price > 2.0] and S[price > 9.0] select different rows
    and can never ride one running host."""
    a = split_shared_prefix(_query_of(T1))
    b = split_shared_prefix(_query_of(
        "from S[price > 9.0][id == 1] select id, price insert into o1"
    ))
    assert a.key() != b.key()


def test_share_key_is_renderer_stable():
    """Render the prefix host back to CQL, re-split what a tenant of
    the rendered mid would look like — the key must reproduce (the
    property checkpoint replay of the share table depends on)."""
    sp = split_shared_prefix(_query_of(T1))
    cql = prefix_cql(sp, MID_STREAM_PREFIX + "x")
    host_q = compile_plan(
        cql, {"S": SCHEMA}, plan_id="h"
    ).source_ast.queries[0]
    assert render_expr(host_q.input.filters[0]) == render_expr(
        sp.predicate
    )


def test_split_refusals():
    # no filters: nothing to lift
    assert split_shared_prefix(_query_of(
        "from S select id, price insert into o1"
    )) is None
    # a query already reading a mid stream must never split again
    # (recursion guard); mid streams only exist inside a sharing job,
    # so probe via the suffix the splitter itself emits
    sp = split_shared_prefix(_query_of(T1))
    mid = MID_STREAM_PREFIX + "x"
    s_cql = suffix_cql(_query_of(T1), sp, mid, SCHEMA)
    plan = compile_plan(s_cql, {"S": SCHEMA}, plan_id="sfx")
    assert split_shared_prefix(plan.source_ast.queries[0]) is None
    # pattern with NO conjunct common to every element
    assert split_shared_prefix(_query_of(
        "from every s1 = S[id == 1] -> s2 = S[price > 2.0] "
        "within 60 sec select s1.timestamp as t1 insert into o3"
    )) is None
    # single-bracket filter + plain projection: the residue would keep
    # no structure, so a split buys nothing and costs a loopback hop —
    # refuse (matters for serving fleets full of [id == a] tenants)
    assert split_shared_prefix(_query_of(
        "from S[price > 2.0] select id, price insert into o1"
    )) is None
    # ...but the same bracket is still shareable when the residue keeps
    # a window or a stateful selector
    assert split_shared_prefix(_query_of(
        "from S[price > 2.0] select sum(price) as tot insert into o1"
    )) is not None


# -- row exactness: shared vs unshared, all three modes ----------------------


def _fleet_timeline():
    return [
        _add("t1", T1, 0, "ten0"),
        _add("t2", T2, 100, "ten1"),
        _add("t3", T3, 200, "ten2"),
    ]


@pytest.fixture(scope="module")
def unshared_oracle():
    job = _job(
        [_mk(8, s) for s in (1000, 9000, 17000, 25000)],
        _fleet_timeline(), share=False,
    )
    job.run()
    return _rows(job)


@pytest.mark.parametrize("mode", ["streaming", "fused", "resident"])
def test_shared_fleet_row_exact_vs_unshared(mode, unshared_oracle):
    job = _job(
        [_mk(8, s) for s in (1000, 9000, 17000, 25000)],
        _fleet_timeline(), share=True, fused=(mode == "fused"),
    )
    if mode == "resident":
        ResidentReplay(job).execute()
    else:
        job.run()
    st = job.control_status()["shared"]
    assert len(st) == 1
    entry = list(st.values())[0]
    assert sorted(entry["members"]) == ["t1", "t2", "t3"]
    assert entry["host"].startswith(SHARE_HOST_PREFIX)
    assert _rows(job) == unshared_oracle
    # the host is bookkeeping, not a tenant: hidden from plan listings
    assert not any(
        p.startswith(SHARE_HOST_PREFIX) for p in job.plan_ids
    )


# -- refcounted retire / re-admit --------------------------------------------


def test_retire_refcounts_host_and_readmit_reforms_it():
    tl = [
        _add("t1", T1, 0),
        _add("t2", T2, 100),
        _drop("t1", 9_500),     # host survives on t2
        _drop("t2", 17_500),    # last member: host drops
        _add("t1b", T1, 25_500),  # host re-forms via the AOT cache
    ]
    job = _job([_mk(8, s) for s in (1000, 9000, 17000, 25000)], tl)
    job.run()
    cs = job.control_status()
    assert cs["counters"].get("subplan_share") == 3
    assert cs["counters"].get("subplan_unshare") == 1
    assert len(cs["shared"]) == 1
    assert list(cs["shared"].values())[0]["members"] == ["t1b"]
    # t1b really serves rows after the re-form
    assert job.collected.get("o1")
    # share traffic is tenant-attributed (PR 14 scoping)
    scopes = job.telemetry.snapshot()["scopes"]["tenant"]
    assert scopes["t1"]["counters"]["control.subplan_share"] == 1


# -- checkpoint: the share table rides the snapshot --------------------------


def test_checkpoint_restores_share_table_row_exact():
    b_all = [_mk(8, s) for s in (1000, 9000, 17000, 25000)]
    tl = [_add("t1", T1, 0), _add("t2", T2, 100)]
    j1 = _job(b_all[:2], tl)
    j1.run()
    snap = j1.snapshot()
    assert snap["shared"], "snapshot missing the shared block"
    j2 = _job(b_all[2:], [])
    j2.restore(snap)
    assert j2.control_status()["shared"], "share table not restored"
    j2.run()
    oracle = _job(b_all, tl)
    oracle.run()
    merged = {}
    for j in (j1, j2):
        for sid, rows in j.collected.items():
            merged.setdefault(sid, []).extend(rows)
    assert {s: sorted(r) for s, r in merged.items() if r} == _rows(
        oracle
    )
    # the listing shows each member's host + key after restore
    listing = {q["id"]: q for q in j2.query_listing()}
    for pid in ("t1", "t2"):
        assert listing[pid]["shared"]["host"].startswith(
            SHARE_HOST_PREFIX
        )
