"""Event tables end-to-end: define table, insert, stream-table join,
update/delete with on-conditions (siddhi-core event-table surface,
SURVEY.md §2.10)."""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP


@dataclasses.dataclass
class Event:
    id: int
    kind: int
    price: float
    timestamp: int


FIELDS = ["id", "kind", "price", "timestamp"]


def run(events, cql, out="out", batch_size=4096):
    env = CEPEnvironment(batch_size=batch_size)
    return (
        SiddhiCEP.define("S", events, FIELDS, env=env)
        .cql(cql)
        .returns(out)
    )


def test_insert_then_join():
    # kind==0 events populate the table; kind==1 events look up by id
    events = [
        Event(1, 0, 10.0, 1000),
        Event(2, 0, 20.0, 2000),
        Event(1, 1, 0.0, 3000),
        Event(2, 1, 0.0, 4000),
        Event(3, 1, 0.0, 5000),  # no table row -> no output
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select S.id, T.tprice insert into out",
    )
    assert sorted(out) == [(1, 10.0), (2, 20.0)]


def test_join_sees_same_batch_inserts():
    # batch-granular sequencing: inserts from query 1 are visible to the
    # join in the same micro-batch
    events = [Event(5, 0, 55.0, 1000), Event(5, 1, 0.0, 2000)]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select T.tprice insert into out",
    )
    assert out == [(55.0,)]


def test_update_on_condition():
    events = [
        Event(1, 0, 10.0, 1000),  # insert id=1 price=10
        Event(1, 2, 99.0, 2000),  # update id=1 -> price=99
        Event(1, 1, 0.0, 3000),  # lookup
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 2] select id as tid, price as tprice "
        "update T on T.tid == tid;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select T.tprice insert into out",
        batch_size=1,
    )
    assert out == [(99.0,)]


def test_delete_on_condition():
    events = [
        Event(1, 0, 10.0, 1000),
        Event(2, 0, 20.0, 2000),
        Event(1, 3, 0.0, 3000),  # delete id=1
        Event(1, 1, 0.0, 4000),  # lookup id=1 -> gone
        Event(2, 1, 0.0, 5000),  # lookup id=2 -> present
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 3] select id as tid delete T on T.tid == tid;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select S.id, T.tprice insert into out",
        batch_size=1,
    )
    assert out == [(2, 20.0)]


def test_left_outer_table_join():
    events = [
        Event(1, 0, 10.0, 1000),
        Event(1, 1, 0.0, 2000),
        Event(9, 1, 0.0, 3000),  # no row -> zero-filled table side
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 1] left outer join T on S.id == T.tid "
        "select S.id, T.tprice insert into out",
    )
    assert sorted(out) == [(1, 10.0), (9, 0.0)]


def test_select_from_table_rejected():
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    with pytest.raises(SiddhiQLError):
        run(
            [Event(1, 0, 1.0, 1000)],
            "define table T (tid int);"
            "from T select tid insert into out",
        )


def test_table_table_join_permanently_rejected_with_citation():
    """The ROADMAP carried item is CLOSED as a permanent rejection
    (docs/static_analysis.md "Decided non-features"): a join needs a
    stream side to trigger on, and siddhi-core itself rejects
    static-static joins. Pin the citation so the rejection stays loud
    and sourced — siddhi-core 4.2.40 JoinInputStreamParser by
    class+method."""
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    with pytest.raises(SiddhiQLError) as ei:
        run(
            [Event(1, 0, 1.0, 1000)],
            "define table T (tid int); define table U (uid int);"
            "from T join U on T.tid == U.uid "
            "select T.tid insert into out",
        )
    msg = str(ei.value)
    assert "table-table joins are not supported" in msg
    assert "siddhi-core 4.2.40" in msg
    assert "JoinInputStreamParser.parseInputStream" in msg


def test_table_preserving_outer_join_permanently_rejected():
    """Same decision for the outer-join twin: a table has no arrival
    events to emit unmatched rows on, and only STREAM/WINDOW sides can
    trigger in siddhi-core (JoinInputStreamParser
    .populateJoinProcessors)."""
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    with pytest.raises(SiddhiQLError) as ei:
        run(
            [Event(1, 0, 1.0, 1000)],
            "define table T (tid int, tprice double);"
            "from S[kind == 1] right outer join T on S.id == T.tid "
            "select S.id insert into out",
        )
    msg = str(ei.value)
    assert "outer join preserving the table side is not supported" in msg
    assert "siddhi-core 4.2.40" in msg
    assert "JoinInputStreamParser" in msg


def test_aggregated_table_insert_and_windowed_insert():
    """VERDICT #10: windows/aggregations in table writes."""
    import numpy as np
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    S = StreamSchema(
        [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    Q = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    cql = """
define table Totals (id int, total double);
from S select id, sum(price) as total group by id insert into Totals;
from Q join Totals on Q.id == Totals.id
  select Q.id as qid, Totals.total as total insert into o;
"""
    plan = compile_plan(cql, {"S": S, "Q": Q})
    ids = np.array([1, 2, 1, 2, 1], np.int32)
    pr = np.array([1.0, 10.0, 2.0, 20.0, 3.0])
    ts = np.array([1000, 1001, 1002, 1003, 1004], np.int64)
    qts = np.array([2000, 2001], np.int64)
    job = Job(
        [plan],
        [
            BatchSource("S", S, iter([EventBatch(
                "S", S, {"id": ids, "price": pr, "timestamp": ts}, ts
            )])),
            BatchSource("Q", Q, iter([EventBatch(
                "Q", Q,
                {"id": np.array([1, 2], np.int32), "timestamp": qts},
                qts,
            )])),
        ],
        batch_size=16, time_mode="processing",
    )
    job.run()
    rows = job.results("o")
    # each S arrival appended its running per-id total; the max per id
    # is the final cumulative sum
    by_id = {}
    for qid, total in rows:
        by_id.setdefault(qid, []).append(total)
    assert max(by_id[1]) == 6.0
    assert max(by_id[2]) == 30.0


def test_length_batch_window_table_insert():
    import numpy as np
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    S = StreamSchema(
        [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    Q = StreamSchema(
        [("id", AttributeType.INT), ("timestamp", AttributeType.LONG)]
    )
    cql = """
define table Sums (total double);
from S#window.lengthBatch(3) select sum(price) as total insert into Sums;
from Q join Sums select Q.id as qid, Sums.total as total insert into o;
"""
    plan = compile_plan(cql, {"S": S, "Q": Q})
    pr = np.array([1.0, 2.0, 3.0, 10.0, 20.0, 30.0])
    ts = (1000 + np.arange(6)).astype(np.int64)
    qts = np.array([5000], np.int64)
    job = Job(
        [plan],
        [
            BatchSource("S", S, iter([EventBatch(
                "S", S,
                {"id": np.zeros(6, np.int32), "price": pr,
                 "timestamp": ts},
                ts,
            )])),
            BatchSource("Q", Q, iter([EventBatch(
                "Q", Q,
                {"id": np.array([9], np.int32), "timestamp": qts},
                qts,
            )])),
        ],
        batch_size=16, time_mode="processing",
    )
    job.run()
    totals = sorted(t for _, t in job.results("o"))
    # two tumbled windows of 3: 6.0 and 60.0
    assert totals == [6.0, 60.0]


def test_windowed_update_via_rewrite():
    """Round-4: windowed/aggregated UPDATE (siddhi-core evaluates the
    window chain before the table mutation) — previously a loud
    carve-out. Asserts on the table state directly."""
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.compiler.table import table_key
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    cql = """
define table T (k int, total double);
from S[timestamp < 1002] select k, 0.0 as total insert into T;
from S#window.lengthBatch(4) select k, sum(v) as total group by k
  update T on T.k == k
"""
    # events 0,1 seed one T row per key; the lengthBatch(4) windows then
    # write per-key sums into them
    ks = np.asarray([0, 1, 0, 1, 0, 1, 0, 1], np.int32)
    vs = np.asarray([1.0, 10.0, 2.0, 20.0, 4.0, 40.0, 8.0, 80.0])
    ts = 1000 + np.arange(8, dtype=np.int64)
    batches = [EventBatch("S", schema,
                          {"k": ks, "v": vs, "timestamp": ts}, ts)]
    plan = compile_plan(cql, {"S": schema})
    job = Job([plan], [BatchSource("S", schema, iter(batches))],
              batch_size=8, time_mode="processing")
    job.run()
    rt = next(iter(job._plans.values()))
    tstate = rt.states["@tables"]["T"]
    valid = np.asarray(tstate["valid"])
    tk = np.asarray(tstate[table_key("T", "k")])[valid]
    tot = np.asarray(tstate[table_key("T", "total")])[valid]
    got = dict(zip(tk.tolist(), tot.tolist()))
    # second window flush (events 4..7): key 0 -> 4+8, key 1 -> 40+80
    assert got[0] == pytest.approx(12.0)
    assert got[1] == pytest.approx(120.0)


def test_windowed_delete_via_rewrite():
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.compiler.table import table_key
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    # delete keys whose lengthBatch(4) window count exceeds 2
    cql = """
define table T (k int);
from S[timestamp < 1002] select k insert into T;
from S#window.lengthBatch(4) select k, count() as c group by k
  having c > 2 delete T on T.k == k
"""
    ks = np.asarray([0, 1, 0, 0, 1, 0, 0, 0], np.int32)
    vs = np.ones(8)
    ts = 1000 + np.arange(8, dtype=np.int64)
    batches = [EventBatch("S", schema,
                          {"k": ks, "v": vs, "timestamp": ts}, ts)]
    plan = compile_plan(cql, {"S": schema})
    job = Job([plan], [BatchSource("S", schema, iter(batches))],
              batch_size=8, time_mode="processing")
    job.run()
    rt = next(iter(job._plans.values()))
    tstate = rt.states["@tables"]["T"]
    valid = np.asarray(tstate["valid"])
    tk = np.asarray(tstate[table_key("T", "k")])[valid].tolist()
    # key 0 hit count 3 in window 1 (events 0,2,3) -> deleted;
    # key 1 (count 1 and 1) survives
    assert tk == [1]
