"""Event tables end-to-end: define table, insert, stream-table join,
update/delete with on-conditions (siddhi-core event-table surface,
SURVEY.md §2.10)."""

import dataclasses

import pytest

from flink_siddhi_tpu import CEPEnvironment, SiddhiCEP


@dataclasses.dataclass
class Event:
    id: int
    kind: int
    price: float
    timestamp: int


FIELDS = ["id", "kind", "price", "timestamp"]


def run(events, cql, out="out", batch_size=4096):
    env = CEPEnvironment(batch_size=batch_size)
    return (
        SiddhiCEP.define("S", events, FIELDS, env=env)
        .cql(cql)
        .returns(out)
    )


def test_insert_then_join():
    # kind==0 events populate the table; kind==1 events look up by id
    events = [
        Event(1, 0, 10.0, 1000),
        Event(2, 0, 20.0, 2000),
        Event(1, 1, 0.0, 3000),
        Event(2, 1, 0.0, 4000),
        Event(3, 1, 0.0, 5000),  # no table row -> no output
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select S.id, T.tprice insert into out",
    )
    assert sorted(out) == [(1, 10.0), (2, 20.0)]


def test_join_sees_same_batch_inserts():
    # batch-granular sequencing: inserts from query 1 are visible to the
    # join in the same micro-batch
    events = [Event(5, 0, 55.0, 1000), Event(5, 1, 0.0, 2000)]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select T.tprice insert into out",
    )
    assert out == [(55.0,)]


def test_update_on_condition():
    events = [
        Event(1, 0, 10.0, 1000),  # insert id=1 price=10
        Event(1, 2, 99.0, 2000),  # update id=1 -> price=99
        Event(1, 1, 0.0, 3000),  # lookup
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 2] select id as tid, price as tprice "
        "update T on T.tid == tid;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select T.tprice insert into out",
        batch_size=1,
    )
    assert out == [(99.0,)]


def test_delete_on_condition():
    events = [
        Event(1, 0, 10.0, 1000),
        Event(2, 0, 20.0, 2000),
        Event(1, 3, 0.0, 3000),  # delete id=1
        Event(1, 1, 0.0, 4000),  # lookup id=1 -> gone
        Event(2, 1, 0.0, 5000),  # lookup id=2 -> present
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 3] select id as tid delete T on T.tid == tid;"
        "from S[kind == 1] join T on S.id == T.tid "
        "select S.id, T.tprice insert into out",
        batch_size=1,
    )
    assert out == [(2, 20.0)]


def test_left_outer_table_join():
    events = [
        Event(1, 0, 10.0, 1000),
        Event(1, 1, 0.0, 2000),
        Event(9, 1, 0.0, 3000),  # no row -> zero-filled table side
    ]
    out = run(
        events,
        "define table T (tid int, tprice double);"
        "from S[kind == 0] select id as tid, price as tprice insert into T;"
        "from S[kind == 1] left outer join T on S.id == T.tid "
        "select S.id, T.tprice insert into out",
    )
    assert sorted(out) == [(1, 10.0), (9, 0.0)]


def test_select_from_table_rejected():
    from flink_siddhi_tpu.query.lexer import SiddhiQLError

    with pytest.raises(SiddhiQLError):
        run(
            [Event(1, 0, 1.0, 1000)],
            "define table T (tid int);"
            "from T select tid insert into out",
        )
