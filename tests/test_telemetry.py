"""Telemetry subsystem: histogram correctness, merge algebra,
concurrent snapshot safety, and the end-to-end >= 95% wall-clock
attribution contract the bench's stage_breakdown stands on."""

import json
import threading
import time

import numpy as np
import pytest

from flink_siddhi_tpu.telemetry import (
    LatencyHistogram,
    MetricsRegistry,
    StageTimes,
    TOP_LEVEL_STAGES,
    TraceSampler,
)


# -- histogram percentile correctness ------------------------------------


@pytest.mark.parametrize("dist", ["lognormal", "uniform", "bimodal"])
def test_percentiles_match_numpy(dist):
    rng = np.random.default_rng(7)
    if dist == "lognormal":
        v = rng.lognormal(8, 2, 50_000)
    elif dist == "uniform":
        v = rng.uniform(10, 1_000_000, 50_000)
    else:
        # unbalanced modes so no tested quantile sits in the empty gap
        # between them (there nearest-rank and linear interpolation
        # legitimately disagree by more than any bucket bound)
        v = np.concatenate(
            [rng.normal(500, 40, 20_000), rng.normal(80_000, 9_000, 30_000)]
        )
    v = np.maximum(v, 0).astype(np.int64)
    h = LatencyHistogram()
    h.record_many(v)
    for q in (50, 90, 99, 99.9):
        got = h.percentile(q)
        want = float(np.percentile(v, q))
        # bucket half-width is < 0.8% relative; allow 2% + 2 units for
        # the nearest-rank vs linear-interpolation definition gap
        assert got == pytest.approx(want, rel=0.02, abs=2.0), (
            dist, q, got, want,
        )


def test_linear_region_is_exact():
    # values below 2**sub_bucket_bits land in unit-width buckets
    v = np.array([0, 1, 2, 3, 5, 8, 13, 21, 34, 55, 89, 127])
    h = LatencyHistogram()
    h.record_many(v)
    assert h.percentile(0) == 0
    assert h.percentile(100) == 127
    assert h.percentile(50) in (8.0, 13.0)  # nearest-rank median


def test_extremes_clamped_to_observed_range():
    h = LatencyHistogram()
    h.record(1_000_003)
    # mid-bucket representative must not exceed the recorded max
    assert h.percentile(99.9) == 1_000_003
    assert h.percentile(1) == 1_000_003


# -- merge algebra -------------------------------------------------------


def test_merge_associative_and_equals_whole():
    rng = np.random.default_rng(3)
    parts = [
        np.maximum(rng.lognormal(7, 2, 10_000), 0).astype(np.int64)
        for _ in range(3)
    ]

    def hist_of(*arrays):
        h = LatencyHistogram()
        for a in arrays:
            h.record_many(a)
        return h

    a, b, c = (hist_of(p) for p in parts)
    left = hist_of(parts[0]).merge(hist_of(parts[1])).merge(c)
    right = hist_of(parts[0]).merge(
        hist_of(parts[1]).merge(hist_of(parts[2]))
    )
    whole = hist_of(*parts)
    for other in (left, right):
        assert np.array_equal(other.counts, whole.counts)
        assert other.count == whole.count
        assert other.snapshot() == whole.snapshot()
    # originals unchanged by being merge sources
    assert a.count == 10_000 and c.count == 10_000


def test_merge_rejects_geometry_mismatch():
    h1 = LatencyHistogram(sub_bucket_bits=7)
    h2 = LatencyHistogram(sub_bucket_bits=5)
    with pytest.raises(ValueError, match="geometry"):
        h1.merge(h2)


# -- concurrency ---------------------------------------------------------


def test_concurrent_record_and_snapshot():
    """Metrics readers snapshot while writers record: no exception, no
    lost updates, every observed snapshot internally consistent."""
    reg = MetricsRegistry()
    n_threads, per_thread = 4, 5_000
    stop = threading.Event()
    errors = []

    def writer(seed):
        rng = np.random.default_rng(seed)
        vals = np.maximum(rng.lognormal(6, 1, per_thread), 0)
        for v in vals.astype(np.int64):
            reg.histogram("lat").record(int(v))
            reg.inc("events")

    def reader():
        while not stop.is_set():
            try:
                snap = reg.snapshot()
                json.dumps(snap)  # must always be JSON-safe
                h = snap["histograms"].get("lat")
                if h and h["count"]:
                    assert h["p50_ms"] <= h["p99_ms"] <= h["max_ms"]
            except Exception as e:  # surfaced after join
                errors.append(e)
                return

    threads = [
        threading.Thread(target=writer, args=(s,))
        for s in range(n_threads)
    ]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert not errors
    assert reg.histogram("lat").count == n_threads * per_thread
    assert reg.counter("events").value == n_threads * per_thread


# -- spans ---------------------------------------------------------------


def test_nested_spans_do_not_double_count():
    st = StageTimes()
    with st.span("outer"):
        time.sleep(0.01)
        with st.span("inner"):
            time.sleep(0.01)
    snap = st.snapshot()
    assert "outer" in snap and "nested.inner" in snap
    assert "inner" not in snap  # only the nested.* name accrues
    assert snap["outer"]["seconds"] >= snap["nested.inner"]["seconds"]


def test_disabled_registry_is_inert():
    reg = MetricsRegistry(enabled=False)
    with reg.span("x"):
        pass
    reg.record_seconds("h", 0.5)
    reg.inc("c")
    snap = reg.snapshot()
    assert snap["stages"] == {}
    assert snap["histograms"].get("h", {}).get("count", 0) == 0
    assert snap["counters"].get("c", 0) == 0


def test_stage_ring_is_bounded():
    st = StageTimes(ring_capacity=8)
    for i in range(100):
        st.add("s", 0.001)
    assert len(st.recent(1000)) == 8


# -- per-event trace sampling (telemetry/tracing.py) ----------------------


def _synthetic_trace_run(sampler_list, chunks=40, per=512):
    """Drive samplers through an identical stamped/completed event
    stream whose latency profile varies by chunk (later-stamped chunks
    complete sooner), producing a non-degenerate distribution every
    sampler observes identically."""
    all_rows = []
    for c in range(chunks):
        ts = np.arange(c * per, (c + 1) * per, dtype=np.int64)
        for tr in sampler_list:
            tr.stamp_ingest(ts)
        all_rows.extend((int(t), ()) for t in ts)
        time.sleep(0.002 + 0.002 * (c % 4))
    for tr in sampler_list:
        tr.complete_rows(0, all_rows)


def test_sampled_trace_converges_to_full_histogram():
    """A 1-in-16 deterministic sample's e2e percentiles approximate the
    sample-everything histogram: the sampling rule (ts % N == 0) is
    unbiased w.r.t. the latency profile."""
    full = TraceSampler(MetricsRegistry(), sample_every=1)
    samp = TraceSampler(MetricsRegistry(), sample_every=16)
    # sampled completes FIRST: the full sampler's completion sweep
    # (20k dict pops) takes tens of ms, which would otherwise shift
    # every sampled latency by that much and fake a divergence
    _synthetic_trace_run([samp, full])
    h_full = full.registry.histogram("trace.e2e")
    h_samp = samp.registry.histogram("trace.e2e")
    assert h_full.count == 40 * 512
    assert h_samp.count == 40 * 512 // 16
    for q in (50, 90, 99):
        a, b = h_full.percentile_ms(q), h_samp.percentile_ms(q)
        # chunk-quantized latencies: agree within ~2 chunk steps
        # + 25% relative
        assert b == pytest.approx(a, rel=0.25, abs=12.0), (q, a, b)


def test_trace_completion_first_wins_and_marks_legs():
    reg = MetricsRegistry()
    tr = TraceSampler(reg, sample_every=4)
    ts = np.arange(0, 64, dtype=np.int64)
    tr.stamp_ingest(ts)
    assert tr.sampled == 16
    tr.mark(ts, "dispatch")
    assert reg.histogram("trace.ingest_to_dispatch").count == 16
    rows = [(int(t), ()) for t in ts]
    tr.complete_rows(0, rows)
    assert tr.completed == 16
    # duplicate emission (same timestamps): stamps already popped
    tr.complete_rows(0, rows)
    assert tr.completed == 16
    assert reg.histogram("trace.e2e").count == 16
    snap = tr.snapshot()
    assert snap["pending"] == 0
    assert len(snap["recent"]) == 16
    json.dumps(snap)


def test_trace_pending_is_bounded():
    tr = TraceSampler(MetricsRegistry(), sample_every=1, max_pending=64)
    tr.stamp_ingest(np.arange(0, 1000, dtype=np.int64))
    assert tr.snapshot()["pending"] <= 64
    assert tr.evicted >= 1000 - 64
    # evicted stamps cannot complete (no stale latencies recorded)
    tr.complete_rows(0, [(5, ())])
    assert tr.completed == 0


def test_trace_shard_histograms_merge_into_snapshot():
    """The sharded drain completes traces into PER-SHARD histograms;
    snapshot(extra_hists=...) folds them via LatencyHistogram.merge —
    counts must equal the sum and the base registry stays untouched."""
    reg = MetricsRegistry()
    tr = TraceSampler(reg, sample_every=1)
    shard_hists = [LatencyHistogram() for _ in range(4)]
    for s in range(4):
        ts = np.arange(s * 100, s * 100 + 100, dtype=np.int64)
        tr.stamp_ingest(ts)
        tr.complete_rows(
            0, [(int(t), ()) for t in ts], hist=shard_hists[s]
        )
    assert tr.completed == 400
    assert reg.histogram("trace.e2e").count == 0  # per-shard only
    snap = tr.snapshot(extra_hists=shard_hists)
    assert snap["e2e"]["count"] == 400
    json.dumps(snap)


def test_trace_disabled_is_inert():
    tr = TraceSampler(MetricsRegistry(), sample_every=0)
    assert not tr.enabled
    tr.stamp_ingest(np.arange(100, dtype=np.int64))
    tr.mark(np.arange(100, dtype=np.int64), "dispatch")
    tr.complete_rows(0, [(0, ())])
    assert tr.sampled == 0 and tr.completed == 0
    # and when the whole registry is off, sampling is off too
    reg = MetricsRegistry(enabled=False)
    tr2 = TraceSampler(reg, sample_every=1)
    assert not tr2.enabled


def test_trace_sampling_overhead_within_noise():
    """A/B: the same small job with trace sampling on vs off. The
    per-batch cost is one vectorized mod over the timestamp column, so
    the measured delta must stay within CI noise (generous 1.8x + 250ms
    bound — this is a 2-core container; the check exists to catch a
    pathological per-event Python loop sneaking in, not 2% drifts)."""

    def run_once(sample_every):
        job = _small_job(n_events=60_000, batch=8_192)
        job.tracer.sample_every = sample_every
        job.run_cycle()  # first cycle pays the jit compile: off the clock
        t0 = time.perf_counter()
        while not job.finished:
            job.run_cycle()
        job.flush()
        return time.perf_counter() - t0, job

    on = min(run_once(64)[0] for _ in range(3))
    off = min(run_once(0)[0] for _ in range(3))
    assert on <= off * 1.8 + 0.25, (on, off)
    # and the on-run actually traced: completions feed trace.e2e
    _, job = run_once(64)
    snap = job.tracer.snapshot()
    assert snap["completed"] > 0
    assert snap["e2e"]["count"] == snap["completed"]


def test_streaming_job_traces_end_to_end():
    """Integration: a streaming Job completes traces for sampled events
    whose rows reach collectors, and metrics() carries the trace view."""
    job = _small_job(n_events=16_384, batch=4_096)
    job.tracer.sample_every = 8
    while not job.finished:
        job.run_cycle()
    job.flush()
    m = job.metrics()
    trace = m["telemetry"]["trace"]
    assert trace["sample_every"] == 8
    assert trace["sampled"] > 0
    # the filter keeps id==3 (~1/10 of events); sampled ∩ matched
    # completions must have landed in the e2e histogram
    assert trace["completed"] > 0
    assert trace["e2e"]["count"] == trace["completed"]
    assert trace["e2e"]["p50_ms"] <= trace["e2e"]["p99_ms"]
    json.dumps(m)


# -- end-to-end attribution ----------------------------------------------


def _small_job(n_events=20_000, batch=4_096):
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("price", AttributeType.DOUBLE)]
    )
    rng = np.random.default_rng(11)
    batches = []
    for start in range(0, n_events, batch):
        m = min(batch, n_events - start)
        cols = {
            "id": rng.integers(0, 10, m).astype(np.int32),
            "price": rng.random(m) * 50.0,
        }
        ts = 1_000 + start + np.arange(m, dtype=np.int64)
        batches.append(EventBatch("s", schema, cols, ts))
    plan = compile_plan(
        "from s[id == 3] select id, price insert into out",
        {"s": schema},
        plan_id="t",
    )
    src = BatchSource("s", schema, iter(batches))
    return Job(
        [plan], [src], batch_size=batch, time_mode="processing"
    )


def test_resident_replay_attributes_95pct_of_wall_clock():
    """The tentpole contract: a bounded replay's wall clock decomposes
    into named telemetry stages covering >= 95% — no unattributed
    off-clock time (round-5 verdict, weak #2)."""
    from flink_siddhi_tpu.runtime.replay import ResidentReplay

    job = _small_job()
    rep = ResidentReplay(job)
    t0 = time.perf_counter()
    rep.stage()
    rep.run()
    job.flush()
    elapsed = time.perf_counter() - t0
    snap = job.telemetry.stages.snapshot()
    attributed = sum(
        d["seconds"]
        for name, d in snap.items()
        if name in TOP_LEVEL_STAGES
    )
    assert attributed / elapsed >= 0.95, snap
    # the staging phases the round-5 verdict called "one opaque
    # number" are now individually named
    assert "stage.compile" in snap
    assert "tape_build" in snap
    assert job.results("out")  # the instrumented run still works


def test_streaming_job_metrics_carry_telemetry():
    job = _small_job(n_events=8_192)
    while not job.finished:
        job.run_cycle()
    job.flush()
    m = job.metrics()
    tel = m["telemetry"]
    assert tel["enabled"] is True
    assert "dispatch" in tel["stages"]
    assert "tape_build" in tel["stages"]
    json.dumps(m)  # metrics() must stay JSON-serializable end to end


def test_sharded_job_merges_shard_histograms():
    import jax

    if not hasattr(jax, "shard_map"):
        pytest.skip(
            "jax.shard_map unavailable in this environment "
            "(the whole sharded lane is down here, same as seed)"
        )
    from flink_siddhi_tpu.parallel.sharded import ShardedJob
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("price", AttributeType.DOUBLE)]
    )
    rng = np.random.default_rng(5)
    m = 4_096
    cols = {
        "id": rng.integers(0, 64, m).astype(np.int32),
        "price": rng.random(m) * 10.0,
    }
    ts = 1_000 + np.arange(m, dtype=np.int64)
    plan = compile_plan(
        "from s select id, price insert into out",
        {"s": schema},
        plan_id="t",
    )
    src = BatchSource(
        "s", schema, iter([EventBatch("s", schema, cols, ts)])
    )
    job = ShardedJob(
        [plan], [src], n_shards=4, batch_size=m,
        time_mode="processing",
    )
    while not job.finished:
        job.run_cycle()
    job.flush()
    mtr = job.metrics()
    merged = mtr["telemetry"]["histograms"]["drain.shard_decode"]
    # one decode sample per shard per drain, folded across shards
    assert merged["count"] >= 4
    routed = mtr["telemetry"]["gauges"]["route.cumulative_per_shard"]
    assert sum(routed["t"]) == m
    json.dumps(mtr)
