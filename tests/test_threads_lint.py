"""fstrace (analysis/threads.py) machinery: annotations with mandatory
reasons, the runloop-only walk boundary, cross-module ownership, the
receiver-hint conservatism, and the mtime-keyed sweep cache behind
`fstlint --changed`. The per-rule fire/quiet contracts live in
tests/test_fstlint.py next to the other fixture cases."""

import os

import pytest

from flink_siddhi_tpu.analysis import fstlint
from flink_siddhi_tpu.analysis.threads import analyze_sources


def _rules(findings):
    return [(f.rule, f.line) for f in findings]


def test_bare_threadsafe_mark_is_a_finding():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        # fst:threadsafe\n"
        "        self.stats = {}\n"
    )
    findings = analyze_sources({"t.py": src})
    assert [(f.rule) for f in findings] == ["FST202"]
    assert "without a reason" in findings[0].message


def test_bare_blocking_ok_mark_is_a_finding():
    src = (
        "import time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        import threading\n"
        "        self._lock = threading.Lock()\n"
        "    def wait(self):\n"
        "        with self._lock:\n"
        "            # fst:blocking-ok\n"
        "            time.sleep(1)\n"
    )
    findings = analyze_sources({"t.py": src})
    assert [f.rule for f in findings] == ["FST203"]
    assert "without a reason" in findings[0].message


def test_threadsafe_with_reason_silences_fst202():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        # fst:threadsafe single writer; reader snapshots\n"
        "        self.stats = {}\n"
        "    # fst:thread-root name=a\n"
        "    def wa(self):\n"
        "        self.stats['x'] = 1\n"
        "    # fst:thread-root name=b\n"
        "    def rb(self):\n"
        "        return dict(self.stats)\n"
    )
    assert analyze_sources({"t.py": src}) == []


def test_runloop_only_bounds_the_offthread_walk():
    """A `# fst:runloop-only` def is the run loop's private surface:
    the service walk stops there, so its mutations are not attributed
    to the service thread. Without the mark, the same shape flags."""
    tpl = (
        "class Job:\n"
        "    def __init__(self):\n"
        "        self._acc = {}\n"
        "    # fst:thread-root name=run-loop\n"
        "    def run_cycle(self):\n"
        "        self._acc['n'] = 1\n"
        "        self.drain()\n"
        "{mark}"
        "    def drain(self):\n"
        "        self._acc['n'] = 0\n"
        "class Service:\n"
        "    def __init__(self, job):\n"
        "        self.job = job\n"
        "    # fst:thread-root name=service\n"
        "    def do_GET(self):\n"
        "        self.job.drain()\n"
    )
    flagged = analyze_sources({"t.py": tpl.replace("{mark}", "")})
    assert any(f.rule == "FST201" for f in flagged)
    quiet = analyze_sources(
        {"t.py": tpl.replace("{mark}", "    # fst:runloop-only\n")}
    )
    assert quiet == []


def test_cross_module_ownership_resolves_by_receiver_hint():
    """service code in one module mutating Job state defined in
    another is still caught — resolution joins on the method name
    gated by the receiver<->class hint (`self.job.retire()` -> Job)."""
    job_mod = (
        "class Job:\n"
        "    def __init__(self):\n"
        "        self._plans = {}\n"
        "    # fst:thread-root name=run-loop\n"
        "    def run_cycle(self):\n"
        "        self._plans['p'] = 1\n"
        "    def retire(self, pid):\n"
        "        self._plans.pop(pid, None)\n"
    )
    svc_mod = (
        "class Service:\n"
        "    def __init__(self, job):\n"
        "        self.job = job\n"
        "    # fst:thread-root name=service\n"
        "    def do_DELETE(self, pid):\n"
        "        self.job.retire(pid)\n"
    )
    findings = analyze_sources({"job.py": job_mod, "svc.py": svc_mod})
    assert [(f.rule, f.path) for f in findings] == [
        ("FST201", "job.py")
    ]
    # an implausible receiver drops the edge instead of guessing
    svc2 = svc_mod.replace("self.job = job", "self.widget = job"
                           ).replace("self.job.retire", "self.widget.retire")
    assert analyze_sources({"job.py": job_mod, "svc.py": svc2}) == []


def test_locked_writes_are_not_ownership_violations():
    """State the run loop itself only mutates under a lock has a
    synchronization story; FST201 polices the lock-free single-writer
    state only."""
    src = (
        "class Job:\n"
        "    def __init__(self):\n"
        "        import threading\n"
        "        self._lock = threading.Lock()\n"
        "        self.ring = {}\n"
        "    # fst:thread-root name=run-loop\n"
        "    def run_cycle(self):\n"
        "        with self._lock:\n"
        "            self.ring['a'] = 1\n"
        "    def record(self):\n"
        "        with self._lock:\n"
        "            self.ring['b'] = 2\n"
        "class Service:\n"
        "    def __init__(self, job):\n"
        "        self.job = job\n"
        "    # fst:thread-root name=service\n"
        "    def do_POST(self):\n"
        "        self.job.record()\n"
    )
    assert analyze_sources({"t.py": src}) == []


def test_lock_context_inherited_by_locked_only_helpers():
    """A helper whose every call site holds the lock inherits lock
    context — blocking inside it is still blocking under the lock
    (the kafka _read_frame shape)."""
    src = (
        "class C:\n"
        "    def __init__(self, sock):\n"
        "        import threading\n"
        "        self._lock = threading.Lock()\n"
        "        self._sock = sock\n"
        "    def call(self):\n"
        "        with self._lock:\n"
        "            return self._read()\n"
        "    def _read(self):\n"
        "        return self._sock.recv(4)\n"
    )
    findings = analyze_sources({"t.py": src})
    assert [f.rule for f in findings] == ["FST203"]


# -- the sweep cache behind `fstlint --changed` ----------------------------


def test_sweep_cache_reuses_unchanged_files(tmp_path, monkeypatch):
    cache = tmp_path / "cache.json"
    monkeypatch.setattr(fstlint, "CACHE_PATH", str(cache))
    calls = []
    real = fstlint.lint_module

    def counting(source, path):
        calls.append(path)
        return real(source, path)

    monkeypatch.setattr(fstlint, "lint_module", counting)
    assert fstlint.main([]) == 0
    assert cache.exists()
    first = len(calls)
    assert first > 50  # the full default surface was linted
    assert fstlint.main([]) == 0
    assert len(calls) == first  # warm run re-linted NOTHING
    # touching one file re-lints exactly that file; restore the real
    # stamp afterwards or the repo's LIVE sweep cache (the tier-1
    # repo-lints-clean gate's) sees a stale whole-set key and pays a
    # full FST2xx re-run on the next real fstlint invocation
    target = os.path.join(fstlint.REPO_ROOT, "bench.py")
    st = os.stat(target)
    try:
        os.utime(target)
        assert fstlint.main([]) == 0
        assert calls[first:] == ["bench.py"]
    finally:
        os.utime(target, ns=(st.st_atime_ns, st.st_mtime_ns))


def test_changed_reports_only_stale_files(tmp_path, monkeypatch):
    cache = tmp_path / "cache.json"
    monkeypatch.setattr(fstlint, "CACHE_PATH", str(cache))
    assert fstlint.main([]) == 0  # builds the cache
    # an up-to-date cache: --changed has nothing to report even if a
    # (hypothetical) finding existed elsewhere
    assert fstlint.main(["--changed"]) == 0
    with pytest.raises(SystemExit):
        fstlint.main(["--changed", "some/path.py"])
    with pytest.raises(SystemExit):
        # a baseline regenerated from the stale-files subset would
        # drop unchanged files' suppressions
        fstlint.main(
            ["--changed", "--write-baseline", str(tmp_path / "b.toml")]
        )
