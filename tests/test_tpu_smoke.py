"""Real-TPU smoke lane: result-ASSERTING runs on the actual chip.

Everything else in tests/ runs on the virtual CPU mesh, and bench.py
(the only other thing that touches the real device) asserts nothing —
so f32/Pallas-lowering divergence on hardware would go unseen (round-3
verdict item 8). This 5-minute lane runs the headline pattern, a
sliding window aggregation, and a join at small N against the same
Python oracles the CPU tests use, with Pallas COMPILED (not
interpreted).

Invocation (one TPU client at a time — see .claude/skills/verify):

    FST_TPU_SMOKE=1 timeout 600 python -m pytest -m tpu tests/ -q
"""

import numpy as np
import pytest

pytestmark = pytest.mark.tpu

from flink_siddhi_tpu.compiler.config import EngineConfig  # noqa: E402
from flink_siddhi_tpu.compiler.plan import compile_plan  # noqa: E402
from flink_siddhi_tpu.runtime.executor import Job  # noqa: E402
from flink_siddhi_tpu.runtime.sources import BatchSource  # noqa: E402
from flink_siddhi_tpu.schema.batch import EventBatch  # noqa: E402
from flink_siddhi_tpu.schema.stream_schema import StreamSchema  # noqa: E402
from flink_siddhi_tpu.schema.types import AttributeType  # noqa: E402

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
     ("timestamp", AttributeType.LONG)]
)


@pytest.fixture(scope="module")
def on_tpu():
    import jax

    devs = jax.devices()
    if not devs or devs[0].platform in ("cpu",):
        pytest.skip("no accelerator visible")
    return devs[0]


def _batches(n, batch, seed=7, n_ids=6):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_ids, n).astype(np.int32)
    prices = np.round(rng.random(n) * 100, 3)
    ts = (1000 + np.arange(n)).astype(np.int64)
    return ids, prices, ts, [
        EventBatch(
            "S", SCHEMA,
            {"id": ids[s:s + batch], "price": prices[s:s + batch],
             "timestamp": ts[s:s + batch]},
            ts[s:s + batch],
        )
        for s in range(0, n, batch)
    ]


def _run(cql, batches, batch, config=None):
    plan = compile_plan(cql, {"S": SCHEMA}, config=config)
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job


def test_headline_pattern_matches_oracle_on_device(on_tpu):
    ids, prices, ts, batches = _batches(4096, 1024)
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] -> "
        "s3 = S[id == 3] within 5 sec "
        "select s1.timestamp as t1, s3.timestamp as t3, "
        "s3.price as price insert into m"
    )
    job = _run(
        cql, batches, 1024,
        EngineConfig(lazy_projection=True, pred_pushdown=True),
    )
    rows = sorted(job.results("m"))
    # per-event oracle (the JVM engine's partial-match walk)
    partials, exp = [], []
    for i in range(len(ids)):
        nxt = []
        for step, t1, _caps in partials:
            if ts[i] - t1 > 5000:
                continue
            want = (2, 3)[step - 1]
            if ids[i] == want:
                if step == 2:
                    exp.append((int(t1), int(ts[i]), float(prices[i])))
                    continue
                nxt.append((step + 1, t1, None))
            else:
                nxt.append((step, t1, _caps))
        partials = nxt
        if ids[i] == 1:
            partials.append((1, ts[i], None))
    exp.sort()
    assert len(rows) == len(exp) > 0
    for (t1, t3, p), (et1, et3, ep) in zip(rows, exp):
        assert (t1, t3) == (et1, et3)
        assert p == pytest.approx(ep, rel=1e-6)


def test_window_groupby_matches_oracle_on_device(on_tpu):
    ids, prices, ts, batches = _batches(3000, 1024)
    cql = (
        "from S#window.length(100) select id, sum(price) as s, "
        "count() as c group by id insert into o"
    )
    job = _run(cql, batches, 1024)
    rows = job.results("o")
    hist = []
    exp = []
    for i in range(len(ids)):
        hist.append((int(ids[i]), float(prices[i])))
        win = hist[-100:]
        mine = [p for k, p in win if k == ids[i]]
        exp.append((int(ids[i]), sum(mine), len(mine)))
    assert len(rows) == len(exp)
    for (k, s, c), (ek, es, ec) in zip(rows, exp):
        assert (k, c) == (ek, ec)
        assert s == pytest.approx(es, rel=1e-4)


def test_join_matches_oracle_on_device(on_tpu):
    t_schema = StreamSchema(
        [("id", AttributeType.INT), ("qty", AttributeType.INT),
         ("timestamp", AttributeType.LONG)]
    )
    rng = np.random.default_rng(5)
    n = 512
    ids_s = rng.integers(0, 4, n).astype(np.int32)
    prices = np.round(rng.random(n) * 10, 2)
    ts_s = (1000 + 2 * np.arange(n)).astype(np.int64)
    ids_t = rng.integers(0, 4, n).astype(np.int32)
    qty = rng.integers(1, 9, n).astype(np.int32)
    ts_t = (1001 + 2 * np.arange(n)).astype(np.int64)
    sb = [EventBatch("S", SCHEMA,
                     {"id": ids_s, "price": prices, "timestamp": ts_s},
                     ts_s)]
    tb = [EventBatch("T", t_schema,
                     {"id": ids_t, "qty": qty, "timestamp": ts_t},
                     ts_t)]
    cql = (
        "from S#window.length(8) join T#window.length(8) "
        "on S.id == T.id "
        "select S.timestamp as st, T.timestamp as tt insert into j"
    )
    plan = compile_plan(cql, {"S": SCHEMA, "T": t_schema})
    job = Job(
        [plan],
        [BatchSource("S", SCHEMA, iter(sb)),
         BatchSource("T", t_schema, iter(tb))],
        batch_size=2048, time_mode="processing",
    )
    job.run()
    got = sorted(job.results("j"))
    # oracle: merged arrival order; each arrival pairs against the
    # other side's last-8 ring
    events = sorted(
        [(int(t), "S", int(i)) for t, i in zip(ts_s, ids_s)]
        + [(int(t), "T", int(i)) for t, i in zip(ts_t, ids_t)]
    )
    ring = {"S": [], "T": []}
    exp = []
    for t, side, k in events:
        other = "T" if side == "S" else "S"
        for (ot, ok) in ring[other][-8:]:
            if ok == k:
                exp.append((t, ot) if side == "S" else (ot, t))
        ring[side].append((t, k))
    exp.sort()
    assert got == exp and len(got) > 0


def test_pallas_compiled_not_interpreted(on_tpu):
    # the chain core's Pallas reverse-cummin must COMPILE on hardware
    # (warmup returns False when the kernel fell back to XLA)
    import os

    from flink_siddhi_tpu.compiler import pallas_ops

    assert not os.environ.get("FST_PALLAS_INTERPRET")
    assert pallas_ops.warmup(), (
        "Pallas kernel unavailable on the real device (XLA fallback)"
    )


def test_session_window_scan_engine_on_device(on_tpu):
    # round-5 verdict item 8: the per-event lax.scan engine (session /
    # sort / unique windows) had never run on real hardware
    ids = np.array([0, 1, 0, 0, 1, 0, 1, 1], dtype=np.int32)
    ts = np.array(
        [1000, 1002, 1005, 1040, 1041, 1100, 1101, 1150],
        dtype=np.int64,
    )
    prices = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0])
    batches = [
        EventBatch(
            "S", SCHEMA,
            {"id": ids[s:s + 4], "price": prices[s:s + 4],
             "timestamp": ts[s:s + 4]},
            ts[s:s + 4],
        )
        for s in range(0, 8, 4)
    ]
    job = _run(
        "from S#window.session(10 ms, id) "
        "select id, sum(price) as s, count() as c insert into o",
        batches, 4,
    )
    rows = sorted(job.results("o"))
    expect = sorted([
        (0, 4.0, 2), (0, 4.0, 1), (0, 6.0, 1),
        (1, 2.0, 1), (1, 5.0, 1), (1, 7.0, 1), (1, 8.0, 1),
    ])
    assert len(rows) == len(expect)
    for (k, s, c), (ek, es, ec) in zip(rows, expect):
        assert (k, c) == (ek, ec)
        assert s == pytest.approx(es, rel=1e-4)


def test_sharded_step_on_device(on_tpu):
    # the shard_map'd step (stacked state + collectives) compiled and
    # executed on the real chip — a 1-device mesh exercises the same
    # program the virtual 8-device CPU mesh runs
    from flink_siddhi_tpu.parallel import ShardedJob

    ids, prices, ts, batches = _batches(2048, 512)
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] "
        "select s1.timestamp as t1, s2.timestamp as t2 insert into o"
    )
    sj = ShardedJob(
        [compile_plan(cql, {"S": SCHEMA})],
        [BatchSource("S", SCHEMA, iter(batches))],
        n_shards=1, batch_size=512, time_mode="processing",
    )
    sj.run()
    got = sorted(sj.results("o"))
    # oracle: every-restart 2-step chain
    partials, exp = [], []
    for i in range(len(ids)):
        nxt = []
        for t1 in partials:
            if ids[i] == 2:
                exp.append((int(t1), int(ts[i])))
            else:
                nxt.append(t1)
        partials = nxt
        if ids[i] == 1:
            partials.append(ts[i])
    assert got == sorted(exp) and got


def test_checkpoint_roundtrip_on_device(on_tpu, tmp_path):
    # device state snapshot mid-stream -> fresh job -> identical tail
    ids, prices, ts, batches = _batches(4096, 512)
    cql = (
        "from S#window.length(64) select id, sum(price) as s "
        "group by id insert into o"
    )

    def build(bs):
        plan = compile_plan(cql, {"S": SCHEMA})
        return Job(
            [plan], [BatchSource("S", SCHEMA, iter(bs))],
            batch_size=512, time_mode="processing",
        )

    solo = build(batches)
    solo.run()
    expect = solo.results("o")

    job1 = build(batches)
    job1.run(max_cycles=4)
    assert not job1.finished
    ck = str(tmp_path / "ck")
    job1.save_checkpoint(ck)
    head = job1.results("o")
    job2 = build(batches[4:])
    job2.restore(ck)
    job2.run()
    got = head + job2.results("o")
    assert len(got) == len(expect) == 4096
    for (k, s), (ek, es) in zip(got, expect):
        assert k == ek
        assert s == pytest.approx(es, rel=1e-5)


def test_resident_replay_on_device(on_tpu):
    # the bounded-replay scan (the bench's execution mode) against the
    # streaming path ON HARDWARE — row-identical
    from flink_siddhi_tpu.runtime.replay import ResidentReplay

    ids, prices, ts, batches = _batches(4096, 1024)
    cql = (
        "from every s1 = S[id == 1] -> s2 = S[id == 2] -> "
        "s3 = S[id == 3] within 5 sec "
        "select s1.timestamp as t1, s3.timestamp as t3 insert into m"
    )
    cfg = EngineConfig(lazy_projection=True, pred_pushdown=True)
    a = _run(cql, list(batches), 1024, cfg)
    plan = compile_plan(cql, {"S": SCHEMA}, config=cfg)
    b = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=1024, time_mode="processing",
    )
    ResidentReplay(b).execute()
    ra, rb = a.results_with_ts("m"), b.results_with_ts("m")
    assert sorted(ra) == sorted(rb) and ra
