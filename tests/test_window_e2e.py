"""End-to-end: windows, aggregations, group-by, having.

Pins the reference's window/aggregation surface (SiddhiCEPITCase.java:
315-318 windowed aggregation, :492-504 group-by; siddhi-core semantics per
SURVEY.md §2.10) against pure-Python oracles: sliding windows emit one row per
arriving event over the current window contents; batch windows emit per-group
rows when the window tumbles; no window = cumulative aggregation.
"""

import dataclasses
import math

import pytest

from flink_siddhi_tpu import SiddhiCEP, CEPEnvironment


@dataclasses.dataclass
class Event:
    id: int
    name: str
    price: float
    timestamp: int


FIELDS = ["id", "name", "price", "timestamp"]


def make_events(n, start_ts=1000, id_mod=4, step=1000):
    return [
        Event(i % id_mod, f"name_{i % 3}", float(i), start_ts + step * i)
        for i in range(n)
    ]


def run(events, cql, out="out", batch_size=4096):
    env = CEPEnvironment(batch_size=batch_size)
    return (
        SiddhiCEP.define("inputStream", events, FIELDS, env=env)
        .cql(cql)
        .returns(out)
    )


# --------------------------------------------------------------------------
# sliding length windows
# --------------------------------------------------------------------------

@pytest.mark.parametrize("batch_size", [4096, 7])
def test_length_window_sum(batch_size):
    events = make_events(20)
    out = run(
        events,
        "from inputStream#window.length(5) "
        "select sum(price) as total insert into out",
        batch_size=batch_size,
    )
    expected = []
    for i in range(len(events)):
        w = events[max(0, i - 4) : i + 1]
        expected.append((sum(e.price for e in w),))
    assert out == expected


@pytest.mark.parametrize("batch_size", [4096, 7])
def test_length_window_group_by(batch_size):
    events = make_events(24)
    out = run(
        events,
        "from inputStream#window.length(6) "
        "select id, sum(price) as total, count() as c "
        "group by id insert into out",
        batch_size=batch_size,
    )
    expected = []
    for i in range(len(events)):
        w = events[max(0, i - 5) : i + 1]
        grp = [e for e in w if e.id == events[i].id]
        expected.append(
            (events[i].id, sum(e.price for e in grp), len(grp))
        )
    assert out == expected


def test_length_window_min_max_avg():
    events = make_events(15)
    out = run(
        events,
        "from inputStream#window.length(4) "
        "select min(price) as lo, max(price) as hi, avg(price) as mean "
        "insert into out",
    )
    for i, row in enumerate(out):
        w = [e.price for e in events[max(0, i - 3) : i + 1]]
        assert row[0] == min(w)
        assert row[1] == max(w)
        assert row[2] == pytest.approx(sum(w) / len(w))


def test_length_window_stddev_distinctcount():
    events = make_events(12, id_mod=3)
    out = run(
        events,
        "from inputStream#window.length(5) "
        "select stddev(price) as sd, distinctCount(id) as dc "
        "insert into out",
    )
    for i, row in enumerate(out):
        w = events[max(0, i - 4) : i + 1]
        vals = [e.price for e in w]
        mean = sum(vals) / len(vals)
        var = sum((v - mean) ** 2 for v in vals) / len(vals)
        assert row[0] == pytest.approx(math.sqrt(var), abs=1e-4)
        assert row[1] == len({e.id for e in w})


def test_length_window_with_filter():
    events = make_events(30)
    out = run(
        events,
        "from inputStream[id == 2]#window.length(3) "
        "select sum(price) as total insert into out",
    )
    matching = [e for e in events if e.id == 2]
    expected = []
    for i in range(len(matching)):
        w = matching[max(0, i - 2) : i + 1]
        expected.append((sum(e.price for e in w),))
    assert out == expected


# --------------------------------------------------------------------------
# sliding time windows
# --------------------------------------------------------------------------

@pytest.mark.parametrize("batch_size", [4096, 5])
def test_time_window_sum(batch_size):
    events = make_events(20)
    out = run(
        events,
        "from inputStream#window.time(3 sec) "
        "select sum(price) as total, count() as c insert into out",
        batch_size=batch_size,
    )
    expected = []
    for i, cur in enumerate(events):
        w = [
            e
            for e in events[: i + 1]
            if e.timestamp > cur.timestamp - 3000
        ]
        expected.append((sum(e.price for e in w), len(w)))
    assert out == expected


def test_time_window_group_by():
    events = make_events(18, id_mod=3)
    out = run(
        events,
        "from inputStream#window.time(4000) "
        "select id, avg(price) as mean group by id insert into out",
    )
    for i, row in enumerate(out):
        cur = events[i]
        w = [
            e
            for e in events[: i + 1]
            if e.timestamp > cur.timestamp - 4000 and e.id == cur.id
        ]
        assert row[0] == cur.id
        assert row[1] == pytest.approx(
            sum(e.price for e in w) / len(w)
        )


# --------------------------------------------------------------------------
# cumulative (no window)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("batch_size", [4096, 6])
def test_cumulative_sum_count(batch_size):
    events = make_events(20)
    out = run(
        events,
        "from inputStream select sum(price) as s, count() as c "
        "insert into out",
        batch_size=batch_size,
    )
    run_sum = 0.0
    for i, row in enumerate(out):
        run_sum += events[i].price
        assert row == (run_sum, i + 1)


@pytest.mark.parametrize("batch_size", [4096, 6])
def test_cumulative_group_by(batch_size):
    events = make_events(24)
    out = run(
        events,
        "from inputStream select id, sum(price) as s, min(price) as lo, "
        "max(price) as hi group by id insert into out",
        batch_size=batch_size,
    )
    for i, row in enumerate(out):
        grp = [e for e in events[: i + 1] if e.id == events[i].id]
        assert row == (
            events[i].id,
            sum(e.price for e in grp),
            min(e.price for e in grp),
            max(e.price for e in grp),
        )


def test_cumulative_group_by_string_key():
    events = make_events(15)
    out = run(
        events,
        "from inputStream select name, count() as c group by name "
        "insert into out",
    )
    for i, row in enumerate(out):
        grp = [e for e in events[: i + 1] if e.name == events[i].name]
        assert row == (events[i].name, len(grp))


# --------------------------------------------------------------------------
# batch (tumbling) windows
# --------------------------------------------------------------------------

@pytest.mark.parametrize("batch_size", [4096, 7])
def test_length_batch_sum(batch_size):
    events = make_events(23)
    out = run(
        events,
        "from inputStream#window.lengthBatch(5) "
        "select sum(price) as total, count() as c insert into out",
        batch_size=batch_size,
    )
    expected = []
    for start in range(0, 20, 5):  # only complete batches flush
        chunk = events[start : start + 5]
        expected.append((sum(e.price for e in chunk), 5))
    assert out == expected


@pytest.mark.parametrize("batch_size", [4096, 9])
def test_length_batch_group_by(batch_size):
    events = make_events(20, id_mod=2)
    out = run(
        events,
        "from inputStream#window.lengthBatch(4) "
        "select id, sum(price) as total group by id insert into out",
        batch_size=batch_size,
    )
    expected = set()
    for start in range(0, 20, 4):
        chunk = events[start : start + 4]
        for gid in sorted({e.id for e in chunk}):
            grp = [e for e in chunk if e.id == gid]
            expected.add((gid, sum(e.price for e in grp)))
    assert len(out) == len(expected)
    assert set(out) == expected


@pytest.mark.parametrize("batch_size", [4096, 5])
def test_time_batch(batch_size):
    events = make_events(12)  # ts 1000..12000 step 1000
    out = run(
        events,
        "from inputStream#window.timeBatch(3 sec) "
        "select sum(price) as total, count() as c insert into out",
        batch_size=batch_size,
    )
    # windows of 3s anchored at first event ts=1000: [1000,4000) [4000,7000)
    # [7000,10000) [10000,13000); the last flushes at end-of-stream
    expected = []
    t0 = events[0].timestamp
    k = 0
    while True:
        lo, hi = t0 + k * 3000, t0 + (k + 1) * 3000
        chunk = [e for e in events if lo <= e.timestamp < hi]
        if not chunk:
            break
        expected.append((sum(e.price for e in chunk), len(chunk)))
        k += 1
    assert out == expected


# --------------------------------------------------------------------------
# having / expression-of-aggregates
# --------------------------------------------------------------------------

def test_having_on_alias():
    events = make_events(20)
    out = run(
        events,
        "from inputStream#window.length(5) "
        "select sum(price) as total having total > 30.0 insert into out",
    )
    expected = []
    for i in range(len(events)):
        w = events[max(0, i - 4) : i + 1]
        t = sum(e.price for e in w)
        if t > 30.0:
            expected.append((t,))
    assert out == expected


def test_having_group_by():
    events = make_events(24)
    out = run(
        events,
        "from inputStream select id, count() as c group by id "
        "having c >= 3 insert into out",
    )
    expected = []
    for i in range(len(events)):
        grp = [e for e in events[: i + 1] if e.id == events[i].id]
        if len(grp) >= 3:
            expected.append((events[i].id, len(grp)))
    assert out == expected


def test_aggregate_in_expression():
    events = make_events(10)
    out = run(
        events,
        "from inputStream#window.length(4) "
        "select sum(price) / count() as mean, timestamp "
        "insert into out",
    )
    for i, row in enumerate(out):
        w = [e.price for e in events[max(0, i - 3) : i + 1]]
        assert row[0] == pytest.approx(sum(w) / len(w))
        assert row[1] == events[i].timestamp


def test_window_passthrough_projection():
    # window + plain select: current events pass through unchanged
    events = make_events(6)
    out = run(
        events,
        "from inputStream#window.length(3) select id, price "
        "insert into out",
    )
    assert out == [(e.id, e.price) for e in events]


def test_no_consumer_fast_path_counts_only():
    # drain fast path: with retention off and no sinks, rows are counted
    # but never fetched/decoded; adding a sink re-enables full decode
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import CallbackSource
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    cql = (
        "from S#window.length(4) select id, sum(price) as total "
        "group by id insert into out"
    )

    class Rec:
        def __init__(self, id, price, timestamp):
            self.id, self.price, self.timestamp = id, price, timestamp

    def run(with_sink):
        src = CallbackSource("S", schema)
        job = Job(
            [compile_plan(cql, {"S": schema})], [src],
            batch_size=16, time_mode="processing", retain_results=False,
        )
        rows = []
        if with_sink:
            job.add_sink("out", lambda ts, row: rows.append(row))
        for i in range(32):
            src.emit(Rec(i % 3, float(i), 1000 + i), 1000 + i)
        for _ in range(4):
            job.run_cycle()
        job.flush()
        # poll any pending drains to completion
        for rt in job._plans.values():
            job._drain_poll(rt, block=True)
        return job, rows

    job_ns, rows_ns = run(with_sink=False)
    assert rows_ns == []
    assert job_ns.emitted_counts.get("out", 0) == 32  # counted, not decoded
    job_s, rows_s = run(with_sink=True)
    assert len(rows_s) == 32
    assert job_s.emitted_counts.get("out", 0) == 32


@pytest.mark.parametrize("batch_size", [4096, 23])
def test_length_window_group_minmax_oracle(batch_size):
    # pins the prefix-path sparse-table range min/max (group-major
    # arrival RMQ): randomized prices, group-by, window larger than the
    # groups' in-window counts, across batch boundaries
    import random

    rnd = random.Random(11)
    events = [
        Event(rnd.randrange(5), "n", float(rnd.randrange(1000)) / 4,
              1000 + 100 * i)
        for i in range(300)
    ]
    out = run(
        events,
        "from inputStream#window.length(37) "
        "select id, min(price) as lo, max(price) as hi, "
        "sum(price) as tot group by id insert into out",
        batch_size=batch_size,
    )
    assert len(out) == len(events)
    for i, row in enumerate(out):
        w = [
            e.price
            for e in events[max(0, i - 36): i + 1]
            if e.id == events[i].id
        ]
        assert row[0] == events[i].id
        assert row[1] == min(w), f"row {i} min"
        assert row[2] == max(w), f"row {i} max"
        assert row[3] == pytest.approx(sum(w), rel=1e-5)


def test_time_window_minmax_straggler_stays_exact():
    # review regression: time-window min/max must NOT use the last-cnt
    # suffix range query — a cross-batch timestamp straggler is
    # conservatively early-evicted, making the live set non-contiguous.
    # batch_size=1 forces each event into its own poll.
    events = [
        Event(0, "n", 100.0, 10000),
        Event(0, "n", 1.0, 7000),    # straggler: regressed timestamp
        Event(0, "n", 50.0, 13000),
    ]
    out = run(
        events,
        "from inputStream#window.time(5 sec) "
        "select min(price) as lo, max(price) as hi, count() as c "
        "insert into out",
        batch_size=1,
    )
    # at the third event the engine's live set is {100.0, 50.0} (the
    # straggler was conservatively evicted): min/max must agree with
    # its own count/sum view
    lo, hi, c = out[-1]
    assert c == 2
    assert (lo, hi) == (50.0, 100.0)


def test_cumulative_f32_sum_compensated_drift():
    """Round-4 verdict item 6: an unbounded cumulative sum() must not
    silently stall once the f32 accumulator outgrows its mantissa.
    3M events of value 1000.0 push the running sum to 3e9 (f32 grain
    there is 256); the Neumaier-compensated accumulator stays within
    1e-6 relative of the f64 oracle where a bare f32 sum drifts ~1e-3."""
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("v", AttributeType.DOUBLE), ("timestamp", AttributeType.LONG)]
    )
    n, batch = 3_000_000, 262_144
    batches = []
    for s in range(0, n, batch):
        m = min(batch, n - s)
        ts = 1000 + np.arange(s, s + m, dtype=np.int64)
        batches.append(
            EventBatch(
                "S", schema,
                {"v": np.full(m, 1000.0), "timestamp": ts},
                ts,
            )
        )
    plan = compile_plan(
        "from S select sum(v) as total insert into o", {"S": schema}
    )
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=batch, time_mode="processing", retain_results=False,
    )
    last = {}
    job.add_sink("o", lambda ts_, row: last.__setitem__("v", row[0]))
    job.run()
    oracle = 1000.0 * n  # exact in f64
    assert last["v"] == pytest.approx(oracle, rel=1e-6)


def test_blocked_window_group_code_projection_matches_eager():
    """Round-4 wire opt: plain group-key projections ship as @group
    CODES and decode back through the encoder — results must match the
    eager (raw column) path exactly."""
    import numpy as np

    from flink_siddhi_tpu.compiler.config import EngineConfig
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
         ("timestamp", AttributeType.LONG)]
    )
    cql = (
        "from S#window.length(50) select id, sum(price) as s, "
        "count() as c group by id insert into o"
    )
    rng = np.random.default_rng(17)
    n = 600
    ids = rng.integers(0, 9, n).astype(np.int32)
    prices = np.round(rng.random(n) * 10, 2)
    ts = 1000 + np.arange(n, dtype=np.int64)

    def run(cfg):
        batches = [
            EventBatch(
                "S", schema,
                {"id": ids[s:s + 64], "price": prices[s:s + 64],
                 "timestamp": ts[s:s + 64]},
                ts[s:s + 64],
            )
            for s in range(0, n, 64)
        ]
        plan = compile_plan(cql, {"S": schema}, config=cfg)
        job = Job([plan], [BatchSource("S", schema, iter(batches))],
                  batch_size=64, time_mode="processing")
        job.run()
        return plan, job.results("o")

    plan_e, eager = run(EngineConfig())
    plan_l, opt = run(EngineConfig(lazy_projection=True))
    # the raw group column dropped off the wire
    assert "S.id" not in (plan_l.spec.device_columns or ("S.id",))
    assert plan_l.artifacts[0].group_code_proj[0] is not None
    assert len(eager) == len(opt) == n
    for (ke, se, ce), (ko, so, co) in zip(eager, opt):
        assert (ke, ce) == (ko, co)
        assert so == pytest.approx(se, rel=1e-5)


def test_blocked_int_sum_exact_beyond_f32():
    """Round-5: integer sums route through the blocked path via base-2^11
    digit planes — totals past 2^24 (where a plain f32 pipeline loses
    integer exactness) must stay bit-exact."""
    import numpy as np

    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch
    from flink_siddhi_tpu.schema.stream_schema import StreamSchema
    from flink_siddhi_tpu.schema.types import AttributeType

    schema = StreamSchema(
        [("k", AttributeType.INT), ("v", AttributeType.INT),
         ("timestamp", AttributeType.LONG)]
    )
    rng = np.random.default_rng(3)
    n, C = 4000, 600
    ks = rng.integers(0, 3, n).astype(np.int32)
    # values near 2^24: a C=600 window sums to ~1e10 mod 2^32, far past
    # exact f32 territory; +1 odd offsets catch low-bit loss
    vs = (rng.integers(1 << 23, 1 << 25, n) * 2 + 1).astype(np.int32)
    ts = 1000 + np.arange(n, dtype=np.int64)
    batches = [
        EventBatch(
            "S", schema,
            {"k": ks[s:s + 512], "v": vs[s:s + 512],
             "timestamp": ts[s:s + 512]},
            ts[s:s + 512],
        )
        for s in range(0, n, 512)
    ]
    cql = (
        f"from S#window.length({C}) "
        "select k, sum(v) as s, min(v) as mn, max(v) as mx "
        "group by k insert into o"
    )
    plan = compile_plan(cql, {"S": schema})
    art = plan.artifacts[0]
    assert art._blocked(), "int sums + min/max must take the blocked path"
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=512, time_mode="processing",
    )
    job.run()
    rows = job.results("o")
    assert len(rows) == n
    from collections import deque
    win = deque()
    for i, (k, s, mn, mx) in enumerate(rows):
        win.append(i)
        if len(win) > C:
            win.popleft()
        member = [j for j in win if ks[j] == ks[i]]
        exact = int(np.sum(vs[member], dtype=np.int64) & 0xFFFFFFFF)
        if exact >= 1 << 31:
            exact -= 1 << 32
        assert k == ks[i]
        assert s == exact, (i, s, exact)
        assert mn == min(int(vs[j]) for j in member)
        assert mx == max(int(vs[j]) for j in member)
