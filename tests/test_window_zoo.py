"""Round-4 window zoo: timeLength, externalTimeBatch, sort, unique —
each against a per-event Python oracle (siddhi-core 4.2.40 window
surface; the reference treats any window generically,
SiddhiExecutionPlanner.java:194-210)."""

import numpy as np
import pytest

from flink_siddhi_tpu.compiler.plan import compile_plan
from flink_siddhi_tpu.query.lexer import SiddhiQLError
from flink_siddhi_tpu.runtime.executor import Job
from flink_siddhi_tpu.runtime.sources import BatchSource
from flink_siddhi_tpu.schema.batch import EventBatch
from flink_siddhi_tpu.schema.stream_schema import StreamSchema
from flink_siddhi_tpu.schema.types import AttributeType

SCHEMA = StreamSchema(
    [("id", AttributeType.INT), ("price", AttributeType.DOUBLE),
     ("timestamp", AttributeType.LONG)]
)


def run(cql, ids, prices, ts, batch=8):
    n = len(ids)
    batches = [
        EventBatch(
            "S", SCHEMA,
            {
                "id": np.asarray(ids[s:s + batch], np.int32),
                "price": np.asarray(prices[s:s + batch], np.float64),
                "timestamp": np.asarray(ts[s:s + batch], np.int64),
            },
            np.asarray(ts[s:s + batch], np.int64),
        )
        for s in range(0, n, batch)
    ]
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=batch, time_mode="processing",
    )
    job.run()
    return job


def make(n=60, seed=3, span=40):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 5, n).tolist()
    prices = np.round(rng.random(n) * 100, 2).tolist()
    ts = (1000 + np.cumsum(rng.integers(1, 9, n))).tolist()
    return ids, prices, ts


def test_timelength_window_oracle():
    ids, prices, ts = make()
    cql = (
        "from S#window.timeLength(20 ms, 5) "
        "select sum(price) as s, count() as c insert into o"
    )
    job = run(cql, ids, prices, ts)
    rows = job.results("o")
    # oracle: member iff within last 5 events AND ts > cur - 20
    exp = []
    hist = []
    for i in range(len(ids)):
        hist.append((ts[i], prices[i]))
        win = [p for t, p in hist[-5:] if t > ts[i] - 20]
        exp.append((sum(win), len(win)))
    assert len(rows) == len(exp)
    for (s, c), (es, ec) in zip(rows, exp):
        assert c == ec
        assert s == pytest.approx(es, rel=1e-4)


def test_external_time_batch_oracle():
    # external timestamps drive the tumbling boundary, not event time
    ids, prices, _ = make(40)
    ext = (5000 + np.cumsum(np.random.default_rng(9).integers(1, 15, 40)))
    ts = (1000 + np.arange(40)).tolist()  # event time: dense
    schema = SCHEMA
    cql = (
        "from S#window.externalTimeBatch(timestamp, 30 ms) "
        "select sum(price) as s, count() as c insert into o"
    )
    # feed ext values through the `timestamp` attribute
    n = 40
    batches = [
        EventBatch(
            "S", schema,
            {
                "id": np.asarray(ids[s:s + 8], np.int32),
                "price": np.asarray(prices[s:s + 8], np.float64),
                "timestamp": np.asarray(ext[s:s + 8], np.int64),
            },
            np.asarray(ts[s:s + 8], np.int64),
        )
        for s in range(0, n, 8)
    ]
    plan = compile_plan(cql, {"S": schema})
    job = Job(
        [plan], [BatchSource("S", schema, iter(batches))],
        batch_size=8, time_mode="processing",
    )
    job.run()
    rows = job.results("o")
    # oracle: tumbling 30ms windows of the EXTERNAL ts, first event
    # anchors t0; incomplete final window flushes at stream end
    t0 = int(ext[0])
    buckets = {}
    for i in range(n):
        b = (int(ext[i]) - t0) // 30
        buckets.setdefault(b, []).append(prices[i])
    exp = [
        (sum(v), len(v)) for _, v in sorted(buckets.items())
    ]
    assert len(rows) == len(exp)
    for (s, c), (es, ec) in zip(rows, exp):
        assert c == ec
        assert s == pytest.approx(es, rel=1e-4)


def test_sort_window_oracle_asc():
    ids, prices, ts = make(50)
    cql = (
        "from S#window.sort(3, price) "
        "select sum(price) as s, count() as c, min(price) as mn "
        "insert into o"
    )
    job = run(cql, ids, prices, ts)
    rows = job.results("o")
    kept = []
    exp = []
    for p in prices:
        kept = sorted(kept + [p])[:3]  # asc: keep 3 smallest
        exp.append((sum(kept), len(kept), min(kept)))
    assert len(rows) == len(exp)
    for (s, c, mn), (es, ec, emn) in zip(rows, exp):
        assert c == ec
        assert s == pytest.approx(es, rel=1e-4)
        assert mn == pytest.approx(emn, rel=1e-4)


def test_sort_window_oracle_desc():
    ids, prices, ts = make(50, seed=5)
    cql = (
        "from S#window.sort(4, price, 'desc') "
        "select max(price) as mx, count() as c insert into o"
    )
    job = run(cql, ids, prices, ts)
    rows = job.results("o")
    kept = []
    exp = []
    for p in prices:
        kept = sorted(kept + [p], reverse=True)[:4]  # keep 4 largest
        exp.append((max(kept), len(kept)))
    for (mx, c), (emx, ec) in zip(rows, exp):
        assert c == ec
        assert mx == pytest.approx(emx, rel=1e-4)


def test_unique_window_oracle():
    ids, prices, ts = make(60, seed=7)
    cql = (
        "from S#window.unique(id) "
        "select sum(price) as s, count() as c insert into o"
    )
    job = run(cql, ids, prices, ts)
    rows = job.results("o")
    latest = {}
    exp = []
    for i, p in zip(ids, prices):
        latest[i] = p  # latest event per key replaces the old one
        exp.append((sum(latest.values()), len(latest)))
    assert len(rows) == len(exp)
    for (s, c), (es, ec) in zip(rows, exp):
        assert c == ec
        assert s == pytest.approx(es, rel=1e-4)


def test_unique_window_grows_past_initial_bucket():
    n = 400
    rng = np.random.default_rng(11)
    ids = rng.integers(0, 300, n).tolist()  # > the 128 initial bucket
    prices = [1.0] * n
    ts = (1000 + np.arange(n)).tolist()
    cql = "from S#window.unique(id) select count() as c insert into o"
    job = run(cql, ids, prices, ts, batch=64)
    rows = job.results("o")
    seen = set()
    exp = []
    for i in ids:
        seen.add(i)
        exp.append(len(seen))
    assert [r[0] for r in rows] == exp


def test_sort_window_rejects_stddev_loudly():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "from S#window.sort(3, price) select stddev(price) as s "
            "insert into o",
            {"S": SCHEMA},
        )


def test_delay_window_oracle():
    # events pass through 10 ms late (emission ts = arrival + delay);
    # stream end flushes the remainder
    cql = "from S#window.delay(10 ms) select id insert into o"
    ids = [0, 1, 2, 3, 4]
    ts = [1000, 1002, 1020, 1021, 1040]
    job = run(cql, ids, [0.0] * 5, ts, batch=2)
    rows = job.results_with_ts("o")
    assert [r[0] for _, r in rows] == ids
    assert [t for t, _ in rows] == [1010, 1012, 1030, 1031, 1050]


def test_session_window_oracle():
    # per-key sessions close after a 10ms gap; aggregates emit on the
    # key's next arrival past the gap or at stream end
    cql = (
        "from S#window.session(10 ms, id) "
        "select id, sum(price) as s, count() as c insert into o"
    )
    ids = [1, 2, 1, 1, 2, 1, 2]
    prices = [1.0, 10.0, 2.0, 4.0, 20.0, 8.0, 40.0]
    ts = [1000, 1001, 1005, 1008, 1030, 1040, 1041]
    job = run(cql, ids, prices, ts, batch=3)
    rows = sorted(job.results("o"))
    # oracle: key 1 sessions [1000,1005,1008] (sum 7, closes via ev@1040)
    #         then [1040] (sum 8, flush); key 2: [1001] (closes @1030),
    #         [1030, 1041]? gap 10: 1041-1030=11 > 10 -> separate:
    #         [1030] closes via ev@1041, [1041] flushes
    exp = sorted([
        (1, 7.0, 3), (1, 8.0, 1),
        (2, 10.0, 1), (2, 20.0, 1), (2, 40.0, 1),
    ])
    assert len(rows) == len(exp)
    for (k, s_, c), (ek, es, ec) in zip(rows, exp):
        assert (k, c) == (ek, ec)
        assert s_ == pytest.approx(es, rel=1e-5)


def test_session_window_plain_select_passes_through():
    # like every window's CURRENT-event path, a session window without
    # aggregation passes arriving events through unchanged
    cql = (
        "from S#window.session(10 ms, id) select price insert into o"
    )
    job = run(cql, [1, 2, 1], [1.0, 2.0, 3.0], [1000, 1001, 1002])
    assert [r[0] for r in job.results("o")] == [1.0, 2.0, 3.0]


def test_session_window_rejects_mixed_plain_attr_with_aggs():
    with pytest.raises(SiddhiQLError):
        compile_plan(
            "from S#window.session(10 ms, id) select price, "
            "count() as c insert into o",
            {"S": SCHEMA},
        )


# -- round-5: frequent / lossyFrequent (heavy-hitter sketches) -----------

def test_frequent_window_oracle():
    """Misra-Gries: tracked-value table of `count` slots; a full table
    decrements all counters, evicts zeros, and drops the arrival."""
    ids, prices, ts = make(n=120, seed=9)
    job = run(
        "from S#window.frequent(2, id) "
        "select id, count() as c, sum(price) as s insert into out",
        ids, prices, ts,
    )
    rows = job.results("out")

    # per-event oracle: table of at most 2 tracked ids -> (freq, latest
    # price); admitted arrivals emit (count of tracked, sum of latest
    # prices per tracked value)
    table = {}
    latest = {}
    expect = []
    for i, p in zip(ids, prices):
        if i in table:
            table[i] += 1
            latest[i] = p
        elif len(table) < 2:
            table[i] = 1
            latest[i] = p
        else:
            table = {k: v - 1 for k, v in table.items()}
            for k in [k for k, v in table.items() if v == 0]:
                del table[k]
                del latest[k]
            continue  # the arrival itself is NOT admitted
        expect.append((i, len(table), sum(latest.values())))
    assert len(rows) == len(expect)
    for (i1, c1, s1), (i2, c2, s2) in zip(rows, expect):
        assert (i1, c1) == (i2, c2)
        assert s1 == pytest.approx(s2, rel=1e-4)


def test_lossy_frequent_window_oracle():
    """Lossy counting: every arrival tracked (delta = bucket-1);
    bucket boundaries prune f+delta <= bucket; emission needs
    f >= (support-error)*N."""
    ids, prices, ts = make(n=150, seed=4)
    support, error = 0.3, 0.1
    job = run(
        f"from S#window.lossyFrequent({support}, {error}, id) "
        "select id, count() as c insert into out",
        ids, prices, ts,
    )
    rows = job.results("out")

    width = int(np.ceil(1.0 / error))
    table = {}  # id -> [freq, delta]
    n = 0
    expect = []
    for i in ids:
        n += 1
        b = int(np.ceil(n / width))
        if i in table:
            table[i][0] += 1
        else:
            table[i] = [1, b - 1]
        if n % width == 0:
            for k in [k for k, (f, d) in table.items() if f + d <= b]:
                del table[k]
        thresh = (support - error) * n
        if i in table and table[i][0] >= thresh:
            member = sum(
                1 for k, (f, d) in table.items() if f >= thresh
            )
            expect.append((i, member))
    assert len(rows) == len(expect)
    assert rows == expect


def test_frequent_rejects_partition():
    with pytest.raises(SiddhiQLError, match="partition"):
        compile_plan(
            "partition with (id of S) begin "
            "from S#window.frequent(2, id) select count() as c "
            "insert into out end",
            {"S": SCHEMA},
        )


# -- round-5: #window.cron (host-scheduled flush boundaries) -------------

def test_cron_schedule_enumeration():
    from flink_siddhi_tpu.utils.cron import CronSchedule

    # every 5 seconds
    s = CronSchedule.parse("0/5 * * * * ?")
    t0 = 1_700_000_000_000  # some UTC instant
    f1 = s.next_fire(t0)
    assert f1 is not None and f1 > t0 and (f1 // 1000) % 5 == 0
    # every minute at second 30
    s2 = CronSchedule.parse("30 * * * * ?")
    f2 = s2.next_fire(t0)
    assert (f2 // 1000) % 60 == 30
    # window ids are monotone and advance once per 5s fire
    ts = np.arange(t0, t0 + 20_000, 700, dtype=np.int64)
    wids = s.window_ids(ts)
    assert (np.diff(wids) >= 0).all()
    assert np.unique(wids).size == 4  # 20s span of a 5s cadence
    # PURE: a fresh instance maps the same timestamps identically
    # (window ids are absolute fire counts, no data-dependent anchor)
    assert (
        CronSchedule.parse("0/5 * * * * ?").window_ids(ts) == wids
    ).all()
    # '0/1' means EVERY second, not just second 0
    s3 = CronSchedule.parse("0/1 * * * * ?")
    w3 = s3.window_ids(ts)
    assert np.unique(w3).size == 20
    # Quartz day-of-week: 1 == SUN == 'SUN'; 2023-11-19 is a Sunday
    sun = int(
        np.datetime64("2023-11-19T12:00:00").astype(
            "datetime64[ms]"
        ).astype(np.int64)
    )
    for expr in ("0 0 12 ? * SUN", "0 0 12 ? * 1"):
        sd = CronSchedule.parse(expr)
        f = sd.next_fire(sun - 1)
        assert f == sun, (expr, f, sun)
    # calendar extensions reject loudly
    with pytest.raises(SiddhiQLError, match="extension"):
        CronSchedule.parse("0 0 0 L * ?")
    with pytest.raises(SiddhiQLError, match="6-7 fields"):
        CronSchedule.parse("*/5 * * * *")


def test_cron_window_oracle():
    """#window.cron('0/2 * * * * ?'): tumbling flush at every fire (2s
    cadence); matches a per-event oracle bucketing by fires."""
    from flink_siddhi_tpu.utils.cron import CronSchedule

    rng = np.random.default_rng(8)
    n = 80
    ids = rng.integers(0, 3, n).tolist()
    prices = np.round(rng.random(n) * 10, 2).tolist()
    # ~350ms spacing from an epoch-aligned start => several 2s windows
    t0 = 1_700_000_000_137
    ts = (t0 + np.cumsum(rng.integers(200, 500, n))).tolist()
    job = run(
        "from S#window.cron('0/2 * * * * ?') "
        "select id, sum(price) as s, count() as c "
        "group by id insert into out",
        ids, prices, ts, batch=16,
    )
    rows = job.results("out")

    sched = CronSchedule.parse("0/2 * * * * ?")
    wids = sched.window_ids(np.asarray(ts, dtype=np.int64))
    expect = {}
    for i, w in enumerate(wids.tolist()):
        key = (w, ids[i])
        s, c = expect.get(key, (0.0, 0))
        expect[key] = (s + prices[i], c + 1)
    got = {}
    for idv, s, c in rows:
        got.setdefault((idv, c, round(s, 2)), 0)
        got[(idv, c, round(s, 2))] += 1
    want = {}
    for (w, idv), (s, c) in expect.items():
        want.setdefault((idv, c, round(s, 2)), 0)
        want[(idv, c, round(s, 2))] += 1
    assert len(rows) == len(expect)
    assert got == want


def test_cron_into_table_and_dow_edges():
    from flink_siddhi_tpu.utils.cron import CronSchedule

    # bare '0' tolerated as Sunday; 0 inside a range rejects loudly
    sun = int(
        np.datetime64("2023-11-19T12:00:00").astype(
            "datetime64[ms]"
        ).astype(np.int64)
    )
    assert CronSchedule.parse("0 0 12 ? * 0").next_fire(sun - 1) == sun
    with pytest.raises(SiddhiQLError, match="range"):
        CronSchedule.parse("0 0 12 ? * 0-6")

    # cron window feeding a TABLE insert (the wrapper must forward the
    # host-computed window-id column)
    from flink_siddhi_tpu.compiler.plan import compile_plan
    from flink_siddhi_tpu.runtime.executor import Job
    from flink_siddhi_tpu.runtime.sources import BatchSource
    from flink_siddhi_tpu.schema.batch import EventBatch

    t0 = 1_700_000_000_137
    n = 40
    ids = [i % 3 for i in range(n)]
    prices = [float(i) for i in range(n)]
    ts = (t0 + np.cumsum(np.full(n, 700))).tolist()
    cql = (
        "define table T (id int, s double); "
        "from S#window.cron('0/2 * * * * ?') "
        "select id, sum(price) as s group by id insert into T; "
        "from S[id == 0] join T on S.id == T.id "
        "select T.s as s insert into out"
    )
    batches = [
        EventBatch(
            "S", SCHEMA,
            {
                "id": np.asarray(ids[s:s + 8], np.int32),
                "price": np.asarray(prices[s:s + 8], np.float64),
                "timestamp": np.asarray(ts[s:s + 8], np.int64),
            },
            np.asarray(ts[s:s + 8], np.int64),
        )
        for s in range(0, n, 8)
    ]
    plan = compile_plan(cql, {"S": SCHEMA})
    job = Job(
        [plan], [BatchSource("S", SCHEMA, iter(batches))],
        batch_size=8, time_mode="processing",
    )
    job.run()  # must not KeyError on the cron wid column
    assert job.results("out")
